"""CLI driver (reference L5).

The reference selects a backend by which binary you built (`make main |
multi-thread | mpi`, Makefile:1-9) and uses fixed positional argv
(main.cpp:118 ``./main train.arff test.arff k``; multi-thread.cpp:137 adds a
thread count; mpi.cpp:123 gets its parallelism from ``mpiexec -np``).

We preserve that convention with *personas*: the repo Makefile emits wrapper
scripts ``./main``, ``./multi-thread``, ``./mpi``, ``./tpu`` that invoke this
module with ``--persona``, keeping the reference's 3/4-positional-arg contract
intact while optional trailing flags expose TPU knobs (mesh shape, precision,
tiles — SURVEY.md §5.6). Timing wraps the classify region only, parsing
excluded, and the result line is byte-compatible with main.cpp:146.

Beyond the reference's one-shot shape, the CLI has subcommands (argv that
does not start with one implies ``classify``, so the positional contract
above is untouched):

- ``classify``   — the reference-parity batch run (default);
- ``save-index`` — parse a train ARFF once into a versioned index
  artifact (``knn_tpu/serve/artifact.py``);
- ``serve``      — a long-lived micro-batching HTTP server over such an
  artifact (``knn_tpu/serve/`` — docs/SERVING.md);
- ``replay``     — re-drive a captured workload artifact open-loop
  against a live server or an in-process batcher, verifying answers
  bit-identical where ``index_version``/``mutation_seq`` match
  (``knn_tpu/obs/replay.py`` — docs/OBSERVABILITY.md §Workload capture
  & replay).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
from typing import Optional, Sequence

from knn_tpu import obs
from knn_tpu.data.arff import load_arff
from knn_tpu.utils.cli_format import result_line, result_json
from knn_tpu.utils.evaluate import confusion_matrix, accuracy
from knn_tpu.utils.timing import RegionTimer, maybe_profile

# Exit-code contract (pinned by tests/test_cli.py::TestExitCodes):
# 0 = success; EXIT_USAGE (2) = the user's input was rejected before any
# classification/serving ran (bad flags, bad k, missing/malformed files,
# unknown backend, --no-fallback against an unavailable backend, a
# missing/corrupt/newer-format index artifact, bad serve policy values);
# EXIT_RUNTIME (1) = the computation itself failed (every ladder rung
# exhausted, artifact write failures, a serve port that cannot bind).
# One-line messages on stderr, never a traceback.
EXIT_USAGE = 2
EXIT_RUNTIME = 1

# Subcommands (`classify` is implied when argv starts with anything else,
# keeping the reference's positional invocation byte-compatible).
_SUBCOMMANDS = ("classify", "serve", "save-index", "replay", "route",
                "history", "report")

# persona -> (default backend, usage string modeled on the reference's)
_PERSONAS = {
    "main": ("native", "Usage: ./main datasets/train.arff datasets/test.arff k"),
    "multi-thread": (
        "native-mt",
        "Usage: ./multi-thread datasets/train.arff datasets/test.arff k numThreads",
    ),
    "mpi": ("tpu-sharded", "Usage: ./mpi datasets/train.arff datasets/test.arff k"),
    "tpu": ("tpu", "Usage: ./tpu datasets/train.arff datasets/test.arff k"),
}


def build_parser() -> argparse.ArgumentParser:
    """Top-level parser with subcommands. ``run`` prepends ``classify``
    when argv doesn't start with a subcommand name, so the reference's
    bare positional invocation (``knn_tpu train.arff test.arff k``) keeps
    working unchanged."""
    p = argparse.ArgumentParser(
        prog="knn_tpu",
        description="TPU-native KNN: reference-parity batch classify, "
                    "index building, and a micro-batching server",
    )
    sub = p.add_subparsers(dest="command",
                           metavar="{classify,serve,save-index,replay,"
                                   "route,history,report}")
    _add_classify_args(sub.add_parser(
        "classify",
        help="one-shot classify (default; bare positional argv implies it)",
        description="TPU-native KNN classifier (reference-parity CLI)",
    ))
    _add_serve_args(sub.add_parser(
        "serve",
        help="long-lived micro-batching HTTP server over a prebuilt index "
             "(docs/SERVING.md)",
        description="Serve /predict, /kneighbors, /healthz, /metrics from "
                    "an index artifact built by `knn_tpu save-index`. The "
                    "process warms the configured batch shapes (first-call "
                    "compile) before reporting ready.",
    ))
    _add_save_index_args(sub.add_parser(
        "save-index",
        help="build a versioned index artifact from a train ARFF file",
        description="Parse TRAIN once and write an index artifact "
                    "(arrays.npz + manifest.json) that `knn_tpu serve` "
                    "boots from without re-parsing ARFF.",
    ))
    _add_route_args(sub.add_parser(
        "route",
        help="a fault-tolerant router over N serve replicas "
             "(docs/SERVING.md §Running a replica set)",
        description="Route /predict and /kneighbors reads to healthy "
                    "replicas (health-polled + passively demoted, "
                    "cross-replica retry, optional tail hedging), "
                    "/insert and /delete writes to the one primary, "
                    "with coordinated reload, serialized compaction, "
                    "and optional automatic failover.",
    ))
    _add_replay_args(sub.add_parser(
        "replay",
        help="re-drive a captured workload against a live server or an "
             "in-process batcher and verify the answers "
             "(docs/OBSERVABILITY.md §Workload capture & replay)",
        description="Replay a workload artifact (serve --capture-dir / "
                    "POST /admin/capture) open-loop with its original "
                    "inter-arrival timing, replay mutations in sequence "
                    "order, verify answers bit-identical wherever "
                    "index_version/mutation_seq match the capture, and "
                    "emit a verdict JSON (p50/p99/QPS, divergence "
                    "counts, captured-vs-replayed comparison).",
    ))
    _add_history_cmd_args(sub.add_parser(
        "history",
        help="query a durable metrics-history directory post-mortem "
             "(docs/OBSERVABILITY.md §History & alerting)",
        description="Decode the segment ring a serve/route process wrote "
                    "under --history-dir — the process may be long dead; "
                    "a torn final segment (crash mid-append) is repaired, "
                    "corruption anywhere else refused typed — and print "
                    "the selected series.",
    ))
    _add_report_args(sub.add_parser(
        "report",
        help="stitch history, alerts, captures, and logs into one "
             "incident report (docs/SERVING.md runbook)",
        description="Build a deterministic markdown+JSON incident report "
                    "from a --history-dir: metrics history, alert "
                    "fire/resolve pairs and action outcomes, alert-armed "
                    "workload captures, frozen slowest-K forensics, and "
                    "access-log errors on ONE merged timeline.",
    ))
    return p


def _add_history_cmd_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("dir", help="the --history-dir a serve/route wrote")
    p.add_argument("--metric", default=None, metavar="NAME",
                   help="filter to one instrument (default: all)")
    p.add_argument("--label", action="append", default=[], metavar="K=V",
                   help="label subset filter (repeatable)")
    p.add_argument("--window", default=None, metavar="W",
                   help="trailing window back from the newest snapshot "
                   "(e.g. 300, 300s, 5m, 1h; default: everything)")
    p.add_argument("--json", action="store_true",
                   help="print the full query document as JSON instead "
                   "of the human summary")


def _add_report_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--history", required=True, metavar="DIR",
                   help="the --history-dir the incident's process wrote")
    p.add_argument("--window", default=None, metavar="W",
                   help="trailing window back from the newest artifact "
                   "timestamp (e.g. 15m, 1h; default: everything)")
    p.add_argument("--access-log", default=None, metavar="FILE",
                   help="the serve/route --access-log file; its error "
                   "lines join the timeline")
    p.add_argument("--captures", default=None, metavar="DIR",
                   help="the serve --capture-dir; workload manifests "
                   "(alert-armed ones included) join the timeline")
    p.add_argument("--out", default=None, metavar="FILE",
                   help="write the markdown report to FILE (default: "
                   "stdout)")
    p.add_argument("--json-out", default=None, metavar="FILE",
                   help="also write the JSON document to FILE")


def _add_route_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("replicas", nargs="+", metavar="REPLICA_URL",
                   help="replica base URLs (e.g. http://127.0.0.1:8099); "
                   "at least one. Join N cooperating serve processes "
                   "into one shard group with '+': url1+url2 forwards "
                   "to url1 and treats the pair as usable only while "
                   "BOTH are healthy (docs/SERVING.md §Sharded serving)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8098,
                   help="TCP port (0 picks an ephemeral port, reported "
                   "in the ready line)")
    p.add_argument("--health-interval-s", type=float, default=1.0,
                   help="active /healthz poll interval per replica "
                   "(passive demotion on forward errors is immediate "
                   "regardless)")
    p.add_argument("--health-timeout-s", type=float, default=2.0,
                   help="per-poll timeout before a replica is marked "
                   "unusable")
    p.add_argument("--forward-timeout-s", type=float, default=30.0,
                   help="per-forward timeout for reads and writes")
    p.add_argument("--admin-timeout-s", type=float, default=300.0,
                   help="timeout for coordinated reload/compact calls "
                   "(reloads warm a whole index)")
    p.add_argument("--hedge-ms", default="off", metavar="MS|auto|off",
                   help="tail-read hedging: fire a second attempt on "
                   "another replica once the first has been out this "
                   "long ('auto' derives the delay from the observed "
                   "read p99, so ~1%% of reads hedge; 'off' default)")
    p.add_argument("--auto-failover", choices=["on", "off"],
                   default="off",
                   help="promote the most-caught-up usable follower "
                   "automatically once the primary has been unusable "
                   "for --failover-after-s (off: POST /admin/promote "
                   "is the operator's lever)")
    p.add_argument("--failover-after-s", type=float, default=3.0,
                   help="how long the primary must be continuously "
                   "unusable before --auto-failover acts")
    p.add_argument("--flight-recorder-size", type=int, default=256,
                   help="router-side flight recorder ring (last-N "
                   "request timelines at /debug/requests, stitched "
                   "cross-tier with ?id=; 0 disables tracing)")
    p.add_argument("--slowest-k", type=int, default=32,
                   help="slowest-request reservoir kept alongside the "
                   "flight recorder ring")
    p.add_argument("--access-log", default=None, metavar="PATH",
                   help="append one JSON line per routed request "
                   "(outcome, replica, attempts, hedged) to PATH "
                   "('-' = stderr; default: off)")
    p.add_argument("--event-log", default=None, metavar="PATH",
                   help="append-only fleet audit log (demote/promote/"
                   "auto-failover/rejoin/hedge-fired/reload events as "
                   "JSON lines) to PATH ('-' = stderr), also served at "
                   "/debug/events (default: off — nothing constructed)")
    p.add_argument("--scale-cmd", default=None, metavar="CMD",
                   help="fleet autoscaler (docs/SERVING.md §Surviving "
                   "an overload): when offered load approaches the "
                   "usable fleet's summed sustainable QPS, run "
                   "`CMD up URL` to boot the next registered-but-down "
                   "replica (snapshot bootstrap catches it up under "
                   "live traffic); when load recedes well under "
                   "capacity, run `CMD down URL` to drain a surplus "
                   "non-primary back out. Scale decisions land in the "
                   "fleet audit log (--event-log) and "
                   "knn_fleet_scale_total. Unset (default): zero "
                   "autoscaler machinery")
    p.add_argument("--scale-min", type=int, default=1,
                   help="autoscaler floor: never drain below this many "
                   "usable replicas (default 1)")
    p.add_argument("--scale-max", type=int, default=None,
                   help="autoscaler ceiling: never boot past this many "
                   "usable replicas (default: every registered replica)")
    p.add_argument("--scale-cooldown-s", type=float, default=60.0,
                   help="freeze between autoscale actions (a booted "
                   "replica needs time to bootstrap, warm, and show up "
                   "in the capacity sum before the next decision)")
    _add_history_args(p)


def _add_replay_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("workload", help="workload artifact directory "
                   "(manifest.json + queries.npz + events.jsonl)")
    target = p.add_mutually_exclusive_group(required=True)
    target.add_argument("--url", default=None, metavar="BASE_URL",
                        help="replay against a live server (e.g. "
                        "http://127.0.0.1:8099)")
    target.add_argument("--index", default=None, metavar="DIR",
                        help="replay against an in-process micro-batcher "
                        "over this index artifact (no HTTP overhead — "
                        "the mode `make replay-gate` uses)")
    p.add_argument("--speed", type=float, default=1.0,
                   help="arrival-clock multiplier: 1 = original "
                   "inter-arrival timing, 2 = twice as fast, 0 = no "
                   "pacing (fire as fast as the driver runs)")
    p.add_argument("--verify", choices=["tag", "always", "off"],
                   default="tag",
                   help="answer verification: 'tag' (default) requires "
                   "bit-identical digests wherever index_version and "
                   "mutation_seq match the capture; 'always' compares "
                   "every answered pair (for a rebuilt-but-identical "
                   "index whose version tag necessarily moved); 'off' "
                   "skips verification")
    p.add_argument("--max-batch", type=int, default=None,
                   help="in-process batcher policy (default: the "
                   "workload's captured policy, else 256)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="in-process batcher policy (default: the "
                   "workload's captured policy, else 2.0)")
    p.add_argument("--mutable", choices=["on", "off"], default="off",
                   help="in-process mutation replay: 'on' builds a "
                   "mutable engine over --index and re-applies the "
                   "captured insert/delete stream (this WRITES epoch "
                   "records into the artifact directory — replay into a "
                   "copy). 'off' (default) skips mutations with a "
                   "warning; reads still replay, their mutation_seq "
                   "tags simply won't match")
    p.add_argument("--platform", default=os.environ.get("KNN_TPU_PLATFORM"),
                   help="force a JAX platform (e.g. cpu, tpu) for the "
                   "in-process mode")
    p.add_argument("--verdict-out", default=None, metavar="FILE",
                   help="write the verdict JSON to FILE (stdout always "
                   "gets the one-line summary + the JSON)")
    p.add_argument("--fail-on-divergence", action="store_true",
                   help="exit 1 when any verified answer diverged "
                   "(CI-gate mode; default: report and exit 0)")


def _add_serve_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("index", help="index artifact directory (save-index output)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8099,
                   help="TCP port (0 picks an ephemeral port, reported in "
                   "the ready line)")
    p.add_argument("--max-batch", type=int, default=256,
                   help="close a micro-batch at this many coalesced query "
                   "rows")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="...or when the oldest queued request has waited "
                   "this long (the latency price of coalescing — "
                   "docs/SERVING.md)")
    p.add_argument("--max-queue-rows", type=int, default=4096,
                   help="admission bound: queued rows beyond this are "
                   "refused with HTTP 429")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline (HTTP 504 on expiry); "
                   "requests may override with a deadline_ms body field")
    p.add_argument("--drain-timeout-s", type=float, default=10.0,
                   help="SIGTERM graceful-drain window: answer every "
                   "in-flight request within this many seconds (remainders "
                   "fail 504), then exit 0 (docs/SERVING.md ops runbook)")
    p.add_argument("--warmup-batches", default=None, metavar="B1,B2,...",
                   help="batch shapes to compile before reporting ready "
                   "(default: 1, --max-batch, and every --batch-buckets "
                   "bucket)")
    p.add_argument("--batch-buckets", default="auto",
                   metavar="B1,B2,...|auto|off",
                   help="compiled-shape bucket ladder (docs/SERVING.md "
                   "§Tuning the bucket ladder): each dispatched batch "
                   "pads to the smallest bucket >= its rows instead of "
                   "the single 128-row quantum, every bucket pre-compiles "
                   "at warmup, and continuous batching tops a closed "
                   "batch up to its bucket boundary for free. 'auto' "
                   "(default): a geometric ladder 16,32,... capped at "
                   "--max-batch; 'off': the legacy single-quantum pad")
    p.add_argument("--result-cache-rows", type=int, default=0,
                   metavar="ROWS",
                   help="exact-match result cache capacity in cached "
                   "query rows (docs/SERVING.md): identical query rows "
                   "at the same (index_version, mutation_seq) point are "
                   "answered without a dispatch — bit-identical by "
                   "construction, invalidated by reload/compaction, "
                   "knn_cache_* counters. 0 (default) constructs "
                   "nothing; leave it off for high-entropy query streams")
    p.add_argument("--shards", default=None, metavar="N|auto",
                   help="mesh-sharded serving (docs/SERVING.md §Sharded "
                   "serving): partition the index across N shards of the "
                   "device mesh — train rows for the exact rungs, whole "
                   "IVF cells for the approximate rung, delta slots for "
                   "the mutable tail — answering bit-identically to the "
                   "single-device ladder from one serve process. 'auto' "
                   "shards one per visible device; unset (default) "
                   "constructs no shard machinery at all")
    p.add_argument("--platform", default=os.environ.get("KNN_TPU_PLATFORM"),
                   help="force a JAX platform (e.g. cpu, tpu) before model "
                   "warmup")
    p.add_argument("--access-log", default=None, metavar="FILE",
                   help="append one structured JSON line per terminal "
                   "request outcome (request_id, status, outcome, rung, "
                   "phase breakdown) to FILE; '-' logs to stderr")
    p.add_argument("--flight-recorder-size", type=int, default=256,
                   help="per-request timelines kept for /debug/requests "
                   "(0 disables request tracing entirely)")
    p.add_argument("--slowest-k", type=int, default=32,
                   help="slowest-request reservoir size for /debug/slowest")
    p.add_argument("--slo-availability-target", type=float, default=0.999,
                   help="availability SLO: target fraction of non-400 "
                   "requests answered 200")
    p.add_argument("--slo-latency-ms", type=float, default=100.0,
                   help="latency SLO threshold: a 200 slower than this "
                   "spends latency error budget")
    p.add_argument("--slo-latency-target", type=float, default=0.99,
                   help="latency SLO: target fraction of requests answered "
                   "200 within --slo-latency-ms")
    p.add_argument("--slo-fast-rung-target", type=float, default=0.99,
                   help="degradation SLO: target fraction of requests "
                   "served by the model's own engine, not a fallback rung")
    p.add_argument("--slo-windows", default=None, metavar="S1,S2,...",
                   help="burn-rate windows in seconds (default: 300,3600 — "
                   "the 5m/1h pair)")
    p.add_argument("--shadow-rate", type=float, default=0.0,
                   help="shadow-score this fraction of served requests "
                   "against the exact oracle rung in a background worker "
                   "(recall/accuracy SLIs, knn_quality_* metrics, "
                   "/debug/quality — docs/OBSERVABILITY.md §Quality & "
                   "drift); 0 (default) disables the layer entirely")
    p.add_argument("--drift-rate", type=float, default=0.0,
                   help="fold this fraction of served query rows into the "
                   "query-drift sketch, scored against the artifact's "
                   "training sketch (knn_drift_* gauges); 0 disables")
    p.add_argument("--quality-queue", type=int, default=256,
                   help="bounded shadow/drift sample queue: a full queue "
                   "sheds samples (counted), never blocks serving")
    p.add_argument("--quality-seed", type=int, default=0,
                   help="RNG seed for shadow/drift sampling (deterministic "
                   "sample selection in soak gates)")
    p.add_argument("--slo-quality-target", type=float, default=0.999,
                   help="quality SLO: target fraction of shadow-scored "
                   "requests whose answers match the oracle rung exactly")
    p.add_argument("--cost-accounting", choices=["on", "off"], default="on",
                   help="per-request device-cost attribution + the "
                   "capacity/headroom model (knn_cost_*/knn_capacity_* "
                   "metrics, GET /debug/capacity, the x-knn-class request "
                   "class tag — docs/OBSERVABILITY.md §Cost & capacity); "
                   "'off' constructs nothing and skips class-header "
                   "parsing entirely")
    p.add_argument("--capacity-window-s", type=int, default=60,
                   help="trailing observation window for the capacity "
                   "rate rings / duty cycle / headroom model")
    p.add_argument("--ivf-probes", type=int, default=None, metavar="P",
                   help="serve the approximate ivf rung over the "
                   "artifact's IVF partition, probing the nearest P "
                   "cells per query (needs a format-3 artifact built "
                   "with `save-index --ivf-cells`; docs/INDEXES.md). "
                   "With shadow scoring on, the burn-aware probe policy "
                   "widens P toward exact while the quality SLI burns "
                   "and narrows back when the budget is healthy. Omitted "
                   "(default): exact-only serving, zero IVF machinery")
    p.add_argument("--ivf-recall-floor", type=float, default=0.95,
                   help="recall@k floor the ivf rung is held to: a "
                   "shadow-scored ivf answer under this mean recall "
                   "burns the quality SLO (the signal the probe policy "
                   "acts on)")
    p.add_argument("--mutable", choices=["on", "off"], default="off",
                   help="online-mutable serving (docs/INDEXES.md "
                   "§Mutable tier): POST /insert and /delete mutate a "
                   "delta tier + tombstone set merged into every answer "
                   "under the shared (distance, index) contract, with a "
                   "write-ahead epoch log in the artifact directory and "
                   "background compaction folding writes into fresh "
                   "index generations (POST /admin/compact forces one). "
                   "'off' (the default) constructs zero mutable "
                   "machinery and keeps today's immutable behavior "
                   "byte-identical")
    p.add_argument("--delta-cap", type=int, default=4096,
                   help="delta-tier row bound: inserts past this are "
                   "refused HTTP 429 until compaction folds the tier "
                   "(back-pressure, not data loss)")
    p.add_argument("--compact-threshold", type=int, default=1024,
                   help="pending mutations (delta rows + tombstones) "
                   "that trigger a background compaction")
    p.add_argument("--compact-interval-s", type=float, default=30.0,
                   help="background compaction check interval; 0 "
                   "disables the timer thread (threshold kicks and "
                   "/admin/compact still compact)")
    p.add_argument("--capture-dir", default=None, metavar="DIR",
                   help="workload capture (docs/OBSERVABILITY.md "
                   "§Workload capture & replay): finalized capture "
                   "windows land versioned workload artifacts under DIR "
                   "that `knn_tpu replay` re-drives. Windows are armed "
                   "by POST /admin/capture or the burn trigger below. "
                   "Omitted (default): zero capture machinery")
    p.add_argument("--capture-rate", type=float, default=1.0,
                   help="per-request sampling probability while a "
                   "capture window is armed (mutations are never "
                   "sampled — replay needs the complete stream)")
    p.add_argument("--capture-max-requests", type=int, default=65536,
                   help="a capture window finalizes itself at this many "
                   "captured events (bounded memory, bounded artifact)")
    p.add_argument("--capture-queue", type=int, default=1024,
                   help="bounded capture sample queue: a full queue "
                   "sheds records (counted), never blocks serving")
    p.add_argument("--capture-burn-threshold", type=float, default=None,
                   metavar="BURN",
                   help="burn-triggered capture: arm a window "
                   "automatically when the chosen SLO objective's "
                   "short-window burn rate exceeds BURN (e.g. 2.0 = "
                   "burning budget at twice the sustainable rate) — "
                   "incident forensics at workload granularity. Omitted "
                   "(default): manual/boot arming only")
    p.add_argument("--capture-burn-objective",
                   choices=["availability", "latency", "fast_rung",
                            "quality"],
                   default="availability",
                   help="which SLO objective's burn rate arms the "
                   "burn-triggered capture")
    p.add_argument("--capture-burn-window-s", type=float, default=60.0,
                   help="burn-triggered capture windows auto-stop after "
                   "this many seconds (or at --capture-max-requests, "
                   "whichever first)")
    p.add_argument("--follower-of", default=None, metavar="PRIMARY_URL",
                   help="boot as a READ-ONLY replica of the primary at "
                   "this base URL (docs/SERVING.md §Running a replica "
                   "set): client /insert//delete are refused 409, "
                   "primary-shipped WAL records apply through POST "
                   "/admin/wal-append, and POST /admin/promote flips "
                   "this process to primary in place. Requires "
                   "--mutable on. A rebooting ex-primary passes the NEW "
                   "primary here; its unacknowledged WAL tail past the "
                   "takeover point is truncated before replay")
    p.add_argument("--replicate-to", default=None,
                   metavar="URL1,URL2,...",
                   help="boot as the PRIMARY of a replica set, fanning "
                   "every acknowledged WAL record out to these follower "
                   "base URLs (one ordered cursor each; follower lag in "
                   "/healthz fleet block + knn_fleet_replication_lag_seq). "
                   "Requires --mutable on")
    p.add_argument("--replicate-ack", choices=["any", "none"],
                   default="any",
                   help="write-durability bar with --replicate-to: "
                   "'any' (default) holds each mutation's 200 until at "
                   "least one follower confirmed its seq — that is what "
                   "makes promoting the most-caught-up follower lose "
                   "zero acknowledged writes; 'none' acks on the local "
                   "WAL flush alone (faster, loses the failover "
                   "guarantee)")
    p.add_argument("--replicate-ack-timeout-s", type=float, default=5.0,
                   help="how long a mutation waits for the follower ack "
                   "before returning the typed 503 applied-but-"
                   "unconfirmed outcome")
    p.add_argument("--bootstrap", choices=["auto", "off"], default="auto",
                   help="with --follower-of over a BLANK index directory: "
                   "'auto' (default) pulls the primary's current "
                   "generation over the chunked, digest-verified "
                   "/admin/snapshot transfer before boot — 'add a "
                   "replica under live traffic' is one command; the WAL "
                   "shipper then catches the replica up from the "
                   "installed cursor. 'off' restores the old typed "
                   "refusal on a missing artifact. An EXISTING artifact "
                   "is never overwritten at boot (a stale replica "
                   "re-seeds through POST /admin/bootstrap instead)")
    p.add_argument("--priority", default=None,
                   metavar="CLASS=LEVEL,...",
                   help="priority admission (docs/RESILIENCE.md "
                   "§Degradation order): map request classes to shed "
                   "priority levels (e.g. 'interactive=0,batch=1,"
                   "bulk=2'; LOWER = more protected). Past the knee "
                   "(headroom under the floor, or availability/latency "
                   "burn over threshold) the HIGHEST levels shed first "
                   "with a typed 429 + headroom-derived Retry-After, "
                   "walking down tier by tier; level-0 classes are "
                   "never shed by policy. Unclassified requests shed at "
                   "the 'default' class's level (0 if unmapped). Needs "
                   "--cost-accounting on (the class parser). Unset "
                   "(default): zero admission machinery")
    p.add_argument("--brownout", choices=["on", "off"], default="off",
                   help="reversible brownout ladder (knn_tpu/control/"
                   "brownout.py): under sustained pressure walk "
                   "quality/cost knobs down one cooldown at a time — "
                   "shadow/drift sampling rates, ivf nprobe to base, "
                   "deadline tightening — each step audited and walked "
                   "back on recovery; compaction and shadow scoring "
                   "defer while measured headroom is negative. Needs at "
                   "least one such knob enabled. 'off' (default): no "
                   "controller thread, nothing constructed")
    p.add_argument("--autotune-interval-s", type=float, default=None,
                   metavar="S",
                   help="adaptive batching (knn_tpu/control/autotune.py)"
                   ": every S seconds capture a short live-arrival "
                   "window, sweep max_wait_ms candidates through the "
                   "what-if frontier, and apply the best one ONLY after "
                   "captured-workload replay verifies bit-identical "
                   "answers (refusals audited). Needs --capture-dir and "
                   "--cost-accounting on. Unset (default): max_wait_ms "
                   "stays the operator's static setting")
    _add_history_args(p)


def _add_history_args(p: argparse.ArgumentParser) -> None:
    """The history/alerting flags serve and route share
    (docs/OBSERVABILITY.md §History & alerting)."""
    p.add_argument("--history-dir", default=None, metavar="DIR",
                   help="durable metrics history (knn_tpu/obs/history.py)"
                   ": append delta-encoded registry snapshots to an "
                   "on-disk segment ring under DIR, queryable live at "
                   "GET /debug/history and post-mortem via `knn_tpu "
                   "history DIR` — the record survives the process. "
                   "Unset (default): zero history machinery")
    p.add_argument("--history-interval-s", type=float, default=5.0,
                   metavar="S",
                   help="snapshot cadence for --history-dir (and the "
                   "alert-rule evaluation cadence); default 5")
    p.add_argument("--history-retention-s", type=float, default=3600.0,
                   metavar="S",
                   help="on-disk retention: whole segments older than "
                   "this are pruned (default 3600)")
    p.add_argument("--alert-rules", default=None, metavar="RULES.json",
                   help="declarative alerting (knn_tpu/obs/alerts.py): "
                   "threshold / burn-rate / absence / derivative rules "
                   "with for: durations and hysteretic fire->resolve, "
                   "evaluated each --history-interval-s; transitions "
                   "land in alerts.jsonl under --history-dir, "
                   "knn_alerts_firing{alert}, and GET /debug/alerts; "
                   "optional actions arm a workload capture, grab a "
                   "device profile, or run an audited operator command. "
                   "Unset (default): zero alerting machinery")


def _add_save_index_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("train", help="train ARFF file")
    p.add_argument("out", help="output artifact directory")
    p.add_argument("--k", type=int, default=5)
    p.add_argument("--family", choices=["classifier", "regressor"],
                   default="classifier")
    p.add_argument("--backend", default="tpu",
                   help="classifier backend recorded in the manifest")
    p.add_argument("--metric",
                   choices=["euclidean", "manhattan", "chebyshev", "cosine"],
                   default="euclidean")
    p.add_argument("--weights", choices=["uniform", "distance"],
                   default="uniform")
    p.add_argument("--engine", choices=["auto", "stripe", "xla"],
                   default="auto",
                   help="candidate engine (regressor; for the classifier "
                   "it is recorded as a backend option when not auto)")
    p.add_argument("--ivf-cells", type=int, default=None, metavar="N",
                   help="also build an IVF partition: k-means the train "
                   "rows into N cells and persist centroids + the "
                   "cell-sorted row layout in the artifact (format 3) — "
                   "what `serve --ivf-probes` answers from "
                   "(docs/INDEXES.md). Euclidean metric only")
    p.add_argument("--ivf-seed", type=int, default=0,
                   help="k-means seed (deterministic partitions; recorded "
                   "in the manifest)")
    p.add_argument("--ivf-iters", type=int, default=25,
                   help="max Lloyd iterations for the partition build")


def _add_classify_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("train", help="train ARFF file")
    p.add_argument("test", help="test ARFF file")
    p.add_argument("k", type=int, help="number of neighbors")
    p.add_argument(
        "threads",
        nargs="?",
        type=int,
        default=None,
        help="worker count (multi-thread persona's 4th positional arg)",
    )
    p.add_argument("--persona", choices=sorted(_PERSONAS), default="tpu")
    p.add_argument("--backend", default=None, help="override the persona's backend")
    p.add_argument(
        "--no-fallback", action="store_true",
        help="disable the graceful-degradation ladder (docs/RESILIENCE.md): "
        "an unavailable backend exits 2 instead of substituting a rung, and "
        "a failing one exits 1 with its typed error instead of degrading "
        "(transient-fault retry stays on)",
    )
    p.add_argument(
        "--metric",
        choices=["euclidean", "manhattan", "chebyshev", "cosine"],
        default="euclidean",
        help="distance metric (euclidean = reference semantics; others are "
        "framework extensions, unsupported by the native backends)",
    )
    p.add_argument(
        "--precision", choices=["exact", "fast", "bf16", "auto"], default="exact",
        help="distance form: exact (reference parity), fast (MXU matmul), "
        "bf16 (bfloat16 MXU operands, tpu-pallas only), "
        "auto (defer to the backend's default)",
    )
    p.add_argument(
        "--engine",
        choices=["auto", "stripe", "xla", "full", "tiled"],
        default="auto",
        help="candidate kernel for the tpu/sharded backends: auto (stripe on "
        "real TPU for exact narrow-feature problems), stripe (lane-striped "
        "Pallas kernel), xla (tiled scan); full/tiled are tpu-ring-only "
        "per-step scorers",
    )
    p.add_argument("--query-tile", type=int, default=256)
    p.add_argument("--train-tile", type=int, default=2048)
    p.add_argument("--query-batch", type=int, default=None,
                   help="stream queries through the device in chunks of this "
                   "size (bounds device memory for huge query sets)")
    p.add_argument("--devices", type=int, default=None,
                   help="device count for sharded backends (default: all)")
    p.add_argument("--platform", default=os.environ.get("KNN_TPU_PLATFORM"),
                   help="force a JAX platform (e.g. cpu, tpu) before backend init")
    p.add_argument(
        "--sweep-k", default=None, metavar="K1,K2,...",
        help="classify at every listed k from ONE shared candidate retrieval "
        "(positional k is ignored): prints the canonical result line per k, "
        "each reporting the total sweep time. Runs the exact retrieval path "
        "with --engine auto/stripe/xla; options it cannot honor (--backend, "
        "--approx, non-exact --precision, --query-batch, tile/thread/device "
        "knobs) are rejected. Predictions per k are identical to individual "
        "runs",
    )
    p.add_argument(
        "--dump-predictions", default=None, metavar="FILE.npy",
        help="save the int32 prediction vector (with --sweep-k: one file per "
        "k, FILE.k{K}.npy) — lets graders diff predictions, not just the "
        "accuracy field",
    )
    p.add_argument("--json", action="store_true", help="emit structured JSON metrics")
    p.add_argument(
        "--metrics-out", default=None, metavar="FILE",
        help="write the observability metrics document (per-phase span "
        "totals + counters/gauges/histograms) to FILE as JSON; a .prom/.txt "
        "suffix selects the Prometheus text exposition. Implies enabling "
        "the knn_tpu.obs tracer for this run",
    )
    p.add_argument(
        "--trace-out", default=None, metavar="FILE",
        help="write a Chrome/Perfetto trace_event JSON of the run's nested "
        "spans to FILE (open in chrome://tracing or ui.perfetto.dev). "
        "Implies enabling the knn_tpu.obs tracer for this run",
    )
    p.add_argument(
        "--profile-out", default=None, metavar="FILE",
        help="capture a jax.profiler device profile spanning the classify "
        "region and write ONE merged Perfetto-loadable trace to FILE: host "
        "phase spans ride the device timeline as TraceAnnotations "
        "(docs/OBSERVABILITY.md §Device & fleet). Implies enabling the "
        "knn_tpu.obs tracer for this run",
    )
    p.add_argument("--trace-dir", default=None, help="jax.profiler trace output dir")
    p.add_argument("--warmup", action="store_true",
                   help="run once before timing (excludes compile time)")
    p.add_argument("--approx", action="store_true",
                   help="TPU hardware approximate top-k (not prediction-"
                   "exact). Measured r4 on 1M random rows, k=10: ~10x the "
                   "exact stripe kernel at recall ~0.92. A sampled-recall "
                   "guard (r5) scores 128 queries against exact top-k and "
                   "falls back to exact selection with a warning when the "
                   "measured recall misses --recall-target. (r4's headline "
                   "hazard — 0.002 recall on a 33x-tiled set — re-measured "
                   "r5 as mostly tie-order divergence between distance "
                   "forms on duplicate rows; same-values selection recall "
                   "there is ~0.99, worst observed 0.92 with contiguous "
                   "duplicates. The guard measures the same-values recall, "
                   "which is what approx selection actually loses)")
    p.add_argument("--recall-target", type=float, default=None,
                   help="per-candidate expected recall for --approx "
                   "(0 < r <= 1, default 0.95; higher = slower, closer to "
                   "exact)")


def _dump_predictions(path: str, preds) -> bool:
    """Save a prediction vector, keeping the CLI's error contract (a bad
    path reports ``error: ...`` and exits 1, never a traceback). Runs AFTER
    the result line so a failed save can't discard the computed output."""
    import numpy as np

    try:
        np.save(path, preds)
        return True
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return False


def _setup_obs(args) -> Optional[str]:
    """Enable the span tracer when observability artifacts were requested,
    failing fast (before any parse/compute) on unwritable destinations.
    Returns an error message or None."""
    if not (args.metrics_out or args.trace_out or args.profile_out):
        return None
    from knn_tpu.obs.export import check_parent_dir

    for path in (args.metrics_out, args.trace_out, args.profile_out):
        if path:
            try:
                check_parent_dir(path)
            except OSError as e:
                return str(e)
    obs.enable()
    obs.reset()  # artifacts describe THIS run, not ambient prior spans
    return None


@contextlib.contextmanager
def _maybe_capture(path: Optional[str]):
    """Wrap the classify region in a device-profile capture when
    ``--profile-out`` was given (obs/devprof.py); yields the Capture (its
    ``.trace`` is readable after the region) or None."""
    if not path:
        yield None
        return
    from knn_tpu.obs import devprof

    with devprof.capture() as cap:
        yield cap


def _write_profile(path: str, cap) -> bool:
    """Write the captured device profile, keeping the artifact-write
    contract (after the result line; one-line error + exit 1 on failure)."""
    import json

    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(cap.trace, f)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return False
    return True


def _phase_breakdown(classify_span) -> dict:
    """``{phase: total_ms}`` over the direct children of the timed classify
    region — sequential children partition the region, so the totals sum
    to ~the headline wall time (docs/OBSERVABILITY.md)."""
    return obs.tracer().phase_totals(classify_span)


def _write_obs_artifacts(args, classify_span, wall_ms) -> bool:
    """Write --trace-out / --metrics-out. Runs AFTER the result line so a
    failed save can't discard the computed output (the --dump-predictions
    contract)."""
    if not (args.metrics_out or args.trace_out):
        return True
    from knn_tpu.obs.export import write_metrics, write_trace

    try:
        if args.trace_out:
            write_trace(args.trace_out, obs.tracer())
        if args.metrics_out:
            write_metrics(
                args.metrics_out, obs.tracer(), obs.registry(),
                phase_parent=classify_span, wall_ms=wall_ms,
            )
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return False
    return True


def run(argv: Optional[Sequence[str]] = None, stdout=None) -> int:
    """CLI entry. Observability enabled via --metrics-out/--trace-out is
    scoped to this call: the prior global on/off state is restored on the
    way out, so a long-lived embedder that invokes the CLI once with
    artifacts does not keep paying tracing cost (or growing the span
    buffer) on every later predict. (``serve`` keeps obs enabled for its
    own lifetime — its /metrics endpoint IS the artifact — and never
    returns here until shutdown.)"""
    was_enabled = obs.enabled()
    try:
        return _run(argv, stdout)
    finally:
        if not was_enabled and obs.enabled():
            obs.disable()


def _normalize_argv(argv: Optional[Sequence[str]]) -> "list[str]":
    """Prepend ``classify`` unless argv already names a subcommand (or asks
    for top-level help) — the backward-compat shim that keeps the
    reference's bare 3/4-positional invocation and every persona wrapper
    working against the subcommand parser."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or (argv[0] not in _SUBCOMMANDS
                    and argv[0] not in ("-h", "--help")):
        argv = ["classify"] + argv
    return argv


def _apply_platform(platform: str) -> Optional[str]:
    """Force a JAX platform pre-init (shared by classify and serve). Same
    discipline as init_from_env (multihost.py): skip the no-op write
    (jax.config.update clears initialized backends even for a same value).
    Returns an error message or None."""
    import jax

    if getattr(jax.config, "jax_platforms", None) != platform:
        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError as e:
            return f"--platform {platform}: {e}"
    return None


def _run(argv: Optional[Sequence[str]], stdout) -> int:
    stdout = stdout or sys.stdout
    parser = build_parser()
    try:
        args = parser.parse_args(_normalize_argv(argv))
    except SystemExit as e:
        return e.code if isinstance(e.code, int) else EXIT_USAGE

    # Re-read KNN_TPU_FAULTS so env-armed chaos runs work for in-process
    # run() calls too (the import-time arm only sees the spawn env);
    # inject()-armed plans are preserved — for every subcommand: a served
    # process is exactly where chaos testing matters. A malformed spec is
    # user input: one-line message, usage exit code.
    from knn_tpu.resilience import faults

    try:
        faults.install_from_env()
    except ValueError as e:
        print(f"error: {faults.FAULT_ENV}: {e}", file=sys.stderr)
        return EXIT_USAGE

    if args.command == "serve":
        return _run_serve(args, stdout)
    if args.command == "save-index":
        return _run_save_index(args, stdout)
    if args.command == "replay":
        return _run_replay(args, stdout)
    if args.command == "route":
        return _run_route(args, stdout)
    if args.command == "history":
        return _run_history(args, stdout)
    if args.command == "report":
        return _run_report(args, stdout)
    return _run_classify(args, stdout)


def _run_history(args, stdout) -> int:
    """``knn_tpu history DIR``: the post-mortem contract — decode a dead
    (possibly SIGKILLed) process's segment ring, repairing a torn final
    segment exactly like the mutable WAL tail, and answer a range query.
    Unreadable/corrupt history and bad filters exit 2."""
    import json

    from knn_tpu.obs.history import load_history, parse_window
    from knn_tpu.resilience.errors import DataError

    labels = {}
    for item in args.label:
        k, sep, v = item.partition("=")
        if not sep or not k:
            print(f"error: --label {item!r}: want K=V", file=sys.stderr)
            return EXIT_USAGE
        labels[k] = v
    window_s = None
    if args.window is not None:
        try:
            window_s = parse_window(args.window)
        except ValueError as e:
            print(f"error: --window: {e}", file=sys.stderr)
            return EXIT_USAGE
    try:
        hist = load_history(args.dir)
    except (DataError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    doc = hist.query(metric=args.metric, labels=labels, window_s=window_s)
    doc["segments"] = len(hist.segments)
    doc["samples"] = len(hist.samples)
    doc["repaired_torn_tail"] = hist.repaired
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True), file=stdout)
        return 0
    w = doc["window"]
    print(f"knn-tpu history: {args.dir}: {doc['samples']} snapshot(s) in "
          f"{doc['segments']} segment(s), window {w['from']}..{w['to']}"
          + (" (torn tail repaired)" if hist.repaired else ""),
          file=stdout)
    for s in doc["series"]:
        labels_txt = ",".join(f"{k}={v}"
                              for k, v in sorted(s["labels"].items()))
        pts = s["points"]
        if not pts:
            continue
        first, last = pts[0], pts[-1]
        print(f"  {s['name']}{{{labels_txt}}} [{s['kind']}] "
              f"{len(pts)} point(s): {first[1]} @ {first[0]} -> "
              f"{last[1]} @ {last[0]}", file=stdout)
    if not doc["series"]:
        print("  (no matching series)", file=stdout)
    return 0


def _run_report(args, stdout) -> int:
    """``knn_tpu report --history DIR``: one-command incident report.
    Missing/corrupt inputs exit 2; generation is deterministic (every
    timestamp comes from the artifacts)."""
    import json

    from knn_tpu.obs.history import parse_window
    from knn_tpu.obs.report import build_report, render_markdown
    from knn_tpu.resilience.errors import DataError

    window_s = None
    if args.window is not None:
        try:
            window_s = parse_window(args.window)
        except ValueError as e:
            print(f"error: --window: {e}", file=sys.stderr)
            return EXIT_USAGE
    try:
        doc = build_report(args.history, window=window_s,
                           access_log=args.access_log,
                           captures=args.captures)
    except (DataError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    md = render_markdown(doc)
    if args.json_out is not None:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.out is not None:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(md)
        print(f"knn-tpu report: wrote {args.out}"
              + (f" and {args.json_out}" if args.json_out else ""),
              file=stdout)
    else:
        print(md, file=stdout)
    return 0


def _run_save_index(args, stdout) -> int:
    """``knn_tpu save-index TRAIN OUT``: parse once, write the versioned
    artifact ``knn_tpu serve`` boots from. Bad inputs (missing/malformed
    ARFF, bad k, unknown backend, a clobber target that is not an
    artifact) exit 2; a write failure mid-save exits 1."""
    from knn_tpu.models.knn import KNNClassifier, KNNRegressor
    from knn_tpu.resilience import degrade
    from knn_tpu.serve.artifact import save_index

    if args.family == "classifier" and not degrade.known_backend(args.backend):
        print(f"error: backend '{args.backend}' unavailable", file=sys.stderr)
        return EXIT_USAGE
    if args.ivf_cells is not None:
        # Partition-build validation BEFORE the (possibly huge) parse:
        # flag contradictions are usage errors, not compute failures.
        if args.ivf_cells < 1:
            print(f"error: --ivf-cells must be >= 1, got {args.ivf_cells}",
                  file=sys.stderr)
            return EXIT_USAGE
        if args.metric != "euclidean":
            print(f"error: --ivf-cells partitions by squared-euclidean "
                  f"k-means; --metric {args.metric} would probe cells "
                  f"under the wrong geometry (docs/INDEXES.md)",
                  file=sys.stderr)
            return EXIT_USAGE
        if args.ivf_iters < 1:
            print(f"error: --ivf-iters must be >= 1, got {args.ivf_iters}",
                  file=sys.stderr)
            return EXIT_USAGE
    try:
        train = load_arff(args.train)
        if args.family == "classifier":
            opts = {} if args.engine == "auto" else {"engine": args.engine}
            model = KNNClassifier(
                args.k, backend=args.backend, metric=args.metric,
                weights=args.weights, **opts,
            )
        else:
            model = KNNRegressor(
                args.k, weights=args.weights, metric=args.metric,
                engine=args.engine,
            )
        model.fit(train)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    ivf = None
    if args.ivf_cells is not None:
        if args.ivf_cells > train.num_instances:
            print(f"error: --ivf-cells {args.ivf_cells} exceeds the train "
                  f"rows ({train.num_instances})", file=sys.stderr)
            return EXIT_USAGE
        from knn_tpu.index.ivf import IVFIndex

        ivf = IVFIndex.build(
            train.features, args.ivf_cells, seed=args.ivf_seed,
            iters=args.ivf_iters,
        )
    try:
        out = save_index(model, args.out, ivf=ivf)
    except ValueError as e:  # clobber refusal / non-directory target
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    except OSError as e:  # the write itself failed
        print(f"error: {e}", file=sys.stderr)
        return EXIT_RUNTIME
    ivf_note = ""
    if ivf is not None:
        ivf_note = (f", ivf_cells={ivf.num_cells} "
                    f"(imbalance {ivf.imbalance()}, "
                    f"{ivf.meta['iterations']} iters)")
    print(
        f"wrote index {out}: {train.num_instances} rows x "
        f"{train.num_features} features, family={args.family}, "
        f"k={args.k}{ivf_note}",
        file=stdout,
    )
    return 0


def _history_flag_rows(args):
    """The serve/route-shared validation rows for the history/alerting
    flags (each a ``(bad, msg)`` pair for the exit-2 tables)."""
    return (
        (args.history_interval_s <= 0,
         f"--history-interval-s must be > 0, got "
         f"{args.history_interval_s}"),
        (args.history_retention_s < args.history_interval_s,
         f"--history-retention-s ({args.history_retention_s}) must be >= "
         f"--history-interval-s ({args.history_interval_s})"),
    )


def _load_alert_rules(args):
    """Parse ``--alert-rules`` (None when unset). Returns
    ``(rules_or_None, error_or_None)`` — every failure is a pre-boot
    usage error (exit 2), including actions whose machinery the other
    flags did not enable."""
    if args.alert_rules is None:
        return None, None
    from knn_tpu.obs.alerts import load_rules
    from knn_tpu.resilience.errors import DataError

    try:
        rules = load_rules(args.alert_rules)
    except DataError as e:
        return None, str(e)
    if args.history_dir is None and any(
            a["do"] == "profile" for r in rules for a in r["actions"]):
        return None, ("--alert-rules: profile actions write under "
                      "--history-dir; set it")
    if getattr(args, "capture_dir", None) is None and any(
            a["do"] == "capture" for r in rules for a in r["actions"]):
        return None, ("--alert-rules: capture actions arm the workload "
                      "recorder; set --capture-dir"
                      if hasattr(args, "capture_dir") else
                      "--alert-rules: capture actions need a serve "
                      "process with --capture-dir (routers have no "
                      "workload recorder)")
    return rules, None


def _run_serve(args, stdout) -> int:
    """``knn_tpu serve INDEX``: load the artifact, warm the configured
    batch shapes, then serve until SIGINT/SIGTERM. Bad policy values or a
    bad artifact exit 2 before any compute; bind/warmup failures exit 1."""
    from knn_tpu.resilience.errors import DataError, ResilienceError

    for bad, msg in (
        (args.max_batch < 1, f"--max-batch must be >= 1, got {args.max_batch}"),
        (args.max_wait_ms < 0,
         f"--max-wait-ms must be >= 0, got {args.max_wait_ms}"),
        (args.max_queue_rows < args.max_batch,
         f"--max-queue-rows ({args.max_queue_rows}) must be >= --max-batch "
         f"({args.max_batch})"),
        (args.deadline_ms is not None and args.deadline_ms <= 0,
         f"--deadline-ms must be > 0, got {args.deadline_ms}"),
        (args.drain_timeout_s <= 0,
         f"--drain-timeout-s must be > 0, got {args.drain_timeout_s}"),
        (not 0 <= args.port <= 65535, f"--port out of range: {args.port}"),
        (args.flight_recorder_size < 0,
         f"--flight-recorder-size must be >= 0, got "
         f"{args.flight_recorder_size}"),
        (args.slowest_k < 0, f"--slowest-k must be >= 0, got {args.slowest_k}"),
        (not 0 < args.slo_availability_target < 1,
         f"--slo-availability-target must be in (0, 1), got "
         f"{args.slo_availability_target}"),
        (not 0 < args.slo_latency_target < 1,
         f"--slo-latency-target must be in (0, 1), got "
         f"{args.slo_latency_target}"),
        (not 0 < args.slo_fast_rung_target < 1,
         f"--slo-fast-rung-target must be in (0, 1), got "
         f"{args.slo_fast_rung_target}"),
        (args.slo_latency_ms <= 0,
         f"--slo-latency-ms must be > 0, got {args.slo_latency_ms}"),
        (not 0 <= args.shadow_rate <= 1,
         f"--shadow-rate must be in [0, 1], got {args.shadow_rate}"),
        (not 0 <= args.drift_rate <= 1,
         f"--drift-rate must be in [0, 1], got {args.drift_rate}"),
        (args.quality_queue < 1,
         f"--quality-queue must be >= 1, got {args.quality_queue}"),
        (not 0 < args.slo_quality_target < 1,
         f"--slo-quality-target must be in (0, 1), got "
         f"{args.slo_quality_target}"),
        (args.capacity_window_s < 5,
         f"--capacity-window-s must be >= 5 (shorter windows make every "
         f"rate gauge noise), got {args.capacity_window_s}"),
        (args.ivf_probes is not None and args.ivf_probes < 1,
         f"--ivf-probes must be >= 1, got {args.ivf_probes}"),
        (not 0 < args.ivf_recall_floor <= 1,
         f"--ivf-recall-floor must be in (0, 1], got "
         f"{args.ivf_recall_floor}"),
        (args.delta_cap < 1,
         f"--delta-cap must be >= 1, got {args.delta_cap}"),
        (args.compact_threshold < 1,
         f"--compact-threshold must be >= 1, got "
         f"{args.compact_threshold}"),
        (args.compact_interval_s < 0,
         f"--compact-interval-s must be >= 0, got "
         f"{args.compact_interval_s}"),
        (not 0 < args.capture_rate <= 1,
         f"--capture-rate must be in (0, 1], got {args.capture_rate}"),
        (args.capture_max_requests < 1,
         f"--capture-max-requests must be >= 1, got "
         f"{args.capture_max_requests}"),
        (args.capture_queue < 1,
         f"--capture-queue must be >= 1, got {args.capture_queue}"),
        (args.capture_burn_threshold is not None
         and args.capture_burn_threshold <= 0,
         f"--capture-burn-threshold must be > 0, got "
         f"{args.capture_burn_threshold}"),
        (args.capture_burn_window_s <= 0,
         f"--capture-burn-window-s must be > 0, got "
         f"{args.capture_burn_window_s}"),
        (args.capture_burn_threshold is not None
         and args.capture_dir is None,
         "--capture-burn-threshold needs --capture-dir (the trigger "
         "has nowhere to write its artifact)"),
        (args.result_cache_rows < 0,
         f"--result-cache-rows must be >= 0, got "
         f"{args.result_cache_rows}"),
        (args.follower_of is not None and args.replicate_to is not None,
         "--follower-of and --replicate-to are contradictory: a replica "
         "is born either the primary or a follower"),
        ((args.follower_of is not None or args.replicate_to is not None)
         and args.mutable != "on",
         "--follower-of/--replicate-to ship the mutable tier's "
         "write-ahead log; they need --mutable on"),
        (args.follower_of is not None
         and not args.follower_of.startswith(("http://", "https://")),
         f"--follower-of wants a base URL, got {args.follower_of!r}"),
        (args.replicate_ack_timeout_s <= 0,
         f"--replicate-ack-timeout-s must be > 0, got "
         f"{args.replicate_ack_timeout_s}"),
        (args.priority is not None and args.cost_accounting != "on",
         "--priority sheds by request class, and classes are only "
         "parsed with --cost-accounting on"),
        (args.autotune_interval_s is not None
         and args.autotune_interval_s <= 0,
         f"--autotune-interval-s must be > 0, got "
         f"{args.autotune_interval_s}"),
        (args.autotune_interval_s is not None
         and (args.capture_dir is None or args.cost_accounting != "on"),
         "--autotune-interval-s tunes from captured arrivals against "
         "the fitted dispatch model; it needs --capture-dir and "
         "--cost-accounting on"),
        *_history_flag_rows(args),
    ):
        if bad:
            print(f"error: {msg}", file=sys.stderr)
            return EXIT_USAGE
    alert_rules, err = _load_alert_rules(args)
    if err is not None:
        print(f"error: {err}", file=sys.stderr)
        return EXIT_USAGE
    priority_map = None
    if args.priority is not None:
        from knn_tpu.control.admission import parse_priority_map

        try:
            priority_map = parse_priority_map(args.priority)
        except ValueError as e:
            print(f"error: --priority: {e}", file=sys.stderr)
            return EXIT_USAGE
    slo_windows = None
    if args.slo_windows is not None:
        try:
            slo_windows = sorted(
                {int(s) for s in args.slo_windows.split(",") if s}
            )
            if not slo_windows or slo_windows[0] < 1:
                raise ValueError
        except ValueError:
            print(f"error: --slo-windows wants positive integer seconds, "
                  f"got {args.slo_windows!r}", file=sys.stderr)
            return EXIT_USAGE
    warmup_batches = None
    if args.warmup_batches is not None:
        try:
            warmup_batches = sorted(
                {int(s) for s in args.warmup_batches.split(",") if s}
            )
            if not warmup_batches or warmup_batches[0] < 1:
                raise ValueError
        except ValueError:
            print(f"error: --warmup-batches wants positive integers, got "
                  f"{args.warmup_batches!r}", file=sys.stderr)
            return EXIT_USAGE
    # The compiled-shape bucket ladder (docs/SERVING.md §Tuning the
    # bucket ladder). Always topped by --max-batch so every admissible
    # batch pads onto a shape warmup compiled; buckets past --max-batch
    # are a contradiction (no batch can ever fill them), refused exit 2.
    batch_buckets = None
    if args.batch_buckets != "off":
        if args.batch_buckets == "auto":
            from knn_tpu.models.knn import DEFAULT_BATCH_BUCKETS

            batch_buckets = tuple(sorted(
                {b for b in DEFAULT_BATCH_BUCKETS if b < args.max_batch}
                | {args.max_batch}))
        else:
            try:
                parsed = sorted(
                    {int(s) for s in args.batch_buckets.split(",") if s})
                if not parsed or parsed[0] < 1:
                    raise ValueError
            except ValueError:
                print(f"error: --batch-buckets wants positive integers "
                      f"(or 'auto' / 'off'), got {args.batch_buckets!r}",
                      file=sys.stderr)
                return EXIT_USAGE
            if parsed[-1] > args.max_batch:
                print(f"error: --batch-buckets {parsed[-1]} exceeds "
                      f"--max-batch {args.max_batch}; no batch can ever "
                      f"fill that bucket", file=sys.stderr)
                return EXIT_USAGE
            batch_buckets = tuple(sorted({*parsed, args.max_batch}))
    if args.platform:
        err = _apply_platform(args.platform)
        if err is not None:
            print(f"error: {err}", file=sys.stderr)
            return EXIT_USAGE
    # Resolve --shards AFTER the platform applies: 'auto' means one
    # shard per device the configured platform actually exposes.
    shards = None
    if args.shards is not None:
        if str(args.shards).lower() == "auto":
            import jax

            shards = len(jax.devices())
        else:
            try:
                shards = int(args.shards)
                if shards < 1:
                    raise ValueError
            except ValueError:
                print(f"error: --shards wants a positive integer or "
                      f"'auto', got {args.shards!r}", file=sys.stderr)
                return EXIT_USAGE
    from knn_tpu.serve import artifact
    from knn_tpu.serve.server import ServeApp, make_server, serve_forever

    replicate_to = None
    if args.replicate_to is not None:
        replicate_to = [u.strip() for u in args.replicate_to.split(",")
                        if u.strip()]
        bad_urls = [u for u in replicate_to
                    if not u.startswith(("http://", "https://"))]
        if not replicate_to or bad_urls:
            print(f"error: --replicate-to wants comma-separated base "
                  f"URLs, got {args.replicate_to!r}", file=sys.stderr)
            return EXIT_USAGE
    mutable_on = args.mutable == "on"
    if args.follower_of is not None and args.bootstrap == "auto":
        # Snapshot bootstrap (docs/SERVING.md §Adding a replica under
        # live traffic): a blank index directory + --follower-of means
        # this process is JOINING the fleet — pull the primary's current
        # generation over the chunked, digest-verified /admin/snapshot
        # transfer before anything else boots. An existing artifact is
        # never touched here (a stale replica re-seeds through POST
        # /admin/bootstrap, where abandoning a lineage is explicit).
        from knn_tpu.fleet import bootstrap as _bootstrap
        from knn_tpu.resilience.errors import DataError as _DataError

        if not _bootstrap.artifact_present(args.index):
            try:
                doc = _bootstrap.install_snapshot(args.index,
                                                  args.follower_of)
            except (_DataError, OSError) as e:
                print(f"error: snapshot bootstrap from "
                      f"{args.follower_of} failed: {e}", file=sys.stderr)
                return EXIT_USAGE
            print(f"knn-tpu serve: {_bootstrap.summary_line(doc)}",
                  file=sys.stderr, flush=True)
    if args.follower_of is not None:
        # Rejoin reconciliation (docs/SERVING.md §Running a replica
        # set): BEFORE the engine replays this artifact's WAL, drop the
        # tail past the new primary's takeover point — on an ex-primary
        # that tail is unacknowledged by construction, and under the new
        # lineage those seqs name different mutations. Best-effort: an
        # unreachable primary just means boot on the local log (the
        # wal-append digest check still catches divergence, typed).
        from knn_tpu.fleet.replica import reconcile_wal_with_primary
        from knn_tpu.resilience.errors import DataError as _DataError

        try:
            outcome = reconcile_wal_with_primary(args.index,
                                                 args.follower_of)
        except _DataError as e:
            print(f"error: {e}", file=sys.stderr)
            return EXIT_USAGE
        if outcome and outcome.get("reconciled"):
            if outcome.get("dropped"):
                print(f"knn-tpu serve: rejoin truncated "
                      f"{outcome['dropped']} unacknowledged WAL "
                      f"record(s) past the takeover seq "
                      f"{outcome['cap']}", file=sys.stderr, flush=True)
        elif outcome:
            print(f"warning: rejoin reconciliation skipped "
                  f"({outcome.get('reason')}); booting on the local "
                  f"log", file=sys.stderr, flush=True)
    try:
        if mutable_on:
            # The mutable tier owns the artifact's lifecycle: boot from
            # the generation CURRENT.json points at (the most recent
            # completed compaction), falling back to the root artifact
            # for a never-compacted index; the engine replays any epoch
            # records newer than that generation's fold point.
            base_dir, current = artifact.resolve_mutable_base(args.index)
        else:
            base_dir, current = args.index, None
        model = artifact.load_index(base_dir)
        manifest = artifact.read_manifest(base_dir)
        version = artifact.index_version(manifest)
    except DataError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    if batch_buckets is not None:
        # Install the ladder BEFORE warmup: the pad, the executable-cache
        # key, and padded-row accounting all resolve from this one
        # definition, and warm() compiles one executable per bucket.
        from knn_tpu.models.knn import set_query_buckets

        set_query_buckets(batch_buckets)
    # The /metrics endpoint is this process's observability artifact;
    # serving without it would be flying blind.
    obs.enable()
    from knn_tpu.obs.slo import DEFAULT_WINDOWS_S, SLOTracker

    slo = SLOTracker(
        availability_target=args.slo_availability_target,
        latency_target_ms=args.slo_latency_ms,
        latency_target=args.slo_latency_target,
        fast_rung_target=args.slo_fast_rung_target,
        quality_target=args.slo_quality_target,
        windows_s=slo_windows or DEFAULT_WINDOWS_S,
    )
    try:
        app = ServeApp(
            model, max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            max_queue_rows=args.max_queue_rows, deadline_ms=args.deadline_ms,
            index_path=args.index, index_version=version,
            flight_recorder_size=args.flight_recorder_size,
            slowest_k=args.slowest_k, access_log=args.access_log, slo=slo,
            shadow_rate=args.shadow_rate, drift_rate=args.drift_rate,
            quality_queue=args.quality_queue, quality_seed=args.quality_seed,
            reference_sketch=artifact.reference_sketch(manifest),
            cost_accounting=(args.cost_accounting == "on"),
            capacity_window_s=args.capacity_window_s,
            ivf_probes=args.ivf_probes,
            ivf_recall_floor=args.ivf_recall_floor,
            mutable=mutable_on, delta_cap=args.delta_cap,
            compact_threshold=args.compact_threshold,
            compact_interval_s=args.compact_interval_s,
            mutable_current=current,
            mutable_base_dir=base_dir if mutable_on else None,
            capture_dir=args.capture_dir,
            capture_rate=args.capture_rate,
            capture_max_requests=args.capture_max_requests,
            capture_queue=args.capture_queue,
            capture_burn_threshold=args.capture_burn_threshold,
            capture_burn_objective=args.capture_burn_objective,
            capture_burn_window_s=args.capture_burn_window_s,
            batch_buckets=batch_buckets,
            result_cache_rows=args.result_cache_rows,
            follower_of=args.follower_of, replicate_to=replicate_to,
            replicate_ack=args.replicate_ack,
            replicate_ack_timeout_s=args.replicate_ack_timeout_s,
            shards=shards,
            priority_map=priority_map,
            brownout=(args.brownout == "on"),
            autotune_interval_s=args.autotune_interval_s,
            history_dir=args.history_dir,
            history_interval_s=args.history_interval_s,
            history_retention_s=args.history_retention_s,
            alert_rules=alert_rules,
        )
    except OSError as e:  # an unwritable --access-log / --capture-dir path
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    except DataError as e:  # --ivf-probes against an exact-only artifact
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    except ValueError as e:  # a malformed/mismatched manifest drift sketch
        print(f"error: {args.index}: {e}", file=sys.stderr)
        return EXIT_USAGE
    try:
        server = make_server(app, args.host, args.port)
    except OSError as e:
        print(f"error: cannot bind {args.host}:{args.port}: {e}",
              file=sys.stderr)
        app.close()
        return EXIT_RUNTIME
    host, port = server.server_address[:2]
    try:
        warmed = app.warm(warmup_batches)
    except (ResilienceError, ValueError, RuntimeError) as e:
        print(f"error: warmup failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        server.server_close()
        app.close()
        return EXIT_RUNTIME
    ivf_note = ""
    if app.ivf is not None:
        ivf_note = (f", ivf_probes={args.ivf_probes}/"
                    f"{model.ivf_.num_cells}")
    mutable_note = ""
    if app.mutable is not None:
        m = app.mutable.export()
        mutable_note = (f", mutable=on (gen={m['generation']}, "
                        f"epoch={m['epoch']}, "
                        f"replayed_delta={m['delta_slots']}, "
                        f"delta_cap={args.delta_cap})")
    fleet_note = ""
    if app.fleet is not None:
        role = app.fleet.role
        fleet_note = (f", fleet={role}"
                      + (f" of {args.follower_of}"
                         if role == "follower"
                         else f" -> {len(replicate_to or ())} follower(s)"
                              f" ack={args.replicate_ack}"))
    shard_note = ""
    if app.shards is not None:
        plan = app.model.shard_plan_
        shard_note = (f", shards={plan.num_shards}"
                      + ("/cells" if getattr(app.model, 'ivf_', None)
                         is not None else ""))
    bucket_note = ""
    if batch_buckets is not None:
        bucket_note = f", buckets={'/'.join(str(b) for b in batch_buckets)}"
    if args.result_cache_rows > 0:
        bucket_note += f", result_cache_rows={args.result_cache_rows}"
    control_note = ""
    if app.control_block() is not None:
        parts = []
        if app.admission is not None:
            parts.append("priority=" + "/".join(
                f"{c}:{level}"
                for c, level in sorted(priority_map.items())))
        if app.brownout is not None:
            parts.append("brownout="
                         + "+".join(s.name for s in app.brownout.steps))
        if app.autotune is not None:
            parts.append(f"autotune={args.autotune_interval_s:g}s")
        control_note = ", " + ", ".join(parts)
    print(
        f"knn-tpu serve: ready on http://{host}:{port} "
        f"(family={app.family}, k={model.k}, "
        f"train_rows={model.train_.num_instances}, "
        f"index_version={version}{ivf_note}{mutable_note}{fleet_note}"
        f"{shard_note}{bucket_note}{control_note}, "
        f"warmed={sorted(warmed)})",
        file=stdout, flush=True,
    )
    return serve_forever(server, drain_timeout_s=args.drain_timeout_s)


def _run_route(args, stdout) -> int:
    """``knn_tpu route URL...``: boot the fleet router. Bad policy values
    (or a router port that cannot bind) follow the serve exit-code
    contract. The router loads no model — it is up in milliseconds and
    restartable with zero state loss."""
    for bad, msg in (
        (not 0 <= args.port <= 65535, f"--port out of range: {args.port}"),
        (args.health_interval_s <= 0,
         f"--health-interval-s must be > 0, got {args.health_interval_s}"),
        (args.health_timeout_s <= 0,
         f"--health-timeout-s must be > 0, got {args.health_timeout_s}"),
        (args.forward_timeout_s <= 0,
         f"--forward-timeout-s must be > 0, got {args.forward_timeout_s}"),
        (args.admin_timeout_s <= 0,
         f"--admin-timeout-s must be > 0, got {args.admin_timeout_s}"),
        (args.failover_after_s <= 0,
         f"--failover-after-s must be > 0, got {args.failover_after_s}"),
        (args.flight_recorder_size < 0,
         f"--flight-recorder-size must be >= 0, got "
         f"{args.flight_recorder_size}"),
        (args.slowest_k < 0,
         f"--slowest-k must be >= 0, got {args.slowest_k}"),
        (args.scale_min < 1,
         f"--scale-min must be >= 1, got {args.scale_min}"),
        (args.scale_max is not None and args.scale_max < args.scale_min,
         f"--scale-max ({args.scale_max}) must be >= --scale-min "
         f"({args.scale_min})"),
        (args.scale_cooldown_s <= 0,
         f"--scale-cooldown-s must be > 0, got {args.scale_cooldown_s}"),
        (args.scale_cmd is None
         and (args.scale_min != 1 or args.scale_max is not None),
         "--scale-min/--scale-max bound the autoscaler; they need "
         "--scale-cmd"),
        *_history_flag_rows(args),
    ):
        if bad:
            print(f"error: {msg}", file=sys.stderr)
            return EXIT_USAGE
    alert_rules, rules_err = _load_alert_rules(args)
    if rules_err is not None:
        print(f"error: {rules_err}", file=sys.stderr)
        return EXIT_USAGE
    for spec in args.replicas:
        members = [u for u in spec.split("+") if u]
        if not members:
            print(f"error: empty replica spec {spec!r}", file=sys.stderr)
            return EXIT_USAGE
        for url in members:
            if not url.startswith(("http://", "https://")):
                print(f"error: replica URL {url!r} must start with "
                      f"http:// or https://", file=sys.stderr)
                return EXIT_USAGE
    from knn_tpu.fleet.router import (
        RouterApp,
        make_router_server,
        router_forever,
    )
    from knn_tpu.resilience.errors import DataError

    # The /metrics endpoint is the router's observability artifact
    # (the serve rule).
    obs.enable()
    try:
        app = RouterApp(
            args.replicas,
            health_interval_s=args.health_interval_s,
            poll_timeout_s=args.health_timeout_s,
            forward_timeout_s=args.forward_timeout_s,
            admin_timeout_s=args.admin_timeout_s,
            hedge=args.hedge_ms,
            auto_failover=(args.auto_failover == "on"),
            failover_after_s=args.failover_after_s,
            flight_recorder_size=args.flight_recorder_size,
            slowest_k=args.slowest_k,
            access_log=args.access_log,
            event_log=args.event_log,
            scale_cmd=args.scale_cmd,
            scale_min=args.scale_min,
            scale_max=args.scale_max,
            scale_cooldown_s=args.scale_cooldown_s,
            history_dir=args.history_dir,
            history_interval_s=args.history_interval_s,
            history_retention_s=args.history_retention_s,
            alert_rules=alert_rules,
        )
    except ValueError as e:  # bad --hedge-ms / duplicate replica URLs
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    except OSError as e:  # an unwritable --access-log / --event-log path
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    except DataError as e:  # burn_rate rules need serve's SLO tracker
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    try:
        server = make_router_server(app, args.host, args.port)
    except OSError as e:
        print(f"error: cannot bind {args.host}:{args.port}: {e}",
              file=sys.stderr)
        app.close()
        return EXIT_RUNTIME
    host, port = server.server_address[:2]
    usable = app.set.export()["usable"]
    scale_note = ""
    if args.scale_cmd is not None:
        scale_note = (f", scale={args.scale_min}.."
                      f"{args.scale_max or len(args.replicas)}")
    print(
        f"knn-tpu route: ready on http://{host}:{port} "
        f"(replicas={len(args.replicas)}, usable={usable}, "
        f"hedge={args.hedge_ms}, auto_failover={args.auto_failover}"
        f"{scale_note})",
        file=stdout, flush=True,
    )
    return router_forever(server)


def _run_replay(args, stdout) -> int:
    """``knn_tpu replay WORKLOAD (--url BASE | --index DIR)``: re-drive a
    captured workload and print/write the verdict JSON. A bad workload
    or index artifact exits 2 (typed, before any compute); a replay that
    cannot run exits 1; a completed replay exits 0 — unless
    ``--fail-on-divergence`` and a verified answer diverged."""
    import json

    from knn_tpu.obs.replay import replay_workload
    from knn_tpu.obs.workload import load_workload
    from knn_tpu.resilience.errors import DataError

    for bad, msg in (
        (args.speed < 0, f"--speed must be >= 0, got {args.speed}"),
        (args.max_batch is not None and args.max_batch < 1,
         f"--max-batch must be >= 1, got {args.max_batch}"),
        (args.max_wait_ms is not None and args.max_wait_ms < 0,
         f"--max-wait-ms must be >= 0, got {args.max_wait_ms}"),
        (args.url is not None and args.mutable == "on",
         "--mutable applies to the in-process --index mode only (a live "
         "server owns its own mutable engine)"),
    ):
        if bad:
            print(f"error: {msg}", file=sys.stderr)
            return EXIT_USAGE
    try:
        wl = load_workload(args.workload)
    except DataError as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE
    policy = wl.manifest.get("policy") or {}
    batcher = None
    workload_has_mutations = wl.manifest.get("mutations", 0) > 0
    replay_mutations = True
    engine = None
    try:
        if args.index is not None:
            if args.platform:
                err = _apply_platform(args.platform)
                if err is not None:
                    print(f"error: {err}", file=sys.stderr)
                    return EXIT_USAGE
            from knn_tpu.obs.capacity import CapacityTracker
            from knn_tpu.serve import artifact
            from knn_tpu.serve.batcher import MicroBatcher

            try:
                model = artifact.load_index(args.index)
                manifest = artifact.read_manifest(args.index)
                version = artifact.index_version(manifest)
            except DataError as e:
                print(f"error: {e}", file=sys.stderr)
                return EXIT_USAGE
            if model.train_.num_features != wl.manifest["num_features"]:
                print(f"error: {args.index}: feature width "
                      f"{model.train_.num_features} does not match the "
                      f"workload's {wl.manifest['num_features']} — this "
                      f"workload was captured against a different schema",
                      file=sys.stderr)
                return EXIT_USAGE
            max_batch = args.max_batch or policy.get("max_batch") or 256
            max_wait = (args.max_wait_ms
                        if args.max_wait_ms is not None
                        else policy.get("max_wait_ms", 2.0))
            if workload_has_mutations and args.mutable != "on":
                print("warning: the workload carries "
                      f"{wl.manifest['mutations']} mutations but "
                      "--mutable is off; skipping them (reads still "
                      "replay; their mutation_seq tags will not match)",
                      file=sys.stderr)
                replay_mutations = False
            if args.mutable == "on":
                from knn_tpu.mutable.engine import MutableEngine

                engine = MutableEngine(model, args.index,
                                       version=version)
            capacity = CapacityTracker(max_batch)
            artifact.warmup(model, batch_sizes=(1, max_batch),
                            kinds=("predict",))
            batcher = MicroBatcher(
                model, max_batch=max_batch, max_wait_ms=max_wait,
                index_version=version, capacity=capacity,
                mutable=engine,
            )
            verdict = replay_workload(
                wl, batcher=batcher, speed=args.speed,
                verify=args.verify, replay_mutations=replay_mutations)
            verdict["capacity"] = capacity.export()
        else:
            # A live target owns its mutable engine; mutations replay
            # over HTTP (an immutable server surfaces them as typed 404
            # mutation errors in the verdict).
            verdict = replay_workload(wl, base_url=args.url.rstrip("/"),
                                      speed=args.speed, verify=args.verify)
    except (OSError, RuntimeError, ValueError) as e:
        print(f"error: replay failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return EXIT_RUNTIME
    finally:
        if batcher is not None:
            batcher.close()
        if engine is not None:
            engine.close()
    m, v = verdict["measured"], verdict["verify"]
    print(
        f"replayed {m['requests']} requests "
        f"({verdict['mutations']['fired']} mutations) at speed "
        f"{args.speed}: p50 {m['p50_ms']} ms / p99 {m['p99_ms']} ms / "
        f"{m['qps']} q/s; verified {v['verified']}, divergences "
        f"{v['divergences']}, tag-mismatch skipped "
        f"{v['skipped_tag_mismatch']}",
        file=stdout,
    )
    doc = json.dumps(verdict)
    if args.verdict_out:
        try:
            from pathlib import Path

            Path(args.verdict_out).parent.mkdir(parents=True,
                                                exist_ok=True)
            with open(args.verdict_out, "w", encoding="utf-8") as f:
                f.write(doc + "\n")
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return EXIT_RUNTIME
    print(doc, file=stdout)
    if args.fail_on_divergence and v["divergences"] > 0:
        print(f"error: {v['divergences']} verified answer(s) diverged "
              f"from the capture", file=sys.stderr)
        return EXIT_RUNTIME
    return 0


def _run_classify(args, stdout) -> int:
    obs_err = _setup_obs(args)
    if obs_err is not None:
        print(f"error: {obs_err}", file=sys.stderr)
        return EXIT_USAGE

    # --sweep-k argument validation happens BEFORE any backend resolution or
    # file loading: the sweep never touches a backend (so backend fallback
    # warnings would mislead), and a flag error should not cost a
    # multi-hundred-MB parse.
    sweep_ks = None
    if args.sweep_k is not None:
        try:
            sweep_ks = sorted({int(s) for s in args.sweep_k.split(",") if s})
            if not sweep_ks or sweep_ks[0] < 1:
                raise ValueError
        except ValueError:
            print(f"error: --sweep-k wants positive integers, got "
                  f"{args.sweep_k!r}", file=sys.stderr)
            return EXIT_USAGE
        # Reject options the retrieval path cannot honor rather than
        # silently computing something else (the backends' own rule,
        # backends/tpu.py forced-stripe branch).
        rejected = [
            name for name, bad in (
                ("--backend", args.backend is not None),
                ("--approx", args.approx),
                ("--precision", args.precision not in ("exact", "auto")),
                ("--query-batch", args.query_batch is not None),
                ("--engine full/tiled", args.engine in ("full", "tiled")),
                ("--threads", args.threads is not None),
                ("--devices", args.devices is not None),
                ("--query-tile", args.query_tile != 256),
                ("--train-tile", args.train_tile != 2048),
            ) if bad
        ]
        if rejected:
            print(
                f"error: --sweep-k runs the exact candidate-retrieval path; "
                f"incompatible with {', '.join(rejected)}",
                file=sys.stderr,
            )
            return EXIT_USAGE

    if args.platform:
        err = _apply_platform(args.platform)
        if err is not None:
            print(f"error: {err}", file=sys.stderr)
            return EXIT_RUNTIME

    # Multi-host init (the MPI_Init analogue) — no-op unless a cluster
    # launcher set coordinator env vars.
    from knn_tpu.parallel.mesh import maybe_init_distributed

    maybe_init_distributed()

    if sweep_ks is not None:
        from knn_tpu.models.knn import sweep_k

        try:
            train = load_arff(args.train)
            test = load_arff(args.test)
            train.validate_for_knn(max(sweep_ks), test)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return EXIT_USAGE
        try:
            if args.warmup:
                sweep_k(train, test, sweep_ks, metric=args.metric,
                        engine=args.engine)
            with _maybe_capture(args.profile_out) as capture:
                with maybe_profile(args.trace_dir):
                    with RegionTimer() as t:
                        with obs.span("classify", mode="sweep",
                                      engine=args.engine) as classify_span:
                            preds_by_k = sweep_k(
                                train, test, sweep_ks, metric=args.metric,
                                engine=args.engine,
                            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return EXIT_RUNTIME
        phases = _phase_breakdown(classify_span) if obs.enabled() else None
        base = args.dump_predictions
        if base and base.endswith(".npy"):
            base = base[:-4]
        for k in sweep_ks:
            acc = accuracy(confusion_matrix(
                preds_by_k[k], test.labels, test.num_classes))
            print(
                result_line(k, test.num_instances, train.num_instances, t.ms, acc),
                file=stdout,
            )
            if args.json:
                print(
                    result_json(k, test.num_instances, train.num_instances,
                                t.ms, acc, f"sweep:{args.engine}",
                                phases=phases),
                    file=stdout,
                )
            if base:
                if not _dump_predictions(f"{base}.k{k}.npy", preds_by_k[k]):
                    return 1
        if not _write_obs_artifacts(args, classify_span,
                                    round(t.ns / 1e6, 3)):
            return 1
        if capture is not None and not _write_profile(args.profile_out,
                                                      capture):
            return 1
        return 0

    backend_name = args.backend or _PERSONAS[args.persona][0]
    # Static rung of the degradation ladder (docs/RESILIENCE.md): a known
    # but unbuilt/unregistered backend substitutes its first available rung
    # up front — unless --no-fallback, where asking for an unavailable
    # backend and forbidding substitution is a contradiction (exit 2).
    from knn_tpu.backends import available_backends
    from knn_tpu.resilience import degrade

    if backend_name not in available_backends():
        if not degrade.known_backend(backend_name):
            print(f"error: backend '{backend_name}' unavailable", file=sys.stderr)
            return EXIT_USAGE
        fallback = degrade.fallback_for(backend_name, available_backends())
        if fallback is None:
            print(f"error: backend '{backend_name}' unavailable", file=sys.stderr)
            return EXIT_USAGE
        if args.no_fallback:
            print(
                f"error: backend '{backend_name}' unavailable and "
                f"--no-fallback forbids degrading to '{fallback}'",
                file=sys.stderr,
            )
            return EXIT_USAGE
        reason = (
            "native runtime unavailable (run `make native`)"
            if backend_name.startswith("native")
            else "backend not registered on this install"
        )
        print(
            f"warning: backend '{backend_name}' unavailable — {reason}; "
            f"falling back to '{fallback}'",
            file=sys.stderr,
        )
        backend_name = fallback

    try:
        train = load_arff(args.train)
        test = load_arff(args.test)
        train.validate_for_knn(args.k, test)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return EXIT_USAGE

    opts = dict(
        query_tile=args.query_tile,
        train_tile=args.train_tile,
    )
    if args.metric != "euclidean":
        opts["metric"] = args.metric
    if args.query_batch is not None:
        opts["query_batch"] = args.query_batch
    if args.precision != "auto":
        opts["precision"] = args.precision
    if args.engine != "auto":
        opts["engine"] = args.engine
    if args.approx:
        opts["approx"] = True
    if args.recall_target is not None:
        if not args.approx:
            print("error: --recall-target only applies with --approx",
                  file=sys.stderr)
            return EXIT_USAGE
        opts["recall_target"] = args.recall_target
    if args.threads is not None:
        opts["num_threads"] = args.threads
    if args.devices is not None:
        opts["num_devices"] = args.devices

    from knn_tpu.resilience.errors import ResilienceError

    try:
        if args.warmup:
            warm = degrade.predict_with_ladder(
                backend_name, train, test, args.k, opts,
                no_fallback=args.no_fallback,
            )
            # Start the timed run from the rung (and query_batch) the
            # warmup survived on, so the timed region measures the serving
            # configuration rather than re-walking the failures.
            backend_name, opts = warm.backend, warm.opts
        with _maybe_capture(args.profile_out) as capture:
            with maybe_profile(args.trace_dir):
                with RegionTimer() as t:
                    with obs.span("classify",
                                  backend=backend_name) as classify_span:
                        result = degrade.predict_with_ladder(
                            backend_name, train, test, args.k, opts,
                            no_fallback=args.no_fallback,
                        )
        predictions = result.predictions
        backend_name = result.backend  # report where it actually ran
    except ResilienceError as e:
        # Ladder exhausted (or --no-fallback): one line, typed, exit 1.
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return EXIT_RUNTIME
    except ValueError as e:  # e.g. metric unsupported by this backend
        print(f"error: {e}", file=sys.stderr)
        return EXIT_RUNTIME

    cm = confusion_matrix(predictions, test.labels, test.num_classes)
    acc = accuracy(cm)
    print(
        result_line(args.k, test.num_instances, train.num_instances, t.ms, acc),
        file=stdout,
    )
    if args.dump_predictions and not _dump_predictions(
        args.dump_predictions, predictions
    ):
        return 1
    if args.json:
        phases = _phase_breakdown(classify_span) if obs.enabled() else None
        print(
            result_json(args.k, test.num_instances, train.num_instances, t.ms, acc,
                        backend_name, phases=phases),
            file=stdout,
        )
    # The artifact records the precise region wall (float ms); the result
    # line above keeps the reference's integer-floor contract.
    if not _write_obs_artifacts(args, classify_span, round(t.ns / 1e6, 3)):
        return 1
    if capture is not None and not _write_profile(args.profile_out, capture):
        return 1
    return 0


def main() -> None:
    sys.exit(run())


if __name__ == "__main__":
    main()
