"""knn_tpu — a TPU-native k-nearest-neighbor framework.

A ground-up re-design of the capabilities of the reference C++ project
``srna99/KNN-using-p_threads-and-MPI`` (serial / pthread / MPI KNN over ARFF
datasets) as a JAX / XLA / shard_map / Pallas framework:

- ``knn_tpu.data``      — ARFF ingest emitting dense ``float32 [N, D]`` arrays
  (replaces the reference's ``libarff`` AoS object graph; a native C++
  scanner/lexer/parser lives in ``knn_tpu/native/arff``).
- ``knn_tpu.ops``       — the algorithm kernels: pairwise squared-Euclidean
  distance, index-stable running top-k, majority vote (replaces the KNN inner
  loops duplicated across main.cpp:25-85 / multi-thread.cpp:37-104 /
  mpi.cpp:26-90).
- ``knn_tpu.backends``  — execution strategies over the one algorithm:
  ``oracle`` (NumPy, bit-exact reference semantics), ``native`` (C++ serial +
  thread-pool), ``tpu`` (single-device jit, tiled).
- ``knn_tpu.parallel``  — multi-device strategies over a ``jax.sharding.Mesh``:
  query-sharded (the MPI analogue), train-sharded with all-gather top-k merge,
  and a ring schedule (ring-attention structure with top-k accumulation).
- ``knn_tpu.models``    — the high-level ``KNNClassifier`` / ``KNNRegressor``
  APIs (kneighbors / radius_neighbors retrieval, uniform or inverse-distance
  weighting, pluggable metric).
- ``knn_tpu.resilience`` — fault injection, retry/backoff, the graceful
  backend-degradation ladder, and the typed error taxonomy
  (docs/RESILIENCE.md).
- ``knn_tpu.utils``     — timing, padding, evaluation, output formatting.

The behavioral contract (SURVEY.md §3.5) is preserved exactly: squared
Euclidean over the first D-1 attributes, first-seen train index wins distance
ties, lowest class id wins vote ties, ``num_classes = max(label)+1``.
"""

__version__ = "0.2.0"

from knn_tpu.data.dataset import Dataset
from knn_tpu.data.arff import load_arff, write_arff
from knn_tpu.models.knn import KNNClassifier, KNNRegressor, sweep_k

__all__ = [
    "Dataset", "load_arff", "write_arff", "KNNClassifier", "KNNRegressor",
    "sweep_k", "__version__",
]
