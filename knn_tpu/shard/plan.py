"""Deterministic shard partitioning — THE one plan definition.

Every consumer of a shard boundary reads it from here: the serving
:class:`~knn_tpu.shard.model.ShardedModel` (raw train rows for the exact
rungs, IVF cell runs for the approximate rung, delta slots for the
mutable tail) and the multi-process train-sharded launcher path
(``parallel/multihost.predict_train_sharded_global``). Plans are pure
functions of ``(size, num_shards)`` — no RNG, no ambient state — which
is what makes compaction's re-partition deterministic: the folded
generation's new row count in, the same boundaries out, on every replica
that folds the same WAL prefix.

All partitions are CONTIGUOUS. Contiguity is what keeps per-shard ids a
plain offset (``local + row_start``), keeps the IVF permutation slice a
valid segment space for the fused kernel, and keeps the delta-tail slice
a positional-id range (``base_n + slot_start``) the existing sentinel
rules still cover.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import numpy as np


class ShardPlan(NamedTuple):
    """One frozen partition of an index across ``num_shards`` shards.

    ``row_starts`` — ``num_shards + 1`` monotone train-row boundaries
    (shard ``s`` owns rows ``[row_starts[s], row_starts[s+1])`` of the
    RAW train matrix, or of the cell-sorted permutation when
    ``cell_starts`` is set); ``cell_starts`` — the matching IVF cell
    boundaries when the plan partitions a cell permutation, else None.
    """

    num_shards: int
    row_starts: Tuple[int, ...]
    cell_starts: Optional[Tuple[int, ...]] = None

    def rows(self, s: int) -> Tuple[int, int]:
        return self.row_starts[s], self.row_starts[s + 1]

    def cells(self, s: int) -> Tuple[int, int]:
        assert self.cell_starts is not None
        return self.cell_starts[s], self.cell_starts[s + 1]

    @property
    def total_rows(self) -> int:
        return self.row_starts[-1]

    def export(self) -> dict:
        """The /healthz + /debug/capacity shard-topology block."""
        return {
            "num_shards": self.num_shards,
            "rows_per_shard": [
                self.row_starts[s + 1] - self.row_starts[s]
                for s in range(self.num_shards)
            ],
            "by_cells": self.cell_starts is not None,
        }


def effective_shards(requested: int, size: int) -> int:
    """Clamp the shard count to what the partition can hold: at least 1,
    at most one shard per unit (the ``shards > cells`` / ``shards >
    rows`` degenerates collapse to one-unit shards, never empty ones)."""
    if requested < 1:
        raise ValueError(f"shards must be >= 1, got {requested}")
    return max(1, min(int(requested), max(1, int(size))))


def plan_rows(n: int, num_shards: int) -> ShardPlan:
    """Balanced contiguous row partition: the first ``n % S`` shards take
    one extra row — the same quota rule everywhere, so re-planning the
    same ``(n, S)`` always reproduces the same boundaries."""
    s = effective_shards(num_shards, n)
    base, rem = divmod(max(0, int(n)), s)
    starts = [0]
    for i in range(s):
        starts.append(starts[-1] + base + (1 if i < rem else 0))
    return ShardPlan(s, tuple(starts))


def plan_rows_uniform(n: int, num_shards: int, stride: int) -> ShardPlan:
    """The padded equal-width partition a ``shard_map`` collective
    executes: shard ``s`` owns padded rows ``[s*stride, (s+1)*stride)``
    of which ``row_starts[s+1] - row_starts[s]`` are valid, filled
    front-to-back — boundary ``min(n, s * stride)``, the closed form of
    the device-side ``clip(n - s*stride, 0, stride)`` valid-row rule in
    ``parallel/train_sharded.build_train_sharded_fn``. Unlike
    :func:`plan_rows`, trailing shards may be EMPTY: the shard count is
    the (fixed) global device count, not a tunable."""
    if num_shards < 1:
        raise ValueError(f"shards must be >= 1, got {num_shards}")
    if stride < 1:
        raise ValueError(f"stride must be >= 1, got {stride}")
    n = max(0, int(n))
    starts = tuple(min(n, s * int(stride)) for s in range(num_shards + 1))
    return ShardPlan(int(num_shards), starts)


def plan_cells(cell_offsets: np.ndarray, num_shards: int) -> ShardPlan:
    """Contiguous CELL runs balanced by row weight: walk the cell-sorted
    permutation greedily closing a shard at the boundary nearest its
    proportional row target, while leaving every remaining shard at
    least one cell. A probed cell therefore belongs WHOLLY to one shard
    — the invariant the per-shard segment scorer needs."""
    cell_offsets = np.asarray(cell_offsets, np.int64)
    c = int(cell_offsets.shape[0]) - 1
    total = int(cell_offsets[-1])
    s = effective_shards(num_shards, c)
    cell_starts = [0]
    row_starts = [0]
    for i in range(1, s):
        target = total * i // s
        # First boundary whose cumulative rows reach the target, floored
        # so the remaining s - i shards keep >= 1 cell each.
        j = int(np.searchsorted(cell_offsets, target, side="left"))
        j = max(cell_starts[-1] + 1, min(j, c - (s - i)))
        cell_starts.append(j)
        row_starts.append(int(cell_offsets[j]))
    cell_starts.append(c)
    row_starts.append(total)
    return ShardPlan(s, tuple(row_starts), tuple(cell_starts))


def plan_delta(count: int, num_shards: int) -> Tuple[Tuple[int, int], ...]:
    """Contiguous delta-slot slices ``((d0, d1), ...)`` — one per shard,
    possibly empty — partitioning slots ``[0, count)`` with the
    :func:`plan_rows` quota rule. The WAL replay order IS the slot
    order, so this is deterministic across compactions and replicas by
    construction; shards past the live count get empty slices rather
    than the plan shrinking (the shard topology never depends on the
    delta fill level)."""
    num_shards = max(1, int(num_shards))
    count = max(0, int(count))
    base, rem = divmod(count, num_shards)
    out = []
    start = 0
    for i in range(num_shards):
        end = start + base + (1 if i < rem else 0)
        out.append((start, end))
        start = end
    return tuple(out)
