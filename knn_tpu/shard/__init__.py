"""Mesh-sharded serving: one serve process, one index spread over shards.

The distributed IVF of Johnson et al.'s billion-scale search (PAPERS.md)
realized inside the serving ladder: the train matrix (exact rungs) and
the IVF cell permutation (approximate rung) partition into deterministic
contiguous shards (:mod:`knn_tpu.shard.plan`), each shard dispatches the
existing per-device retrieval — the XLA tiled scan or PR 13's fused
segment gather+score+select — and the per-shard survivors merge through
``models/ordering.lexicographic_topk`` followed by the existing host
exact re-rank, so the sharded answer is bit-identical to the
single-device rungs on the same artifact (:mod:`knn_tpu.shard.model`).

The mutable delta tail shards with the base: the WAL stays the single
ordered truth, each shard fuses its contiguous slice of the
device-resident delta (``mutable/device_tail.slice_view``) into its own
dispatch, and compaction re-partitions deterministically because the
plan is a pure function of (row count, shard count).

Everything here is imported lazily — ``serve --shards`` unset constructs
none of it (``scripts/check_disabled_overhead.py`` pins the module out
of ``sys.modules`` on a default boot).
"""

from __future__ import annotations
