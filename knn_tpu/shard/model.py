"""Sharded model wrappers: one serve process, an index spread over shards.

:func:`make_sharded` rebinds a fitted :class:`~knn_tpu.models.knn.
KNNClassifier` / :class:`~knn_tpu.models.knn.KNNRegressor` into its
sharded twin — same fitted state (the instance ``__dict__`` carries
over, so ``isinstance`` checks and every non-retrieval method keep
working), retrieval fanned out over :class:`~knn_tpu.shard.plan.
ShardPlan` slices through ``knn_tpu/shard/dispatch.py``:

- the exact rungs partition the RAW train matrix by rows
  (``plan_rows``) — each shard is an ordinary ``_kneighbors_arrays``
  call over its slice, merged bit-identically on the host;
- the ivf rung swaps ``model.ivf_`` for a :class:`ShardedIVFIndex`
  whose cell permutation is partitioned by whole cells (``plan_cells``)
  — ``search``/``search_merged`` are INHERITED, only the device scorer
  underneath fans out, so coverage widening, scorer auto-selection,
  host fallback, and the stats contract stay the single-device code;
- the mutable delta tail partitions by slots (``plan_delta``) and rides
  each shard's dispatch (``mutable/device_tail.slice_view``).

The serving batcher detects a sharded model by its ``shard_plan_``
attribute and routes rungs through :meth:`sharded_kneighbors`; the
oracle rung and every host fallback keep the unsharded paths (the full
train matrix is host-resident either way — sharding is a DEVICE memory
topology, not a host one).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

from knn_tpu.index.ivf import IVFIndex
from knn_tpu.models.knn import KNNClassifier, KNNRegressor
from knn_tpu.shard import dispatch
from knn_tpu.shard.plan import (ShardPlan, plan_cells, plan_delta,
                                plan_rows)

#: Metric path label for the sharded ivf rung's per-shard instruments
#: (the exact rungs use ``dispatch.SERVE_PATH``).
IVF_PATH = "serve-sharded-ivf"


class _ShardState:
    """Per-model shard machinery for the exact rungs: the frozen plan,
    per-shard host row slices, and per-shard executable caches (each
    shard's padded train-row count is its own compiled shape — sharing
    one cache dict would thrash the retrieval executables)."""

    __slots__ = ("plan", "features", "caches", "train_features", "last")

    def __init__(self, train_features: np.ndarray, num_shards: int):
        self.train_features = np.ascontiguousarray(
            train_features, np.float32)
        self.plan: ShardPlan = plan_rows(
            self.train_features.shape[0], num_shards)
        self.features = tuple(
            np.ascontiguousarray(
                self.train_features[self.plan.rows(s)[0]:
                                    self.plan.rows(s)[1]])
            for s in range(self.plan.num_shards))
        self.caches = tuple({} for _ in range(self.plan.num_shards))
        self.last: dict = {"dispatches": 0}

    def merge_tails(self, view, k: int):
        """Per-shard fused delta tails for one dispatch: slot slices
        from the ONE plan definition, empty slices carrying None (the
        plain retrieval executable — no zero-capacity tail shape).
        Returns ``(tails, slices)``; slices feed the sentinel fixups."""
        from knn_tpu.mutable.device_tail import (make_merge_tail,
                                                 slice_view)

        slices = plan_delta(view.count, self.plan.num_shards)
        tails = tuple(
            make_merge_tail(slice_view(view.device, d0, d1), k)
            if d1 > d0 else None
            for d0, d1 in slices)
        return tails, slices

    def note_dispatch(self, walls_ms: dict, stragglers: Optional[dict],
                      path: str = dispatch.SERVE_PATH) -> None:
        self.last["dispatches"] += 1
        self.last[path] = {
            "walls_ms": {str(s): round(w, 3)
                         for s, w in walls_ms.items()},
            "stragglers": stragglers,
        }

    def export(self) -> dict:
        out = dict(self.plan.export())
        out.update(self.last)
        return out


class _ShardedMixin:
    """Shared sharded-retrieval surface; mixed in FIRST so its
    ``kneighbors`` override wins the MRO."""

    def _shard_init(self, num_shards: int) -> None:
        self._shard_state = _ShardState(
            np.asarray(self.train_.features, np.float32), num_shards)
        ivf = getattr(self, "ivf_", None)
        if ivf is not None and not isinstance(ivf, ShardedIVFIndex):
            self.ivf_ = ShardedIVFIndex.wrap(
                ivf, self._shard_state.plan.num_shards)

    @property
    def shard_plan_(self) -> ShardPlan:
        """The batcher's sharded-model detection key."""
        return self._shard_state.plan

    def _sharded_engine(self) -> str:
        fn = getattr(self, "_retrieval_engine", None)
        return fn() if fn is not None else self.engine

    def sharded_kneighbors(self, feats: np.ndarray, view=None):
        """The fanned-out retrieval: ``(dists [Q,k], idx [Q,k])``
        bit-identical to the single-device exact rungs; ``view`` fuses a
        live mutable snapshot (caller guarantees fused eligibility —
        see ``serve/batcher.py``)."""
        return dispatch.exact_sharded(
            self._shard_state, np.asarray(feats, np.float32), self.k,
            self.metric, self._sharded_engine(), view=view)

    def shard_export(self) -> dict:
        """The /healthz + /debug/capacity shard block (exact-rung state;
        the ivf rung's twin rides ``self.ivf_.shard_export()``)."""
        out = self._shard_state.export()
        ivf = getattr(self, "ivf_", None)
        if isinstance(ivf, ShardedIVFIndex):
            out["ivf"] = ivf.shard_export()
        return out


class ShardedClassifier(_ShardedMixin, KNNClassifier):
    """:class:`KNNClassifier` answering from the sharded index. The
    candidate set is bit-identical to the unsharded model's, so every
    derived output (votes, probabilities, weighted scores) is too."""

    def kneighbors(self, test):
        train = self.train_
        train.validate_for_knn(self.k, test)
        return self.sharded_kneighbors(test.features)

    def predict(self, test) -> np.ndarray:
        if self.weights == "distance":
            # _weighted_class_scores retrieves via self.kneighbors —
            # already sharded.
            scores = self._weighted_class_scores(test)
            return np.argmax(scores, axis=1).astype(np.int32)
        # The unsharded predict dispatches a whole-train backend; the
        # sharded model predicts from its candidate set — identical
        # predictions by the shared (distance, index, first-max vote)
        # contracts.
        return self.predict_from_candidates(*self.kneighbors(test))

    def kneighbors_async(self, test):
        from knn_tpu.models.knn import AsyncResult

        train = self.train_
        train.validate_for_knn(self.k, test)
        feats = np.asarray(test.features, np.float32)
        return AsyncResult(lambda: self.sharded_kneighbors(feats))

    def predict_async(self, test):
        from knn_tpu.models.knn import AsyncResult

        handle = self.kneighbors_async(test)
        return AsyncResult(
            lambda: self.predict_from_candidates(*handle.result()))


class ShardedRegressor(_ShardedMixin, KNNRegressor):
    """:class:`KNNRegressor` answering from the sharded index
    (``predict`` inherits — it aggregates over ``self.kneighbors``)."""

    def kneighbors(self, test):
        self._check_features(test)
        return self.sharded_kneighbors(test.features)

    def kneighbors_async(self, test):
        from knn_tpu.models.knn import AsyncResult

        self._check_features(test)
        feats = np.asarray(test.features, np.float32)
        return AsyncResult(lambda: self.sharded_kneighbors(feats))


def make_sharded(model, num_shards: int):
    """Rebind a fitted model as its sharded twin. The returned instance
    shares the fitted state (train dataset, backend opts, ``ivf_`` —
    rebound to a :class:`ShardedIVFIndex`) and IS-A instance of the
    original class, so serving-side ``isinstance`` dispatch and artifact
    bookkeeping are untouched."""
    if isinstance(model, KNNClassifier):
        cls = ShardedClassifier
    elif isinstance(model, KNNRegressor):
        cls = ShardedRegressor
    else:
        raise TypeError(
            f"cannot shard a {type(model).__name__}; expected a fitted "
            f"KNNClassifier or KNNRegressor")
    model.train_  # raises if unfitted — shard plans need the row count
    new = cls.__new__(cls)
    new.__dict__.update(model.__dict__)
    new._shard_init(num_shards)
    return new


class ShardedIVFIndex(IVFIndex):
    """An :class:`IVFIndex` whose device scorer fans out over whole-cell
    shard slices. ONLY ``_score_device`` changes: ``search`` /
    ``search_merged`` / coverage / the host scorer are inherited, so the
    probe semantics, stats, auto-selection, and the host fallback are
    the single-device code verbatim — a shard dispatch failure under
    ``scorer="auto"`` degrades to the host scorer exactly as before.

    Bit-identity: per-pair device distances are shape-invariant
    (feature-axis reduction), each shard's ``segment_topk`` survivors
    are exact top-(k+margin) under THE tie contract within the shard,
    and the cross-shard ``lexicographic_topk`` merge selects under the
    same contract — so the merged survivor set contains everything the
    single-device margin selection keeps, and the SAME host exact
    re-rank (``_exact_rerank`` / ``rerank_merged``) produces the same
    final bits."""

    __slots__ = ("shard_plan", "_shard_cache")

    @classmethod
    def wrap(cls, base: IVFIndex, num_shards: int) -> "ShardedIVFIndex":
        new = cls(base.centroids, base.row_perm, base.cell_offsets,
                  meta=base.meta)
        new.shard_plan = plan_cells(base.cell_offsets, num_shards)
        new._shard_cache = {}
        return new

    def _shard_device_operands(self, train_x: np.ndarray, s: int):
        """Per-shard permuted operands (rows ``[r0, r1)`` of the cell
        permutation), memoized on train identity like the base
        ``_device_operands``. The pad id stays the GLOBAL ``N`` — the
        operands are built from the full train matrix — so the
        inherited re-rank's ``cand >= n`` pad masking still applies."""
        from knn_tpu.ops import segment_score

        hit = self._shard_cache.get(("device", s))
        if hit is not None and hit[0] is train_x:
            return hit[1], hit[2]
        r0, r1 = self.shard_plan.rows(s)
        perm_rows, perm_ids = segment_score.device_operands(
            train_x, self.row_perm[r0:r1])
        self._shard_cache[("device", s)] = (train_x, perm_rows, perm_ids)
        return perm_rows, perm_ids

    def _score_device(self, train_x: np.ndarray, queries: np.ndarray,
                      k: int, sel: np.ndarray, counts: np.ndarray,
                      tail=None, view=None, metric: str = "euclidean"):
        """The fanned-out device scorer: each shard scores the probed
        cells that fall in its cell run (a probed cell belongs WHOLLY to
        one shard — the ``plan_cells`` invariant) plus its delta-slot
        slice, survivors merge through ``lexicographic_topk``, and the
        INHERITED host re-rank restores exact bits. Dispatches are
        sequential (``segment_topk`` is a blocking host entry), so the
        per-shard walls feeding the straggler gauges are honest
        end-to-end times."""
        from knn_tpu.models.knn import candidate_padded_rows
        from knn_tpu.ops import segment_score
        from knn_tpu.ops.segment_score import RERANK_PAD

        q = queries.shape[0]
        plan = self.shard_plan
        fused = tail is not None
        starts_g = self.cell_offsets[:-1][sel]
        lens_g = self.cell_sizes[sel]
        slices = plan_delta(view.count, plan.num_shards) if fused else None

        parts_d, parts_i, walls = [], [], {}
        waste = 0
        t0 = time.monotonic()
        for s in range(plan.num_shards):
            r0, _r1 = plan.rows(s)
            c0, c1 = plan.cells(s)
            inshard = (sel >= c0) & (sel < c1)
            st = np.where(inshard, starts_g - r0, 0).astype(np.int32)
            ln = np.where(inshard, lens_g, 0).astype(np.int32)
            m_s = int(ln.sum(axis=1).max()) if q else 0
            tail_s = None
            if fused:
                from knn_tpu.mutable.device_tail import slice_view

                d0, d1 = slices[s]
                tail_s = slice_view(view.device, d0, d1)
            perm_rows, perm_ids = self._shard_device_operands(train_x, s)
            d_s, i_s = segment_score.segment_topk(
                perm_rows, perm_ids, queries, st, ln, m_s, k,
                tail=tail_s)
            walls[s] = (time.monotonic() - t0) * 1e3
            waste += q * candidate_padded_rows(m_s) - int(ln.sum())
            if fused:
                d_s, i_s = self._fixup_fused(d_s, i_s, slices[s], view)
            parts_d.append(np.asarray(d_s, np.float32))
            parts_i.append(np.asarray(i_s, np.int64))

        stragglers = dispatch.note_shard_metrics(
            walls, parts_d, parts_i, path=IVF_PATH)
        self._shard_cache["last"] = {
            "walls_ms": {str(s): round(w, 3) for s, w in walls.items()},
            "stragglers": stragglers,
        }

        width = sum(p.shape[1] for p in parts_d)
        md, mi = dispatch.merge_survivors(parts_d, parts_i,
                                          min(k + RERANK_PAD, width))
        if not fused:
            d, i = self._exact_rerank(train_x, queries, mi, k)
        else:
            from knn_tpu.mutable.device_tail import rerank_merged

            d, i = rerank_merged(view, train_x, queries, mi, k, metric)
        return d, i, max(waste, 0)

    @staticmethod
    def _fixup_fused(d_s, i_s, slot_slice: Tuple[int, int], view):
        """Per-shard sentinel fixups for the fused path, in GLOBAL id
        space (``view.base_n == N``, the train row count):

        - the device core only remaps base ids ``>= base_n + d0`` to its
          slice sentinel, so for shards whose slot slice starts past 0
          (or is empty) the base PAD id ``N`` slips through un-remapped
          and would read as delta slot 0 downstream — rewrite it to the
          parent sentinel. The shard owning slot 0 must NOT rewrite:
          its genuine slot-0 candidates carry id ``N`` (and its pads
          were device-remapped already).
        - the slice sentinel ``base_n + d1`` is a REAL slot id of the
          next shard — rewrite to the parent sentinel (no-op for the
          last shard, whose slice sentinel IS the parent's).

        Genuine ids never collide with either rewrite target (base ids
        ``< N``, shard-``s`` delta ids in ``[N+d0, N+d1)``)."""
        d0, d1 = slot_slice
        i_s = np.asarray(i_s, np.int64)
        d_s = np.asarray(d_s, np.float32)
        sent = view.sentinel
        targets = []
        if d0 > 0 or d1 == d0:
            targets.append(view.base_n)
        slice_sent = view.base_n + d1
        if slice_sent != sent:
            targets.append(slice_sent)
        for t in targets:
            stale = i_s == t
            if stale.any():
                i_s = np.where(stale, sent, i_s)
                d_s = np.where(stale, np.inf, d_s)
        return d_s, i_s

    def shard_export(self) -> dict:
        out = dict(self.shard_plan.export())
        last = self._shard_cache.get("last")
        if last is not None:
            out["last"] = last
        return out
