"""Per-shard dispatch + cross-shard merge — the sharded retrieval core.

One serving dispatch fans the query block out to every shard's slice of
the index (all device work in flight before the first resolve — the
shards pipeline through the device queue), then merges the per-shard
survivors on the host through ``models/ordering.lexicographic_topk``:

- **exact rungs** (:func:`exact_sharded`): each shard runs the ordinary
  ``models/knn._kneighbors_arrays`` over its contiguous row slice. The
  per-pair subtraction-form distance reduces over the FEATURE axis only,
  so a pair's distance is bit-identical whether the train operand is the
  full matrix or a shard slice — which makes the cross-shard
  lexicographic merge of per-shard exact top-k EXACTLY the single-device
  answer, distances included. No re-rank is needed; the merge is the
  proof.
- **mutable exact rungs** (``view=`` given): each shard fuses its
  contiguous delta-tail slice (``mutable/device_tail.slice_view``) into
  its own dispatch via ``make_merge_tail``, per-shard survivors carry
  the RERANK_PAD margin, and the existing host exact re-rank
  (``mutable/device_tail.rerank_merged``) restores the bit-exact merged
  answer — the same margin + re-rank contract the single-device fused
  path makes.
- the **ivf rung** lives on :class:`knn_tpu.shard.model.ShardedIVFIndex`
  (per-shard segment scorer + the existing ``_exact_rerank``), but its
  cross-shard merge comes back through :func:`merge_survivors` here.

Per-shard walls/candidates feed the ``knn_shard_*`` instruments and the
straggler gauges (``obs/aggregate.local_straggler_gauges``).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

#: The label every serving shard instrument carries — the in-process
#: logical-shard topology, distinct from the multihost per-process paths
#: (obs/instrument.STRATEGY_PATHS).
SERVE_PATH = "serve-sharded"


def note_shard_metrics(walls_ms: dict, parts_d, parts_i,
                       path: str = SERVE_PATH) -> Optional[dict]:
    """Record per-shard instruments for one fanned-out dispatch: the
    per-shard wall gauges + candidate/byte counters, then the derived
    max/min/skew straggler family. Returns the straggler summary (or
    None when obs is off)."""
    from knn_tpu import obs

    if not obs.enabled():
        return None
    from knn_tpu.obs import aggregate, instrument

    for s, wall in walls_ms.items():
        instrument.record_shard_wall(path, s, wall)
        d, i = parts_d[s], parts_i[s]
        instrument.record_shard_candidates(
            path, s, int(d.shape[0] * d.shape[1]),
            int(d.nbytes + i.nbytes))
    return aggregate.local_straggler_gauges(path, walls_ms)


def merge_survivors(parts_d, parts_i, keep: int):
    """Cross-shard top-``keep``: concatenate every shard's survivor
    columns (ragged widths fine — small shards contribute fewer) and
    select under THE (distance, index) contract. Ids must already be
    GLOBAL and sentinel-sanitized by the caller."""
    from knn_tpu.models.ordering import lexicographic_topk

    all_d = np.concatenate(parts_d, axis=1)
    all_i = np.concatenate(parts_i, axis=1)
    return lexicographic_topk(all_d, all_i, keep)


def _sanitize_fused(d, i, r0: int, n_s: int, slice_stop: int, view):
    """Host fixups for one shard's fused (base + delta-slice) survivors:

    - local base ids (``< n_s``) offset to global rows;
    - the SLICE sentinel (``view.base_n + slice_stop`` — a real slot id
      of the NEXT shard when the slice stops short of the parent count,
      see ``device_tail.slice_view``) remaps to the parent sentinel with
      +inf distance, so a dead-slot marker from shard ``s`` can never be
      re-scored as a live delta row of shard ``s+1``.

    Base ids after the offset stay strictly below ``view.base_n`` and
    genuine delta ids strictly below the slice sentinel, so the equality
    rewrite can never touch a real candidate."""
    i = np.asarray(i, np.int64)
    d = np.asarray(d, np.float32)
    base = i < n_s
    i = np.where(base, i + r0, i)
    slice_sent = view.base_n + slice_stop
    stale = i == slice_sent
    if stale.any():
        i = np.where(stale, view.sentinel, i)
        d = np.where(stale, np.inf, d)
    return d, i


def exact_sharded(state, feats: np.ndarray, k: int, metric: str,
                  engine: str, view=None):
    """The sharded exact retrieval: ``(dists [Q,k], idx [Q,k])``
    bit-identical to the single-device exact rungs on the same train
    matrix (see the module docstring for why). ``state`` is the
    :class:`knn_tpu.shard.model._ShardState`; ``view`` a live
    :class:`~knn_tpu.mutable.state.MutableView` carrying a device tail
    (the caller — ``serve/batcher.py`` — guarantees fused eligibility:
    device tail present, no base tombstones, euclidean metric)."""
    from knn_tpu.models.knn import _kneighbors_arrays
    from knn_tpu.ops.segment_score import RERANK_PAD

    feats = np.ascontiguousarray(feats, np.float32)
    plan = state.plan
    fused = view is not None
    if fused:
        engine = "xla"  # merge_tail is an XLA-path hook
        tails, slices = state.merge_tails(view, k)
    else:
        tails, slices = (None,) * plan.num_shards, None

    from knn_tpu import obs

    if obs.enabled():
        from knn_tpu.obs import devprof

        # The fanout itself is part of what compiles: N per-shard
        # executables per bucket, keyed so a sharded boot never reads as
        # cache aliasing with an unsharded one.
        devprof.record_executable_lookup("retrieval", (
            "sharded-fanout", plan.num_shards, feats.shape[0], k,
            bool(fused)))

    # Dispatch EVERY shard deferred before resolving any: device work for
    # shard s+1 queues behind shard s instead of waiting on its host sync.
    resolves = []
    for s in range(plan.num_shards):
        r0, r1 = plan.rows(s)
        k_s = min(k, r1 - r0)
        resolves.append(_kneighbors_arrays(
            state.features[s], feats, k_s, metric=metric, engine=engine,
            cache=state.caches[s], deferred=True, merge_tail=tails[s],
        ))

    parts_d, parts_i, walls = [], [], {}
    t0 = time.monotonic()
    for s, resolve in enumerate(resolves):
        d, i = resolve()
        walls[s] = (time.monotonic() - t0) * 1e3
        r0, r1 = plan.rows(s)
        if fused:
            d, i = _sanitize_fused(d, i, r0, r1 - r0, slices[s][1], view)
        else:
            d, i = np.asarray(d, np.float32), np.asarray(i, np.int64) + r0
        parts_d.append(d)
        parts_i.append(i)

    stragglers = note_shard_metrics(walls, parts_d, parts_i)
    state.note_dispatch(walls, stragglers)

    if not fused:
        return merge_survivors(parts_d, parts_i, k)

    # Mutable merge: survivors selected by DEVICE distances with the
    # RERANK_PAD margin, then the existing host exact re-rank — base
    # candidates keep their pass-through rung distances, delta rows
    # re-score through the oracle einsum (device_tail.rerank_merged),
    # exactly the single-device fused contract.
    from knn_tpu.mutable.device_tail import rerank_merged

    width = sum(p.shape[1] for p in parts_d)
    md, mi = merge_survivors(parts_d, parts_i,
                             min(k + RERANK_PAD, width))
    return rerank_merged(view, state.train_features, feats, mi, k,
                         metric, base_d=md)
