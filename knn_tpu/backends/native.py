"""Native C++ runtime backends: ``native`` (serial — the `make main` analogue)
and ``native-mt`` (thread pool — the `make multi-thread` analogue), both over
the single kernel in native/runtime/knn_runtime.cc with reference-exact
semantics. Importing this module raises OSError when the shared library hasn't
been built (``make native``); the registry treats that as "not available".
"""

from __future__ import annotations

import ctypes

import numpy as np

from knn_tpu.backends import register
from knn_tpu.data.dataset import Dataset
from knn_tpu.native import build_if_missing


def _load():
    lib = ctypes.CDLL(str(build_if_missing("libknn_runtime.so")))
    lib.knn_native_predict.argtypes = [
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.knn_native_predict.restype = ctypes.c_int
    return lib


_lib = _load()


def knn_native(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    num_threads: int = 1,
) -> np.ndarray:
    import time

    from knn_tpu import obs
    from knn_tpu.resilience.errors import DataError
    from knn_tpu.resilience.retry import guarded_call

    train_x = np.ascontiguousarray(train_x, np.float32)
    train_y = np.ascontiguousarray(train_y, np.int32)
    test_x = np.ascontiguousarray(test_x, np.float32)
    q = test_x.shape[0]
    out = np.empty(q, np.int32)
    t0 = time.monotonic()
    with obs.span("kernel", backend="native", threads=num_threads):
        # ``native.load`` covers the runtime library failing at call time
        # (unloadable .so, ABI break) — injected or real; OSErrors retry
        # then classify to DeviceError so the ladder degrades to oracle.
        rc = guarded_call(
            "native.load",
            lambda: _call_native(train_x, train_y, test_x, k, num_classes,
                                 num_threads, out),
        )
    if obs.enabled():
        obs.histogram_observe(
            "knn_kernel_ms", (time.monotonic() - t0) * 1e3,
            help="native C++ kernel wall ms", backend="native",
        )
    if rc != 0:
        # Nonzero rc is the kernel's argument validation (bad k/shapes):
        # input data, not device failure.
        raise DataError(f"knn_native_predict failed (rc={rc})")
    return out


def _call_native(train_x, train_y, test_x, k, num_classes, num_threads, out):
    q = test_x.shape[0]
    return _lib.knn_native_predict(
        train_x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        train_y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        train_x.shape[0],
        train_x.shape[1],
        test_x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        q,
        k,
        num_classes,
        num_threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )


@register("native")
def predict_serial(
    train: Dataset, test: Dataset, k: int, metric: str = "euclidean", **_unused
) -> np.ndarray:
    if metric != "euclidean":
        raise ValueError("the native runtime implements euclidean only")
    train.validate_for_knn(k, test)
    return knn_native(
        train.features, train.labels, test.features, k, train.num_classes,
        num_threads=1,
    )


@register("native-mt")
def predict_mt(
    train: Dataset, test: Dataset, k: int, num_threads: int = 0,
    metric: str = "euclidean", **_unused
) -> np.ndarray:
    if metric != "euclidean":
        raise ValueError("the native runtime implements euclidean only")
    train.validate_for_knn(k, test)
    return knn_native(
        train.features, train.labels, test.features, k, train.num_classes,
        num_threads=num_threads,
    )
