"""Single-device TPU backend.

Replaces the pthread backend wholesale: where multi-thread.cpp:170-192 forks T
workers over contiguous query ranges, here ONE jit-compiled batched kernel
covers the whole query set — the MXU/VPU is the "thread pool".

Two compiled paths:

- ``knn_forward``       — full [Q, N] distance matrix + top_k + vote, for
  datasets whose distance matrix fits comfortably in HBM/host memory.
- ``knn_forward_tiled`` — ``lax.scan`` over query tiles × train tiles with an
  index-stable running top-k carry (the blockwise/"long-context" formulation:
  the train set plays the role sequence length plays in attention —
  SURVEY.md §5.7). Static tile shapes keep XLA happy; ragged edges are
  padded + masked to +inf (utils/padding.py).

``precision``: "exact" uses the subtraction-form distance for prediction
parity with the reference; "fast" uses the MXU matmul expansion
(ops/distance.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from knn_tpu.backends import register
from knn_tpu.data.dataset import Dataset
from knn_tpu.ops.distance import _DIST_FNS, resolve_form
from knn_tpu.ops.topk import topk_smallest, merge_topk, merge_topk_labeled
from knn_tpu.ops.vote import vote
from knn_tpu.utils.padding import pad_axis_to_multiple



@functools.partial(
    jax.jit,
    static_argnames=("k", "num_classes", "precision", "approx", "recall_target"),
)
def knn_forward(
    train_x: jnp.ndarray,
    train_y: jnp.ndarray,
    test_x: jnp.ndarray,
    k: int,
    num_classes: int,
    precision: str = "exact",
    approx: bool = False,
    recall_target: float = 0.95,
) -> jnp.ndarray:
    """Full-matrix KNN classify: [N,D] train, [N] labels, [Q,D] queries ->
    [Q] int32 predictions.

    ``approx=True`` swaps ``lax.top_k`` for ``lax.approx_max_k`` — the TPU's
    hardware-accelerated approximate selection, with ``recall_target``
    setting the per-candidate expected recall (higher = slower + closer to
    exact). A capability with no reference analogue: trade exact candidate
    selection for throughput on very large N. Not prediction-parity;
    opt-in only."""
    d = _DIST_FNS[precision](test_x, train_x)
    if approx:
        _, idx = lax.approx_max_k(-d, k, recall_target=recall_target)
        idx = idx.astype(jnp.int32)
    else:
        _, idx = topk_smallest(d, k)
    return vote(train_y[idx], num_classes)


def forward_tiled_core(
    train_x: jnp.ndarray,
    train_y: jnp.ndarray,
    test_x: jnp.ndarray,
    n_train_valid: jnp.ndarray,
    k: int,
    num_classes: int,
    precision: str = "exact",
    query_tile: int = 256,
    train_tile: int = 2048,
) -> jnp.ndarray:
    """Tiled KNN classify with running top-k.

    Both axes must already be padded to tile multiples (train rows beyond
    ``n_train_valid`` are masked to +inf distance). Scans query tiles in an
    outer ``lax.map`` and train tiles in an inner ``lax.scan``; the carry is
    the per-query (dists, global-index) candidate set, merged per tile with an
    index-stable lexicographic top-k (ops/topk.py) so first-seen-wins tie
    semantics survive tiling (SURVEY.md §7 hard part (b))."""
    n_pad = train_x.shape[0]
    q_pad = test_x.shape[0]
    assert n_pad % train_tile == 0 and q_pad % query_tile == 0
    n_tiles = n_pad // train_tile
    kk = min(k, train_tile)
    dist_fn = _DIST_FNS[precision]

    train_tiles_x = train_x.reshape(n_tiles, train_tile, -1)

    def per_query_tile(q_block: jnp.ndarray) -> jnp.ndarray:
        def scan_tile(carry, inp):
            run_d, run_i = carry
            t_idx, t_x = inp
            d = dist_fn(q_block, t_x)  # [query_tile, train_tile]
            col_gidx = t_idx * train_tile + jnp.arange(train_tile)
            d = jnp.where(col_gidx[None, :] < n_train_valid, d, jnp.inf)
            tile_d, tile_i = topk_smallest(d, kk, index_base=t_idx * train_tile)
            run_d, run_i = merge_topk(run_d, run_i, tile_d, tile_i, k)
            return (run_d, run_i), None

        init = (
            jnp.full((query_tile, k), jnp.inf, train_x.dtype),
            jnp.full((query_tile, k), jnp.iinfo(jnp.int32).max, jnp.int32),
        )
        (run_d, run_i), _ = lax.scan(
            scan_tile, init, (jnp.arange(n_tiles), train_tiles_x)
        )
        safe_i = jnp.minimum(run_i, train_y.shape[0] - 1)
        return vote(train_y[safe_i], num_classes)

    q_blocks = test_x.reshape(q_pad // query_tile, query_tile, -1)
    preds = lax.map(per_query_tile, q_blocks)
    return preds.reshape(q_pad)


knn_forward_tiled = jax.jit(
    forward_tiled_core,
    static_argnames=("k", "num_classes", "precision", "query_tile", "train_tile"),
)


def forward_candidates_core(
    train_x: jnp.ndarray,
    train_y: jnp.ndarray,
    test_x: jnp.ndarray,
    n_train_valid: jnp.ndarray,
    k: int,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 2048,
    index_base: int | jnp.ndarray = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Like :func:`forward_tiled_core` but stops before the vote, returning the
    per-query candidate triple ``(dists [Q,k], global_idx [Q,k], labels [Q,k])``
    sorted by (distance, index). This is the building block the distributed
    paths share: per-shard candidates are produced here, merged across the mesh
    (all-gather or ring), and only then voted on — the KNN equivalent of the
    reference's per-rank sub-predictions before MPI_Gatherv (mpi.cpp:175-186),
    except candidates (not final votes) cross the wire so train sharding stays
    exact.

    ``index_base`` positions this shard's rows in the global train order (e.g.
    ``axis_index * shard_rows``); local column indices beyond ``n_train_valid``
    are masked to +inf.
    """
    n_pad = train_x.shape[0]
    q_pad = test_x.shape[0]
    assert n_pad % train_tile == 0 and q_pad % query_tile == 0
    n_tiles = n_pad // train_tile
    kk = min(k, train_tile)
    dist_fn = _DIST_FNS[precision]
    train_tiles_x = train_x.reshape(n_tiles, train_tile, -1)
    train_tiles_y = train_y.reshape(n_tiles, train_tile)

    def per_query_tile(q_block):
        def scan_tile(carry, inp):
            run_d, run_i, run_l = carry
            t_idx, t_x, t_y = inp
            d = dist_fn(q_block, t_x)
            col = t_idx * train_tile + jnp.arange(train_tile)
            d = jnp.where(col[None, :] < n_train_valid, d, jnp.inf)
            tile_d, local_i = lax.top_k(-d, kk)
            tile_d = -tile_d
            tile_l = t_y[local_i]
            tile_i = (local_i + t_idx * train_tile + index_base).astype(jnp.int32)
            merged = merge_topk_labeled(
                run_d, run_i, run_l, tile_d, tile_i, tile_l, k
            )
            return merged, None

        init = (
            jnp.full((query_tile, k), jnp.inf, train_x.dtype),
            jnp.full((query_tile, k), jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.zeros((query_tile, k), train_y.dtype),
        )
        (run_d, run_i, run_l), _ = lax.scan(
            scan_tile, init, (jnp.arange(n_tiles), train_tiles_x, train_tiles_y)
        )
        return run_d, run_i, run_l

    q_blocks = test_x.reshape(q_pad // query_tile, query_tile, -1)
    d, i, l = lax.map(per_query_tile, q_blocks)
    return (
        d.reshape(q_pad, k),
        i.reshape(q_pad, k),
        l.reshape(q_pad, k),
    )


# Jitted candidate retrieval — models.kneighbors dispatches through this
# instead of tracing forward_candidates_core op-by-op eagerly.
knn_forward_candidates = jax.jit(
    forward_candidates_core,
    static_argnames=("k", "precision", "query_tile", "train_tile"),
)


# [Q, N] float32 distance-matrix cells above which the tiled path is used.
_FULL_MATRIX_CELL_LIMIT = 16 * 1024 * 1024


def _record_stripe_lookup(train_x, test_x, k, num_classes, precision,
                          query_batch) -> None:
    """Executable-cache attribution for the stripe dispatch points. The
    kernel's host entry pads internally, so the raw signature is a
    conservative key: a raw-shape change that pads to the same blocks
    counts as a miss here while the kernel actually reuses its executable
    — never the other way around."""
    from knn_tpu import obs

    if not obs.enabled():
        return
    from knn_tpu.obs import devprof

    devprof.record_executable_lookup("tpu", (
        "stripe", train_x.shape, train_x.dtype.str, test_x.shape,
        k, num_classes, precision, query_batch,
    ))

# Sampled-recall guard for approx mode (VERDICT r4 #7). approx_max_k's
# recall target assumes the true top-k land at ~random positions; inputs
# whose near-neighbors sit at regular strides (e.g. a dataset built by
# tiling a base set) are adversarial to its positional binning — measured
# recall collapsed to 0.002 on a 33x-tiled set (r4) while the flag
# silently returned garbage. The guard scores a small query sample's
# approx candidates against exact top-k and falls back to exact selection
# (with a RuntimeWarning) when the measured recall misses the target by
# more than the sampling noise allows.
_GUARD_SAMPLE = 128
_GUARD_MARGIN = 0.05


@functools.partial(
    jax.jit, static_argnames=("k", "recall_target", "precision")
)
def _guard_recall_core(tx, qx, k, recall_target, precision):
    """(exact top-k, approx top-k) index sets for the guard sample, one
    fused dispatch. Distances via the SAME resolved form the guarded
    predict will use (euclidean exact/fast or a metric extension) — the
    guard compares SELECTION behavior on identical values."""
    d = _DIST_FNS[precision](qx, tx)
    _, exact_idx = lax.top_k(-d, k)
    _, approx_idx = lax.approx_max_k(-d, k, recall_target=recall_target)
    return exact_idx.astype(jnp.int32), approx_idx.astype(jnp.int32)


def sampled_approx_recall(
    train_x: np.ndarray, test_x: np.ndarray, k: int, recall_target: float,
    precision: str = "fast",
) -> float:
    """Mean recall@k of ``lax.approx_max_k`` against exact top-k on an
    evenly-strided sample of up to ``_GUARD_SAMPLE`` queries, under the
    resolved distance form ``precision``. Cost: one [sample, N] distance
    block + two selections — noise next to the full predict it guards."""
    q = test_x.shape[0]
    sample = test_x[np.linspace(0, q - 1, min(_GUARD_SAMPLE, q)).astype(int)]
    exact_idx, approx_idx = jax.device_get(_guard_recall_core(
        jnp.asarray(train_x), jnp.asarray(sample), k, recall_target,
        precision,
    ))
    hits = sum(
        len(set(exact_idx[i]) & set(approx_idx[i]))
        for i in range(sample.shape[0])
    )
    return hits / (sample.shape[0] * k)


def _predict_query_batched(
    train_x, train_y, test_x, k, num_classes, *,
    precision, query_tile, train_tile, force_tiled, approx, query_batch,
    recall_target=0.95,
):
    """Stream queries in fixed ``query_batch`` chunks (last chunk padded so
    one compiled shape serves every dispatch). A small in-flight window of
    dispatched chunks keeps the device pipeline full while bounding device
    memory — only ``window`` chunk inputs/outputs are resident at once, so
    the query set can exceed HBM; fetching a result retires its buffers.
    The streaming analogue of how the pthread backend keeps every worker
    busy on its query range."""
    q = test_x.shape[0]
    n = train_x.shape[0]
    train_tile = max(train_tile, k)
    use_full = not force_tiled and query_batch * n <= _FULL_MATRIX_CELL_LIMIT
    if use_full or approx:
        tx, ty = jnp.asarray(train_x), jnp.asarray(train_y)
    else:
        txp, _ = pad_axis_to_multiple(train_x, train_tile, axis=0)
        typ, _ = pad_axis_to_multiple(train_y, train_tile, axis=0)
        tx, ty = jnp.asarray(txp), jnp.asarray(typ)
        nv = jnp.asarray(n, jnp.int32)

    from knn_tpu.resilience.retry import guarded_call
    from knn_tpu.utils.windowed import windowed_dispatch

    def dispatch(s):
        chunk = test_x[s : s + query_batch]
        if chunk.shape[0] < query_batch:  # pad: one shape, one executable
            chunk = np.pad(chunk, ((0, query_batch - chunk.shape[0]), (0, 0)))
        if use_full or approx:
            return guarded_call("backend.compile", lambda: knn_forward(
                tx, ty, jnp.asarray(chunk), k=k, num_classes=num_classes,
                precision=precision, approx=approx, recall_target=recall_target,
            ))
        qp, _ = pad_axis_to_multiple(chunk, query_tile, axis=0)
        return guarded_call("backend.compile", lambda: knn_forward_tiled(
            tx, ty, jnp.asarray(qp), nv,
            k=k, num_classes=num_classes, precision=precision,
            query_tile=query_tile, train_tile=train_tile,
        ))

    def fetch(out, s):
        # Fetching frees our reference to the device buffers; trim tile
        # padding per chunk so concatenation preserves global query order.
        # Execution errors from the async dispatch surface here.
        return guarded_call("device.put", lambda: np.asarray(out)[:query_batch])

    results = windowed_dispatch(range(0, q, query_batch), dispatch, fetch)
    return np.concatenate(results)[:q]


def predict_arrays(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    precision: str = "exact",
    query_tile: int = 256,
    train_tile: int = 2048,
    force_tiled: bool = False,
    approx: bool = False,
    metric: str = "euclidean",
    query_batch: "int | None" = None,
    engine: str = "auto",
    device_cache: "dict | None" = None,
    recall_target: float = 0.95,
) -> np.ndarray:
    """Host-side entry: pads, dispatches to the right compiled path, unpads.
    ``approx`` (full-matrix path only) uses TPU hardware approximate top-k.
    ``metric`` selects the distance (euclidean honors ``precision`` forms —
    ops/distance.py::resolve_form). ``query_batch`` streams the query set
    through the device in fixed-size host chunks — bounded device memory for
    query sets far larger than HBM, with a fixed in-flight dispatch window so
    transfers overlap compute (the chunked path always uses the XLA kernels).
    ``engine``: "auto" (default) hands exact euclidean narrow-feature problems
    on a real TPU to the lane-striped Pallas kernel (~2.5x the XLA
    formulations — docs/KERNELS.md); "stripe" forces that kernel (interpreted
    off-TPU, so it is testable anywhere); "xla" keeps the jit
    full-matrix/tiled paths. ``device_cache`` (normally the train
    ``Dataset.device_cache``) memoizes device-side train layouts on the
    stripe paths so repeat predicts skip the host pad+transpose+upload."""
    if engine not in ("auto", "stripe", "xla"):
        raise ValueError(
            f"unknown engine {engine!r}; choose 'auto', 'stripe', or 'xla'"
        )
    precision = resolve_form(precision, metric)
    q = test_x.shape[0]
    n = train_x.shape[0]
    if q == 0:
        return np.empty(0, np.int32)
    if query_batch is not None and query_batch < 1:
        raise ValueError(f"query_batch must be >= 1, got {query_batch}")
    if approx and engine != "stripe":
        if q <= _GUARD_SAMPLE:
            # The guard sample would BE the whole query set: scoring it
            # computes every query's exact top-k and throws it away, making
            # approx strictly slower than exact. Run exact outright — the
            # flag promises speed at reduced fidelity, and at this size
            # exact is both faster and better.
            approx = False
        else:
            measured = sampled_approx_recall(
                train_x, test_x, k, recall_target, precision,
            )
            if measured < recall_target - _GUARD_MARGIN:
                import warnings

                warnings.warn(
                    f"approx top-k sampled recall {measured:.3f} is below "
                    f"the recall target {recall_target} (structured/strided "
                    "inputs defeat approx_max_k's positional binning); "
                    "falling back to exact selection",
                    RuntimeWarning,
                    stacklevel=2,
                )
                approx = False
    if engine == "stripe":
        # Forced stripe: reject options the kernel cannot honor rather than
        # silently computing something else; its host entry chunks queries
        # itself (query_batch caps the chunk size).
        if metric != "euclidean":
            raise ValueError("the stripe engine implements euclidean only")
        if approx or force_tiled:
            raise ValueError("engine='stripe' is incompatible with approx/force_tiled")
        from knn_tpu.ops.pallas_knn import stripe_classify_arrays
        from knn_tpu.resilience.retry import guarded_call

        _record_stripe_lookup(train_x, test_x, k, num_classes, precision,
                              query_batch)
        # The stripe host entry transfers + compiles + fetches internally:
        # nested guards give both fault points (and both failure classes)
        # coverage over the one call.
        return guarded_call("device.put", lambda: guarded_call(
            "backend.compile", lambda: stripe_classify_arrays(
                train_x, train_y, test_x, k, num_classes, precision=precision,
                max_rows=query_batch, cache=device_cache,
            )))
    # Shared auto-engine rule (ops/pallas_knn.py::stripe_auto_eligible):
    # exact euclidean, narrow features, small k, real TPU. Checked BEFORE the
    # query_batch streaming path — the stripe host entry chunks queries
    # itself (max_rows), so batched callers keep the fast kernel and the
    # device cache instead of silently downgrading to the XLA scan.
    from knn_tpu.ops.pallas_knn import stripe_auto_eligible

    if (
        engine == "auto"
        and not approx
        and not force_tiled
        and metric == "euclidean"
        and stripe_auto_eligible(precision, train_x.shape[1], k)
    ):
        from knn_tpu.ops.pallas_knn import stripe_classify_arrays
        from knn_tpu.resilience.retry import guarded_call

        _record_stripe_lookup(train_x, test_x, k, num_classes, precision,
                              query_batch)
        return guarded_call("device.put", lambda: guarded_call(
            "backend.compile", lambda: stripe_classify_arrays(
                train_x, train_y, test_x, k, num_classes, precision=precision,
                max_rows=query_batch, cache=device_cache,
            )))
    if query_batch is not None and q > query_batch:
        return _predict_query_batched(
            train_x, train_y, test_x, k, num_classes,
            precision=precision, query_tile=query_tile, train_tile=train_tile,
            force_tiled=force_tiled, approx=approx, query_batch=query_batch,
            recall_target=recall_target,
        )
    from knn_tpu import obs
    from knn_tpu.obs.instrument import record_transfer
    from knn_tpu.resilience.retry import guarded_call

    if approx or (not force_tiled and q * n <= _FULL_MATRIX_CELL_LIMIT):
        if obs.enabled():
            from knn_tpu.obs import devprof

            # Host-side executable-cache attribution: first dispatch of
            # this signature since enable/reset compiles (miss).
            devprof.record_executable_lookup("tpu", (
                "xla-full", train_x.shape, train_x.dtype.str, test_x.shape,
                k, num_classes, precision, approx, recall_target,
            ))
        with obs.span("prepare", engine="xla-full"):
            txj, tyj, qxj = guarded_call("device.put", lambda: (
                jnp.asarray(train_x), jnp.asarray(train_y),
                jnp.asarray(test_x),
            ))
        if obs.enabled():
            record_transfer(train_x.nbytes + train_y.nbytes + test_x.nbytes)
        with obs.span("dispatch", engine="xla-full", approx=approx):
            out = guarded_call("backend.compile", lambda: knn_forward(
                txj, tyj, qxj,
                k=k, num_classes=num_classes, precision=precision,
                approx=approx, recall_target=recall_target,
            ))
        with obs.span("fetch", engine="xla-full"):
            # Async dispatch surfaces execution errors (incl. OOM) at the
            # blocking fetch: classify them as device failures.
            return guarded_call("device.put", lambda: np.asarray(out))

    train_tile = max(train_tile, k)  # per-tile top-k needs k <= tile width
    if obs.enabled():
        from knn_tpu.obs import devprof

        # Key on the PADDED shapes — those are the executable's operand
        # shapes, so two raw sizes padding to one quantum share a hit.
        devprof.record_executable_lookup("tpu", (
            "xla-tiled", -(-n // train_tile) * train_tile,
            train_x.shape[1], train_x.dtype.str,
            -(-q // query_tile) * query_tile,
            k, num_classes, precision, query_tile, train_tile,
        ))
    with obs.span("prepare", engine="xla-tiled"):
        tx, _ = pad_axis_to_multiple(train_x, train_tile, axis=0)
        ty, _ = pad_axis_to_multiple(train_y, train_tile, axis=0)
        qx, _ = pad_axis_to_multiple(test_x, query_tile, axis=0)
        txj, tyj, qxj = guarded_call("device.put", lambda: (
            jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(qx),
        ))
    if obs.enabled():
        record_transfer(tx.nbytes + ty.nbytes + qx.nbytes)
    with obs.span("dispatch", engine="xla-tiled"):
        out = guarded_call("backend.compile", lambda: knn_forward_tiled(
            txj, tyj, qxj,
            jnp.asarray(n, jnp.int32),
            k=k, num_classes=num_classes, precision=precision,
            query_tile=query_tile, train_tile=train_tile,
        ))
    with obs.span("fetch", engine="xla-tiled"):
        return guarded_call("device.put", lambda: np.asarray(out)[:q])


@register("tpu")
def predict(
    train: Dataset,
    test: Dataset,
    k: int,
    precision: str = "exact",
    query_tile: int = 256,
    train_tile: int = 2048,
    force_tiled: bool = False,
    approx: bool = False,
    metric: str = "euclidean",
    query_batch: "int | None" = None,
    engine: str = "auto",
    recall_target: float = 0.95,
    **_unused,
) -> np.ndarray:
    train.validate_for_knn(k, test)
    if not (0.0 < recall_target <= 1.0):
        raise ValueError(f"recall_target must be in (0, 1], got {recall_target}")
    return predict_arrays(
        train.features, train.labels, test.features, k, train.num_classes,
        precision=precision, query_tile=query_tile, train_tile=train_tile,
        force_tiled=force_tiled, approx=approx, metric=metric,
        query_batch=query_batch, engine=engine,
        device_cache=train.device_cache, recall_target=recall_target,
    )
