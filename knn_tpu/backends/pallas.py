"""Pallas-kernel backend (``tpu-pallas``).

The hand-tiled VMEM kernel path for wide-feature / large-N configurations
(BASELINE.json config 5). Same strategy signature as every other backend;
``precision`` selects the in-kernel distance form — "exact" (default) for
reference-parity ties, "fast" for the MXU matmul on wide features
(ops/pallas_knn.py docstring).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from knn_tpu.backends import register
from knn_tpu.data.dataset import Dataset
from knn_tpu.ops.pallas_knn import predict_pallas


@register("tpu-pallas")
def predict(
    train: Dataset,
    test: Dataset,
    k: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    interpret: Optional[bool] = None,
    precision: str = "auto",
    engine: str = "auto",
    metric: str = "euclidean",
    **_unused,
) -> np.ndarray:
    if metric != "euclidean":
        raise ValueError("the pallas kernels implement euclidean only")
    train.validate_for_knn(k, test)
    from knn_tpu import obs
    from knn_tpu.obs.instrument import record_transfer

    if obs.enabled():
        from knn_tpu.obs import devprof

        record_transfer(
            train.features.nbytes + train.labels.nbytes
            + test.features.nbytes, backend="tpu-pallas",
        )
        # First dispatch of this signature compiles the kernel (miss);
        # repeats ride Mosaic's executable cache (hit).
        devprof.record_executable_lookup("tpu-pallas", (
            train.features.shape, train.features.dtype.str,
            test.features.shape, k, train.num_classes,
            block_q, block_n, precision, engine,
        ))
    from knn_tpu.resilience.retry import guarded_call

    # precision="auto" resolves inside predict_pallas (exact for narrow
    # features, fast for wide — ops/pallas_knn._resolve_stripe_precision).
    # Nested guards: the kernel entry transfers AND compiles internally, so
    # both fault points (and both failure classes) cover the one call.
    with obs.span("kernel", backend="tpu-pallas", engine=engine):
        return guarded_call("device.put", lambda: guarded_call(
            "backend.compile", lambda: predict_pallas(
                train.features, train.labels, test.features, k,
                train.num_classes,
                block_q=block_q, block_n=block_n, interpret=interpret,
                precision=precision, engine=engine,
            )))
