"""Execution backends: one algorithm, pluggable execution strategies.

The reference duplicates the whole algorithm per backend binary
(main.cpp / multi-thread.cpp / mpi.cpp, ~70% copy-paste — SURVEY.md §0);
here each backend is a thin strategy over the shared ops layer. Registry keys
follow the reference's Makefile-target convention (Makefile:1-9):

- ``oracle``       — NumPy, bit-exact reference kernel semantics (the parity
                     oracle; replaces serial main.cpp as the golden path).
- ``native``       — C++ serial kernel (knn_tpu/native/runtime), the true
                     `make main` analogue.
- ``native-mt``    — C++ pthread-pool kernel, the `make multi-thread` analogue.
- ``tpu``          — single-device jit (tiled); replaces all pthread threads
                     with one batched kernel.
- ``tpu-sharded``  — shard_map over the test-query axis (the MPI analogue).
- ``tpu-train-sharded`` — train rows sharded + all-gather top-k merge.
- ``tpu-ring``     — ring schedule over train shards (ring-attention shape).
- ``tpu-pallas``   — hand-tiled Pallas kernel, VMEM-resident running top-k
                     (the wide-feature / BASELINE config-5 path).

Because every backend implements the same reference-exact contract, the
registry doubles as a degradation ladder: persistent typed failures walk
``tpu-sharded → tpu → tpu-pallas → native → oracle`` with bit-identical
predictions at every rung (``knn_tpu.resilience.degrade`` — the CLI's
default execution path; ``--no-fallback`` opts out). See
docs/RESILIENCE.md.
"""

from __future__ import annotations

from typing import Callable, Dict

import numpy as np

_REGISTRY: Dict[str, Callable] = {}


def register(name: str):
    """Register a backend predict fn, wrapped with the observability layer
    (a ``predict`` span + per-backend call/query/wall metrics — no-ops
    while ``knn_tpu.obs`` is disabled). The module-level fn stays unwrapped
    so direct imports (tests, scripts) see the raw strategy."""

    def deco(fn):
        from knn_tpu.obs.instrument import observed_backend

        _REGISTRY[name] = observed_backend(name, fn)
        return fn

    return deco


def get_backend(name: str) -> Callable:
    _ensure_loaded()
    if name not in _REGISTRY:
        raise KeyError(f"unknown backend '{name}'; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def available_backends():
    _ensure_loaded()
    return sorted(_REGISTRY)


_LOADED = False


def _ensure_loaded():
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    # Import for registration side effects.
    from knn_tpu.backends import oracle as _oracle  # noqa: F401
    from knn_tpu.backends import tpu as _tpu  # noqa: F401

    try:
        from knn_tpu.backends import pallas as _pallas  # noqa: F401
    except ImportError:
        pass  # pallas unavailable on this jax build

    try:
        from knn_tpu.backends import native as _native  # noqa: F401
    except (ImportError, OSError):
        pass  # native runtime not built
    try:
        from knn_tpu.parallel import query_sharded as _qs  # noqa: F401
        from knn_tpu.parallel import train_sharded as _ts  # noqa: F401
        from knn_tpu.parallel import ring as _ring  # noqa: F401
    except ImportError:
        pass
