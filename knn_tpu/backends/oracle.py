"""NumPy oracle backend — the bit-exact reimplementation of the reference's
serial KNN kernel (main.cpp:25-85), used as the golden-prediction source for
every other backend.

Contract reproduced (SURVEY.md §3.5):
1. squared Euclidean over feature columns only (class excluded);
2. among equal distances the lowest train index wins (the reference's strict
   ``<`` insertion keeps the first-scanned candidate, main.cpp:46-61) —
   realized here with a stable lexicographic (distance, index) sort;
3. vote ties break to the lowest class id (strict ``>`` argmax from -1,
   main.cpp:69-76) — realized with np.argmax's first-max rule;
4. ``num_classes`` comes from the *train* set (main.cpp:27).
"""

from __future__ import annotations

import numpy as np

from knn_tpu.backends import register
from knn_tpu.data.dataset import Dataset


def _metric_dists(test_block, train_x, metric: str) -> np.ndarray:
    """[chunk, D] queries x [N, D] train -> [chunk, N] float32 distances per
    metric, with formulas matching ops/distance.py so oracle/TPU parity
    holds. The [chunk, N, D] diff tensor is materialized only for the metrics
    that read it."""
    if metric in ("euclidean", "manhattan", "chebyshev"):
        diff = test_block[:, None, :] - train_x[None, :, :]
    if metric == "euclidean":
        return np.einsum("qnd,qnd->qn", diff, diff, dtype=np.float32)
    if metric == "manhattan":
        return np.abs(diff).sum(axis=-1, dtype=np.float32)
    if metric == "chebyshev":
        if diff.shape[-1] == 0:
            return np.zeros(diff.shape[:2], np.float32)
        return np.abs(diff).max(axis=-1).astype(np.float32)
    if metric == "cosine":
        qn = np.sqrt((test_block * test_block).sum(-1, dtype=np.float32))[:, None]
        tn = np.sqrt((train_x * train_x).sum(-1, dtype=np.float32))[None, :]
        cross = test_block @ train_x.T
        denom = qn * tn
        with np.errstate(invalid="ignore"):
            sim = np.where(denom > 0, cross / np.where(denom > 0, denom, 1.0), 0.0)
        d = (1.0 - sim).astype(np.float32)
        # NaN features poison cross/denom but `denom > 0` is False for NaN,
        # which would leave those rows at d=1.0; enforce NaN -> +inf.
        d[np.isnan(cross) | np.isnan(denom)] = np.inf
        return d
    raise ValueError(f"unknown metric {metric!r}")


def oracle_kneighbors(
    train_x: np.ndarray,
    test_x: np.ndarray,
    k: int,
    metric: str = "euclidean",
):
    """Host-only candidate retrieval: ``(dists [Q,k], indices [Q,k])``
    under the framework's (distance, train-index) tie order. This is THE
    reference retrieval contract realized over a full scan — selection
    goes through :func:`~knn_tpu.models.ordering.lexicographic_topk`, the
    one shared tie-order helper every host rung (including the IVF
    candidate scorer) selects with. :func:`knn_oracle` votes from it, and
    it is the terminal rung of the SERVING degradation ladder
    (``knn_tpu/serve/batcher.py``), which cannot fail for device reasons
    because no device is involved (predictions voted from these
    candidates are bit-identical to every other rung — SURVEY.md §3.5).
    """
    from knn_tpu import obs
    from knn_tpu.models.ordering import lexicographic_topk

    train_x = np.asarray(train_x, np.float32)
    test_x = np.asarray(test_x, np.float32)
    n, q = train_x.shape[0], test_x.shape[0]
    k = min(k, n)
    dists_out = np.empty((q, k), np.float32)
    idx_out = np.empty((q, k), np.int64)
    arange_n = np.arange(n)
    # Process queries in chunks so the [chunk, N] distance block stays
    # cache-friendly.
    d_feat = max(train_x.shape[1], 1)
    chunk = max(1, min(q, int(4e7) // max(n * d_feat, 1)))
    for s in range(0, q, chunk):
        e = min(q, s + chunk)
        with obs.span("distance", metric=metric, backend="oracle"):
            dists = _metric_dists(test_x[s:e], train_x, metric)
            # Framework-wide policy: NaN distances count as +inf (the
            # reference is UB here — SURVEY.md §3.5.5); +inf candidates
            # are admitted in (distance, index) order.
            np.nan_to_num(dists, copy=False, nan=np.inf)
        with obs.span("top-k", backend="oracle"):
            dists_out[s:e], idx_out[s:e] = lexicographic_topk(
                dists, arange_n, k)
    return dists_out, idx_out


def knn_oracle(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    metric: str = "euclidean",
) -> np.ndarray:
    """Pure-array oracle: float32 [N,D] train, int32 [N] labels, float32 [Q,D]
    queries -> int32 [Q] predictions — :func:`oracle_kneighbors`'s
    candidates plus the reference vote (ties to the lowest class id)."""
    from knn_tpu import obs

    train_y = np.asarray(train_y, np.int32)
    _, idx = oracle_kneighbors(train_x, test_x, k, metric)
    q = idx.shape[0]
    preds = np.empty(q, np.int32)
    with obs.span("vote", backend="oracle"):
        for row in range(q):
            counts = np.bincount(train_y[idx[row]], minlength=num_classes)
            preds[row] = np.argmax(counts)
    return preds


@register("oracle")
def predict(
    train: Dataset, test: Dataset, k: int, metric: str = "euclidean", **_unused
) -> np.ndarray:
    train.validate_for_knn(k, test)
    return knn_oracle(
        train.features, train.labels, test.features, k, train.num_classes,
        metric=metric,
    )
