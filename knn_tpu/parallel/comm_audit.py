"""Collective-traffic audit for the distributed paths (VERDICT r4 #8).

The multichip dryrun proves the sharded paths PREDICT correctly; this
module proves they COMMUNICATE what the design says they do, in an
environment that cannot run pods. The jitted shard_map fns are lowered
(not executed) and the collective ops are parsed out of the StableHLO
with their per-execution payload shapes; the dryrun asserts the bytes
match the analytic model:

- train-sharded: three ``all_gather`` ops (distances f32, global indices
  i32, labels i32), each ``[q_local, k*P]`` — k·P·(4+4+4) bytes per local
  query, the Gatherv analogue of mpi.cpp:186.
- ring: ``collective_permute`` of the resident train shard (+ its labels)
  once per scan step, P-1 steps per call — shard_bytes·(P-1) total, the
  rotation mpi.cpp's scatter/gather pair never needed because MPI
  replicates the train set (mpi.cpp:136-139).

Parsing the UNOPTIMIZED lowering is deliberate: it is the communication
*spec* of the program (XLA's combiner passes may later fuse the three
all-gathers into one, but the bytes on the wire are unchanged).
"""

from __future__ import annotations

import re
from typing import List, Tuple

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "i64": 8, "i32": 4, "i16": 2, "i8": 1, "i1": 1,
    "ui64": 8, "ui32": 4, "ui16": 2, "ui8": 1,
}

# Both StableHLO spellings: the quoted generic form
# ('"stablehlo.all_gather"(...) ... -> tensor<...>') and the unquoted
# pretty-printed one a future jax's lower().as_text() may emit (ADVICE r5
# #5) — a parser matching only one would silently return [] on the other
# and fail the audit with a misleading shape-mismatch message.
_COLLECTIVE_RE = re.compile(
    r'"?stablehlo\.(all_gather|collective_permute|all_reduce|reduce_scatter'
    r'|all_to_all)"?'
    r'.*?->\s*tensor<((?:\d+x)*)([a-z]+\d+)>',
)


class CollectiveParseError(AssertionError):
    """Zero collectives parsed from a lowering that is KNOWN to
    communicate: the lowering text format changed (or the regex rotted) —
    a parser defect, distinct from a genuine byte-model mismatch."""


def collective_ops(lowered_text: str) -> List[Tuple[str, Tuple[int, ...], str, int]]:
    """Parse collectives from lowered StableHLO text: a list of
    ``(kind, result_shape, dtype, result_bytes)`` in program order. The
    result shape is the PER-DEVICE shape inside the manual computation
    (shard_map bodies are per-device programs), so ``result_bytes`` is what
    one device holds after the op — the all-gather wire cost per device is
    ``result_bytes * (P-1)/P`` of that (each device already owns 1/P)."""
    out = []
    for m in _COLLECTIVE_RE.finditer(lowered_text):
        kind, dims, dtype = m.groups()
        shape = tuple(int(x) for x in dims.split("x") if x)
        n = 1
        for s in shape:
            n *= s
        out.append((kind, shape, dtype, n * _DTYPE_BYTES[dtype]))
    return out


def summarize(ops) -> str:
    return ", ".join(
        f"{kind}[{'x'.join(map(str, shape))}]{dtype}={b}B"
        for kind, shape, dtype, b in ops
    )


# --- The analytic byte model -------------------------------------------
#
# ONE definition serving two consumers: the static StableHLO audits below
# assert the lowering against it, and the live collective-traffic counters
# (knn_tpu/obs/instrument.py::record_collective, called from the sharded
# predict entries) record the same numbers at runtime — so
# ``knn_collective_bytes_total`` can be cross-checked for EXACT equality
# with the spec (tests/test_obs.py).


def model_train_sharded_bytes(q_local: int, k: int, n_t: int) -> int:
    """Post-gather candidate buffer bytes per device per call: three
    all-gathers (distances f32, global indices i32, labels i32), each
    ``[q_local, k * n_t]`` — the Gatherv analogue of mpi.cpp:186."""
    return q_local * k * n_t * (4 + 4 + 4)


def model_ring_bytes(shard_bytes: int, label_bytes: int, n_dev: int) -> int:
    """Bytes moved per device per ring call: the resident train shard + its
    labels permute once per scan step, P-1 steps."""
    return (shard_bytes + label_bytes) * (n_dev - 1)


def model_query_sharded_bytes(q_pad: int, d: int,
                              feat_bytes: int = 4,
                              pred_bytes: int = 4) -> int:
    """Data-movement spec of the query-sharded path: no collective runs in
    the shard_map body (train is replicated up front, exactly as every MPI
    rank loads both files — mpi.cpp:136-139); what crosses the wire per
    call is the scatter of the padded query block in (the in_spec ==
    MPI_Scatter) and the gather of the predictions out (the out_spec ==
    MPI_Gatherv)."""
    return q_pad * d * feat_bytes + q_pad * pred_bytes


def audit_train_sharded(lowered_text: str, q_local: int, k: int, n_t: int):
    """Assert the train-sharded lowering's collectives match the model:
    exactly three all-gathers (d, i, l) of ``[q_local, k*n_t]`` 4-byte
    elements. Returns ``(measured_bytes, expected_bytes)`` per device per
    step (post-gather buffer size, all three ops)."""
    ops = collective_ops(lowered_text)
    if not ops:
        raise CollectiveParseError(
            "no collectives parsed from the train-sharded lowering — "
            "lowering format changed? (_COLLECTIVE_RE matched nothing in a "
            "program known to all-gather)"
        )
    gathers = [o for o in ops if o[0] == "all_gather"]
    others = [o for o in ops if o[0] != "all_gather"]
    if others:
        raise AssertionError(
            f"train-sharded lowering has unexpected collectives: "
            f"{summarize(others)}"
        )
    if len(gathers) != 3:
        raise AssertionError(
            f"train-sharded lowering should all-gather exactly (d, i, l); "
            f"got {summarize(gathers)}"
        )
    for kind, shape, dtype, b in gathers:
        if shape != (q_local, k * n_t):
            raise AssertionError(
                f"all-gather shape {shape} != model ({q_local}, {k * n_t})"
            )
    measured = sum(o[3] for o in gathers)
    expected = model_train_sharded_bytes(q_local, k, n_t)
    if measured != expected:
        raise AssertionError(f"gathered bytes {measured} != model {expected}")
    return measured, expected


def audit_ring(lowered_text: str, shard_bytes: int, label_bytes: int, n_dev: int):
    """Assert the ring lowering's collectives match the model: the scan body
    permutes the resident train shard and its labels once per step, and
    nothing else crosses the wire. Returns ``(measured_total, expected_total)``
    bytes moved per device per call (per-step payload x (P-1) steps)."""
    ops = collective_ops(lowered_text)
    if not ops:
        raise CollectiveParseError(
            "no collectives parsed from the ring lowering — lowering "
            "format changed? (_COLLECTIVE_RE matched nothing in a program "
            "known to collective-permute)"
        )
    permutes = [o for o in ops if o[0] == "collective_permute"]
    others = [o for o in ops if o[0] != "collective_permute"]
    if others:
        raise AssertionError(
            f"ring lowering has unexpected collectives: {summarize(others)}"
        )
    if len(permutes) != 2:
        raise AssertionError(
            f"ring should permute exactly (train shard, labels); got "
            f"{summarize(permutes)}"
        )
    per_step = sum(o[3] for o in permutes)
    expected_step = shard_bytes + label_bytes
    if per_step != expected_step:
        raise AssertionError(
            f"ring per-step payload {per_step}B != model {expected_step}B "
            f"({summarize(permutes)})"
        )
    return per_step * (n_dev - 1), model_ring_bytes(
        shard_bytes, label_bytes, n_dev
    )
