"""Train-set sharding with all-gather top-k merge — the capability the
reference lacks entirely (SURVEY.md §2.3: the TP/"model-parallel" analogue for
KNN; BASELINE.json config 4).

Train rows are sharded across the mesh's ``t`` axis (optionally combined with
query sharding on a ``q`` axis → 2-D mesh). Each device computes its shard's
top-k candidates *with global train indices and labels attached*, the k·P
candidates are all-gathered over ICI, merged with a lexicographic
(distance, global-index) sort — preserving the reference's first-seen-wins tie
rule regardless of shard boundaries — and only then voted on.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from knn_tpu.backends import register
from knn_tpu.backends.tpu import forward_candidates_core
from knn_tpu.data.dataset import Dataset
from knn_tpu.ops.vote import vote
from knn_tpu.parallel.mesh import make_mesh, make_mesh_2d, default_mesh_shape
from knn_tpu.utils.padding import pad_axis_to_multiple


def merge_candidates_vote(
    d: jnp.ndarray, i: jnp.ndarray, l: jnp.ndarray, k: int, num_classes: int
) -> jnp.ndarray:
    """[Q, C>=k] candidate triples -> [Q] predictions, tie-stable."""
    s_d, s_i, s_l = lax.sort((d, i, l), dimension=-1, num_keys=2)
    return vote(s_l[..., :k], num_classes)


def build_train_sharded_fn(
    mesh: Mesh,
    k: int,
    num_classes: int,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 1024,
    q_axis: Optional[str] = "q",
    t_axis: str = "t",
):
    """fn(train_x, train_y, test_x, n_train_valid) -> preds.

    train padded to ``n_t * train_tile`` multiples and sharded over ``t_axis``;
    test padded to ``n_q * query_tile`` and sharded over ``q_axis`` (or
    replicated when the mesh has no query axis).
    """
    n_t = mesh.shape[t_axis]
    q_spec = P(q_axis) if q_axis else P()

    def per_shard(train_x, train_y, test_block, n_valid):
        # Global position of this shard's rows: shards are laid out in axis
        # order, so axis_index * rows_per_shard is the reference scan order.
        shard_rows = train_x.shape[0]
        t_idx = lax.axis_index(t_axis)
        base = (t_idx * shard_rows).astype(jnp.int32)
        local_valid = jnp.clip(n_valid - t_idx * shard_rows, 0, shard_rows)
        d, gi, lbl = forward_candidates_core(
            train_x, train_y, test_block, local_valid,
            k=k, precision=precision,
            query_tile=query_tile, train_tile=min(train_tile, shard_rows),
            index_base=base,
        )
        # k candidates/shard -> k*n_t per query, concatenated in shard order
        # over ICI. tiled=True keeps the candidate axis flat.
        all_d = lax.all_gather(d, t_axis, axis=1, tiled=True)
        all_i = lax.all_gather(gi, t_axis, axis=1, tiled=True)
        all_l = lax.all_gather(lbl, t_axis, axis=1, tiled=True)
        return merge_candidates_vote(all_d, all_i, all_l, k, num_classes)

    sharded = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(t_axis), P(t_axis), q_spec, P()),
        out_specs=q_spec,
        check_vma=False,
    )
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def _cached_fn(n_q, n_t, k, num_classes, precision, query_tile, train_tile):
    # Cache the jitted shard_map closure so repeat predicts (and --warmup)
    # reuse XLA's compile cache instead of retracing a fresh closure.
    mesh = make_mesh_2d(n_q, n_t)
    return build_train_sharded_fn(
        mesh, k, num_classes, precision, query_tile, train_tile
    )


def predict_train_sharded(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    num_devices: Optional[int] = None,
    mesh_shape: Optional[Tuple[int, int]] = None,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 1024,
) -> np.ndarray:
    """2-D sharded KNN: queries over 'q', train rows over 't'."""
    n = num_devices or len(jax.devices())
    if mesh_shape is None:
        mesh_shape = default_mesh_shape(n)
    n_q, n_t = mesh_shape

    q = test_x.shape[0]
    shard_quota = -(-train_x.shape[0] // n_t)  # ceil rows per shard
    train_tile = max(min(train_tile, shard_quota), k)
    shard_rows = -(-shard_quota // train_tile) * train_tile
    tx, _ = pad_axis_to_multiple(train_x, shard_rows * n_t, axis=0)
    ty, _ = pad_axis_to_multiple(train_y, shard_rows * n_t, axis=0)
    qx, _ = pad_axis_to_multiple(test_x, n_q * query_tile, axis=0)
    fn = _cached_fn(n_q, n_t, k, num_classes, precision, query_tile, train_tile)
    out = fn(
        jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(qx),
        jnp.asarray(train_x.shape[0], jnp.int32),
    )
    return np.asarray(out)[:q]


@register("tpu-train-sharded")
def predict(
    train: Dataset,
    test: Dataset,
    k: int,
    num_devices: Optional[int] = None,
    mesh_shape: Optional[Tuple[int, int]] = None,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 1024,
    metric: str = "euclidean",
    **_unused,
) -> np.ndarray:
    from knn_tpu.ops.distance import resolve_form

    precision = resolve_form(precision, metric)
    train.validate_for_knn(k, test)
    return predict_train_sharded(
        train.features, train.labels, test.features, k, train.num_classes,
        num_devices=num_devices, mesh_shape=mesh_shape, precision=precision,
        query_tile=query_tile, train_tile=train_tile,
    )
