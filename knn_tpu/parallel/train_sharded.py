"""Train-set sharding with all-gather top-k merge — the capability the
reference lacks entirely (SURVEY.md §2.3: the TP/"model-parallel" analogue for
KNN; BASELINE.json config 4).

Train rows are sharded across the mesh's ``t`` axis (optionally combined with
query sharding on a ``q`` axis → 2-D mesh). Each device computes its shard's
top-k candidates *with global train indices and labels attached*, the k·P
candidates are all-gathered over ICI, merged with a lexicographic
(distance, global-index) sort — preserving the reference's first-seen-wins tie
rule regardless of shard boundaries — and only then voted on.
"""

from __future__ import annotations

import functools
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from knn_tpu import obs
from knn_tpu.backends import register
from knn_tpu.backends.tpu import forward_candidates_core
from knn_tpu.data.dataset import Dataset
from knn_tpu.obs.instrument import record_collective, record_shard_dispatch
from knn_tpu.ops.vote import vote
from knn_tpu.parallel.mesh import make_mesh, make_mesh_2d, default_mesh_shape, shard_map_compat
from knn_tpu.resilience.retry import guarded_call
from knn_tpu.utils.padding import pad_axis_to_multiple


def resolve_shard_engine(engine: str, precision: str, d: int, k: int) -> str:
    """Shared engine-selection rule for the distributed paths: ``auto`` routes
    stripe-eligible problems (ops/pallas_knn.py::stripe_auto_eligible — the
    rule every dispatch point shares) to the lane-striped Pallas kernel, and
    to the XLA tiled scan otherwise."""
    if engine not in ("auto", "stripe", "xla"):
        raise ValueError(
            f"unknown engine {engine!r}; choose 'auto', 'stripe', or 'xla'"
        )
    if engine != "auto":
        return engine
    from knn_tpu.ops.pallas_knn import stripe_auto_eligible

    return "stripe" if stripe_auto_eligible(precision, d, k) else "xla"


def xla_shard_layout(
    n: int, n_t: int, train_tile: int, k: int
) -> Tuple[int, int]:
    """THE padded-shape rule for the XLA train-sharded path: clamp the tile
    to the per-shard row quota (floored at k — the per-tile top-k needs
    k <= tile width), then round the quota up to a tile multiple. One
    definition shared by :func:`predict_train_sharded` and the dryrun's
    collective-bytes audit, so the audited lowering is the executed one."""
    shard_quota = -(-n // n_t)
    train_tile = max(min(train_tile, shard_quota), k)
    shard_rows = -(-shard_quota // train_tile) * train_tile
    return train_tile, shard_rows


def merge_candidates_vote(
    d: jnp.ndarray, i: jnp.ndarray, l: jnp.ndarray, k: int, num_classes: int
) -> jnp.ndarray:
    """[Q, C>=k] candidate triples -> [Q] predictions, tie-stable.

    The cross-shard merge selects through
    ``models/ordering.lexicographic_topk_jax`` — THE (distance, index)
    contract's device realization — with the gathered labels riding the
    sort as payload, so shard boundaries can never reorder equal
    distances differently from the single-device rungs (pinned on
    adversarial tie plateaus by tests/test_shard.py)."""
    from knn_tpu.models.ordering import lexicographic_topk_jax

    _s_d, _s_i, s_l = lexicographic_topk_jax(d, i, k, l)
    return vote(s_l, num_classes)


def build_train_sharded_fn(
    mesh: Mesh,
    k: int,
    num_classes: int,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 1024,
    q_axis: Optional[str] = "q",
    t_axis: str = "t",
):
    """fn(train_x, train_y, test_x, n_train_valid) -> preds.

    train padded to ``n_t * train_tile`` multiples and sharded over ``t_axis``;
    test padded to ``n_q * query_tile`` and sharded over ``q_axis`` (or
    replicated when the mesh has no query axis).
    """
    n_t = mesh.shape[t_axis]
    q_spec = P(q_axis) if q_axis else P()

    def per_shard(train_x, train_y, test_block, n_valid):
        # Global position of this shard's rows: shards are laid out in axis
        # order, so axis_index * rows_per_shard is the reference scan order.
        shard_rows = train_x.shape[0]
        t_idx = lax.axis_index(t_axis)
        base = (t_idx * shard_rows).astype(jnp.int32)
        local_valid = jnp.clip(n_valid - t_idx * shard_rows, 0, shard_rows)
        d, gi, lbl = forward_candidates_core(
            train_x, train_y, test_block, local_valid,
            k=k, precision=precision,
            query_tile=query_tile, train_tile=min(train_tile, shard_rows),
            index_base=base,
        )
        # k candidates/shard -> k*n_t per query, concatenated in shard order
        # over ICI. tiled=True keeps the candidate axis flat.
        all_d = lax.all_gather(d, t_axis, axis=1, tiled=True)
        all_i = lax.all_gather(gi, t_axis, axis=1, tiled=True)
        all_l = lax.all_gather(lbl, t_axis, axis=1, tiled=True)
        return merge_candidates_vote(all_d, all_i, all_l, k, num_classes)

    sharded = shard_map_compat(
        per_shard,
        mesh=mesh,
        in_specs=(P(t_axis), P(t_axis), q_spec, P()),
        out_specs=q_spec,
        check_vma=False,
    )
    return jax.jit(sharded)


def build_train_sharded_stripe_fn(
    mesh: Mesh,
    k: int,
    num_classes: int,
    precision: str,
    block_q: int,
    block_n: int,
    d_true: int,
    interpret: bool,
    q_axis: Optional[str] = "q",
    t_axis: str = "t",
    assume_finite: bool = False,
):
    """Stripe-engine variant of :func:`build_train_sharded_fn`: per-shard
    candidates come from the lane-striped Pallas kernel (the single-chip
    headline kernel) instead of the XLA tiled scan, so a pod runs at
    headline-kernel throughput per chip (VERDICT r1 #1).

    fn(train_xT, train_y, test_x, n_train_valid) -> preds, where ``train_xT``
    is the TRANSPOSED padded train matrix ``[D_pad, n_t * shard_rows]``
    sharded over its *column* axis (shard_rows % block_n == 0) and ``test_x``
    is ``[n_q * q_shard, D_pad]`` with q_shard % block_q == 0.
    """
    from knn_tpu.ops.pallas_knn import stripe_candidates_core

    q_spec = P(q_axis) if q_axis else P()

    def per_shard(train_xT, train_y, test_block, n_valid):
        shard_rows = train_xT.shape[1]
        t_idx = lax.axis_index(t_axis)
        base = (t_idx * shard_rows).astype(jnp.int32)
        local_valid = jnp.clip(n_valid - base, 0, shard_rows)
        d, gi, lbl = stripe_candidates_core(
            train_xT, train_y, test_block, local_valid, k,
            block_q=block_q, block_n=block_n, d_true=d_true,
            precision=precision, interpret=interpret, index_base=base,
            assume_finite=assume_finite,
        )
        all_d = lax.all_gather(d, t_axis, axis=1, tiled=True)
        all_i = lax.all_gather(gi, t_axis, axis=1, tiled=True)
        all_l = lax.all_gather(lbl, t_axis, axis=1, tiled=True)
        return merge_candidates_vote(all_d, all_i, all_l, k, num_classes)

    sharded = shard_map_compat(
        per_shard,
        mesh=mesh,
        # Train is sharded over its column (row-index) axis because it is
        # stored transposed; labels over their only axis; queries over q.
        in_specs=(P(None, t_axis), P(t_axis), q_spec, P()),
        out_specs=q_spec,
        check_vma=False,
    )
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def _cached_fn(n_q, n_t, k, num_classes, precision, query_tile, train_tile):
    # Cache the jitted shard_map closure so repeat predicts (and --warmup)
    # reuse XLA's compile cache instead of retracing a fresh closure.
    mesh = make_mesh_2d(n_q, n_t)
    return build_train_sharded_fn(
        mesh, k, num_classes, precision, query_tile, train_tile
    )


@functools.lru_cache(maxsize=None)
def _cached_stripe_fn(
    n_q, n_t, k, num_classes, precision, block_q, block_n, d_true, interpret,
    assume_finite,
):
    mesh = make_mesh_2d(n_q, n_t)
    return build_train_sharded_stripe_fn(
        mesh, k, num_classes, precision, block_q, block_n, d_true, interpret,
        assume_finite=assume_finite,
    )


def _predict_train_sharded_stripe(
    train_x, train_y, test_x, k, num_classes, n_q, n_t, precision,
    block_q=None, block_n=None, interpret=None,
):
    from knn_tpu.ops.pallas_knn import stripe_inputs_finite, stripe_prepare_sharded

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q, n = test_x.shape[0], train_x.shape[0]
    with obs.span("prepare", path="train-sharded", engine="stripe"):
        txT, ty, qx, block_q, block_n = stripe_prepare_sharded(
            train_x, train_y, test_x, k, n_t, n_q,
            block_q=block_q, block_n=block_n, precision=precision,
        )
        fn = _cached_stripe_fn(
            n_q, n_t, k, num_classes, precision, block_q, block_n,
            train_x.shape[1], interpret, stripe_inputs_finite(train_x, test_x),
        )
    if obs.enabled():
        from knn_tpu.parallel.comm_audit import model_train_sharded_bytes

        record_collective(
            "train-sharded", "all_gather",
            model_train_sharded_bytes(qx.shape[0] // n_q, k, n_t),
        )
    t0 = time.monotonic()
    with obs.span("dispatch", path="train-sharded", engine="stripe"):
        out = guarded_call("collective.step", lambda: fn(
            jnp.asarray(txT), jnp.asarray(ty), jnp.asarray(qx),
            jnp.asarray(n, jnp.int32),
        ))
    with obs.span("fetch", path="train-sharded"):
        preds = guarded_call("collective.step", lambda: np.asarray(out)[:q])
    record_shard_dispatch("train-sharded", t0)
    return preds


def predict_train_sharded(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    num_devices: Optional[int] = None,
    mesh_shape: Optional[Tuple[int, int]] = None,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 1024,
    engine: str = "auto",
    interpret: Optional[bool] = None,
) -> np.ndarray:
    """2-D sharded KNN: queries over 'q', train rows over 't'. ``engine``
    picks the per-shard candidate kernel (resolve_shard_engine): ``stripe`` =
    the lane-striped Pallas kernel, ``xla`` = the tiled scan."""
    n = num_devices or len(jax.devices())
    if mesh_shape is None:
        mesh_shape = default_mesh_shape(n)
    n_q, n_t = mesh_shape

    engine = resolve_shard_engine(engine, precision, train_x.shape[1], k)
    if engine == "stripe":
        return _predict_train_sharded_stripe(
            train_x, train_y, test_x, k, num_classes, n_q, n_t, precision,
            interpret=interpret,
        )

    q = test_x.shape[0]
    with obs.span("prepare", path="train-sharded", engine="xla"):
        train_tile, shard_rows = xla_shard_layout(
            train_x.shape[0], n_t, train_tile, k
        )
        tx, _ = pad_axis_to_multiple(train_x, shard_rows * n_t, axis=0)
        ty, _ = pad_axis_to_multiple(train_y, shard_rows * n_t, axis=0)
        qx, _ = pad_axis_to_multiple(test_x, n_q * query_tile, axis=0)
        fn = _cached_fn(
            n_q, n_t, k, num_classes, precision, query_tile, train_tile
        )
    if obs.enabled():
        from knn_tpu.parallel.comm_audit import model_train_sharded_bytes

        record_collective(
            "train-sharded", "all_gather",
            model_train_sharded_bytes(qx.shape[0] // n_q, k, n_t),
        )
    t0 = time.monotonic()
    with obs.span("dispatch", path="train-sharded", engine="xla"):
        out = guarded_call("collective.step", lambda: fn(
            jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(qx),
            jnp.asarray(train_x.shape[0], jnp.int32),
        ))
    with obs.span("fetch", path="train-sharded"):
        preds = guarded_call("collective.step", lambda: np.asarray(out)[:q])
    record_shard_dispatch("train-sharded", t0)
    return preds


@register("tpu-train-sharded")
def predict(
    train: Dataset,
    test: Dataset,
    k: int,
    num_devices: Optional[int] = None,
    mesh_shape: Optional[Tuple[int, int]] = None,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 1024,
    metric: str = "euclidean",
    engine: str = "auto",
    **_unused,
) -> np.ndarray:
    from knn_tpu.ops.distance import resolve_form

    precision = resolve_form(precision, metric)
    if metric != "euclidean" and engine == "stripe":
        raise ValueError("the stripe engine implements euclidean only")
    train.validate_for_knn(k, test)
    return predict_train_sharded(
        train.features, train.labels, test.features, k, train.num_classes,
        num_devices=num_devices, mesh_shape=mesh_shape, precision=precision,
        query_tile=query_tile, train_tile=train_tile, engine=engine,
    )
