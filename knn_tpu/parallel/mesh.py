"""Mesh construction and multi-host initialization.

The reference's communicator setup is ``MPI_Init / Comm_rank / Comm_size``
(mpi.cpp:130-132); the TPU-native equivalent is a ``jax.sharding.Mesh`` over
the device grid, with ``jax.distributed.initialize`` for multi-host (DCN)
deployments (SURVEY.md §5.8). Collectives then ride ICI within a slice and DCN
across slices — chosen by XLA from the sharding layout, not hand-written.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the supported jax range: the top-level name
    when present, else the 0.4.x ``jax.experimental.shard_map`` spelling.
    The replication-check knob is introspected because its rename
    (``check_rep`` -> ``check_vma``) postdates the top-level promotion —
    some versions have ``jax.shard_map(..., check_rep=...)``."""
    import inspect

    if hasattr(jax, "shard_map"):
        fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as fn
    params = inspect.signature(fn).parameters
    knob = "check_vma" if "check_vma" in params else "check_rep"
    return fn(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{knob: check_vma},
    )



def maybe_init_distributed() -> None:
    """Initialize multi-host JAX when launched under a cluster runtime.

    The single-controller analogue of MPI_Init (mpi.cpp:130). No-ops unless
    cluster environment variables are present (set by the launcher), so
    single-host runs need no configuration — matching ``mpiexec -np`` being
    the only knob the reference exposes.
    """
    # Check env FIRST: jax.process_count() would initialize the local backend,
    # and jax.distributed.initialize() must run before any backend init.
    from knn_tpu.parallel.multihost import init_from_env

    try:
        if init_from_env():  # our launcher's explicit coordinator env vars
            return
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return
        raise  # coordinator unreachable etc. — fail loudly, not single-process
    if not (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    ):
        return
    try:
        jax.distributed.initialize()
    except RuntimeError:
        pass  # already initialized (e.g. by the launcher)


def make_mesh(
    num_devices: Optional[int] = None, axis_names: Sequence[str] = ("q",)
) -> Mesh:
    """1-D mesh over the first ``num_devices`` devices (default: all)."""
    devices = jax.devices()
    n = num_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices but only {len(devices)} available")
    return Mesh(np.array(devices[:n]), axis_names=tuple(axis_names))


def make_mesh_2d(
    q_devices: int, t_devices: int, axis_names: Tuple[str, str] = ("q", "t")
) -> Mesh:
    """2-D (query × train) mesh: data parallelism over queries on one axis,
    train-set sharding (the tensor-parallel analogue) on the other."""
    devices = jax.devices()
    need = q_devices * t_devices
    if need > len(devices):
        raise ValueError(
            f"mesh {q_devices}x{t_devices} needs {need} devices, have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(q_devices, t_devices)
    return Mesh(grid, axis_names=axis_names)


def default_mesh_shape(n: int) -> Tuple[int, int]:
    """Factor ``n`` into (q, t) as close to square as possible, favoring the
    query (pure-DP) axis for any remainder."""
    t = int(np.floor(np.sqrt(n)))
    while t > 1 and n % t:
        t -= 1
    return n // t, t
