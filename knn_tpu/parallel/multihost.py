"""Multi-process (multi-host) query-sharded KNN — the full MPI replacement.

The reference's distributed story is ``mpiexec -np P ./mpi train test k``:
P processes, each loading both ARFF files (mpi.cpp:136-139), rank 0
scattering query ranges (mpi.cpp:173) and gathering sub-predictions
(mpi.cpp:186). The TPU-native equivalent here is **multi-controller JAX**:

- ``jax.distributed.initialize``      = ``MPI_Init`` (mpi.cpp:130)
- process id / count                  = ``MPI_Comm_rank/size`` (mpi.cpp:131-132)
- a global ``Mesh`` over all devices of all processes; DCN between hosts,
  ICI within a slice — XLA chooses from the sharding layout
- query-axis in_spec                  = ``MPI_Scatter``
- a resharding constraint to replicated on the output = ``MPI_Gatherv`` +
  broadcast (stronger than the reference: every process gets the result)

Every process runs this same program (SPMD), loads the full datasets
(replicated IO, exactly the reference's choice), and materializes only its
addressable shards of the global query array via
``jax.make_array_from_callback`` — no host-to-host data transfer happens for
inputs at all.

Run it like mpiexec via the launcher::

    python scripts/launch_multihost.py -np 2 train.arff test.arff 5

or on a real TPU pod by starting one copy per host with the coordinator env
vars set (KNN_TPU_COORD_ADDR, KNN_TPU_NUM_PROCS, KNN_TPU_PROC_ID), or with no
env at all on Cloud TPU where ``jax.distributed.initialize()`` auto-detects.
"""

from __future__ import annotations

import functools
import os
import sys

import numpy as np

_COORD_ENV = "KNN_TPU_COORD_ADDR"
_NPROC_ENV = "KNN_TPU_NUM_PROCS"
_PROCID_ENV = "KNN_TPU_PROC_ID"


def init_from_env() -> bool:
    """``MPI_Init``: initialize multi-controller JAX from launcher env vars.

    Returns True if distributed mode was (or already is) initialized. Must run
    before any JAX backend touch. Falls through to
    ``jax.distributed.initialize()`` auto-detection when our explicit vars are
    absent but a cluster env (Cloud TPU / Slurm / Open MPI) is present.
    """
    import jax

    # Honor a FRAMEWORK-requested platform (the launcher's KNN_TPU_PLATFORM,
    # also the CLI --platform default) even where a sitecustomize forces one
    # programmatically (the axon TPU tunnel does; see .claude/skills/verify).
    # Deliberately NOT JAX_PLATFORMS: on the axon box the tunnel exports
    # JAX_PLATFORMS=axon ambiently, so re-applying the ENVIRONMENT here
    # trampled configs set explicitly in-process (e.g. the test conftest's
    # 8-device CPU mesh flipped to the 1-chip TPU the first time a CLI
    # entry ran before backend init — r5). jax itself already reads
    # JAX_PLATFORMS as the config default; nothing is lost by not
    # re-applying it. Skip the no-op write too: jax.config.update clears
    # initialized backends even for a same value.
    plat = os.environ.get("KNN_TPU_PLATFORM")
    if plat and getattr(jax.config, "jax_platforms", None) != plat:
        try:
            jax.config.update("jax_platforms", plat)
        except RuntimeError:
            pass  # backend already initialized

    coord = os.environ.get(_COORD_ENV)
    if coord is None:
        return False
    nproc = os.environ.get(_NPROC_ENV)
    procid = os.environ.get(_PROCID_ENV)
    if nproc is None or procid is None:
        raise ValueError(
            f"{_COORD_ENV} is set but {_NPROC_ENV}/{_PROCID_ENV} are not; the "
            f"launcher must export all three (see scripts/launch_multihost.py)"
        )
    from knn_tpu.resilience.retry import guarded_call

    # MPI_Init with MPI's failure mode removed: a coordinator that isn't up
    # yet retries with backoff; a dead one surfaces as WorkerLostError (via
    # classify_exception's multihost.init rule) instead of a raw RPC
    # traceback, so _worker_main can degrade to solo.
    guarded_call("multihost.init", lambda: jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(procid),
    ))
    return True


def _global_fn_from_per_shard(per_shard):
    """Global mesh + jitted shard_map closure over ALL processes' devices:
    query-axis in_spec = MPI_Scatter; the replicated resharding constraint on
    the output = MPI_Gatherv + broadcast, emitted by XLA over ICI/DCN."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), axis_names=("q",))
    from knn_tpu.parallel.mesh import shard_map_compat

    sharded = shard_map_compat(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(), P("q"), P()),
        out_specs=P("q"),
        check_vma=False,
    )

    @jax.jit
    def fn(tx, ty, qx, nv):
        out = sharded(tx, ty, qx, nv)
        return jax.lax.with_sharding_constraint(out, NamedSharding(mesh, P()))

    return mesh, fn


@functools.lru_cache(maxsize=None)
def _cached_global_fn(k, num_classes, precision, query_tile, train_tile):
    """XLA tiled-scan engine, cached so repeat predicts (warmup, loops) reuse
    XLA's compile cache instead of retracing — the same pattern as
    query_sharded._cached_fn."""
    from knn_tpu.backends.tpu import forward_tiled_core

    def per_shard(train_x, train_y, test_block, n_valid):
        return forward_tiled_core(
            train_x, train_y, test_block, n_valid,
            k=k, num_classes=num_classes, precision=precision,
            query_tile=query_tile, train_tile=train_tile,
        )

    return _global_fn_from_per_shard(per_shard)


@functools.lru_cache(maxsize=None)
def _cached_global_stripe_fn(
    k, num_classes, precision, block_q, block_n, d_true, interpret,
    assume_finite,
):
    """Lane-striped Pallas engine for the multi-host path: each process's
    devices classify their query shards with the single-chip headline kernel
    over the replicated (transposed) train set — the full mpiexec replacement
    at headline-kernel throughput per chip (VERDICT r1 #1 extended to
    multi-controller). The per-shard body is shared with the
    single-controller path (query_sharded.stripe_per_shard_classify)."""
    from knn_tpu.parallel.query_sharded import stripe_per_shard_classify

    return _global_fn_from_per_shard(stripe_per_shard_classify(
        k, num_classes, precision, block_q, block_n, d_true, interpret,
        assume_finite,
    ))


def predict_query_sharded_global(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    precision: str = "exact",
    query_tile: int = 64,
    train_tile: int = 2048,
    engine: str = "auto",
    interpret: "bool | None" = None,
) -> np.ndarray:
    """Query-sharded classify over ALL devices of ALL processes.

    Call identically from every process with identical (replicated) host
    arrays. Returns the full prediction vector on every process. ``engine``
    follows the shared rule (train_sharded.resolve_shard_engine): ``auto``
    routes stripe-eligible problems to the lane-striped Pallas kernel.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from knn_tpu.parallel.train_sharded import resolve_shard_engine
    from knn_tpu.utils.padding import pad_axis_to_multiple

    q = test_x.shape[0]
    n = train_x.shape[0]
    engine = resolve_shard_engine(engine, precision, train_x.shape[1], k)

    if engine == "stripe":
        from knn_tpu.parallel.query_sharded import stripe_query_sharded_prep

        n_dev = len(jax.devices())
        # n_t=1: train replicated (transposed for the kernel), queries split.
        tx, ty, qx, block_q, block_n, interpret, assume_finite = (
            stripe_query_sharded_prep(
                train_x, train_y, test_x, k, n_dev, interpret,
                precision=precision,
            )
        )
        mesh, fn = _cached_global_stripe_fn(
            k, num_classes, precision, block_q, block_n, train_x.shape[1],
            interpret, assume_finite,
        )
    else:
        train_tile = max(min(train_tile, n), k)
        mesh, fn = _cached_global_fn(
            k, num_classes, precision, query_tile, train_tile
        )
        n_dev = mesh.devices.size
        qx, _ = pad_axis_to_multiple(
            test_x.astype(np.float32), n_dev * query_tile, axis=0
        )
        tx, _ = pad_axis_to_multiple(train_x.astype(np.float32), train_tile, axis=0)
        ty, _ = pad_axis_to_multiple(train_y.astype(np.int32), train_tile, axis=0)

    def make_global(host_arr: np.ndarray, spec: P):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            host_arr.shape, sharding, lambda idx: host_arr[idx]
        )

    g_train_x = make_global(np.ascontiguousarray(tx), P())
    g_train_y = make_global(np.ascontiguousarray(ty), P())
    g_test_x = make_global(np.ascontiguousarray(qx), P("q"))
    g_nv = make_global(np.asarray(n, np.int32), P())

    from knn_tpu.obs.instrument import record_shard_dispatch
    from knn_tpu.resilience.retry import guarded_call

    import time

    t0 = time.monotonic()
    out = guarded_call(
        "collective.step", lambda: fn(g_train_x, g_train_y, g_test_x, g_nv)
    )

    def fetch():
        if out.is_fully_addressable:
            # Single-process (incl. the degraded-to-solo path): some jax
            # versions keep the output q-sharded despite the replication
            # constraint, making addressable_data(0) ONE SHARD; assembling
            # from all local shards is correct either way.
            return np.asarray(out)[:q]
        # Multi-process: the replication constraint guarantees every
        # process holds a full copy as its addressable data.
        return np.asarray(out.addressable_data(0))[:q]

    preds = guarded_call("collective.step", fetch)
    # This process's dispatch->fetch wall IS the fleet straggler signal:
    # obs/aggregate.py derives knn_shard_dispatch_ms_max/min + skew from
    # this gauge across the merged {proc=...} snapshots.
    record_shard_dispatch("query-sharded", t0)
    return preds


@functools.lru_cache(maxsize=None)
def _cached_global_train_sharded_fn(k, num_classes, precision, query_tile,
                                    train_tile):
    """Train-sharded twin of :func:`_cached_global_fn`: a 1-D ``t`` mesh
    over ALL processes' devices, queries replicated, train rows
    scattered — the per-shard body, all-gather merge, and tie contract
    are the single-controller ``build_train_sharded_fn`` verbatim, so
    the launcher path cannot drift from the tested one."""
    import jax
    from jax.sharding import Mesh

    from knn_tpu.parallel.train_sharded import build_train_sharded_fn

    mesh = Mesh(np.array(jax.devices()), axis_names=("t",))
    fn = build_train_sharded_fn(
        mesh, k, num_classes, precision, query_tile, train_tile,
        q_axis=None, t_axis="t",
    )
    return mesh, fn


def predict_train_sharded_global(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    precision: str = "exact",
    query_tile: int = 64,
    train_tile: int = 2048,
) -> np.ndarray:
    """Train-sharded classify over ALL devices of ALL processes: the
    index that does not fit one device, under the real launcher.

    Call identically from every process with identical (replicated) host
    arrays; returns the full prediction vector on every process (the
    out-spec is replicated — ``MPI_Allgatherv`` rather than the
    query-sharded path's scatter/gather). The row partition is
    ``knn_tpu.shard.plan.plan_rows_uniform`` — the serve tier's plan
    module — with the stride from ``train_sharded.xla_shard_layout``,
    so the launcher and the mesh-sharded serve path agree on what a
    shard boundary is. XLA tiled-scan engine only: the stripe kernel's
    transposed column sharding is a single-controller layout
    (``stripe_prepare_sharded``); callers wanting stripe use
    ``--strategy query-sharded``.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from knn_tpu.parallel.train_sharded import xla_shard_layout
    from knn_tpu.shard.plan import plan_rows_uniform
    from knn_tpu.utils.padding import pad_axis_to_multiple

    q, n = test_x.shape[0], train_x.shape[0]
    n_dev = len(jax.devices())
    train_tile, shard_rows = xla_shard_layout(n, n_dev, train_tile, k)
    plan = plan_rows_uniform(n, n_dev, shard_rows)
    mesh, fn = _cached_global_train_sharded_fn(
        k, num_classes, precision, query_tile, train_tile
    )
    tx, _ = pad_axis_to_multiple(
        train_x.astype(np.float32), shard_rows * n_dev, axis=0
    )
    ty, _ = pad_axis_to_multiple(
        train_y.astype(np.int32), shard_rows * n_dev, axis=0
    )
    qx, _ = pad_axis_to_multiple(test_x.astype(np.float32), query_tile, axis=0)

    def make_global(host_arr: np.ndarray, spec: P):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            host_arr.shape, sharding, lambda idx: host_arr[idx]
        )

    g_train_x = make_global(np.ascontiguousarray(tx), P("t"))
    g_train_y = make_global(np.ascontiguousarray(ty), P("t"))
    g_test_x = make_global(np.ascontiguousarray(qx), P())
    g_nv = make_global(np.asarray(n, np.int32), P())

    from knn_tpu import obs
    from knn_tpu.obs.instrument import record_collective, record_shard_dispatch
    from knn_tpu.resilience.retry import guarded_call

    if obs.enabled():
        from knn_tpu.parallel.comm_audit import model_train_sharded_bytes

        record_collective(
            "train-sharded", "all_gather",
            model_train_sharded_bytes(qx.shape[0], k, plan.num_shards),
        )

    import time

    t0 = time.monotonic()
    out = guarded_call(
        "collective.step", lambda: fn(g_train_x, g_train_y, g_test_x, g_nv)
    )

    def fetch():
        if out.is_fully_addressable:
            return np.asarray(out)[:q]
        return np.asarray(out.addressable_data(0))[:q]

    preds = guarded_call("collective.step", fetch)
    record_shard_dispatch("train-sharded", t0)
    return preds


def _worker_main(argv) -> int:
    """SPMD worker body — one copy per process (see module docstring)."""
    import argparse

    p = argparse.ArgumentParser(prog="knn_tpu.parallel.multihost")
    p.add_argument("train")
    p.add_argument("test")
    p.add_argument("k", type=int)
    p.add_argument("--query-tile", type=int, default=64)
    p.add_argument("--train-tile", type=int, default=2048)
    p.add_argument("--engine", default="auto", choices=["auto", "stripe", "xla"],
                   help="per-shard candidate kernel (auto: stripe on real TPU "
                   "for exact narrow-feature problems)")
    p.add_argument("--strategy", default="query-sharded",
                   choices=["query-sharded", "train-sharded"],
                   help="what the global mesh scatters: queries "
                   "(MPI_Scatter of test rows, the reference's layout) or "
                   "train rows (the index that does not fit one device; "
                   "all-gathered top-k merge, docs/SERVING.md §Sharded "
                   "serving). train-sharded is XLA-engine only")
    p.add_argument("--dump-predictions", default=None,
                   help="rank 0 writes the prediction vector here (npy)")
    p.add_argument("--metrics-out", default=None,
                   help="rank 0 writes the AGGREGATED fleet metrics here "
                   "(JSON): every process's registry snapshot merged with "
                   "{proc=N} labels plus the straggler gauges "
                   "(knn_shard_dispatch_ms_max/min, skew) — "
                   "obs/aggregate.py. Implies enabling knn_tpu.obs on "
                   "every process")
    args = p.parse_args(argv)
    if args.strategy == "train-sharded" and args.engine == "stripe":
        # The stripe kernel's transposed column sharding is a
        # single-controller layout; see predict_train_sharded_global.
        print("error: --strategy train-sharded implements the xla engine "
              "only; drop --engine stripe or use --strategy query-sharded",
              file=sys.stderr)
        return 2

    import jax

    from knn_tpu import obs
    from knn_tpu.resilience import faults
    from knn_tpu.resilience.errors import WorkerLostError, classify_exception

    if args.metrics_out:
        # Every process records; rank 0 merges after the predict. Enabled
        # BEFORE init so even the init-retry/degrade counters aggregate.
        obs.enable()

    def degrade_to_solo(e: Exception) -> None:
        err = classify_exception(e, "multihost.init")
        if not isinstance(err, WorkerLostError):
            err = WorkerLostError(str(err), reason=type(e).__name__)
        # The reference's MPI answer to a lost rank is a dead job
        # (mpi.cpp has no recovery at all); ours is a logged, counted
        # degradation to single-process on the local devices.
        obs.counter_add(
            "knn_worker_lost_total",
            help="multihost workers lost or never joined (degraded to solo)",
            reason=err.reason,
        )
        obs.counter_add(
            "knn_fallback_total",
            help="degradation-ladder moves (backend -> fallback backend)",
            from_backend="multihost", to="solo", reason=err.reason,
        )
        print(
            f"multihost: WorkerLostError ({err.reason}): {err} — "
            f"degrading to single-process",
            file=sys.stderr,
        )

    try:
        # init_from_env's own ValueError (partial launcher env) propagates:
        # a misconfigured launcher is a usage error, and N processes
        # silently degrading to N solo runs would each print the rank-0
        # report. Cluster failures (WorkerLostError from its guarded init)
        # degrade.
        inited = init_from_env()
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 — classified + logged in the helper
        degrade_to_solo(e)
        inited = True  # do not also attempt auto-detection
    if not inited:
        # No explicit launcher env: fall back to jax's cluster
        # auto-detection (Cloud TPU pods, Slurm, Open MPI). On a plain host
        # this raises (ValueError for a missing coordinator on this jax) —
        # degrade to solo through the typed path, never a bare swallow.
        try:
            faults.fault_point("multihost.init")
            jax.distributed.initialize()
        except Exception as e:  # noqa: BLE001
            degrade_to_solo(e)

    from knn_tpu.data.arff import load_arff
    from knn_tpu.utils.cli_format import result_line
    from knn_tpu.utils.evaluate import accuracy, confusion_matrix
    from knn_tpu.utils.timing import RegionTimer

    rank = jax.process_index()
    # Replicated load on every process — the reference's exact IO strategy
    # (mpi.cpp:136-139).
    try:
        train = load_arff(args.train)
        test = load_arff(args.test)
        train.validate_for_knn(args.k, test)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    from knn_tpu.resilience.errors import ResilienceError

    try:
        with RegionTimer() as t:
            if args.strategy == "train-sharded":
                preds = predict_train_sharded_global(
                    train.features, train.labels, test.features, args.k,
                    train.num_classes,
                    query_tile=args.query_tile, train_tile=args.train_tile,
                )
            else:
                preds = predict_query_sharded_global(
                    train.features, train.labels, test.features, args.k,
                    train.num_classes,
                    query_tile=args.query_tile, train_tile=args.train_tile,
                    engine=args.engine,
                )
    except ResilienceError as e:
        # A mid-collective failure with peers already joined: degrading N
        # processes to N solo runs would duplicate the rank-0 report, so
        # (like the reference's MPI job) the worker dies — but with a
        # one-line typed error, not a traceback, and the reason counted.
        obs.counter_add(
            "knn_worker_lost_total",
            help="multihost workers lost or never joined (degraded to solo)",
            reason=type(e).__name__,
        )
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 1

    if args.metrics_out:
        # Fleet aggregation is a COLLECTIVE (process_allgather): every
        # process must enter it, not just rank 0.
        from knn_tpu.obs import aggregate

        merged, stragglers = aggregate.aggregate_multihost()
    if rank == 0:  # rank-0 reporting, like mpi.cpp:188-199
        acc = accuracy(confusion_matrix(preds, test.labels, test.num_classes))
        print(
            result_line(
                args.k, test.num_instances, train.num_instances, t.ms, acc
            ),
            flush=True,
        )
        if args.dump_predictions:
            np.save(args.dump_predictions, preds)
        if args.metrics_out and merged is not None:
            import json

            try:
                with open(args.metrics_out, "w", encoding="utf-8") as f:
                    json.dump(
                        {
                            "processes": jax.process_count(),
                            "stragglers": stragglers,
                            "metrics": merged.to_json(),
                        },
                        f, indent=1,
                    )
            except OSError as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
    return 0


if __name__ == "__main__":
    sys.exit(_worker_main(sys.argv[1:]))
