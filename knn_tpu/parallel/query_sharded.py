"""Query-sharded data parallelism — the MPI backend's TPU-native replacement.

The reference scatters contiguous ``[start, end)`` query ranges to P ranks
(``MPI_Scatter``, mpi.cpp:173), each rank classifies its slice, and rank 0
reassembles with ``MPI_Gatherv`` (mpi.cpp:186). Here the same structure is a
``shard_map`` over the mesh's query axis: the in_spec IS the scatter, the
out_spec IS the gather, and XLA emits the collectives over ICI/DCN. Ragged
query counts (Gatherv's variable per-rank lengths) become pad + slice
(SURVEY.md §5.8).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from knn_tpu import obs
from knn_tpu.backends import register
from knn_tpu.backends.tpu import forward_tiled_core
from knn_tpu.data.dataset import Dataset
from knn_tpu.obs.instrument import record_collective, record_shard_dispatch
from knn_tpu.parallel.mesh import make_mesh, shard_map_compat
from knn_tpu.resilience.retry import guarded_call
from knn_tpu.utils.padding import pad_axis_to_multiple


def build_query_sharded_fn(
    mesh: Mesh,
    k: int,
    num_classes: int,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 2048,
    axis: str = "q",
):
    """Returns a jitted fn(train_x, train_y, test_x, n_train_valid) -> preds.

    test_x must be padded to ``mesh.shape[axis] * query_tile`` multiples and
    train to ``train_tile`` multiples. Train data is replicated to every
    device, exactly as every MPI rank loads both files (mpi.cpp:136-139).
    """

    def per_shard(train_x, train_y, test_block, n_valid):
        return forward_tiled_core(
            train_x, train_y, test_block, n_valid,
            k=k, num_classes=num_classes, precision=precision,
            query_tile=query_tile, train_tile=train_tile,
        )

    sharded = shard_map_compat(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(sharded)


def stripe_per_shard_classify(
    k: int,
    num_classes: int,
    precision: str,
    block_q: int,
    block_n: int,
    d_true: int,
    interpret: bool,
    assume_finite: bool,
):
    """THE per-shard stripe classify body shared by every query-sharded
    formulation (single-controller shard_map here, the multi-controller
    global mesh in parallel/multihost.py): lane-striped Pallas candidates
    over the replicated transposed train set, then the vote. One definition
    so gating/block-size changes cannot drift between the single-process and
    multi-host engines."""
    from knn_tpu.ops.pallas_knn import stripe_candidates_core
    from knn_tpu.ops.vote import vote

    def per_shard(train_xT, train_y, test_block, n_valid):
        _, _, lbl = stripe_candidates_core(
            train_xT, train_y, test_block, n_valid, k,
            block_q=block_q, block_n=block_n, d_true=d_true,
            precision=precision, interpret=interpret,
            assume_finite=assume_finite,
        )
        return vote(lbl, num_classes)

    return per_shard


def stripe_query_sharded_prep(
    train_x, train_y, test_x, k, n_dev, interpret,
    block_q=None, block_n=None, precision="exact",
):
    """Shared host-side prep for the stripe query-sharded paths: resolve
    interpret mode, lay out the replicated transposed train + ``n_dev``-way
    padded queries (n_t=1: only queries split), and evaluate the finiteness
    gate. Returns ``(txT, ty, qx, block_q, block_n, interpret,
    assume_finite)``."""
    from knn_tpu.ops.pallas_knn import stripe_inputs_finite, stripe_prepare_sharded

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    txT, ty, qx, block_q, block_n = stripe_prepare_sharded(
        train_x, train_y, test_x, k, 1, n_dev,
        block_q=block_q, block_n=block_n, precision=precision,
    )
    return (
        txT, ty, qx, block_q, block_n, interpret,
        stripe_inputs_finite(train_x, test_x),
    )


def build_query_sharded_stripe_fn(
    mesh: Mesh,
    k: int,
    num_classes: int,
    precision: str,
    block_q: int,
    block_n: int,
    d_true: int,
    interpret: bool,
    axis: str = "q",
    assume_finite: bool = False,
):
    """Stripe-engine variant of :func:`build_query_sharded_fn`: each device
    classifies its query shard with the lane-striped Pallas kernel over the
    replicated train set (VERDICT r1 #1 — the distributed MPI analogue at
    single-chip headline throughput). ``train_xT`` is the TRANSPOSED padded
    train matrix ``[D_pad, N_pad]``; queries per shard must be a ``block_q``
    multiple. ``assume_finite`` (only when pallas_knn.stripe_inputs_finite
    holds for the unpadded inputs) selects the kernel's cheaper
    index-retirement-free selection rounds."""
    per_shard = stripe_per_shard_classify(
        k, num_classes, precision, block_q, block_n, d_true, interpret,
        assume_finite,
    )

    sharded = shard_map_compat(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def _cached_fn(n_dev, k, num_classes, precision, query_tile, train_tile):
    # Cache the jitted shard_map closure so repeat predicts (and --warmup)
    # reuse XLA's compile cache instead of retracing a fresh closure.
    mesh = make_mesh(n_dev, axis_names=("q",))
    return build_query_sharded_fn(
        mesh, k, num_classes, precision, query_tile, train_tile
    )


@functools.lru_cache(maxsize=None)
def _cached_stripe_fn(
    n_dev, k, num_classes, precision, block_q, block_n, d_true, interpret,
    assume_finite,
):
    mesh = make_mesh(n_dev, axis_names=("q",))
    return build_query_sharded_stripe_fn(
        mesh, k, num_classes, precision, block_q, block_n, d_true, interpret,
        assume_finite=assume_finite,
    )


def _predict_query_sharded_stripe(
    train_x, train_y, test_x, k, num_classes, n_dev, precision,
    mesh=None, block_q=None, block_n=None, interpret=None,
):
    q, n = test_x.shape[0], train_x.shape[0]
    with obs.span("prepare", path="query-sharded", engine="stripe"):
        txT, ty, qx, block_q, block_n, interpret, assume_finite = (
            stripe_query_sharded_prep(
                train_x, train_y, test_x, k, n_dev, interpret,
                block_q=block_q, block_n=block_n, precision=precision,
            )
        )
        if mesh is not None:
            fn = build_query_sharded_stripe_fn(
                mesh, k, num_classes, precision, block_q, block_n,
                train_x.shape[1], interpret, assume_finite=assume_finite,
            )
        else:
            fn = _cached_stripe_fn(
                n_dev, k, num_classes, precision, block_q, block_n,
                train_x.shape[1], interpret, assume_finite,
            )
    if obs.enabled():
        from knn_tpu.parallel.comm_audit import model_query_sharded_bytes

        record_collective(
            "query-sharded", "scatter_gather",
            model_query_sharded_bytes(qx.shape[0], qx.shape[1]),
        )
    t0 = time.monotonic()
    with obs.span("dispatch", path="query-sharded", engine="stripe"):
        out = guarded_call("collective.step", lambda: fn(
            jnp.asarray(txT), jnp.asarray(ty), jnp.asarray(qx),
            jnp.asarray(n, jnp.int32),
        ))
    with obs.span("fetch", path="query-sharded"):
        preds = guarded_call("collective.step", lambda: np.asarray(out)[:q])
    record_shard_dispatch("query-sharded", t0)
    return preds


def predict_query_sharded(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    num_devices: Optional[int] = None,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 2048,
    mesh: Optional[Mesh] = None,
    engine: str = "auto",
    interpret: Optional[bool] = None,
) -> np.ndarray:
    from knn_tpu.parallel.train_sharded import resolve_shard_engine

    engine = resolve_shard_engine(engine, precision, train_x.shape[1], k)
    if engine == "stripe":
        n_dev = mesh.shape["q"] if mesh is not None else (
            num_devices or len(jax.devices())
        )
        return _predict_query_sharded_stripe(
            train_x, train_y, test_x, k, num_classes, n_dev, precision,
            mesh=mesh, interpret=interpret,
        )
    q = test_x.shape[0]
    with obs.span("prepare", path="query-sharded", engine="xla"):
        train_tile = max(min(train_tile, train_x.shape[0]), k)
        if mesh is not None:
            n_dev = mesh.shape["q"]
            fn = build_query_sharded_fn(
                mesh, k, num_classes, precision, query_tile, train_tile
            )
        else:
            n_dev = num_devices or len(jax.devices())
            fn = _cached_fn(
                n_dev, k, num_classes, precision, query_tile, train_tile
            )
        qx, _ = pad_axis_to_multiple(test_x, n_dev * query_tile, axis=0)
        tx, _ = pad_axis_to_multiple(train_x, train_tile, axis=0)
        ty, _ = pad_axis_to_multiple(train_y, train_tile, axis=0)
    if obs.enabled():
        from knn_tpu.parallel.comm_audit import model_query_sharded_bytes

        record_collective(
            "query-sharded", "scatter_gather",
            model_query_sharded_bytes(qx.shape[0], qx.shape[1]),
        )
    t0 = time.monotonic()
    with obs.span("dispatch", path="query-sharded", engine="xla"):
        out = guarded_call("collective.step", lambda: fn(
            jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(qx),
            jnp.asarray(train_x.shape[0], jnp.int32),
        ))
    with obs.span("fetch", path="query-sharded"):
        preds = guarded_call("collective.step", lambda: np.asarray(out)[:q])
    record_shard_dispatch("query-sharded", t0)
    return preds


@register("tpu-sharded")
def predict(
    train: Dataset,
    test: Dataset,
    k: int,
    num_devices: Optional[int] = None,
    precision: str = "exact",
    query_tile: int = 128,
    train_tile: int = 2048,
    metric: str = "euclidean",
    engine: str = "auto",
    **_unused,
) -> np.ndarray:
    from knn_tpu.ops.distance import resolve_form

    precision = resolve_form(precision, metric)
    if metric != "euclidean" and engine == "stripe":
        raise ValueError("the stripe engine implements euclidean only")
    train.validate_for_knn(k, test)
    if jax.process_count() > 1:
        # Launched multi-controller (scripts/launch_multihost.py or a TPU
        # pod): span every process's devices, like mpiexec spanning ranks.
        from knn_tpu.parallel.multihost import predict_query_sharded_global

        return predict_query_sharded_global(
            train.features, train.labels, test.features, k, train.num_classes,
            precision=precision, query_tile=query_tile, train_tile=train_tile,
            engine=engine,
        )
    return predict_query_sharded(
        train.features, train.labels, test.features, k, train.num_classes,
        num_devices=num_devices, precision=precision,
        query_tile=query_tile, train_tile=train_tile, engine=engine,
    )
