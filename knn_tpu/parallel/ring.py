"""Ring-scheduled KNN — ring attention's structure with a top-k accumulator
(SURVEY.md §5.7).

Both queries and train rows are sharded over one mesh axis. Each step, every
device scores its resident query block against the train shard it currently
holds, folds the results into a running top-k candidate set, and passes the
shard to its ring neighbor via ``lax.ppermute`` over ICI. After P steps every
query block has seen every train row while no device ever held more than
1/P-th of the train set — the same memory/comm trade ring attention makes with
KV blocks, with the (associative, commutative) lexicographic top-k merge in
place of softmax accumulation. Because the merge keys on (distance,
global-index), tie semantics are preserved even though shards arrive in
rotated (non-index) order — the case positional tie-breaking would get wrong
(SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from knn_tpu.backends import register
from knn_tpu.data.dataset import Dataset
from knn_tpu.ops.distance import _DIST_FNS
from knn_tpu.ops.topk import merge_topk_labeled
from knn_tpu.ops.vote import vote
from knn_tpu.parallel.mesh import make_mesh
from knn_tpu.utils.padding import pad_axis_to_multiple



def build_ring_fn(
    mesh: Mesh,
    k: int,
    num_classes: int,
    precision: str = "exact",
    axis: str = "r",
):
    """fn(train_x, train_y, test_x, n_train_valid) -> preds; train and test
    both sharded over ``axis``."""
    n_dev = mesh.shape[axis]
    dist_fn = _DIST_FNS[precision]
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def per_shard(train_x, train_y, test_block, n_valid):
        shard_rows = train_x.shape[0]
        kk = min(k, shard_rows)
        my = lax.axis_index(axis)

        def score_and_merge(run, cur_x, cur_y, owner):
            """Fold the currently-held shard into the running candidates."""
            run_d, run_i, run_l = run
            base = (owner * shard_rows).astype(jnp.int32)
            d = dist_fn(test_block, cur_x)  # [q_local, shard_rows]
            local_valid = jnp.clip(n_valid - owner * shard_rows, 0, shard_rows)
            d = jnp.where(jnp.arange(shard_rows)[None, :] < local_valid, d, jnp.inf)
            neg, li = lax.top_k(-d, kk)
            return merge_topk_labeled(
                run_d, run_i, run_l,
                -neg, (li + base).astype(jnp.int32), cur_y[li],
                k,
            )

        q_local = test_block.shape[0]
        run = (
            jnp.full((q_local, k), jnp.inf, train_x.dtype),
            jnp.full((q_local, k), jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.zeros((q_local, k), train_y.dtype),
        )
        # Step 0: score the resident shard; steps 1..P-1: rotate, then score —
        # so only P-1 ppermute rounds cross the wire.
        run = score_and_merge(run, train_x, train_y, my)

        def step(carry, s):
            cur_x, cur_y, run_d, run_i, run_l = carry
            cur_x = lax.ppermute(cur_x, axis, perm)
            cur_y = lax.ppermute(cur_y, axis, perm)
            # After s hops we hold the shard that started at device my - s.
            owner = (my - s) % n_dev
            run = score_and_merge((run_d, run_i, run_l), cur_x, cur_y, owner)
            return (cur_x, cur_y) + run, None

        if n_dev > 1:
            (_, _, _, _, run_l), _ = lax.scan(
                step, (train_x, train_y) + run, jnp.arange(1, n_dev)
            )
        else:
            run_l = run[2]
        return vote(run_l, num_classes)

    sharded = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def _cached_fn(n_dev, k, num_classes, precision):
    # Cache the jitted shard_map closure so repeat predicts (and --warmup)
    # reuse XLA's compile cache instead of retracing a fresh closure.
    mesh = make_mesh(n_dev, axis_names=("r",))
    return build_ring_fn(mesh, k, num_classes, precision)


def predict_ring(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    num_devices: Optional[int] = None,
    precision: str = "exact",
) -> np.ndarray:
    n_dev = num_devices or len(jax.devices())
    q = test_x.shape[0]
    tx, _ = pad_axis_to_multiple(train_x, n_dev, axis=0)
    ty, _ = pad_axis_to_multiple(train_y, n_dev, axis=0)
    qx, _ = pad_axis_to_multiple(test_x, n_dev, axis=0)
    fn = _cached_fn(n_dev, k, num_classes, precision)
    out = fn(
        jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(qx),
        jnp.asarray(train_x.shape[0], jnp.int32),
    )
    return np.asarray(out)[:q]


@register("tpu-ring")
def predict(
    train: Dataset,
    test: Dataset,
    k: int,
    num_devices: Optional[int] = None,
    precision: str = "exact",
    metric: str = "euclidean",
    **_unused,
) -> np.ndarray:
    from knn_tpu.ops.distance import resolve_form

    precision = resolve_form(precision, metric)
    train.validate_for_knn(k, test)
    return predict_ring(
        train.features, train.labels, test.features, k, train.num_classes,
        num_devices=num_devices, precision=precision,
    )
