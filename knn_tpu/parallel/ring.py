"""Ring-scheduled KNN — ring attention's structure with a top-k accumulator
(SURVEY.md §5.7).

Both queries and train rows are sharded over one mesh axis. Each step, every
device scores its resident query block against the train shard it currently
holds, folds the results into a running top-k candidate set, and passes the
shard to its ring neighbor via ``lax.ppermute`` over ICI. After P steps every
query block has seen every train row while no device ever held more than
1/P-th of the train set — the same memory/comm trade ring attention makes with
KV blocks, with the (associative, commutative) lexicographic top-k merge in
place of softmax accumulation. Because the merge keys on (distance,
global-index), tie semantics are preserved even though shards arrive in
rotated (non-index) order — the case positional tie-breaking would get wrong
(SURVEY.md §7 hard part (b)).

Per-step scoring engines (VERDICT r1 #1/#3):

- ``full``   — materialize the whole ``[q_local, shard_rows]`` distance block.
  Fastest at fixture scale; memory O(q_local · N/P).
- ``tiled``  — the XLA tiled candidate scan (backends/tpu.py::
  forward_candidates_core): per-step memory O(query_tile · train_tile), so
  the ring holds xl-scale shards (~1M rows) without blowing HBM.
- ``stripe`` — the lane-striped Pallas kernel (ops/pallas_knn.py), the
  single-chip headline kernel; the ring rotates the *transposed* ``[D_pad,
  shard_rows]`` shard so each step feeds the kernel its native layout.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from knn_tpu import obs
from knn_tpu.backends import register
from knn_tpu.backends.tpu import forward_candidates_core
from knn_tpu.data.dataset import Dataset
from knn_tpu.obs.instrument import record_collective, record_shard_dispatch
from knn_tpu.ops.distance import _DIST_FNS
from knn_tpu.ops.topk import merge_topk_labeled
from knn_tpu.ops.vote import vote
from knn_tpu.parallel.mesh import make_mesh, shard_map_compat
from knn_tpu.resilience.retry import guarded_call
from knn_tpu.utils.padding import pad_axis_to_multiple

# [q_local, shard_rows] cells above which ``engine="auto"`` abandons the
# full-matrix per-step scorer for the tiled one (same ballpark as the
# single-device full-matrix limit in backends/tpu.py).
_FULL_RING_CELL_LIMIT = 16 * 1024 * 1024


def _resolve_ring_engine(
    engine: str, precision: str, d: int, k: int, q_local: int, shard_rows: int
) -> str:
    if engine == "xla":
        # The name the other sharded backends use for their XLA scorer; keep
        # --engine xla working uniformly across backends.
        engine = "tiled"
    if engine not in ("auto", "full", "tiled", "stripe"):
        raise ValueError(
            f"unknown ring engine {engine!r}; choose 'auto', 'full', "
            f"'tiled' (alias 'xla'), or 'stripe'"
        )
    if engine != "auto":
        return engine
    from knn_tpu.ops.pallas_knn import stripe_auto_eligible

    if stripe_auto_eligible(precision, d, k):
        return "stripe"
    if q_local * shard_rows <= _FULL_RING_CELL_LIMIT:
        return "full"
    return "tiled"


def build_ring_fn(
    mesh: Mesh,
    k: int,
    num_classes: int,
    precision: str = "exact",
    axis: str = "r",
    engine: str = "full",
    query_tile: int = 128,
    train_tile: int = 1024,
    block_q: int = 448,
    block_n: int = 2048,
    d_true: Optional[int] = None,
    interpret: bool = False,
    assume_finite: bool = False,
):
    """fn(train, train_y, test_x, n_train_valid) -> preds; train and test both
    sharded over ``axis``. For ``engine="stripe"`` the train argument is the
    TRANSPOSED ``[D_pad, N_pad]`` matrix sharded over its column axis;
    otherwise it is the usual ``[N_pad, D]`` rows."""
    n_dev = mesh.shape[axis]
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def per_shard(train_shard, train_y, test_block, n_valid):
        shard_rows = train_shard.shape[1 if engine == "stripe" else 0]
        my = lax.axis_index(axis)

        def score(cur_t, cur_y, owner):
            """One shard's candidate triple, with global indices."""
            base = (owner * shard_rows).astype(jnp.int32)
            local_valid = jnp.clip(n_valid - base, 0, shard_rows)
            if engine == "stripe":
                from knn_tpu.ops.pallas_knn import stripe_candidates_core

                return stripe_candidates_core(
                    cur_t, cur_y, test_block, local_valid, k,
                    block_q=block_q, block_n=block_n,
                    d_true=d_true if d_true is not None else cur_t.shape[0],
                    precision=precision, interpret=interpret, index_base=base,
                    assume_finite=assume_finite,
                )
            if engine == "tiled":
                return forward_candidates_core(
                    cur_t, cur_y, test_block, local_valid,
                    k=k, precision=precision,
                    query_tile=query_tile,
                    train_tile=min(train_tile, shard_rows),
                    index_base=base,
                )
            # full: one [q_local, shard_rows] distance block per step.
            kk = min(k, shard_rows)
            d = _DIST_FNS[precision](test_block, cur_t)
            d = jnp.where(
                jnp.arange(shard_rows)[None, :] < local_valid, d, jnp.inf
            )
            neg, li = lax.top_k(-d, kk)
            return -neg, (li + base).astype(jnp.int32), cur_y[li]

        def score_and_merge(run, cur_t, cur_y, owner):
            run_d, run_i, run_l = run
            s_d, s_i, s_l = score(cur_t, cur_y, owner)
            return merge_topk_labeled(run_d, run_i, run_l, s_d, s_i, s_l, k)

        q_local = test_block.shape[0]
        run = (
            jnp.full((q_local, k), jnp.inf, jnp.float32),
            jnp.full((q_local, k), jnp.iinfo(jnp.int32).max, jnp.int32),
            jnp.zeros((q_local, k), train_y.dtype),
        )
        # Step 0: score the resident shard; steps 1..P-1: rotate, then score —
        # so only P-1 ppermute rounds cross the wire.
        run = score_and_merge(run, train_shard, train_y, my)

        def step(carry, s):
            cur_t, cur_y, run_d, run_i, run_l = carry
            cur_t = lax.ppermute(cur_t, axis, perm)
            cur_y = lax.ppermute(cur_y, axis, perm)
            # After s hops we hold the shard that started at device my - s.
            owner = (my - s) % n_dev
            run = score_and_merge((run_d, run_i, run_l), cur_t, cur_y, owner)
            return (cur_t, cur_y) + run, None

        if n_dev > 1:
            (_, _, _, _, run_l), _ = lax.scan(
                step, (train_shard, train_y) + run, jnp.arange(1, n_dev)
            )
        else:
            run_l = run[2]
        return vote(run_l, num_classes)

    train_spec = P(None, axis) if engine == "stripe" else P(axis)
    sharded = shard_map_compat(
        per_shard,
        mesh=mesh,
        in_specs=(train_spec, P(axis), P(axis), P()),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(sharded)


@functools.lru_cache(maxsize=None)
def _cached_fn(
    n_dev, k, num_classes, precision, engine, query_tile, train_tile,
    block_q, block_n, d_true, interpret, assume_finite=False,
):
    # Cache the jitted shard_map closure so repeat predicts (and --warmup)
    # reuse XLA's compile cache instead of retracing a fresh closure.
    mesh = make_mesh(n_dev, axis_names=("r",))
    return build_ring_fn(
        mesh, k, num_classes, precision,
        engine=engine, query_tile=query_tile, train_tile=train_tile,
        block_q=block_q, block_n=block_n, d_true=d_true, interpret=interpret,
        assume_finite=assume_finite,
    )


def predict_ring(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    num_devices: Optional[int] = None,
    precision: str = "exact",
    engine: str = "auto",
    query_tile: int = 128,
    train_tile: int = 1024,
    interpret: Optional[bool] = None,
) -> np.ndarray:
    n_dev = num_devices or len(jax.devices())
    q, n, d = test_x.shape[0], train_x.shape[0], train_x.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    engine = _resolve_ring_engine(
        engine, precision, d, k, -(-q // n_dev), -(-n // n_dev)
    )

    if engine == "stripe":
        from knn_tpu.ops.pallas_knn import (
            stripe_inputs_finite, stripe_prepare_sharded,
        )

        with obs.span("prepare", path="ring", engine="stripe"):
            txT, ty, qx, block_q, block_n = stripe_prepare_sharded(
                train_x, train_y, test_x, k, n_dev, n_dev,
                precision=precision,
            )
            fn = _cached_fn(
                n_dev, k, num_classes, precision, "stripe", query_tile,
                train_tile, block_q, block_n, d, interpret,
                stripe_inputs_finite(train_x, test_x),
            )
        if obs.enabled():
            from knn_tpu.parallel.comm_audit import model_ring_bytes

            shard_cols = txT.shape[1] // n_dev
            record_collective(
                "ring", "collective_permute",
                model_ring_bytes(
                    txT.shape[0] * shard_cols * txT.itemsize,
                    shard_cols * ty.itemsize, n_dev,
                ),
            )
        t0 = time.monotonic()
        with obs.span("dispatch", path="ring", engine="stripe"):
            out = guarded_call("collective.step", lambda: fn(
                jnp.asarray(txT), jnp.asarray(ty), jnp.asarray(qx),
                jnp.asarray(n, jnp.int32),
            ))
        with obs.span("fetch", path="ring"):
            preds = guarded_call(
                "collective.step", lambda: np.asarray(out)[:q])
        record_shard_dispatch("ring", t0)
        return preds

    with obs.span("prepare", path="ring", engine=engine):
        if engine == "tiled":
            shard_quota = -(-n // n_dev)  # ceil train rows per shard
            train_tile = max(min(train_tile, shard_quota), 1)
            shard_rows = -(-shard_quota // train_tile) * train_tile
            q_quota = -(-q // n_dev)  # ceil queries per shard
            query_tile = max(8, min(query_tile, -(-q_quota // 8) * 8))
            q_local = -(-q_quota // query_tile) * query_tile
            tx, _ = pad_axis_to_multiple(train_x, shard_rows * n_dev, axis=0)
            ty, _ = pad_axis_to_multiple(train_y, shard_rows * n_dev, axis=0)
            qx, _ = pad_axis_to_multiple(test_x, q_local * n_dev, axis=0)
        else:  # full
            tx, _ = pad_axis_to_multiple(train_x, n_dev, axis=0)
            ty, _ = pad_axis_to_multiple(train_y, n_dev, axis=0)
            qx, _ = pad_axis_to_multiple(test_x, n_dev, axis=0)
        fn = _cached_fn(
            n_dev, k, num_classes, precision, engine, query_tile, train_tile,
            448, 2048, d, interpret,
        )
    if obs.enabled():
        from knn_tpu.parallel.comm_audit import model_ring_bytes

        shard_rows_eff = tx.shape[0] // n_dev
        record_collective(
            "ring", "collective_permute",
            model_ring_bytes(
                shard_rows_eff * tx.shape[1] * tx.itemsize,
                shard_rows_eff * ty.itemsize, n_dev,
            ),
        )
    t0 = time.monotonic()
    with obs.span("dispatch", path="ring", engine=engine):
        out = guarded_call("collective.step", lambda: fn(
            jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(qx),
            jnp.asarray(n, jnp.int32),
        ))
    with obs.span("fetch", path="ring"):
        preds = guarded_call("collective.step", lambda: np.asarray(out)[:q])
    record_shard_dispatch("ring", t0)
    return preds


@register("tpu-ring")
def predict(
    train: Dataset,
    test: Dataset,
    k: int,
    num_devices: Optional[int] = None,
    precision: str = "exact",
    metric: str = "euclidean",
    engine: str = "auto",
    query_tile: int = 128,
    train_tile: int = 1024,
    **_unused,
) -> np.ndarray:
    from knn_tpu.ops.distance import resolve_form

    precision = resolve_form(precision, metric)
    if metric != "euclidean" and engine == "stripe":
        raise ValueError("the stripe engine implements euclidean only")
    train.validate_for_knn(k, test)
    return predict_ring(
        train.features, train.labels, test.features, k, train.num_classes,
        num_devices=num_devices, precision=precision, engine=engine,
        query_tile=query_tile, train_tile=train_tile,
    )
