"""Multi-device execution strategies over a jax.sharding.Mesh.

- ``query_sharded``  — data parallelism over test queries (the MPI analogue:
  MPI_Scatter of ranges + MPI_Gatherv of predictions, mpi.cpp:151-186, becomes
  a sharding annotation + output sharding).
- ``train_sharded``  — train rows sharded across the mesh with an all-gather
  top-k candidate merge (the tensor-parallel analogue for KNN).
- ``ring``           — ring schedule rotating train shards over ICI with a
  running top-k (ring attention's structure with top-k accumulation).
- ``mesh``           — mesh construction/multi-host init helpers.
"""
