"""Pairwise squared-Euclidean distance.

The reference computes ``sum_i (a_i - b_i)^2`` over the feature columns in
float32, one scalar pair at a time (main.cpp:14-23). Two formulations:

- :func:`pairwise_sq_dists` — the subtraction form ``((q - t)**2).sum(-1)``.
  Per-pair summation over the feature axis in float32, the float-faithful form
  used for exact prediction parity with the reference (SURVEY.md §7 hard part
  (a)): identical rows give *exactly* 0, so the dist==0 ties the large dataset
  exercises behave identically.
- :func:`pairwise_sq_dists_dot` — the ``|q|^2 + |t|^2 - 2 q·t`` form, which
  maps the dominant cost onto the MXU as a matmul. Much faster for wide
  features (e.g. MNIST-784) but numerically fuzzier around 0; used by the
  ``fast`` precision mode and the Pallas kernel.
"""

from __future__ import annotations

import jax.numpy as jnp


def pairwise_sq_dists(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """[Q, D], [N, D] -> [Q, N] squared Euclidean distances (subtraction form).

    NaN distances (from missing-value NaN features) map to +inf — the
    framework-wide policy where the reference is UB (SURVEY.md §3.5.5)."""
    diff = queries[:, None, :] - train[None, :, :]
    d = jnp.sum(diff * diff, axis=-1)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


def pairwise_sq_dists_dot(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """[Q, D], [N, D] -> [Q, N] squared distances via the MXU-friendly
    ``|q|^2 - 2 q·t + |t|^2`` expansion, clamped at 0."""
    q2 = jnp.sum(queries * queries, axis=-1, keepdims=True)  # [Q, 1]
    t2 = jnp.sum(train * train, axis=-1)[None, :]  # [1, N]
    cross = queries @ train.T  # [Q, N] — MXU
    d = jnp.maximum(q2 + t2 - 2.0 * cross, 0.0)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


def pairwise_sq_dists_bf16(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """Dot-form distances with bfloat16 MXU operands (float32 accumulation):
    2x matmul throughput at ~3 fewer mantissa digits in the cross term. The
    norm terms stay float32."""
    q2 = jnp.sum(queries * queries, axis=-1, keepdims=True)
    t2 = jnp.sum(train * train, axis=-1)[None, :]
    cross = jnp.dot(
        queries.astype(jnp.bfloat16),
        train.astype(jnp.bfloat16).T,
        preferred_element_type=jnp.float32,
    )
    d = jnp.maximum(q2 + t2 - 2.0 * cross, 0.0)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


_DIST_FNS = {
    "exact": pairwise_sq_dists,
    "fast": pairwise_sq_dists_dot,
    "bf16": pairwise_sq_dists_bf16,
}
