"""Pairwise squared-Euclidean distance.

The reference computes ``sum_i (a_i - b_i)^2`` over the feature columns in
float32, one scalar pair at a time (main.cpp:14-23). Two formulations:

- :func:`pairwise_sq_dists` — the subtraction form ``((q - t)**2).sum(-1)``.
  Per-pair summation over the feature axis in float32, the float-faithful form
  used for exact prediction parity with the reference (SURVEY.md §7 hard part
  (a)): identical rows give *exactly* 0, so the dist==0 ties the large dataset
  exercises behave identically.
- :func:`pairwise_sq_dists_dot` — the ``|q|^2 + |t|^2 - 2 q·t`` form, which
  maps the dominant cost onto the MXU as a matmul. Much faster for wide
  features (e.g. MNIST-784) but numerically fuzzier around 0; used by the
  ``fast`` precision mode and the Pallas kernel.
"""

from __future__ import annotations

import jax.numpy as jnp


def pairwise_sq_dists(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """[Q, D], [N, D] -> [Q, N] squared Euclidean distances (subtraction form).

    NaN distances (from missing-value NaN features) map to +inf — the
    framework-wide policy where the reference is UB (SURVEY.md §3.5.5)."""
    diff = queries[:, None, :] - train[None, :, :]
    d = jnp.sum(diff * diff, axis=-1)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


def pairwise_sq_dists_dot(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """[Q, D], [N, D] -> [Q, N] squared distances via the MXU-friendly
    ``|q|^2 - 2 q·t + |t|^2`` expansion, clamped at 0."""
    q2 = jnp.sum(queries * queries, axis=-1, keepdims=True)  # [Q, 1]
    t2 = jnp.sum(train * train, axis=-1)[None, :]  # [1, N]
    cross = queries @ train.T  # [Q, N] — MXU
    d = jnp.maximum(q2 + t2 - 2.0 * cross, 0.0)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


def pairwise_sq_dists_bf16(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """Dot-form distances with bfloat16 MXU operands (float32 accumulation):
    2x matmul throughput at ~3 fewer mantissa digits in the cross term. The
    norm terms stay float32."""
    q2 = jnp.sum(queries * queries, axis=-1, keepdims=True)
    t2 = jnp.sum(train * train, axis=-1)[None, :]
    cross = jnp.dot(
        queries.astype(jnp.bfloat16),
        train.astype(jnp.bfloat16).T,
        preferred_element_type=jnp.float32,
    )
    d = jnp.maximum(q2 + t2 - 2.0 * cross, 0.0)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


def pairwise_manhattan(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """[Q, D], [N, D] -> [Q, N] L1 (cityblock) distances. A metric extension —
    the reference hard-codes squared Euclidean (main.cpp:14-23)."""
    d = jnp.sum(jnp.abs(queries[:, None, :] - train[None, :, :]), axis=-1)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


def pairwise_chebyshev(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """[Q, D], [N, D] -> [Q, N] L-inf distances (max coordinate gap)."""
    if queries.shape[-1] == 0:  # max has no identity; zero features -> dist 0
        return jnp.zeros((queries.shape[0], train.shape[0]), jnp.float32)
    d = jnp.max(jnp.abs(queries[:, None, :] - train[None, :, :]), axis=-1)
    return jnp.where(jnp.isnan(d), jnp.inf, d)


def pairwise_cosine(queries: jnp.ndarray, train: jnp.ndarray) -> jnp.ndarray:
    """[Q, D], [N, D] -> [Q, N] cosine distances ``1 - q·t/(|q||t|)``; the
    cross term rides the MXU. Zero vectors get distance 1 (orthogonal-like)."""
    qn = jnp.sqrt(jnp.sum(queries * queries, axis=-1, keepdims=True))
    tn = jnp.sqrt(jnp.sum(train * train, axis=-1))[None, :]
    cross = queries @ train.T
    denom = qn * tn
    sim = jnp.where(denom > 0, cross / jnp.where(denom > 0, denom, 1.0), 0.0)
    d = 1.0 - sim
    # NaN features poison cross/denom, and `denom > 0` is False for NaN —
    # without an explicit check those rows would land at d=1.0 instead of
    # following the framework-wide NaN -> +inf policy.
    bad = jnp.isnan(cross) | jnp.isnan(denom) | jnp.isnan(d)
    return jnp.where(bad, jnp.inf, d)


# Distance-form registry. The first three are *forms of squared Euclidean*
# (reference semantics at different speed/accuracy points); the rest are
# metric extensions selected via ``metric=`` (resolve_form).
_DIST_FNS = {
    "exact": pairwise_sq_dists,
    "fast": pairwise_sq_dists_dot,
    "bf16": pairwise_sq_dists_bf16,
    "manhattan": pairwise_manhattan,
    "chebyshev": pairwise_chebyshev,
    "cosine": pairwise_cosine,
}

METRICS = ("euclidean", "manhattan", "chebyshev", "cosine")


def resolve_form(precision: str, metric: str = "euclidean") -> str:
    """Map (metric, precision) onto a ``_DIST_FNS`` key. Euclidean honors the
    precision forms (exact/fast/bf16); every other metric has one form and
    rejects a non-default precision rather than silently ignoring it."""
    if metric in (None, "euclidean"):
        return precision
    if metric not in _DIST_FNS:
        raise ValueError(f"unknown metric {metric!r}; choose from {METRICS}")
    if precision not in ("exact", "auto"):
        raise ValueError(
            f"metric {metric!r} has a single implementation; precision "
            f"{precision!r} does not apply"
        )
    return metric
