"""Device-resident IVF candidate gather + score + select.

The host IVF scorer (``knn_tpu/index/ivf.py``) gathers every probed
cell's rows into a ``[B, M, D]`` block and einsums it on the host — the
last host-resident inner loop of the approximate serving path (ROADMAP
item 2). This module is its device twin, the accelerator-resident IVF
shape of Johnson et al.'s billion-scale search (PAPERS.md):

- the cell-sort already makes probed rows contiguous in the permuted
  train copy, so the gather is ONE ``jnp.take`` over flattened
  (query, cell) segment offsets — no per-probe host slicing;
- candidate distances are the subtraction-form squared euclidean
  (``ops/distance.py`` exact semantics) fused with the gather, and
  selection is ``lax.sort`` with TWO keys — (distance, train index) —
  the in-kernel realization of the ``models/ordering.py`` tie contract;
- the candidate axis pads to the ``models/knn.candidate_padded_rows``
  bucket ladder and queries to ``query_padded_rows``, so compiled
  shapes are reused across dispatches and the executable-cache key,
  the pad, and the waste accounting all read the one definition.

Bit-identity strategy (the ``nprobe == num_cells`` pin): float32
reductions cannot be made bit-equal across numpy and XLA (different
partial-sum association), so the kernel does NOT try — it selects a
small SAFETY MARGIN of extra candidates (``RERANK_PAD``) by device
distances, and the caller re-scores exactly those survivors on the host
with the oracle's own einsum form and selects the final top-k through
``lexicographic_topk`` (einsum per-pair values are shape-invariant, so
the re-ranked distances are bit-identical to the host scorer's). Device
LSB error can demote a true top-k candidate past the margin only if
``RERANK_PAD`` candidates sit within ~1 ulp of each other — exact ties
(duplicate rows) are safe outright because both implementations give
them exactly equal values and the two-key sort breaks them by index.
This is the classic IVF exact-re-rank split (Jégou et al., PAPERS.md):
the O(B·M·D) work rides the device, the O(B·k·D) finish stays exact.

The optional **delta tail** operands fuse the mutable tier's
device-resident delta block (``knn_tpu/mutable/device_tail.py``) into
the SAME selection: delta rows are scored beside the probed candidates
and the one two-key sort covers base+delta — no per-batch host merge.
"""

from __future__ import annotations

import functools

import jax
import numpy as np

#: Extra candidates the device selection keeps beyond k for the host
#: exact re-rank (see the module docstring's bit-identity strategy).
RERANK_PAD = 32


def margin_select(d, ids, kk: int, row_ok=None):
    """Top-``kk`` survivors of ``(d [B, W], ids [B, W])`` for the host
    exact re-rank — the in-kernel selection every device scorer shares
    (traced; callers jit).

    Fast path: ``lax.top_k`` by distance (≈25x cheaper than a full
    two-key sort at serving widths). top_k breaks value ties by
    POSITION, not by train index — safe exactly when no distance
    plateau crosses the selection boundary, because then the selected
    set is ALL candidates with ``d <= kk-th smallest`` (a superset of
    the true (distance, index) top-k whatever the within-plateau
    order; the host re-rank restores the exact order). The in-kernel
    detector counts candidates at-or-under the boundary distance:
    ``count > kk`` means a plateau crossed (adversarial ties, all-inf
    NaN rows) and ``lax.cond`` routes to the exact two-key
    ``lax.sort`` branch — still on device, no host sync, correctness
    never depends on the heuristic. ``row_ok [B] bool`` masks rows out
    of the detector (the bucket ladder's PAD query rows are all-inf by
    construction and their results are sliced off — without the mask
    every padded dispatch would ride the slow branch)."""
    import jax.numpy as jnp
    from jax import lax

    from knn_tpu.models.ordering import lexicographic_topk_jax

    def exact(_):
        sd, si = lexicographic_topk_jax(d, ids, kk)
        return sd, si

    if kk >= d.shape[1]:
        return exact(None)
    neg, pos = lax.top_k(-d, kk)
    sd = -neg
    si = jnp.take_along_axis(ids, pos, axis=1)
    per_row = jnp.sum(d <= sd[:, -1:], axis=1) > kk
    if row_ok is not None:
        per_row = per_row & row_ok
    return lax.cond(jnp.any(per_row), exact, lambda _: (sd, si), None)


def delta_columns(queries, delta_rows, delta_dead, base_n, count):
    """Score the device-resident delta tail (traced): ``(dd [B, cap],
    di [B, cap])`` — subtraction-form squared euclidean per slot, a slot
    live when below ``count`` and not dead, dead/pad slots masked to
    ``(+inf, sentinel = base_n + count)``. THE one definition of the
    delta liveness/sentinel rule shared by the fused ivf kernel
    (:func:`_segment_topk_delta_core`) and the exact rungs' merge
    (``mutable/device_tail._delta_merge_core``)."""
    import jax.numpy as jnp

    ddiff = queries[:, None, :] - delta_rows[None, :, :]
    dd = jnp.sum(ddiff * ddiff, axis=-1)                 # [B, cap]
    slot = jnp.arange(delta_rows.shape[0], dtype=jnp.int32)
    live = (slot < count) & ~delta_dead
    dd = jnp.where(jnp.isnan(dd) | ~live[None, :], jnp.inf, dd)
    sentinel = (base_n + count).astype(jnp.int32)
    di = jnp.where(live, base_n.astype(jnp.int32) + slot, sentinel)
    return dd, jnp.broadcast_to(di[None, :], dd.shape), sentinel


def _segment_scores(perm_rows, perm_ids, queries, starts, lens, m_pad):
    """Gather + score the probed segments (traced): ``(d [B, m_pad],
    ids [B, m_pad])`` with pad slots at (+inf, N)."""
    import jax
    import jax.numpy as jnp

    ends = jnp.cumsum(lens, axis=1)                      # [B, P]
    total = ends[:, -1:]                                 # [B, 1]
    pos = jnp.arange(m_pad, dtype=lens.dtype)            # [M]
    # Which probed segment does flat slot m fall into? Small probe
    # counts take one vectorized compare-sum (measured ~40x faster than
    # batched searchsorted at P=8); wide probes keep the O(M log P)
    # searchsorted.
    if lens.shape[1] <= 32:
        seg = jnp.sum(pos[None, :, None] >= ends[:, None, :],
                      axis=2).astype(lens.dtype)
    else:
        seg = jax.vmap(
            lambda e: jnp.searchsorted(e, pos, side="right"))(ends)
    seg_c = jnp.minimum(seg, lens.shape[1] - 1)
    seg_start = jnp.take_along_axis(starts, seg_c, axis=1)
    seg_base = jnp.take_along_axis(ends - lens, seg_c, axis=1)
    src = seg_start + pos[None, :] - seg_base            # [B, M] perm pos
    valid = pos[None, :] < total
    src = jnp.where(valid, src, perm_rows.shape[0] - 1)  # the pad row
    ids = perm_ids[src]                                  # [B, M]
    gathered = perm_rows[src]                            # [B, M, D]
    diff = queries[:, None, :] - gathered
    d = jnp.sum(diff * diff, axis=-1)
    d = jnp.where(jnp.isnan(d) | ~valid, jnp.inf, d)
    return d, ids


@functools.partial(jax.jit, static_argnames=("m_pad", "kk"))
def _segment_topk_core(perm_rows, perm_ids, queries, starts, lens,
                       row_ok, m_pad, kk):
    """One fused gather+score+select dispatch.

    ``perm_rows [N+1, D]`` — cell-sorted train rows plus one zero pad
    row; ``perm_ids [N+1] int32`` — original train index per permuted
    row, pad slot carrying the sentinel ``N``; ``queries [B, D]``;
    ``starts/lens [B, P] int32`` — each query's probed segments in
    permutation space; ``row_ok [B]`` — False for bucket-pad query
    rows. Returns ``(dists [B, kk] f32, ids [B, kk] i32)`` — the
    margin-selected survivors (see :func:`margin_select`)."""
    d, ids = _segment_scores(perm_rows, perm_ids, queries, starts, lens,
                             m_pad)
    return margin_select(d, ids, kk, row_ok=row_ok)


@functools.partial(jax.jit, static_argnames=("m_pad", "kk"))
def _segment_topk_delta_core(perm_rows, perm_ids, queries, starts, lens,
                             row_ok, delta_rows, delta_dead, base_n,
                             count, m_pad, kk):
    """:func:`_segment_topk_core` with the mutable delta tail fused in:
    ``delta_rows [cap, D]`` is the device-resident delta buffer,
    ``delta_dead [cap] bool`` its tombstone mask (a slot is live when
    below ``count`` and not dead), and delta candidates carry positional
    ids ``base_n + slot`` (dead/pad slots the past-everything sentinel
    ``base_n + count``) so the ONE selection ranks base and delta
    together under the shared tie contract."""
    import jax.numpy as jnp

    bd, bi = _segment_scores(perm_rows, perm_ids, queries, starts, lens,
                             m_pad)
    dd, di, sentinel = delta_columns(queries, delta_rows, delta_dead,
                                     base_n, count)
    # Probed base candidates carry raw train indices < base_n; pad slots
    # carry N == base_n which collides with delta slot 0 — remap base
    # pads to the sentinel before the merged selection.
    bi = jnp.where(bi >= base_n.astype(jnp.int32), sentinel, bi)
    all_d = jnp.concatenate([bd, dd], axis=1)
    all_i = jnp.concatenate([bi, di], axis=1)
    return margin_select(all_d, all_i, kk, row_ok=row_ok)


def device_operands(train_x: np.ndarray, row_perm: np.ndarray):
    """Build the device-resident permuted-train operands: ``(perm_rows
    [N+1, D] f32, perm_ids [N+1] i32)`` with the pad row zero and the
    pad id ``N`` (the sentinel the scorer masks to +inf). One upload per
    (train, partition) pair — the caller memoizes."""
    import jax.numpy as jnp

    n = train_x.shape[0]
    if n >= 2 ** 31 - 1:
        raise ValueError(
            f"device IVF scorer indexes rows in int32; {n} rows need the "
            f"host scorer")
    rows = np.concatenate(
        [np.ascontiguousarray(train_x[row_perm], np.float32),
         np.zeros((1, train_x.shape[1]), np.float32)])
    ids = np.concatenate(
        [np.asarray(row_perm, np.int64), [n]]).astype(np.int32)
    return jnp.asarray(rows), jnp.asarray(ids)


def segment_topk(perm_rows, perm_ids, queries: np.ndarray,
                 starts: np.ndarray, lens: np.ndarray, m_actual: int,
                 k: int, tail=None):
    """Host entry: pad to the compiled-shape ladders, dispatch, fetch.

    ``queries [B, D]`` host float32; ``starts/lens [B, P]`` the probed
    segments (permutation-space start + length per probe); ``m_actual``
    the batch's largest per-query candidate count. ``tail`` — an
    optional :class:`~knn_tpu.mutable.device_tail.DeviceTailView` whose
    delta block is fused into the same selection. Returns ``(dists
    [B, kk] f32, ids [B, kk] i64)`` — the device's top-(k+margin)
    survivors for the host exact re-rank, NOT the final answer.
    """
    import jax
    import jax.numpy as jnp

    from knn_tpu import obs
    from knn_tpu.models.knn import candidate_padded_rows, query_padded_rows

    b, d_feat = queries.shape
    m_pad = max(candidate_padded_rows(m_actual), 1)
    b_pad = max(query_padded_rows(b), 1)
    width = m_pad + (tail.features.shape[0] if tail is not None else 0)
    kk = min(k + RERANK_PAD, width)
    if obs.enabled():
        from knn_tpu.obs import devprof

        devprof.record_executable_lookup("retrieval", (
            "ivf-segment", b_pad, lens.shape[1], m_pad, d_feat, kk,
            tail.features.shape[0] if tail is not None else 0,
        ))
    qx = queries
    if b_pad != b:
        qx = np.zeros((b_pad, d_feat), np.float32)
        qx[:b] = queries
    sl = np.zeros((b_pad, lens.shape[1]), np.int32)
    st = np.zeros((b_pad, lens.shape[1]), np.int32)
    sl[:b] = lens
    st[:b] = starts
    row_ok = jnp.asarray(np.arange(b_pad) < b)
    if tail is None:
        sd, si = _segment_topk_core(
            perm_rows, perm_ids, jnp.asarray(qx), jnp.asarray(st),
            jnp.asarray(sl), row_ok, m_pad=m_pad, kk=kk)
    else:
        sd, si = _segment_topk_delta_core(
            perm_rows, perm_ids, jnp.asarray(qx), jnp.asarray(st),
            jnp.asarray(sl), row_ok, tail.features, tail.dead,
            jnp.asarray(tail.base_n, jnp.int32),
            jnp.asarray(tail.count, jnp.int32), m_pad=m_pad, kk=kk)
    d_h, i_h = jax.device_get((sd, si))
    return d_h[:b], i_h[:b].astype(np.int64)


@functools.partial(jax.jit, static_argnames=("need",))
def _rank_cells_core(queries, centroids, need):
    from knn_tpu.ops.distance import pairwise_sq_dists_dot
    from knn_tpu.ops.topk import approx_smallest_indices

    d = pairwise_sq_dists_dot(queries, centroids)
    return approx_smallest_indices(d, need)


def rank_cells_approx(queries: np.ndarray, centroids_dev,
                      need: int) -> np.ndarray:
    """Approximate top-``need`` centroid ranking on device:
    ``lax.approx_max_k`` over matmul-form centroid distances (ranking
    only — probed candidates are still scored exactly, so this trades
    recall, never correctness). Used once ``num_cells`` crosses the
    ``index/ivf.py`` threshold; exact ranking keeps the small-C path."""
    import jax.numpy as jnp

    from knn_tpu import obs

    if obs.enabled():
        from knn_tpu.obs import devprof

        devprof.record_executable_lookup("retrieval", (
            "ivf-rank-approx", queries.shape[0],
            centroids_dev.shape[0], need,
        ))
    out = _rank_cells_core(jnp.asarray(queries), centroids_dev, need)
    return np.asarray(out).astype(np.int64)
