from knn_tpu.ops.distance import pairwise_sq_dists, pairwise_sq_dists_dot
from knn_tpu.ops.topk import (
    topk_smallest,
    merge_topk,
    merge_topk_labeled,
    sort_candidates_labeled,
)
from knn_tpu.ops.vote import vote

__all__ = [
    "pairwise_sq_dists",
    "pairwise_sq_dists_dot",
    "topk_smallest",
    "merge_topk",
    "merge_topk_labeled",
    "sort_candidates_labeled",
    "vote",
]
