from knn_tpu.ops.distance import pairwise_sq_dists, pairwise_sq_dists_dot
from knn_tpu.ops.topk import topk_smallest, merge_topk
from knn_tpu.ops.vote import vote

__all__ = [
    "pairwise_sq_dists",
    "pairwise_sq_dists_dot",
    "topk_smallest",
    "merge_topk",
    "vote",
]
