"""Compile-time top-k merge networks for the stripe kernel's selection.

The stripe kernel keeps one k-candidate stripe per lane and must, per train
tile, fold ``g`` fresh distance planes (128-column chunks of the tile) into
the running candidates. The round-based formulation pays k passes over all
``g + k`` planes per tile — a min-reduction, an index-select pass, and a
retirement pass each round (``O(4 k (g + k))`` VPU ops). This module
generates the cheaper structure: a **truncated odd-even merge network** —
a tournament of Batcher merges that sorts the fresh planes' per-lane top-k
and merges them with the (sorted) running candidates, with every
compare-exchange whose outputs cannot reach the kept k wires pruned away
(``O(g + k log^2 k)`` comparators, each a handful of elementwise ops).

A network is a list of compare-exchange (CE) ops over *wires*; each wire
holds one ``(distance, index)`` plane. A CE orders two wires by the
lexicographic ``(d, i)`` key — the reference's first-seen-wins tie rule
(main.cpp:47) — so the network needs no retirement passes and no finiteness
gating: ties, +inf padding and NaN-policy +inf distances all flow through
the total order. Correctness is validated exhaustively in the test suite by
the 0-1 principle (a comparator network that sorts every 0-1 input sorts
every input), which covers the truncation because top-k of a union equals
top-k of the unions' top-k's.

Programs are pure Python data generated at trace time and memoized per
``(g, k)``; the kernel emits the corresponding jnp ops.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

# A CE op: (wire_a, wire_b, kind, ordered). After the op, wire_a holds the
# lexicographic min of the two inputs and wire_b the max. ``kind`` marks
# which outputs later ops actually read: "full" (both), "lo" (only the
# min — the max write may be skipped), "hi" (only the max). ``ordered``
# marks leaf CEs between two untouched fresh wires: there the per-lane
# indices are statically ascending (plane order IS index order within a
# lane), so the tie-break half of the swap predicate is constant-false and
# the kernel can emit ``swap = (b.d < a.d)`` alone.
CeOp = Tuple[int, int, str, bool]


def _merge(a: Sequence[int], b: Sequence[int], ops: List[Tuple[int, int]]):
    """Batcher odd-even merge of two sorted wire lists (arbitrary lengths),
    appending CE ops; returns the merged wire order."""
    if not a:
        return list(b)
    if not b:
        return list(a)
    if len(a) == 1 and len(b) == 1:
        ops.append((a[0], b[0]))
        return [a[0], b[0]]
    evens = _merge(a[0::2], b[0::2], ops)
    odds = _merge(a[1::2], b[1::2], ops)
    # Interleave evens/odds and fix up adjacent (odd, next-even) pairs —
    # the classic construction (TAOCP 5.3.4); validated exhaustively by the
    # 0-1 principle in tests/test_topk_net.py for every size in use.
    out = [evens[0]]
    for t in range(len(odds)):
        if t + 1 < len(evens):
            ops.append((odds[t], evens[t + 1]))
            out.append(odds[t])
            out.append(evens[t + 1])
        else:
            out.append(odds[t])
    out.extend(evens[len(odds) + 1 :])
    return out


def _prune(
    ops: Sequence[Tuple[int, int]], keep: Sequence[int], n_fresh: int
) -> List[CeOp]:
    """Drop CEs whose outputs can never reach the kept wires, mark the
    survivors with which side is consumed (a one-sided CE emits fewer
    elementwise ops in the kernel), and flag ordered leaf CEs (see CeOp)."""
    live = set(keep)
    kept: List[CeOp] = []
    for a, b in reversed(ops):
        a_live, b_live = a in live, b in live
        if not (a_live or b_live):
            continue
        kind = "full" if (a_live and b_live) else ("lo" if a_live else "hi")
        kept.append((a, b, kind))
        live.add(a)
        live.add(b)
    kept.reverse()
    # Forward pass for the ordered flag: a CE is ordered when both wires are
    # fresh planes (wire id < n_fresh), untouched so far, and a < b — per
    # lane, fresh plane indices ascend with the wire id.
    virgin = set(range(n_fresh))
    out: List[CeOp] = []
    for a, b, kind in kept:
        ordered = a in virgin and b in virgin and a < b
        virgin.discard(a)
        virgin.discard(b)
        out.append((a, b, kind, ordered))
    return out


@functools.lru_cache(maxsize=None)
def tile_topk_program(g: int, k: int) -> Tuple[Tuple[CeOp, ...], Tuple[int, ...]]:
    """The per-train-tile selection program: wires ``0..g-1`` are the fresh
    distance planes (unsorted singletons), wires ``g..g+k-1`` the running
    candidate levels (sorted ascending per lane). Returns ``(ops,
    out_wires)``: after executing ``ops`` in order, the ``k`` wires in
    ``out_wires`` hold the new sorted running candidates — the per-lane
    lexicographic top-k of all ``g + k`` inputs."""
    ops: List[Tuple[int, int]] = []
    lists: List[List[int]] = [[w] for w in range(g)]
    while len(lists) > 1:
        nxt: List[List[int]] = []
        for i in range(0, len(lists) - 1, 2):
            # Truncate every intermediate list at k: top-k of a union is
            # top-k of the union of top-k's.
            nxt.append(_merge(lists[i], lists[i + 1], ops)[:k])
        if len(lists) % 2:
            nxt.append(lists[-1])
        lists = nxt
    fresh = lists[0][:k]
    running = list(range(g, g + k))
    out = _merge(fresh, running, ops)[:k]
    return tuple(_prune(ops, out, g)), tuple(out)


def program_cost(ops: Sequence[CeOp]) -> int:
    """Elementwise-op estimate for a program (full CE ~9 VPU ops, one-sided
    ~7; ordered CEs save the 4-op tie-break predicate). This is HALF OF THE
    KERNEL'S ROUTING PREDICATE: _knn_stripe_kernel picks the network iff
    ``program_cost(ops) < rounds_cost(g, k, lite)`` at trace time, so the
    weights here are load-bearing — change them and selection routing
    flips."""
    return sum(
        (9 if kind == "full" else 7) - (4 if ordered else 0)
        for _, _, kind, ordered in ops
    )


def rounds_cost(g: int, k: int, lite: bool = True) -> int:
    """Elementwise-op estimate for the legacy round-based selection the
    stripe kernel routes against: k rounds over ``n = g + k`` planes, each a
    d min-tree (n-1), an index-select pass (3n-1), and — before the last
    round — retirement (2n lite, 3n full). The kernel picks the network
    whenever :func:`program_cost` beats this; at k <= 2 two cheap passes
    beat fused (d, i) comparators and the rounds stay."""
    n = g + k
    return k * (4 * n - 2) + (k - 1) * (2 if lite else 3) * n


def simulate(ops: Sequence[CeOp], values: list) -> list:
    """Run a program on host scalars (pure Python, for tests): ``values`` is
    a list of (d, i) tuples indexed by wire. One-sided ops still write both
    wires — kind only marks which side later ops read, so writing both is
    semantics-preserving — keeping the simulation faithful to pruning. The
    ordered flag is honored the way the kernel honors it (no index
    tie-break), so a wrongly-flagged op would surface as a wrong result."""
    vals = list(values)
    for a, b, kind, ordered in ops:
        va, vb = vals[a], vals[b]
        swap = (vb[0] < va[0]) if ordered else (vb < va)
        vals[a], vals[b] = (vb, va) if swap else (va, vb)
    return vals
