"""Compile-time top-k merge networks for the stripe kernel's selection.

The stripe kernel keeps one k-candidate stripe per lane and must, per train
tile, fold ``g`` fresh distance planes (128-column chunks of the tile) into
the running candidates. The round-based formulation pays k passes over all
``g + k`` planes per tile — a min-reduction, an index-select pass, and a
retirement pass each round (``O(4 k (g + k))`` VPU ops). This module
generates the cheaper structure: a **truncated odd-even merge network** —
a tournament of Batcher merges that sorts the fresh planes' per-lane top-k
and merges them with the (sorted) running candidates, with every
compare-exchange whose outputs cannot reach the kept k wires pruned away
(``O(g + k log^2 k)`` comparators, each a handful of elementwise ops).

A network is a list of compare-exchange (CE) ops over *wires*; each wire
holds one ``(distance, index)`` plane. A CE orders two wires by the
lexicographic ``(d, i)`` key — the reference's first-seen-wins tie rule
(main.cpp:47) — so the network needs no retirement passes: ties, +inf
padding and NaN-policy +inf distances all flow through the total order.
(The ``finite=True`` program VARIANT goes further: it resolves tie
predicates using dominance facts that hold only under the kernel's
``assume_finite`` gate — see :func:`tile_topk_program`.) Correctness is
validated exhaustively in the test suite by the 0-1 principle (a
comparator network that sorts every 0-1 input sorts every input), which
covers the truncation because top-k of a union equals top-k of the
unions' top-k's, plus dense-tie fuzzing and multi-tile stream simulation
for the tie modes.

Programs are pure Python data generated at trace time and memoized per
``(g, k)``; the kernel emits the corresponding jnp ops.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

# A CE op: (wire_a, wire_b, kind, tie). After the op, wire_a holds the
# lexicographic min of the two inputs and wire_b the max. ``kind`` marks
# which outputs later ops actually read: "full" (both), "lo" (only the
# min — the max write may be skipped), "hi" (only the max).
#
# ``tie`` encodes what the kernel must emit for the swap predicate:
#   "full" — the generic lexicographic predicate
#            ``(b.d < a.d) | ((b.d == a.d) & (b.i < a.i))``  (4 VPU ops)
#   "a"    — wire a is PROVEN to tie-dominate b (on equal distances a's
#            index is <= b's in every lane for every input), so the
#            tie-break term is constant-false: ``swap = (b.d < a.d)`` (1 op)
#   "b"    — b tie-dominates a: on ties b must win the min slot:
#            ``swap = (b.d <= a.d)``                          (1 op)
#
# Tie dominance is tracked exactly (the matrix pass inside _prune): it
# starts from the kernel's input invariants — fresh planes' per-lane
# indices ascend with the wire id; running levels are (d, i)-sorted; and
# under the finite-inputs gate the running candidates additionally
# tie-dominate every fresh plane (candidates carry earlier tiles'
# indices, and +inf implies the INT_MAX sentinel on both sides) — and
# propagates through each CE: both outputs of a correct CE are
# tie-ordered (the min side takes the smaller index on ties), and a
# third wire keeps its relation to an output only when it related the
# same way to BOTH inputs.
CeOp = Tuple[int, int, str, str]


def _merge(a: Sequence[int], b: Sequence[int], ops: List[Tuple[int, int]]):
    """Batcher odd-even merge of two sorted wire lists (arbitrary lengths),
    appending CE ops; returns the merged wire order."""
    if not a:
        return list(b)
    if not b:
        return list(a)
    if len(a) == 1 and len(b) == 1:
        ops.append((a[0], b[0]))
        return [a[0], b[0]]
    evens = _merge(a[0::2], b[0::2], ops)
    odds = _merge(a[1::2], b[1::2], ops)
    # Interleave evens/odds and fix up adjacent (odd, next-even) pairs —
    # the classic construction (TAOCP 5.3.4); validated exhaustively by the
    # 0-1 principle in tests/test_topk_net.py for every size in use.
    out = [evens[0]]
    for t in range(len(odds)):
        if t + 1 < len(evens):
            ops.append((odds[t], evens[t + 1]))
            out.append(odds[t])
            out.append(evens[t + 1])
        else:
            out.append(odds[t])
    out.extend(evens[len(odds) + 1 :])
    return out


def _prune(
    ops: Sequence[Tuple[int, int]], keep: Sequence[int], n_fresh: int,
    n_wires: int, finite: bool,
) -> List[CeOp]:
    """Drop CEs whose outputs can never reach the kept wires, mark the
    survivors with which side is consumed (a one-sided CE emits fewer
    elementwise ops in the kernel), and resolve each survivor's tie mode
    from the exact tie-dominance matrix (see CeOp)."""
    live = set(keep)
    kept: List[Tuple[int, int, str]] = []
    for a, b in reversed(ops):
        a_live, b_live = a in live, b in live
        if not (a_live or b_live):
            continue
        kind = "full" if (a_live and b_live) else ("lo" if a_live else "hi")
        kept.append((a, b, kind))
        live.add(a)
        live.add(b)
    kept.reverse()

    # Tie-dominance matrix T: T[x][y] means "for every input and lane,
    # equal distances on x and y imply x's index <= y's" at the current
    # point of the program. Initial facts from the kernel's invariants:
    #  - fresh wire indices ascend with wire id (base + w*128 + lane), so
    #    T[x][y] for fresh x < y — unconditionally (this subsumes the old
    #    virgin-leaf rule and survives propagation);
    #  - with finite inputs (the kernel's assume_finite gate): running
    #    candidates tie-dominate every fresh plane (their real indices come
    #    from earlier tiles, and +inf distance implies the INT_MAX index
    #    sentinel on BOTH sides — without the gate a NaN-policy +inf can
    #    carry a real index and the relation breaks), and running levels
    #    tie-dominate each other in level order (they are (d, i)-sorted).
    T = [[False] * n_wires for _ in range(n_wires)]
    for x in range(n_fresh):
        for y in range(x + 1, n_fresh):
            # Holds even under the NaN policy: within a lane, invalidity
            # (the INT_MAX sentinel) is monotone in the wire id — a later
            # fresh wire's global column is strictly larger, so it cannot
            # be valid where an earlier one is not.
            T[x][y] = True
    for r1 in range(n_fresh, n_wires):
        for r2 in range(r1 + 1, n_wires):
            # Levels are (d, i)-sorted per lane: equal d implies i order.
            T[r1][r2] = True
    if finite:
        for r in range(n_fresh, n_wires):
            for f in range(n_fresh):
                T[r][f] = True

    out: List[CeOp] = []
    for a, b, kind in kept:
        if T[a][b]:
            tie = "a"
        elif T[b][a]:
            tie = "b"
        else:
            tie = "full"
        out.append((a, b, kind, tie))
        # Propagate: outputs a' (lex min) and b' (lex max). A third wire c
        # keeps a relation to an output only if it held it against BOTH
        # inputs (the output's (d, i) pair is one of the two, data-
        # dependently). The outputs themselves are always tie-ordered
        # after a correct CE (on ties the min slot takes the smaller
        # index), whatever the predicate used.
        for c in range(n_wires):
            if c == a or c == b:
                continue
            below = T[a][c] and T[b][c]
            above = T[c][a] and T[c][b]
            T[a][c] = T[b][c] = below
            T[c][a] = T[c][b] = above
        T[a][b] = True
        T[b][a] = False
    return out


@functools.lru_cache(maxsize=None)
def tile_topk_program(
    g: int, k: int, finite: bool = False
) -> Tuple[Tuple[CeOp, ...], Tuple[int, ...]]:
    """The per-train-tile selection program: wires ``0..g-1`` are the fresh
    distance planes (unsorted singletons), wires ``g..g+k-1`` the running
    candidate levels (sorted ascending per lane). Returns ``(ops,
    out_wires)``: after executing ``ops`` in order, the ``k`` wires in
    ``out_wires`` hold the new sorted running candidates — the per-lane
    lexicographic top-k of all ``g + k`` inputs.

    ``finite`` — set iff the kernel's ``assume_finite`` gate holds — admits
    the running-candidate tie-dominance facts (see _prune), which prove
    most CEs' tie-break terms constant and shrink the program's VPU cost
    ~2x. Programs generated with ``finite=True`` are only exact under the
    gate's input guarantee (a NaN-policy +inf distance paired with a real
    index violates the candidate/fresh dominance the proof uses)."""
    ops: List[Tuple[int, int]] = []
    lists: List[List[int]] = [[w] for w in range(g)]
    while len(lists) > 1:
        nxt: List[List[int]] = []
        for i in range(0, len(lists) - 1, 2):
            # Truncate every intermediate list at k: top-k of a union is
            # top-k of the union of top-k's.
            nxt.append(_merge(lists[i], lists[i + 1], ops)[:k])
        if len(lists) % 2:
            nxt.append(lists[-1])
        lists = nxt
    fresh = lists[0][:k]
    running = list(range(g, g + k))
    out = _merge(fresh, running, ops)[:k]
    return tuple(_prune(ops, out, g, g + k, finite)), tuple(out)


def program_cost(ops: Sequence[CeOp]) -> int:
    """Elementwise-op estimate for a program (full CE ~9 VPU ops, one-sided
    ~7; a resolved tie mode replaces the 4-op tie-break predicate with one
    compare). This is HALF OF THE KERNEL'S ROUTING PREDICATE:
    _knn_stripe_kernel picks the network iff ``program_cost(ops) <
    rounds_cost(g, k, lite)`` at trace time, so the weights here are
    load-bearing — change them and selection routing flips."""
    return sum(
        (9 if kind == "full" else 7) - (4 if tie != "full" else 0)
        for _, _, kind, tie in ops
    )


def rounds_cost(g: int, k: int, lite: bool = True) -> int:
    """Elementwise-op estimate for the legacy round-based selection the
    stripe kernel routes against: k rounds over ``n = g + k`` planes, each a
    d min-tree (n-1), an index-select pass (3n-1), and — before the last
    round — retirement (2n lite, 3n full). The kernel picks the network
    whenever :func:`program_cost` beats this; at k <= 2 two cheap passes
    beat fused (d, i) comparators and the rounds stay."""
    n = g + k
    return k * (4 * n - 2) + (k - 1) * (2 if lite else 3) * n


def simulate(ops: Sequence[CeOp], values: list) -> list:
    """Run a program on host scalars (pure Python, for tests): ``values`` is
    a list of (d, i) tuples indexed by wire. One-sided ops still write both
    wires — kind only marks which side later ops read, so writing both is
    semantics-preserving — keeping the simulation faithful to pruning. The
    tie mode is honored exactly the way the kernel emits it ("a": plain
    strict compare; "b": <=; "full": lexicographic), so a wrongly-resolved
    tie mode surfaces as a wrong result."""
    vals = list(values)
    for a, b, kind, tie in ops:
        va, vb = vals[a], vals[b]
        if tie == "a":
            swap = vb[0] < va[0]
        elif tie == "b":
            swap = vb[0] <= va[0]
        else:
            swap = vb < va
        vals[a], vals[b] = (vb, va) if swap else (va, vb)
    return vals
