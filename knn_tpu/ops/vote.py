"""Majority vote over neighbor labels.

Replaces the reference's bincount + strict-``>`` argmax (main.cpp:64-78):
ties in the vote break to the *lowest* class id, which ``jnp.argmax`` (first
occurrence of the max) reproduces exactly.
"""

from __future__ import annotations

import jax.numpy as jnp


def vote(neighbor_labels: jnp.ndarray, num_classes: int) -> jnp.ndarray:
    """[..., k] int labels -> [...] int32 predicted class.

    One-hot segment-sum bincount over the class axis, then argmax (first max
    wins → lowest class id on ties, matching main.cpp:69-76).
    """
    one_hot = (neighbor_labels[..., None] == jnp.arange(num_classes)).astype(jnp.int32)
    counts = one_hot.sum(axis=-2)  # [..., num_classes]
    return jnp.argmax(counts, axis=-1).astype(jnp.int32)
