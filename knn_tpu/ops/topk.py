"""Index-stable k-smallest selection and candidate-set merging.

The reference keeps a sorted k-candidate array with strict ``<`` insertion
(main.cpp:46-61): among equal distances the earliest-scanned train index wins.
The equivalents here:

- :func:`topk_smallest` — ``lax.top_k`` on negated distances; top_k breaks
  value ties by lowest position, which equals lowest train index when the
  distance row is laid out in train order. Matches first-seen-wins.
- :func:`merge_topk` — merge two candidate sets (e.g. running state + a new
  train tile, or candidate sets gathered from shards) with an explicit
  lexicographic ``(distance, global_index)`` sort via ``lax.sort`` with
  ``num_keys=2``. This keeps tie-breaking correct even when candidates arrive
  out of global-index order (the ring schedule rotates shards, so positional
  tie-breaking would be wrong there — SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def topk_smallest(
    dists: jnp.ndarray, k: int, index_base: int | jnp.ndarray = 0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[..., N] distances -> ([..., k] dists, [..., k] int32 global indices),
    sorted ascending by (distance, index). ``index_base`` offsets local column
    positions into global train-row indices (for tiles/shards)."""
    neg, idx = lax.top_k(-dists, k)
    return -neg, (idx + index_base).astype(jnp.int32)


def sort_candidates_labeled(
    dists: jnp.ndarray, idx: jnp.ndarray, labels: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort (distance, global-index, label) triples lexicographically by
    (distance, index) along the last axis — the tie-break rule every merging
    path shares. Two sanctioned realizations exist: this two-key sort, and
    ``ops/pallas_knn._merge_topk_rounds`` (k rounds of min-extraction over
    the same keys — cheaper when only the k best are needed). Any change to
    the tie semantics must update both."""
    return lax.sort((dists, idx, labels), dimension=-1, num_keys=2)


def merge_topk_labeled(
    dists_a: jnp.ndarray,
    idx_a: jnp.ndarray,
    labels_a: jnp.ndarray,
    dists_b: jnp.ndarray,
    idx_b: jnp.ndarray,
    labels_b: jnp.ndarray,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Merge two label-carrying candidate sets and keep the k best by
    (distance, global index) — stable under any arrival order (tiles, shards,
    ring rotations)."""
    d = jnp.concatenate([dists_a, dists_b], axis=-1)
    i = jnp.concatenate([idx_a, idx_b], axis=-1)
    l = jnp.concatenate([labels_a, labels_b], axis=-1)
    s_d, s_i, s_l = sort_candidates_labeled(d, i, l)
    return s_d[..., :k], s_i[..., :k], s_l[..., :k]


def merge_topk(
    dists_a: jnp.ndarray,
    idx_a: jnp.ndarray,
    dists_b: jnp.ndarray,
    idx_b: jnp.ndarray,
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge two candidate sets along the last axis and keep the k best by
    (distance, global index) — stable under any arrival order."""
    d = jnp.concatenate([dists_a, dists_b], axis=-1)
    i = jnp.concatenate([idx_a, idx_b], axis=-1)
    d_sorted, i_sorted = lax.sort((d, i), dimension=-1, num_keys=2)
    return d_sorted[..., :k], i_sorted[..., :k]


def approx_smallest_indices(
    dists: jnp.ndarray, k: int, recall_target: float = 0.95
) -> jnp.ndarray:
    """[..., N] distances -> [..., k] int32 indices of the approximately
    k smallest, via ``lax.approx_max_k`` on negated distances — the TPU's
    hardware-binned approximate selection (Chern et al., PAPERS.md).
    Ranking only, no values: the IVF centroid ranker uses this to pick
    probe cells once ``num_cells`` is large enough that an exact argsort
    dominates the query (the probed candidates are still re-scored
    exactly, so what approximation costs is recall, never wrong
    distances — the same contract as every approx rung)."""
    _, idx = lax.approx_max_k(-dists, k, recall_target=recall_target)
    return idx.astype(jnp.int32)
