"""Pallas TPU kernel: tiled pairwise-distance + running top-k candidates.

SURVEY.md §7 step 7 / BASELINE.json config 5 — the wide-feature configuration
(MNIST-784-shaped) where the reference's scalar inner loop (main.cpp:14-23,
D-1 float ops per train row per query) is hopeless. Here the distance block is
one MXU matmul (``|q|^2 - 2 q·t + |t|^2``) and the k-candidate insertion sort
the reference runs per train row (main.cpp:46-61) becomes a VMEM-resident
running top-k that is folded once per train *tile*.

Kernel structure (grid = query tiles × train tiles, train innermost):

    for i in query_tiles:          # parallel
      for j in train_tiles:        # arbitrary (sequential accumulation)
        d  = dist(q_block[i], t_block[j])        # MXU, [BQ, BN]
        out[i] = topk_merge(out[i], (d, gidx))   # VPU, k extraction rounds

The running candidate set lives in the *output* block refs — their index map
ignores ``j``, so the same VMEM buffer persists across the whole train-tile
sweep and is only written back to HBM once per query tile. Train tiles stream
HBM → VMEM via the automatic pallas pipeline (double-buffered by default),
which is exactly the blockwise/"long-context" formulation of §5.7: the train
set plays the role sequence length plays in ring/flash attention, with the
(associative) lexicographic top-k merge in place of the softmax accumulator.

Tie semantics: selection keys on (distance, global train index) — the same
first-seen-wins rule as the reference's strict-``<`` insertion (main.cpp:47)
— so tiling does not perturb which neighbors are kept (§7 hard part (b)).
Two distance forms (mirroring ops/distance.py): ``precision="exact"`` unrolls
the subtraction form over the true feature count — identical rows give
exactly 0, preserving the large dataset's dist==0 ties and golden accuracy —
while ``precision="fast"`` uses one MXU matmul per tile pair, the right mode
for wide features (MNIST-784) where the VPU unroll would dominate.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams (~0.4.38); accept both.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

from knn_tpu.utils.padding import pad_axis_to_multiple
from knn_tpu.utils.windowed import windowed_dispatch

_INT_MAX = np.int32(np.iinfo(np.int32).max)

# Stripe auto-eligibility boundary, shared by every dispatch rule (the
# auto-engine predicate, predict_pallas, and 'auto' precision resolution).
# Measured on v5e (30,803 x 1,718, k=5): stripe-exact beats the XLA
# formulations 1.3x at d=64/100 and 2.25x at d=128; d=256 fails to compile
# at the default blocks.
STRIPE_MAX_D = 128
STRIPE_MAX_K = 16


def _tree_min(planes, n_planes: int):
    """Min fold over ``planes`` (an iterable consumed lazily; ``n_planes``
    is its length). Short lists use the plain sequential fold, consuming
    each plane into the accumulator as it is produced — materializing them
    first (a ``list()``) keeps every leaf live at once and blew the 16 MB
    scoped-VMEM limit on a narrow full-retirement sweep shape. Long lists
    (the xl config's 96+k planes) switch to groups of 8 reduced pairwise
    (log-depth) and chained — there the sequential dependence chain, not
    VPU throughput, bounds the selection rounds, and the per-group liveness
    stays capped at 4 planes."""
    it = iter(planes)
    if n_planes < 48:
        acc = next(it)
        for p in it:
            acc = jnp.minimum(acc, p)
        return acc
    acc = None
    done = False
    while not done:
        grp = []
        for _ in range(8):
            p = next(it, None)
            if p is None:
                done = True
                break
            grp.append(p)
        if not grp:
            break
        while len(grp) > 1:
            nxt = [
                jnp.minimum(grp[j], grp[j + 1])
                for j in range(0, len(grp) - 1, 2)
            ]
            if len(grp) % 2:
                nxt.append(grp[-1])
            grp = nxt
        acc = grp[0] if acc is None else jnp.minimum(acc, grp[0])
    return acc


def _merge_topk_rounds(
    d_cat: jnp.ndarray, i_cat: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k rounds of lexicographic (distance, index) min-extraction over the
    last axis. Pure VPU ops (min / compare / where) — no sort network needed
    for the small k the reference supports (k ≪ tile width)."""
    out_d, out_i = [], []
    for _ in range(k):
        m = jnp.min(d_cat, axis=1, keepdims=True)
        is_min = d_cat == m
        sel = jnp.min(jnp.where(is_min, i_cat, _INT_MAX), axis=1, keepdims=True)
        out_d.append(m)
        out_i.append(sel)
        # Retire the selected entry on BOTH keys: +inf distance alone is a
        # no-op for candidates that are already +inf (NaN-policy distances),
        # which would re-select the same index every round.
        taken = is_min & (i_cat == sel)
        d_cat = jnp.where(taken, jnp.inf, d_cat)
        i_cat = jnp.where(taken, _INT_MAX, i_cat)
    return jnp.concatenate(out_d, axis=1), jnp.concatenate(out_i, axis=1)


def _knn_kernel(
    n_valid_ref, q_ref, t_ref, *rest,
    k: int, block_n: int, d_true: int, precision: str,
):
    # Matmul forms take two extra inputs (precomputed norms); the exact form
    # takes none. Outputs follow.
    if precision in ("fast", "bf16"):
        q2_ref, t2_ref, out_d_ref, out_i_ref = rest
    else:
        out_d_ref, out_i_ref = rest
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_d_ref[:] = jnp.full(out_d_ref.shape, jnp.inf, out_d_ref.dtype)
        out_i_ref[:] = jnp.full(out_i_ref.shape, _INT_MAX, jnp.int32)

    q = q_ref[:]  # [BQ, D]
    t = t_ref[:]  # [BN, D], bf16 when the host entry pre-cast the train set
    if precision in ("fast", "bf16"):
        # MXU distance block: |q|^2 - 2 q·t + |t|^2, clamped at 0. One matmul,
        # but catastrophic cancellation perturbs near-zero distances. "bf16"
        # additionally feeds the MXU bfloat16 operands (f32 accumulation) for
        # 2x matmul throughput at ~3 fewer mantissa digits in the cross term.
        # This wide-feature config is HBM-bound on the train stream (the
        # whole [N, D] matrix re-streams once per query tile), so the host
        # entry stores the train operand AS bf16 — halving the stream is
        # worth more than the matmul speedup itself.
        #
        # The norms arrive PRECOMPUTED ([BQ,1] / [1,BN] blocks): computing
        # them here re-ran the q reduction once per TRAIN tile and the t
        # reduction once per QUERY tile (the kernel body executes per grid
        # step — nothing hoists it), and forced an f32 materialization of a
        # bf16 train tile that cost tile-sized VMEM. One XLA reduction per
        # dispatch outside the kernel replaces all of it (r4); t2 still
        # accumulates from the same bf16-rounded values the matmul consumes.
        q2 = q2_ref[:]  # [BQ, 1]
        t2 = t2_ref[:]  # [1, BN]
        if precision == "bf16":
            q = q.astype(jnp.bfloat16)
            t = t if t.dtype == jnp.bfloat16 else t.astype(jnp.bfloat16)
        cross = jax.lax.dot_general(
            q, t,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d = jnp.maximum(q2 + t2 - 2.0 * cross, 0.0)  # [BQ, BN]
    else:
        # Exact subtraction form, unrolled over the true feature count (the
        # lane padding is skipped): per-pair float accumulation like the
        # reference's inner loop (main.cpp:17-19), so identical rows give
        # exactly 0 and the large dataset's dist==0 ties survive (§7 (a)).
        d = jnp.zeros((q.shape[0], t.shape[0]), jnp.float32)
        for f in range(d_true):
            diff = q[:, f : f + 1] - t[:, f : f + 1].T  # [BQ, BN]
            d = d + diff * diff
    # Framework-wide NaN policy: missing-value NaNs -> +inf distance
    # (ops/distance.py; the reference is UB here, SURVEY.md §3.5.5).
    d = jnp.where(jnp.isnan(d), jnp.inf, d)

    # Global train-row indices for this tile; rows past n_valid (padding) are
    # masked to (+inf, INT_MAX) so they can never win a selection round — the
    # FLT_MAX-init trick of main.cpp:33 applied to padding instead of UB.
    gcol = j * block_n + jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    valid = gcol < n_valid_ref[0]
    d = jnp.where(valid, d, jnp.inf)
    gidx = jnp.where(valid, gcol, _INT_MAX)

    d_cat = jnp.concatenate([out_d_ref[:], d], axis=1)
    i_cat = jnp.concatenate([out_i_ref[:], gidx], axis=1)
    new_d, new_i = _merge_topk_rounds(d_cat, i_cat, k)
    out_d_ref[:] = new_d
    out_i_ref[:] = new_i


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_q", "block_n", "interpret", "d_true", "precision"),
)
def knn_pallas_candidates(
    train_x: jnp.ndarray,
    test_x: jnp.ndarray,
    n_valid: jnp.ndarray,
    k: int,
    block_q: int = 256,
    block_n: int = 1024,
    interpret: bool = False,
    d_true: Optional[int] = None,
    precision: str = "exact",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[N,D] train, [Q,D] queries -> ([Q,k] dists, [Q,k] int32 global indices),
    sorted ascending by (distance, index). N, Q, D must be pre-padded to
    block_n / block_q / lane multiples (zero-pad D — it adds 0 to distances).
    ``d_true`` is the unpadded feature count (the exact path loops over it);
    ``precision`` picks the distance form (module docstring)."""
    n_pad, d_feat = train_x.shape
    q_pad = test_x.shape[0]
    assert n_pad % block_n == 0 and q_pad % block_q == 0
    # A bf16 train operand (half the HBM stream) is only meaningful to the
    # bf16 distance form; the exact unroll and the f32 matmul need f32.
    assert train_x.dtype == jnp.float32 or (
        train_x.dtype == jnp.bfloat16 and precision == "bf16"
    ), f"train dtype {train_x.dtype} requires precision='bf16'"
    grid = (q_pad // block_q, n_pad // block_n)

    kernel = functools.partial(
        _knn_kernel, k=k, block_n=block_n,
        d_true=d_true if d_true is not None else d_feat, precision=precision,
    )
    in_specs = [
        pl.BlockSpec((block_q, d_feat), lambda i, j, n_ref: (i, 0)),
        pl.BlockSpec((block_n, d_feat), lambda i, j, n_ref: (j, 0)),
    ]
    inputs = [test_x, train_x]
    if precision in ("fast", "bf16"):
        # Precomputed norms (see _knn_kernel): one XLA reduction per dispatch
        # instead of a per-grid-step in-kernel recompute. t2 accumulates in
        # f32 from the STORED train values (bf16-rounded when stored bf16).
        t32 = train_x.astype(jnp.float32)
        inputs.append(jnp.sum(test_x * test_x, axis=1, keepdims=True))
        inputs.append(jnp.sum(t32 * t32, axis=1, keepdims=True).T)
        in_specs.append(pl.BlockSpec((block_q, 1), lambda i, j, n_ref: (i, 0)))
        in_specs.append(pl.BlockSpec((1, block_n), lambda i, j, n_ref: (0, j)))
    flops = 2 * q_pad * n_pad * d_feat + 4 * grid[1] * q_pad * k * (block_n + k)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            # Index maps take (grid indices..., scalar-prefetch refs...).
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((block_q, k), lambda i, j, n_ref: (i, 0)),
                pl.BlockSpec((block_q, k), lambda i, j, n_ref: (i, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((q_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, k), jnp.int32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=(q_pad + n_pad) * d_feat * 4 + q_pad * k * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(jnp.asarray(n_valid, jnp.int32).reshape(1), *inputs)


def _knn_stripe_kernel(
    n_valid_ref, q_ref, tT_ref, *rest,
    k: int, block_n: int, d_true: int, n_tiles: int, precision: str = "exact",
    lite_retire: bool = False, select: Optional[str] = None,
):
    """Lane-striped KNN tile kernel (exact subtraction-form distance by
    default; ``precision="fast"/"bf16"`` swaps in the MXU matmul expansion).

    The round-based merge in :func:`_knn_kernel` pays k cross-LANE
    min-reductions per train tile — slow on the VPU. Here each of the 128
    lanes keeps its own k-candidate stripe, and the per-tile selection runs
    across *planes* (128-column chunks of the tile), so the hot loop is pure
    elementwise [BQ, 128] compare/select with zero cross-lane traffic. The
    kernel emits the per-lane candidate sets ``[BQ, k*128]`` (level-major);
    the cheap final 128·k → k merge happens outside in XLA (a cross-lane
    reduction that costs ~20x the whole kernel if done in Mosaic).

    Layout: train arrives TRANSPOSED ``[D, N]`` so each feature contributes a
    sublane-broadcast row plane and the query contributes a lane-broadcast
    column — distances accumulate over the true feature count in source order
    (exact parity with main.cpp:17-19). The candidate buffers are VMEM
    scratch persisting across the train-tile sweep; outputs are written once
    on the last train tile (writing the accumulator through the output refs
    instead costs an HBM write-back per grid step — ~20x the whole kernel).
    """
    if precision in ("fast", "bf16"):
        q2_ref, t2_ref, out_d_ref, out_i_ref, cand_d_ref, cand_i_ref = rest
    else:
        out_d_ref, out_i_ref, cand_d_ref, cand_i_ref = rest
    j = pl.program_id(1)
    lanes = 128

    @pl.when(j == 0)
    def _init():
        cand_d_ref[:] = jnp.full(cand_d_ref.shape, jnp.inf, jnp.float32)
        cand_i_ref[:] = jnp.full(cand_i_ref.shape, _INT_MAX, jnp.int32)

    q = q_ref[:]  # [BQ, D_pad]
    nv = n_valid_ref[0]
    bq = q.shape[0]
    g = block_n // lanes

    if precision in ("fast", "bf16"):
        # MXU distance for the whole tile via |q|^2 - 2 q.t + |t|^2; the
        # transposed train layout makes the cross term one dot with the
        # feature (sublane) axis contracted. Wide-feature mode: not
        # prediction-exact near 0 (ops/distance.py caveats apply).
        #
        # The train tile may arrive STORED as bf16 (wide-feature configs are
        # bound by the [D, N] HBM re-stream per query tile — half the bytes
        # is the speedup); norms then accumulate in f32 from the same
        # bf16-rounded values the matmul consumes, so the distance is exact
        # for the rounded TRAIN operand. The query side still rounds in the
        # cross term only (q2 uses the unrounded f32 query): that shifts
        # every distance for a given query by the same |q|^2 - |q~|^2, so
        # neighbor ORDERING is unaffected (up to ties created by the zero
        # clamp); absolute distances carry ~2^-8 relative query-rounding
        # error (the bench recall guard covers the practical impact).
        # The norms arrive PRECOMPUTED ([BQ,1] / [1,BN] blocks): computing
        # them here re-ran the q reduction once per TRAIN tile and the t
        # reduction once per QUERY tile (the kernel body executes per grid
        # step — nothing hoists it), and the bf16 store's f32 cast
        # materialized a tile-sized VMEM copy. One XLA reduction per
        # dispatch outside replaces all of it (r4); t2 still accumulates in
        # f32 from the same bf16-rounded values the matmul consumes.
        t = tT_ref[:]  # [D_pad, BN], f32 or bf16
        q2 = q2_ref[:]  # [BQ, 1]
        t2 = t2_ref[:]  # [1, BN]
        qc, tc = (q.astype(jnp.bfloat16),
                  t if t.dtype == jnp.bfloat16 else t.astype(jnp.bfloat16)) \
            if precision == "bf16" else (q, t)
        cross = jax.lax.dot_general(
            qc, tc,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d_full = jnp.maximum(q2 + t2 - 2.0 * cross, 0.0)
        if not lite_retire:
            # NaN policy (missing values -> +inf distance). When the host
            # guaranteed finite inputs (assume_finite), finite operands
            # cannot produce NaN here — the check is provably dead, skip it.
            d_full = jnp.where(jnp.isnan(d_full), jnp.inf, d_full)
        chunk_d = [d_full[:, c * lanes : (c + 1) * lanes] for c in range(g)]
    else:
        # Exact subtraction-form distance, accumulated over feature planes in
        # source order: [BQ,1] lane-broadcast minus [1,128] sublane-broadcast
        # per feature. Computed PER 128-LANE CHUNK (same element order, so
        # bit-identical to a whole-tile accumulation) — a single [BQ, BN]
        # accumulator is ~3.7 MB of extra Mosaic stack at the default blocks,
        # which together with the lite rounds' longer-lived index planes
        # pushes past the 16 MB scoped-VMEM limit.
        chunk_d = []
        for c in range(g):
            dc = jnp.zeros((bq, lanes), jnp.float32)
            for f in range(d_true):
                diff = q[:, f : f + 1] - tT_ref[f, c * lanes : (c + 1) * lanes].reshape(1, lanes)
                dc = dc + diff * diff
            # NaN policy gated like the matmul form above: finite inputs
            # (assume_finite) cannot produce NaN, so the per-chunk check is
            # provably dead under the host guarantee.
            chunk_d.append(
                dc if lite_retire else jnp.where(jnp.isnan(dc), jnp.inf, dc)
            )

    # Selection planes: the g tile chunks plus the k running candidate levels.
    # Index planes stay [BQ, 128] (a [BQ, BN] iota next to the broadcast
    # distance planes trips a Mosaic layout-inference crash; 128-wide chunks
    # with scalar offsets lower cleanly).
    i128 = jax.lax.broadcasted_iota(jnp.int32, (bq, lanes), 1)
    d_planes, i_planes = [], []
    for c in range(g):
        gcol = i128 + (j * block_n + c * lanes)
        valid = gcol < nv
        d_planes.append(jnp.where(valid, chunk_d[c], jnp.inf))
        i_planes.append(jnp.where(valid, gcol, _INT_MAX))
    d_planes += [cand_d_ref[:, l * lanes : (l + 1) * lanes] for l in range(k)]
    i_planes += [cand_i_ref[:, l * lanes : (l + 1) * lanes] for l in range(k)]

    # Fold the fresh planes into the running candidates. Two formulations,
    # routed by trace-time op count (both exact, same lexicographic
    # (distance, index) tie rule — first-seen-wins, main.cpp:47):
    #
    # 1. Truncated odd-even merge network (ops/topk_net.py): a tournament
    #    of Batcher merges over (d, i) compare-exchanges, most of whose tie
    #    predicates resolve to a single compare via the compile-time
    #    tie-dominance matrix (r5; `finite` admits the candidate-dominance
    #    facts). Since that resolution it wins the cost race at EVERY k
    #    (device-confirmed down to k=1), so auto routing always picks it.
    # 2. k rounds of min-extraction across planes with retirement — kept
    #    as the select="rounds" probe/A-B baseline.
    from knn_tpu.ops import topk_net

    # finite (== lite_retire == the host's assume_finite gate) admits the
    # tie-dominance facts that prove most CEs' tie-break terms constant —
    # see topk_net._prune; without the gate the NaN-policy +inf distances
    # can carry real indices and only the fresh-plane facts hold.
    net_ops, net_out = topk_net.tile_topk_program(g, k, finite=lite_retire)
    use_net = (
        topk_net.program_cost(net_ops) < topk_net.rounds_cost(g, k, lite_retire)
        if select is None
        else select == "net"
    )
    if use_net:
        for a, b, kind, tie in net_ops:
            ad, bd = d_planes[a], d_planes[b]
            ai, bi = i_planes[a], i_planes[b]
            if tie == "a":
                swap = bd < ad
            elif tie == "b":
                # b tie-dominates a: on equal distances b must win the min
                # slot, so the strict compare becomes <= — still one op.
                swap = bd <= ad
            else:
                swap = (bd < ad) | ((bd == ad) & (bi < ai))
            if kind != "hi":
                d_planes[a] = jnp.minimum(ad, bd)
                i_planes[a] = jnp.where(swap, bi, ai)
            if kind != "lo":
                d_planes[b] = jnp.maximum(ad, bd)
                i_planes[b] = jnp.where(swap, ai, bi)
        for level in range(k):
            cand_d_ref[:, level * lanes : (level + 1) * lanes] = \
                d_planes[net_out[level]]
            cand_i_ref[:, level * lanes : (level + 1) * lanes] = \
                i_planes[net_out[level]]

        @pl.when(j == n_tiles - 1)
        def _writeback_net():
            out_d_ref[:] = cand_d_ref[:]
            out_i_ref[:] = cand_i_ref[:]

        return

    for level in range(k):
        n_planes = len(d_planes)
        m_d = _tree_min(d_planes, n_planes)
        m_i = _tree_min(
            (jnp.where(d_planes[p] == m_d, i_planes[p], _INT_MAX)
             for p in range(n_planes)),
            n_planes,
        )
        cand_d_ref[:, level * lanes : (level + 1) * lanes] = m_d
        cand_i_ref[:, level * lanes : (level + 1) * lanes] = m_i
        if level + 1 < k:
            for p in range(len(d_planes)):
                taken = i_planes[p] == m_i
                d_planes[p] = jnp.where(taken, jnp.inf, d_planes[p])
                if not lite_retire:
                    # Index retirement only matters once a round's minimum is
                    # +inf: the index pass then re-selects the smallest
                    # already-taken STALE index instead of INT_MAX, so deeper
                    # levels hold duplicate (inf, i) pairs — and a retired
                    # finite element's index can be smaller than the lane's
                    # true minimum inf-distance index, hijacking the inf tail
                    # (e.g. finite rows 0 and 128 in one lane, the rest NaN,
                    # k=3: the lite rounds emit [0, 128, 0] where full
                    # retirement emits the correct [0, 128, 1]).
                    #
                    # lite_retire is therefore only set when the caller
                    # guarantees every VALID element's distance is finite
                    # (host gate: stripe_inputs_finite — no NaN and no f32
                    # overflow). Then a lane's inf levels are reached only
                    # after its valid elements are exhausted, the duplicates
                    # carry (inf, i) keys, and the final merge never looks at
                    # them: with k <= n all-finite valid elements, the union
                    # of per-lane lists holds >= k finite candidates, so all
                    # k extraction rounds of _merge_topk_rounds extract at
                    # m < inf. Skipping the write is one fewer VPU op per
                    # plane per round — ~16% off the whole headline step on
                    # v5e (VERDICT r1 #8, scripts/tune_stripe_selection.py).
                    i_planes[p] = jnp.where(taken, _INT_MAX, i_planes[p])

    @pl.when(j == n_tiles - 1)
    def _writeback():
        out_d_ref[:] = cand_d_ref[:]
        out_i_ref[:] = cand_i_ref[:]


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "block_q", "block_n", "interpret", "d_true", "precision",
        "assume_finite", "select",
    ),
)
def knn_pallas_stripe_candidates(
    train_xT: jnp.ndarray,
    test_x: jnp.ndarray,
    n_valid: jnp.ndarray,
    k: int,
    block_q: int = 448,
    block_n: int = 2048,
    interpret: bool = False,
    d_true: Optional[int] = None,
    precision: str = "exact",
    assume_finite: bool = False,
    select: Optional[str] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Lane-striped kernel entry. ``train_xT`` is the TRANSPOSED train
    matrix ``[D_pad, N_pad]`` (N padded to ``block_n``, D padded to a sublane
    multiple); ``test_x`` is ``[Q_pad, D_pad]``. Returns ``([Q,k] dists,
    [Q,k] int32 global indices)`` sorted ascending by (distance, index).
    ``assume_finite`` — set ONLY when :func:`stripe_inputs_finite` holds
    for the unpadded inputs — drops work that finite inputs make provably
    dead: the NaN->+inf distance policy in BOTH distance forms (finite
    operands cannot produce NaN), and the index-retirement writes when the
    round-based selection is in play (see the exactness argument in
    _knn_stripe_kernel). Setting it on inputs that violate the gate feeds
    NaN keys straight into the selection. ``select`` overrides the
    trace-time selection routing ("net" = merge network, "rounds" =
    min-extraction rounds, None = route by op-count estimate) — a
    tuning/probe knob; both formulations are exact."""
    d_pad, n_pad = train_xT.shape
    q_pad = test_x.shape[0]
    assert n_pad % block_n == 0 and q_pad % block_q == 0 and block_n % 128 == 0
    assert d_true is None or d_true <= d_pad
    if select not in (None, "net", "rounds"):
        # A typo ("Net", "network") would otherwise silently route to the
        # rounds formulation and corrupt a probe comparison.
        raise ValueError(
            f"unknown select {select!r}; use None (auto), 'net', or 'rounds'"
        )
    # A bf16-stored train operand (half the HBM re-stream per query tile) is
    # only meaningful to the bf16 distance form; exact/fast need f32.
    assert train_xT.dtype == jnp.float32 or (
        train_xT.dtype == jnp.bfloat16 and precision == "bf16"
    ), f"train dtype {train_xT.dtype} requires precision='bf16'"
    grid = (q_pad // block_q, n_pad // block_n)

    kernel = functools.partial(
        _knn_stripe_kernel,
        k=k,
        block_n=block_n,
        d_true=d_true if d_true is not None else d_pad,
        n_tiles=grid[1],
        precision=precision,
        lite_retire=assume_finite,
        select=select,
    )
    in_specs = [
        pl.BlockSpec((block_q, test_x.shape[1]), lambda i, j, n_ref: (i, 0)),
        pl.BlockSpec((d_pad, block_n), lambda i, j, n_ref: (0, j)),
    ]
    inputs = [test_x, train_xT]
    if precision in ("fast", "bf16"):
        # Precomputed norms (see _knn_stripe_kernel): one XLA reduction per
        # dispatch instead of a per-grid-step in-kernel recompute. t2
        # accumulates in f32 from the STORED train values (bf16-rounded when
        # stored bf16) — XLA fuses the cast into the reduction.
        t32 = train_xT.astype(jnp.float32)
        inputs.append(jnp.sum(test_x * test_x, axis=1, keepdims=True))
        inputs.append(jnp.sum(t32 * t32, axis=0, keepdims=True))
        in_specs.append(pl.BlockSpec((block_q, 1), lambda i, j, n_ref: (i, 0)))
        in_specs.append(pl.BlockSpec((1, block_n), lambda i, j, n_ref: (0, j)))
    cand_d, cand_i = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((block_q, k * 128), lambda i, j, n_ref: (i, 0)),
                pl.BlockSpec((block_q, k * 128), lambda i, j, n_ref: (i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, k * 128), jnp.float32),
                pltpu.VMEM((block_q, k * 128), jnp.int32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((q_pad, k * 128), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, k * 128), jnp.int32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
            # v5e has 128 MB of VMEM; the 16 MB scoped default is what XLA's
            # output-placement heuristic budgets against, and it flips the
            # [Q, 128k] outputs onto the VMEM stack (S(1)) whenever the
            # kernel's own scoped usage reports low — observed the moment
            # the r4 norm hoist freed the in-kernel t32 tile. Raise the
            # kernel's budget instead of fighting the placement: the stack
            # outputs are then a win (no HBM write-back on the last tile).
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        cost_estimate=pl.CostEstimate(
            flops=3 * q_pad * n_pad * (d_true or d_pad) + 8 * q_pad * n_pad * k,
            bytes_accessed=(q_pad + n_pad) * d_pad * 4 + q_pad * k * 128 * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(jnp.asarray(n_valid, jnp.int32).reshape(1), *inputs)

    # Final 128·k -> k merge in XLA. k rounds of lexicographic (distance,
    # index) min-extraction — same tie order as a two-key sort but ~2x
    # cheaper at small k (no full sort of 128k columns).
    return _merge_topk_rounds(cand_d, cand_i, k)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "rows", "d_pad", "block_q", "block_n", "interpret", "d_true",
        "precision", "assume_finite",
    ),
)
def _stripe_candidates_sliced(
    train_xT: jnp.ndarray,
    q_full: jnp.ndarray,
    start: jnp.ndarray,
    n_valid: jnp.ndarray,
    k: int,
    rows: int,
    d_pad: int,
    block_q: int,
    block_n: int,
    interpret: bool,
    d_true: Optional[int],
    precision: str,
    assume_finite: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One chunk of :func:`knn_pallas_stripe_candidates` sliced ON DEVICE
    from the resident UNPADDED query array ``[Q, d_true]``. The chunked
    host entry uploads the raw query bytes once per super-chunk and
    dispatches per-chunk with a traced ``start`` offset — one executable,
    and the host->device traffic is exactly the query payload (44 B/query
    at d=11 instead of 64 padded). That matters doubly on the tunneled
    device: transfers interleaved between kernel dispatches stall the
    stream, and once ANY executable has run, large uploads drop to
    ~20-60 MB/s (r5 probe: the same 42 MB that lands in ~25 ms before the
    first kernel takes 2-7 s after — an axon-layer behavior, not load
    variance, reproduced with a plain XLA matmul). The feature pad to the
    kernel's sublane multiple happens here, device-side, where the copy
    rides HBM bandwidth instead of the tunnel."""
    qb = jax.lax.dynamic_slice(
        q_full, (start.astype(jnp.int32), jnp.int32(0)),
        (rows, q_full.shape[1]),
    )
    if d_pad > q_full.shape[1]:
        qb = jnp.pad(qb, ((0, 0), (0, d_pad - q_full.shape[1])))
    return knn_pallas_stripe_candidates(
        train_xT, qb, n_valid, k,
        block_q=block_q, block_n=block_n, interpret=interpret,
        d_true=d_true, precision=precision, assume_finite=assume_finite,
    )


def _resolve_stripe_precision(precision: str, d: int) -> str:
    """One contract for the stripe host entries (ADVICE r1): ``auto``
    resolves the same way backends/pallas.py does — exact for narrow
    features, fast for wide — instead of being rejected as unknown."""
    if precision == "auto":
        return "exact" if d <= STRIPE_MAX_D else "fast"
    if precision not in ("exact", "fast", "bf16"):
        raise ValueError(
            f"unknown precision {precision!r}; choose auto, exact, fast, or bf16"
        )
    return precision


def stripe_inputs_finite(*arrays: np.ndarray) -> bool:
    """Host-side gate for the kernel's ``assume_finite`` fast path: True when
    every array is NaN/inf-free AND small enough in magnitude that no squared
    euclidean distance can overflow f32 to +inf. Under that guarantee every
    valid element's distance is finite, so the kernel may skip the
    NaN->+inf distance policy entirely (both distance forms, r4) and the
    selection rounds may skip
    index retirement (see _knn_stripe_kernel). The scan is a few hundred
    microseconds on the headline config — noise next to one kernel step."""
    limit = None
    for a in arrays:
        if a.size == 0:
            continue
        if limit is None:
            # |q_f - t_f|^2 summed over d features stays < FLT_MAX when every
            # value's magnitude is below sqrt(FLT_MAX / (4 d)); the extra
            # factor of 2 is headroom for f32 accumulation rounding, which
            # can carry a sum sitting exactly at the bound past FLT_MAX
            # (r2 review — reproduced at d=784 with values at the unpadded
            # limit). Rounding inflates a d-term sum by at most
            # (1 + 2^-24)^d, so 2x slack holds for any representable d.
            d = a.shape[-1] if a.ndim > 1 else 1
            limit = float(np.sqrt(np.finfo(np.float32).max / (8.0 * max(d, 1))))
        m = float(np.max(np.abs(a), initial=0.0))  # NaN propagates -> not finite
        if not np.isfinite(m) or m >= limit:
            return False
    return True


#: Admission budget for the wide-feature matmul stripe ROUTE — deliberately
#: 48 MB, not the kernel's 64 MB ``vmem_limit_bytes``: the limit must also
#: hold what the cost model below does not count — the ``[block_q, 128k]``
#: candidate outputs XLA places on the VMEM stack (S(1)) whenever the
#: retirement loop keeps them live, plus Mosaic's own scheduling slack —
#: so routing admits only shapes that leave that ~25% headroom. A shape
#: that fails here must stay on the merge/XLA formulations: the
#: no-fallback dispatch points (kneighbors, the distributed paths) have no
#: rescue path after Mosaic hard-fails (ADVICE r4).
WIDE_ROUTE_VMEM_BUDGET = 48 << 20


def _wide_tile_bytes(block_n: int, d_pad: int, precision: str) -> int:
    """The double-buffered train tile at its STORE width (bf16 ships the
    transposed operand half-width) — THE fixed VMEM cost of the wide
    matmul stripe forms. One definition shared by the block resolver
    (:func:`stripe_block_sizes`) and the route guard
    (:func:`_wide_tile_fits`), so the two can never drift apart again
    (ADVICE r5 #2)."""
    store_bytes = 2 if precision == "bf16" else 4
    return 2 * block_n * d_pad * store_bytes


def _wide_row_bytes(block_n: int, d_pad: int, k: int) -> int:
    """Per-query-row VMEM for the wide matmul forms: the f32 distance
    stripe (``4 * block_n``), candidate scratch (``2 x [row, 128k]`` at
    d+i widths = ``8 * 128 * k``), and the query row (``4 * d_pad``)."""
    return 4 * block_n + 8 * 128 * k + 4 * d_pad


def _wide_vmem_bytes(block_q: int, block_n: int, d_pad: int, k: int,
                     precision: str) -> int:
    """Modeled VMEM for one wide-form stripe invocation at the given
    blocks — the shared cost function both consumers evaluate."""
    return (_wide_tile_bytes(block_n, d_pad, precision)
            + block_q * _wide_row_bytes(block_n, d_pad, k))


def _wide_tile_fits(precision: str, d_pad: int, k: int) -> bool:
    """Whether the wide-feature matmul stripe route can compile at ALL:
    resolve the blocks :func:`stripe_block_sizes` would actually choose
    for the minimum query block (256 rows — the resolver's own block_q
    floor), then evaluate the shared cost model against
    :data:`WIDE_ROUTE_VMEM_BUDGET`. At the widths where this guard
    matters the resolver's 16 MB tile cap has already floored block_n at
    128, so the verdict is the tightest shape the kernel could run."""
    block_q, block_n = stripe_block_sizes(
        None, None, q=256, k=k, d_pad=d_pad, precision=precision
    )
    return (_wide_vmem_bytes(block_q, block_n, d_pad, k, precision)
            <= WIDE_ROUTE_VMEM_BUDGET)


def stripe_route_ok(precision: str, d: int, k: int) -> bool:
    """Platform-independent half of THE auto-engine rule: which problems
    belong on the lane-striped kernel. Exact euclidean with narrow features
    (d <= 128 measured on v5e: the stripe exact unroll beats the XLA
    full-matrix path 1.3x at d=64/100 and 2.25x at d=128; d=256 failed to
    compile at the r2 blocks), the bf16 matmul form at ANY width (r3: train
    operand stored bf16, 1.7x the merge kernel on the mnist784 shape), and
    the f32 "fast" matmul form for WIDE features (r4: with the norms
    hoisted and the 64 MB vmem budget, stripe fast at (1024, 2048) measured
    ~1.6x the merge kernel's medians on the same shape, interleaved).
    Narrow-feature fast stays on the merge/XLA paths — no measurement says
    stripe wins there. EXTREME widths (f32 fast d_pad ≳ 24k, bf16 ≳ 33k)
    decline the route entirely: no block shape fits the kernel budget, so
    auto dispatch must stay on the merge/XLA formulations."""
    if precision in ("fast", "bf16") and d > STRIPE_MAX_D and not _wide_tile_fits(
        precision, ((d + 7) // 8) * 8, k
    ):
        return False
    return (
        (
            precision == "bf16"
            or (precision == "fast" and d > STRIPE_MAX_D)
            or (precision == "exact" and d <= STRIPE_MAX_D)
        )
        and k <= STRIPE_MAX_K
    )


def stripe_auto_eligible(precision: str, d: int, k: int) -> bool:
    """THE auto-engine rule, shared by every dispatch point (single-device
    backend, kneighbors, the three distributed paths): route to the
    lane-striped kernel when :func:`stripe_route_ok` holds AND a real TPU is
    attached (interpret mode is correct but slow, so CPU meshes default to
    the XLA formulations)."""
    return stripe_route_ok(precision, d, k) and jax.default_backend() == "tpu"


def stripe_prepare_sharded(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    n_t: int,
    n_q: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    precision: str = "exact",
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int, int]:
    """Host-side layout for the distributed stripe paths (train-sharded,
    query-sharded with ``n_t=1``, ring with ``n_t=n_q=P``): resolves
    shard-aware block sizes, pads train rows to ``n_t`` equal shards of a
    ``block_n`` multiple, transposes to the kernel's ``[D_pad, N_pad]``
    layout, pads labels alongside, and pads queries to ``n_q`` equal shards
    of a ``block_q`` multiple with ``d_pad`` features. ``precision`` feeds
    the block resolver so the wide-feature matmul forms get the wide block
    defaults on the distributed paths too. Returns ``(train_xT,
    train_y_padded, test_x_padded, block_q, block_n)``."""
    q, n = test_x.shape[0], train_x.shape[0]
    q_quota = -(-q // n_q)  # ceil queries per q-shard
    shard_quota = -(-n // n_t)  # ceil train rows per t-shard
    block_q, block_n = stripe_block_sizes(
        block_q, block_n, q_quota, k,
        d_pad=((train_x.shape[1] + 7) // 8) * 8, precision=precision,
    )
    block_n = min(block_n, -(-shard_quota // 128) * 128)
    shard_rows = -(-shard_quota // block_n) * block_n
    n_pad = shard_rows * n_t
    txT, d_pad = stripe_prepare_train(
        np.pad(train_x.astype(np.float32), ((0, n_pad - n), (0, 0))), block_n
    )
    ty = np.pad(train_y, (0, n_pad - n))
    q_shard = -(-q_quota // block_q) * block_q
    qx = stripe_prepare_queries(
        np.pad(test_x.astype(np.float32), ((0, n_q * q_shard - q), (0, 0))),
        block_q, d_pad,
    )
    if precision == "bf16" and train_x.shape[1] > 128:
        # Same store rule as the single-device cache (_cached_stripe_train):
        # wide bf16 ships the transposed train operand half-width, which is
        # both the HBM re-stream win and what the wide block budget assumes.
        txT = txT.astype(jnp.bfloat16)
    return txT, ty, qx, block_q, block_n


def stripe_candidates_core(
    train_xT: jnp.ndarray,
    train_y: jnp.ndarray,
    test_x: jnp.ndarray,
    n_valid: jnp.ndarray,
    k: int,
    block_q: int,
    block_n: int,
    d_true: int,
    precision: str = "exact",
    interpret: bool = False,
    index_base: "int | jnp.ndarray" = 0,
    assume_finite: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Label-carrying candidate triple from the lane-striped kernel, for use
    *inside* jit/shard_map (device arrays in, device arrays out, no host
    padding). ``train_xT`` is one shard's transposed ``[D_pad, rows]`` train
    block; ``index_base`` positions its rows in the global train order (e.g.
    ``axis_index * shard_rows``), so the returned global indices keep the
    reference's first-seen-wins tie rule across shard boundaries. Rows at or
    beyond ``n_valid`` (padding) come back as (+inf, INT_MAX, label 0) and can
    never win a (distance, index) merge.

    This is the composition point VERDICT r1 #1 asked for: the distributed
    paths (train-sharded all-gather, query-sharded, ring) obtain per-shard
    candidates from the framework's fastest kernel instead of the ~2.5x
    slower XLA scan, so multi-chip throughput tracks the single-chip
    headline. Interpret mode keeps the same path testable on CPU meshes.
    """
    d, li = knn_pallas_stripe_candidates(
        train_xT, test_x, n_valid, k,
        block_q=block_q, block_n=block_n, interpret=interpret,
        d_true=d_true, precision=precision, assume_finite=assume_finite,
    )
    safe = jnp.minimum(li, train_y.shape[0] - 1)
    lbl = train_y[safe]
    gi = jnp.where(li == _INT_MAX, _INT_MAX, li + index_base).astype(jnp.int32)
    return d, gi, lbl


def stripe_prepare_train(
    train_x: np.ndarray, block_n: int
) -> Tuple[np.ndarray, int]:
    """Lay out the train matrix for the stripe kernel: rows padded to a
    ``block_n`` multiple, features zero-padded to a sublane multiple, then
    transposed to ``[D_pad, N_pad]``. Returns ``(train_xT, d_pad)`` — the
    single definition of the kernel's input layout (bench.py and the host
    entries share it)."""
    d_true = train_x.shape[1]
    d_pad = ((d_true + 7) // 8) * 8
    tx, _ = pad_axis_to_multiple(train_x.astype(np.float32), block_n, axis=0)
    txT = np.ascontiguousarray(np.pad(tx, ((0, 0), (0, d_pad - d_true))).T)
    return txT, d_pad


def stripe_prepare_queries(
    test_x: np.ndarray, block_q: int, d_pad: int
) -> np.ndarray:
    """Pad queries to a ``block_q`` row multiple and ``d_pad`` features."""
    d_true = test_x.shape[1]
    qx, _ = pad_axis_to_multiple(test_x.astype(np.float32), block_q, axis=0)
    return np.pad(qx, ((0, 0), (0, d_pad - d_true)))


def stripe_block_sizes(
    block_q: Optional[int],
    block_n: Optional[int],
    q: int,
    k: int = 5,
    d_pad: Optional[int] = None,
    precision: str = "exact",
) -> Tuple[int, int]:
    """Resolve stripe block sizes: defaults tuned on v5e (448, 2048 for the
    narrow-feature exact unroll), block_n rounded to the 128-lane multiple
    the kernel requires, block_q clipped so one tile covers small query sets
    and scaled down with ``k`` so the candidate scratch (``2 x [block_q,
    128k]``) stays within VMEM.

    The matmul forms (``fast``/``bf16``) get their own defaults: the step is
    bound by the per-query-tile train re-stream, so block_q grows as large as
    the [block_q, block_n] f32 distance buffer + candidate scratch allow —
    (1024, 1024) measured best for the mnist784 shape (1.73 ms vs 2.89 for
    the 512-row merge kernel, same session) — and shrinks with d_pad (query
    block bytes) and k (scratch bytes)."""
    if precision in ("fast", "bf16") and (d_pad or 0) > 128:
        # Wide-feature matmul forms only: the step is bound by the
        # per-query-tile train re-stream, so block_q grows as large as VMEM
        # allows. Narrow-feature bf16/fast keeps the proven narrow defaults
        # below (same selection cost, no re-stream problem).
        block_n = ((max(128, block_n or 2048) + 127) // 128) * 128
        # VERY wide features must shrink the train tile, not die in Mosaic:
        # the double-buffered tile costs 2*block_n*d_pad*store_bytes and
        # the auto dispatch points outside predict_pallas have no merge
        # fallback. Cap the tiles at ~16 MB of the 64 MB kernel budget
        # (e.g. d_pad=8192 f32 fast -> block_n 256).
        # The tile cap divides by the tile-bytes helper's per-row-of-block_n
        # cost so the double-buffered tile (_wide_tile_bytes) stays ~16 MB.
        store_cap = 2 if precision == "bf16" else 4
        tile_cap = (16 << 20) // (2 * max(d_pad, 1) * store_cap) // 128 * 128
        block_n = max(128, min(block_n, max(tile_cap, 128)))
        if block_q is None:
            # Solve _wide_vmem_bytes(block_q) <= budget for block_q (the
            # shared wide-form cost model — _wide_tile_bytes fixed cost +
            # per-row _wide_row_bytes; bf16 stores half-width tiles, so
            # "fast" gets a smaller query block). The budget assumes the
            # kernel's raised 64 MB vmem_limit (r4: the norm hoist removed
            # the in-kernel f32 train-tile materialization, and
            # (1024, 2048) measured best on the mnist784 bf16 shape), with
            # a haircut at high k where scratch liveness grows.
            budget = (((34 if k <= 8 else 28) << 20)
                      - _wide_tile_bytes(block_n, d_pad, precision))
            per_row = _wide_row_bytes(block_n, d_pad, k)
            block_q = max(256, min(1024, budget // per_row // 256 * 256))
    else:
        block_n = ((max(128, block_n or 2048) + 127) // 128) * 128
        if block_q is None:
            # Candidate scratch (d+i) ~= block_q * 128k * 16 B; budget
            # ~10.5 MB of the kernel's 64 MB vmem limit. Swept on v5e r5
            # (110k-query retrieval, d=11 exact): k=5 best at 1024 (463 ->
            # 534k q/s wall vs the old 448 cap), k=10 flat 224-432 then
            # worse at 864, k=16 best near 264 — the budget lands 1024 /
            # 512 / 320 respectively.
            block_q = min(1024, max(8, (10_500_000 // (128 * k * 16)) // 8 * 8))
    block_q = min(block_q, ((q + 7) // 8) * 8)
    return block_q, block_n


def memo_device(cache: Optional[dict], key: tuple, make):
    """THE memoization idiom for ``Dataset.device_cache``: return the cached
    entry for ``key``, else ``make()`` it (host layout + device upload) and
    store it when a cache dict was supplied. One definition so future
    invalidation-rule changes happen in one place."""
    if cache is not None and key in cache:
        return cache[key]
    entry = make()
    if cache is not None:
        cache[key] = entry
    return entry


def _cached_stripe_train(
    train_x: np.ndarray,
    block_n: int,
    cache: Optional[dict],
    precision: str = "exact",
) -> Tuple[jnp.ndarray, int, bool]:
    """Device-resident transposed train layout, memoized in ``cache``
    (normally ``Dataset.device_cache``) so repeat predict/kneighbors calls
    skip the host pad+transpose+upload AND the finiteness scan. Returns
    ``(train_xT device array, d_pad, train_finite)``. ``precision="bf16"``
    on WIDE features stores the operand AS bf16 — that step is bound by the
    per-query-tile train re-stream, so half the bytes is the speedup — and
    the key carries the dtype so f32 and bf16 layouts coexist. Narrow
    features keep f32 storage: no re-stream problem to fix, and the
    in-kernel f32 norm materialization a bf16 operand forces tipped a
    narrow k=9 shape over the scoped-VMEM limit (r3 parity sweep)."""
    dtype = (
        jnp.bfloat16
        if precision == "bf16" and train_x.shape[1] > 128
        else jnp.float32
    )

    def make():
        txT, d_pad = stripe_prepare_train(train_x, block_n)
        return jnp.asarray(txT, dtype), d_pad, stripe_inputs_finite(train_x)

    return memo_device(cache, ("stripe_train", block_n, np.dtype(dtype).name), make)


def stripe_candidates_arrays(
    train_x: np.ndarray,
    test_x: np.ndarray,
    k: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    interpret: Optional[bool] = None,
    precision: str = "exact",
    cache: Optional[dict] = None,
    chunk_rows: Optional[int] = None,
    deferred: bool = False,
):
    """Host entry for the lane-striped kernel: handles padding and the [D, N]
    train transposition, returns unpadded ``([Q,k] dists, [Q,k] indices)``.
    ``interpret`` defaults to on for non-TPU platforms so the same path is
    testable on CPU. ``cache`` (a ``Dataset.device_cache`` dict) memoizes the
    device-side train layout across calls.

    Queries run in bounded chunks with a dispatch window (VERDICT r3 #3):
    chunking bounds the [rows, 128k] kernel-output scratch at large Q, and
    every chunk starts its device->host copy ASYNCHRONOUSLY the moment it
    is dispatched, so the final drains find the bytes already landed.
    Chunks are LARGE (64k rows): on a tunneled device each blocking fetch
    costs a full ~100 ms round trip no matter how the dispatches pipeline
    (measured r4: 448-row chunks turned a 110k-query retrieval into 246
    serial round trips — 27 s of wall for ~60 ms of device compute), so
    the wall-latency win comes from FEW fetches with the copies overlapped,
    not from many small overlapping dispatches. ``chunk_rows`` overrides
    the per-chunk row cap (tests/tuning).

    ``deferred`` returns a zero-arg ``resolve()`` closure instead of the
    arrays: every chunk is dispatched (async copies started) before this
    function returns, and the host-sync cost is paid when the caller
    resolves — the primitive under the model layer's ``kneighbors_async``
    (VERDICT r4 #6: M deferred calls resolved together pay ~one ~100 ms
    tunnel round trip instead of M)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d_true = train_x.shape
    q = test_x.shape[0]
    if q == 0:
        empty = (np.empty((0, k), np.float32), np.empty((0, k), np.int32))
        return (lambda: empty) if deferred else empty
    precision = _resolve_stripe_precision(precision, d_true)
    block_q, block_n = stripe_block_sizes(
        block_q, block_n, q, k, d_pad=((d_true + 7) // 8) * 8,
        precision=precision,
    )
    txTj, d_pad, train_finite = _cached_stripe_train(
        train_x, block_n, cache, precision
    )
    assume_finite = train_finite and stripe_inputs_finite(test_x)
    # Chunk cap scaled down with k (ADVICE r4): each dispatch materializes a
    # [rows, 128k] f32+i32 candidate buffer on device before the fused merge
    # (~670 MB at the 128k-row/k=5 default; transient — executions are
    # serial, so ~2 are ever live). 128k rows measured best at k=5 on v5e
    # (r5: 863 ms vs 932 at 256k-row chunks for a 660k-query sweep);
    # shrinking inversely with k keeps the transient bounded beyond k=8.
    cap = max(8192, (131072 * 8 // max(k, 8)) // 1024 * 1024)
    rows = max(block_q, (chunk_rows or cap) // block_q * block_q)
    nv = jnp.asarray(n, jnp.int32)

    # The query payload is uploaded ONCE per super-chunk, UNPADDED, then
    # row-padded ON DEVICE to a chunk multiple and sliced+feature-padded
    # per chunk (_stripe_candidates_sliced — see there for the tunnel
    # pathologies this sidesteps). The device-side row pad quantizes the
    # Pallas executable's input shape to the chunk grid, so varying query
    # counts share one kernel compile per chunk-count (the pad itself is a
    # cheap per-shape XLA op); pad rows compute garbage the fetch trims.
    # SUPER-chunks bound device residency for query sets past ~1 GB of
    # features — each super pays one upload.
    super_rows = max(rows, (1 << 28) // (d_pad * 4) // rows * rows)

    def run_super(qs0):
        qsub = test_x[qs0 : qs0 + super_rows]
        sq = qsub.shape[0]
        chunk = min(rows, -(-sq // block_q) * block_q)
        buf_rows = -(-sq // chunk) * chunk
        qj = jnp.asarray(np.ascontiguousarray(qsub, np.float32))
        if buf_rows > sq:
            qj = jnp.pad(qj, ((0, buf_rows - sq), (0, 0)))

        def dispatch(s0):
            return _stripe_candidates_sliced(
                txTj, qj, jnp.asarray(s0, jnp.int32), nv, k=k, rows=chunk,
                d_pad=d_pad, block_q=block_q, block_n=block_n,
                interpret=interpret, d_true=d_true, precision=precision,
                assume_finite=assume_finite,
            )

        def fetch(out, s0):
            d_h, i_h = jax.device_get(out)
            return d_h[: min(chunk, sq - s0)], i_h[: min(chunk, sq - s0)]

        from knn_tpu.utils.windowed import windowed_dispatch_deferred

        return windowed_dispatch_deferred(
            range(0, buf_rows, chunk), dispatch, fetch, window=16,
        )

    # First super dispatches now (so a deferred caller's device work is in
    # flight when this returns); later supers launch lazily at resolve time,
    # each after the previous drains, keeping one super's buffers resident.
    first = run_super(0)

    memo = []

    def resolve():
        if not memo:
            # Copy before extending: the drain closure memoizes and returns
            # its own results list, so appending in place would corrupt a
            # repeated resolve() on multi-super query sets — and the later
            # supers must not re-dispatch either, hence the whole-result
            # memo.
            parts = list(first())
            for qs0 in range(super_rows, q, super_rows):
                parts += run_super(qs0)()
            memo.append((
                np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]),
            ))
        return memo[0]

    return resolve if deferred else resolve()


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "num_classes", "block_q", "block_n", "d_true", "interpret",
        "precision", "assume_finite",
    ),
)
def knn_stripe_classify(
    train_xT: jnp.ndarray,
    train_y: jnp.ndarray,
    test_x: jnp.ndarray,
    n_valid: jnp.ndarray,
    k: int,
    num_classes: int,
    block_q: int = 448,
    block_n: int = 2048,
    d_true: Optional[int] = None,
    interpret: bool = False,
    precision: str = "exact",
    assume_finite: bool = False,
) -> jnp.ndarray:
    """One-dispatch classify on pre-padded device arrays: stripe kernel +
    lexicographic merge + vote, fused under a single jit. The headline exact
    path (bench.py) — 2.6x the full-matrix XLA formulation on TPU v5e."""
    from knn_tpu.ops.vote import vote

    _, idx = knn_pallas_stripe_candidates(
        train_xT, test_x, n_valid, k,
        block_q=block_q, block_n=block_n, interpret=interpret, d_true=d_true,
        precision=precision, assume_finite=assume_finite,
    )
    safe = jnp.minimum(idx, train_y.shape[0] - 1)
    return vote(train_y[safe], num_classes)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "num_classes", "rows", "d_pad", "block_q", "block_n", "d_true",
        "interpret", "precision", "assume_finite",
    ),
)
def _stripe_classify_sliced(
    train_xT: jnp.ndarray,
    train_y: jnp.ndarray,
    q_full: jnp.ndarray,
    start: jnp.ndarray,
    n_valid: jnp.ndarray,
    k: int,
    num_classes: int,
    rows: int,
    d_pad: int,
    block_q: int,
    block_n: int,
    d_true: Optional[int],
    interpret: bool,
    precision: str,
    assume_finite: bool,
) -> jnp.ndarray:
    """One classify chunk sliced ON DEVICE from the resident unpadded query
    array — the classify twin of :func:`_stripe_candidates_sliced` (see
    there for the tunnel-transfer pathologies the single-upload design
    sidesteps)."""
    qb = jax.lax.dynamic_slice(
        q_full, (start.astype(jnp.int32), jnp.int32(0)),
        (rows, q_full.shape[1]),
    )
    if d_pad > q_full.shape[1]:
        qb = jnp.pad(qb, ((0, 0), (0, d_pad - q_full.shape[1])))
    return knn_stripe_classify(
        train_xT, train_y, qb, n_valid, k=k, num_classes=num_classes,
        block_q=block_q, block_n=block_n, d_true=d_true,
        interpret=interpret, precision=precision,
        assume_finite=assume_finite,
    )


def stripe_classify_arrays(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    precision: str = "exact",
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    interpret: Optional[bool] = None,
    max_rows: Optional[int] = None,
    cache: Optional[dict] = None,
) -> np.ndarray:
    """Host entry for a full stripe-kernel classify: resolves k-aware block
    sizes, lays out the inputs, runs the fused classify jit in bounded
    chunks, trims padding — the single definition of the stripe host
    dispatch (the tpu backend routes here; the bench scripts drive the raw
    jit directly for pipelined timing). ``interpret`` defaults to on for
    non-TPU platforms so the same path is testable on CPU; ``max_rows``
    caps the per-call query rows (e.g. a caller's query_batch).
    ``precision="auto"`` resolves like backends/pallas.py: exact for narrow
    features (the stripe kernel's home turf), fast for wide. ``cache`` (a
    ``Dataset.device_cache`` dict) memoizes the device-side train layout
    across calls."""
    precision = _resolve_stripe_precision(precision, train_x.shape[1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q = test_x.shape[0]
    if q == 0:
        return np.empty(0, np.int32)
    block_q, block_n = stripe_block_sizes(
        block_q, block_n, q, k,
        d_pad=((train_x.shape[1] + 7) // 8) * 8, precision=precision,
    )
    txTj, d_pad, train_finite = _cached_stripe_train(
        train_x, block_n, cache, precision
    )
    assume_finite = train_finite and stripe_inputs_finite(test_x)
    tyj = memo_device(
        cache, ("stripe_labels",), lambda: jnp.asarray(train_y)
    )
    nv = jnp.asarray(train_x.shape[0], jnp.int32)
    # Chunk calls so each [rows, 128k] candidate buffer stays small: XLA can
    # place the kernel outputs in VMEM (observed at k>8), and an unchunked
    # [Q_pad, 128k] output there blows the scoped limit.
    auto_rows = max(block_q, (4 << 20) // (128 * k * 8) // block_q * block_q)
    rows = min(auto_rows, max(block_q, max_rows)) if max_rows else auto_rows

    # Single upload of the raw query payload per SUPER-chunk + on-device row
    # pad to a chunk multiple + dynamic-slice per chunk — the same design
    # (and the same tunnel-transfer rationale and ~1 GB residency bound) as
    # stripe_candidates_arrays above.
    super_rows = max(rows, (1 << 28) // (d_pad * 4) // rows * rows)
    parts = []
    for qs0 in range(0, q, super_rows):
        qsub = test_x[qs0 : qs0 + super_rows]
        sq = qsub.shape[0]
        chunk = min(rows, -(-sq // block_q) * block_q)
        buf_rows = -(-sq // chunk) * chunk
        qj = jnp.asarray(np.ascontiguousarray(qsub, np.float32))
        if buf_rows > sq:
            qj = jnp.pad(qj, ((0, buf_rows - sq), (0, 0)))

        def dispatch(s0, qj=qj, chunk=chunk):
            return _stripe_classify_sliced(
                txTj, tyj, qj, jnp.asarray(s0, jnp.int32), nv, k=k,
                num_classes=num_classes, rows=chunk, d_pad=d_pad,
                block_q=block_q, block_n=block_n, d_true=train_x.shape[1],
                interpret=interpret, precision=precision,
                assume_finite=assume_finite,
            )

        def fetch(out, s0, sq=sq, chunk=chunk):
            return np.asarray(out)[: min(chunk, sq - s0)]

        parts.extend(
            windowed_dispatch(range(0, buf_rows, chunk), dispatch, fetch)
        )
    return np.concatenate(parts)


def predict_pallas(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    block_q: Optional[int] = None,
    block_n: Optional[int] = None,
    interpret: Optional[bool] = None,
    precision: str = "exact",
    engine: str = "auto",
) -> np.ndarray:
    """Host entry: pad (queries, train rows, feature lanes), run the kernel,
    gather labels, vote. Interpret mode defaults on for non-TPU backends so the
    same code path is testable on the CPU mesh (SURVEY.md §4).

    ``engine``: "stripe" = the lane-striped kernel (elementwise selection;
    supports every precision form), "merge" = the tile-merge kernel,
    "auto" = stripe for narrow-feature exact problems, for bf16 problems
    at any width (wide bf16 stores the train operand half-width — measured
    1.7x the merge kernel on the mnist784 shape), and for wide-feature
    "fast" (r4: ~1.6x the merge kernel with hoisted norms), merge
    otherwise."""
    from knn_tpu.ops.vote import vote

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, q = train_x.shape[0], test_x.shape[0]
    d_true = train_x.shape[1]
    precision = _resolve_stripe_precision(precision, d_true)
    auto_routed = engine == "auto"
    if auto_routed:
        # The shared routing rule (stripe_route_ok, platform check elided —
        # interpret mode runs the same kernel on CPU): narrow-feature exact,
        # any-width bf16, and wide-feature fast go to the stripe kernel
        # (r4: the hoisted norms + 64 MB vmem budget fit the wide f32
        # distance buffer at competitive blocks, ~1.6x the merge kernel).
        engine = "stripe" if stripe_route_ok(precision, d_true, k) else "merge"
    if engine not in ("stripe", "merge"):
        raise ValueError(
            f"unknown pallas engine {engine!r}; use 'auto', 'stripe', or 'merge'"
        )
    if engine == "stripe":
        try:
            _, idx = stripe_candidates_arrays(
                train_x, test_x, k,
                block_q=block_q, block_n=block_n, interpret=interpret,
                precision=precision,
            )
        except MemoryError:
            # Host OOM is NOT a Mosaic corner case: retrying it on the merge
            # kernel would double the work and bury the real bug under a
            # RuntimeWarning (ADVICE r3). ValueError/TypeError stay INSIDE
            # the net: Pallas surfaces trace-time lowering failures on odd
            # (d, k, block) corners as exactly those types, which is the
            # case this fallback exists for.
            raise
        except Exception as e:
            # Auto-routed stripe dispatch can hit a Mosaic compile failure on
            # unmeasured (d, k, block) corners (ADVICE r2): fall back to the
            # merge kernel instead of turning an engine='auto' predict into a
            # hard error — loudly, so the root cause isn't lost if the merge
            # path then fails too. A *forced* stripe engine still propagates.
            # The net stays wide below these carve-outs because the observed
            # compile-failure surface spans RuntimeError, NotImplementedError,
            # XlaRuntimeError, and the axon tunnel's HTTP-500 wrapper.
            if not auto_routed:
                raise
            import warnings

            warnings.warn(
                "auto-routed stripe kernel dispatch failed "
                f"({type(e).__name__}: {e}); falling back to the merge kernel",
                RuntimeWarning,
                stacklevel=2,
            )
            engine = "merge"
    if engine == "merge":
        # bf16 halves the train block in VMEM, which is exactly what lets the
        # bigger query block (fewer train re-streams) fit: (512, 1024) is the
        # v5e sweet spot for the bf16 form, (256, 1024) for f32.
        block_q = block_q or (512 if precision == "bf16" else 256)
        block_n = max(block_n or 1024, k)  # per-tile top-k needs k <= tile width
        tx, _ = pad_axis_to_multiple(train_x.astype(np.float32), block_n, axis=0)
        qx, _ = pad_axis_to_multiple(test_x.astype(np.float32), block_q, axis=0)
        tx, _ = pad_axis_to_multiple(tx, 128, axis=1)  # lane-align features
        qx, _ = pad_axis_to_multiple(qx, 128, axis=1)
        # bf16 stores the train operand AS bf16: this wide-feature config is
        # HBM-bound on the train stream (see _knn_kernel), so halving it is
        # the actual speedup; the matmul consumes the same rounded values.
        txj = jnp.asarray(tx, jnp.bfloat16 if precision == "bf16" else None)

        _, idx = knn_pallas_candidates(
            txj, jnp.asarray(qx), n, k,
            block_q=block_q, block_n=block_n, interpret=interpret,
            d_true=d_true, precision=precision,
        )
        idx = np.asarray(idx)[:q]
    labels = train_y[np.minimum(idx, n - 1)]
    return np.asarray(vote(jnp.asarray(labels), num_classes))
