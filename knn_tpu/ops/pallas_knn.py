"""Pallas TPU kernel: tiled pairwise-distance + running top-k candidates.

SURVEY.md §7 step 7 / BASELINE.json config 5 — the wide-feature configuration
(MNIST-784-shaped) where the reference's scalar inner loop (main.cpp:14-23,
D-1 float ops per train row per query) is hopeless. Here the distance block is
one MXU matmul (``|q|^2 - 2 q·t + |t|^2``) and the k-candidate insertion sort
the reference runs per train row (main.cpp:46-61) becomes a VMEM-resident
running top-k that is folded once per train *tile*.

Kernel structure (grid = query tiles × train tiles, train innermost):

    for i in query_tiles:          # parallel
      for j in train_tiles:        # arbitrary (sequential accumulation)
        d  = dist(q_block[i], t_block[j])        # MXU, [BQ, BN]
        out[i] = topk_merge(out[i], (d, gidx))   # VPU, k extraction rounds

The running candidate set lives in the *output* block refs — their index map
ignores ``j``, so the same VMEM buffer persists across the whole train-tile
sweep and is only written back to HBM once per query tile. Train tiles stream
HBM → VMEM via the automatic pallas pipeline (double-buffered by default),
which is exactly the blockwise/"long-context" formulation of §5.7: the train
set plays the role sequence length plays in ring/flash attention, with the
(associative) lexicographic top-k merge in place of the softmax accumulator.

Tie semantics: selection keys on (distance, global train index) — the same
first-seen-wins rule as the reference's strict-``<`` insertion (main.cpp:47)
— so tiling does not perturb which neighbors are kept (§7 hard part (b)).
Two distance forms (mirroring ops/distance.py): ``precision="exact"`` unrolls
the subtraction form over the true feature count — identical rows give
exactly 0, preserving the large dataset's dist==0 ties and golden accuracy —
while ``precision="fast"`` uses one MXU matmul per tile pair, the right mode
for wide features (MNIST-784) where the VPU unroll would dominate.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from knn_tpu.utils.padding import pad_axis_to_multiple

_INT_MAX = np.int32(np.iinfo(np.int32).max)


def _merge_topk_rounds(
    d_cat: jnp.ndarray, i_cat: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k rounds of lexicographic (distance, index) min-extraction over the
    last axis. Pure VPU ops (min / compare / where) — no sort network needed
    for the small k the reference supports (k ≪ tile width)."""
    out_d, out_i = [], []
    for _ in range(k):
        m = jnp.min(d_cat, axis=1, keepdims=True)
        is_min = d_cat == m
        sel = jnp.min(jnp.where(is_min, i_cat, _INT_MAX), axis=1, keepdims=True)
        out_d.append(m)
        out_i.append(sel)
        # Retire the selected entry on BOTH keys: +inf distance alone is a
        # no-op for candidates that are already +inf (NaN-policy distances),
        # which would re-select the same index every round.
        taken = is_min & (i_cat == sel)
        d_cat = jnp.where(taken, jnp.inf, d_cat)
        i_cat = jnp.where(taken, _INT_MAX, i_cat)
    return jnp.concatenate(out_d, axis=1), jnp.concatenate(out_i, axis=1)


def _knn_kernel(
    n_valid_ref, q_ref, t_ref, out_d_ref, out_i_ref,
    *, k: int, block_n: int, d_true: int, precision: str,
):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_d_ref[:] = jnp.full(out_d_ref.shape, jnp.inf, out_d_ref.dtype)
        out_i_ref[:] = jnp.full(out_i_ref.shape, _INT_MAX, jnp.int32)

    q = q_ref[:]  # [BQ, D]
    t = t_ref[:]  # [BN, D]
    if precision in ("fast", "bf16"):
        # MXU distance block: |q|^2 - 2 q·t + |t|^2, clamped at 0. One matmul,
        # but catastrophic cancellation perturbs near-zero distances. "bf16"
        # additionally feeds the MXU bfloat16 operands (f32 accumulation) for
        # 2x matmul throughput at ~3 fewer mantissa digits in the cross term.
        q2 = jnp.sum(q * q, axis=1, keepdims=True)  # [BQ, 1]
        t2 = jnp.sum(t * t, axis=1, keepdims=True).T  # [1, BN]
        if precision == "bf16":
            q, t = q.astype(jnp.bfloat16), t.astype(jnp.bfloat16)
        cross = jax.lax.dot_general(
            q, t,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d = jnp.maximum(q2 + t2 - 2.0 * cross, 0.0)  # [BQ, BN]
    else:
        # Exact subtraction form, unrolled over the true feature count (the
        # lane padding is skipped): per-pair float accumulation like the
        # reference's inner loop (main.cpp:17-19), so identical rows give
        # exactly 0 and the large dataset's dist==0 ties survive (§7 (a)).
        d = jnp.zeros((q.shape[0], t.shape[0]), jnp.float32)
        for f in range(d_true):
            diff = q[:, f : f + 1] - t[:, f : f + 1].T  # [BQ, BN]
            d = d + diff * diff
    # Framework-wide NaN policy: missing-value NaNs -> +inf distance
    # (ops/distance.py; the reference is UB here, SURVEY.md §3.5.5).
    d = jnp.where(jnp.isnan(d), jnp.inf, d)

    # Global train-row indices for this tile; rows past n_valid (padding) are
    # masked to (+inf, INT_MAX) so they can never win a selection round — the
    # FLT_MAX-init trick of main.cpp:33 applied to padding instead of UB.
    gcol = j * block_n + jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    valid = gcol < n_valid_ref[0]
    d = jnp.where(valid, d, jnp.inf)
    gidx = jnp.where(valid, gcol, _INT_MAX)

    d_cat = jnp.concatenate([out_d_ref[:], d], axis=1)
    i_cat = jnp.concatenate([out_i_ref[:], gidx], axis=1)
    new_d, new_i = _merge_topk_rounds(d_cat, i_cat, k)
    out_d_ref[:] = new_d
    out_i_ref[:] = new_i


@functools.partial(
    jax.jit,
    static_argnames=("k", "block_q", "block_n", "interpret", "d_true", "precision"),
)
def knn_pallas_candidates(
    train_x: jnp.ndarray,
    test_x: jnp.ndarray,
    n_valid: jnp.ndarray,
    k: int,
    block_q: int = 256,
    block_n: int = 1024,
    interpret: bool = False,
    d_true: Optional[int] = None,
    precision: str = "exact",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[N,D] train, [Q,D] queries -> ([Q,k] dists, [Q,k] int32 global indices),
    sorted ascending by (distance, index). N, Q, D must be pre-padded to
    block_n / block_q / lane multiples (zero-pad D — it adds 0 to distances).
    ``d_true`` is the unpadded feature count (the exact path loops over it);
    ``precision`` picks the distance form (module docstring)."""
    n_pad, d_feat = train_x.shape
    q_pad = test_x.shape[0]
    assert n_pad % block_n == 0 and q_pad % block_q == 0
    grid = (q_pad // block_q, n_pad // block_n)

    kernel = functools.partial(
        _knn_kernel, k=k, block_n=block_n,
        d_true=d_true if d_true is not None else d_feat, precision=precision,
    )
    flops = 2 * q_pad * n_pad * d_feat + 4 * grid[1] * q_pad * k * (block_n + k)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            # Index maps take (grid indices..., scalar-prefetch refs...).
            in_specs=[
                pl.BlockSpec((block_q, d_feat), lambda i, j, n_ref: (i, 0)),
                pl.BlockSpec((block_n, d_feat), lambda i, j, n_ref: (j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((block_q, k), lambda i, j, n_ref: (i, 0)),
                pl.BlockSpec((block_q, k), lambda i, j, n_ref: (i, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((q_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, k), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=(q_pad + n_pad) * d_feat * 4 + q_pad * k * 8,
            transcendentals=0,
        ),
        interpret=interpret,
    )(jnp.asarray(n_valid, jnp.int32).reshape(1), test_x, train_x)


def predict_pallas(
    train_x: np.ndarray,
    train_y: np.ndarray,
    test_x: np.ndarray,
    k: int,
    num_classes: int,
    block_q: int = 256,
    block_n: int = 1024,
    interpret: Optional[bool] = None,
    precision: str = "exact",
) -> np.ndarray:
    """Host entry: pad (queries, train rows, feature lanes), run the kernel,
    gather labels, vote. Interpret mode defaults on for non-TPU backends so the
    same code path is testable on the CPU mesh (SURVEY.md §4)."""
    from knn_tpu.ops.vote import vote

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, q = train_x.shape[0], test_x.shape[0]
    d_true = train_x.shape[1]
    block_n = max(block_n, k)  # streaming merge needs k candidates per tile
    tx, _ = pad_axis_to_multiple(train_x.astype(np.float32), block_n, axis=0)
    qx, _ = pad_axis_to_multiple(test_x.astype(np.float32), block_q, axis=0)
    tx, _ = pad_axis_to_multiple(tx, 128, axis=1)  # lane-align features
    qx, _ = pad_axis_to_multiple(qx, 128, axis=1)

    _, idx = knn_pallas_candidates(
        jnp.asarray(tx), jnp.asarray(qx), n, k,
        block_q=block_q, block_n=block_n, interpret=interpret,
        d_true=d_true, precision=precision,
    )
    idx = np.asarray(idx)[:q]
    labels = train_y[np.minimum(idx, n - 1)]
    return np.asarray(vote(jnp.asarray(labels), num_classes))
