"""Adaptive batching: re-tune the coalescing window, replay-proven.

The PR 12 residual, closed: the what-if simulator (:mod:`knn_tpu.obs.whatif`)
could always price a candidate ``max_wait_ms`` against the live captured
arrival process, but the operator had to read the frontier and set a flag
by hand. This controller runs that loop on a cadence:

1. arm a short workload-capture window (:mod:`knn_tpu.obs.workload`)
   over live traffic (skipped without traffic, or while an operator /
   burn-trigger capture already owns the recorder — theirs wins);
2. simulate the candidate grid (:func:`knn_tpu.obs.whatif.default_policy_candidates`
   — the live policy plus halvings/doublings of its wait window) over
   the captured arrivals, costed by the capacity model's CURRENT fitted
   dispatch model;
3. pick the best predicted p99 whose predicted duty cycle stays under
   the bound (a policy that wins latency by saturating the worker is no
   win — the next burst has nowhere to go);
4. **apply the candidate only after replay proves it**: set the live
   batcher's ``max_wait_ms`` to the candidate, re-drive the captured
   reads through it (:func:`knn_tpu.obs.replay.replay_workload`,
   mutations off — the capture's writes already happened), and REVERT
   unless verification reports zero divergences. Batching must never
   change answers (the bit-identity contract); a candidate that does is
   refused and audited, whatever its predicted latency.

Only the coalescing window moves. ``max_batch``/bucket ladders change
compiled shapes and warmup cost — those stay operator decisions.

Every cycle lands one ``knn_control_autotune_total{outcome}`` increment
(``applied`` | ``held`` | ``refused`` | ``skipped``) and one audit-ring
entry; the live window is exported as ``knn_control_max_wait_ms``.
``replay_fn`` is injectable so tests force a refusal without
manufacturing a real divergence.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from knn_tpu import obs
from knn_tpu.control.admission import AUDIT_RING

#: A candidate predicted to run the worker hotter than this is rejected
#: even when its predicted p99 wins — saturation is the knee, not a
#: tuning target.
DUTY_CYCLE_BOUND = 0.85

#: Captured windows with fewer reads than this are not an arrival
#: process, they are noise; the cycle skips rather than tune on them.
MIN_REQUESTS = 32

#: Replay pacing for the verification pass: faster than real time (the
#: cycle must fit inside its cadence) but still paced, so the replayed
#: coalescing pattern resembles the captured one.
VERIFY_SPEED = 8.0


class BatchAutotuner:
    """Cadenced capture → frontier → replay-verified apply loop.

    ``batcher``  — the live :class:`~knn_tpu.serve.batcher.MicroBatcher`
                   (its ``max_wait_ms`` is the one knob this moves);
    ``capacity`` — the :class:`~knn_tpu.obs.capacity.CapacityTracker`
                   whose fitted dispatch model costs candidates;
    ``workload`` — the server's :class:`~knn_tpu.obs.workload.WorkloadCapture`;
    ``interval_s`` — the cadence (``--autotune-interval-s``); each cycle
                   captures for ``min(10, interval_s / 3)`` seconds;
    ``replay_fn`` — test seam; defaults to
                   :func:`knn_tpu.obs.replay.replay_workload`.
    ``autostart=False`` runs no thread; drive :meth:`run_cycle`.
    """

    def __init__(self, batcher, capacity, workload, *,
                 interval_s: float,
                 duty_cycle_bound: float = DUTY_CYCLE_BOUND,
                 min_requests: int = MIN_REQUESTS,
                 replay_fn: Optional[Callable] = None,
                 autostart: bool = True):
        if interval_s <= 0:
            raise ValueError(
                f"autotune interval must be > 0 s, got {interval_s}")
        if workload is None:
            raise ValueError(
                "autotune needs the workload-capture layer "
                "(--capture-dir) — the frontier is only as good as the "
                "arrival process it is fitted to")
        if capacity is None:
            raise ValueError(
                "autotune needs the capacity layer (--cost-accounting) — "
                "candidates are costed by its fitted dispatch model")
        self.batcher = batcher
        self.capacity = capacity
        self.workload = workload
        self.interval_s = float(interval_s)
        self.duty_cycle_bound = float(duty_cycle_bound)
        self.min_requests = int(min_requests)
        self._replay_fn = replay_fn
        self._lock = threading.Lock()
        self.cycles = 0
        self.outcomes = {"applied": 0, "held": 0, "refused": 0,
                         "skipped": 0}
        self._audit: deque = deque(maxlen=AUDIT_RING)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self._thread = threading.Thread(
                target=self._run, name="knn-control-autotune", daemon=True)
            self._thread.start()

    # -- the cadence loop --------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001 — a failed cycle must not
                pass           # kill the cadence; the next one retries

    def run_cycle(self) -> dict:
        """One capture → frontier → verify → apply cycle. Returns the
        audit entry (also appended to the ring + counted). Public so the
        soak and tests drive cycles deterministically."""
        with self._lock:
            self.cycles += 1
        outcome, detail = self._cycle_inner()
        entry = {"ts": time.time(), "outcome": outcome, **detail}
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            self._audit.append(entry)
        obs.counter_add(
            "knn_control_autotune_total",
            help="autotune cycles by outcome (applied = replay-verified "
                 "policy change; refused = candidate failed bit-identity "
                 "replay; held = live policy already best; skipped = no "
                 "usable capture/model)",
            outcome=outcome,
        )
        obs.gauge_set(
            "knn_control_max_wait_ms", float(self.batcher.max_wait_ms),
            help="the batcher's live coalescing window (autotune moves "
                 "it; flags set its boot value)",
        )
        return entry

    def _cycle_inner(self):
        from knn_tpu.obs.whatif import default_policy_candidates, frontier
        from knn_tpu.obs.workload import CaptureStateError, load_workload

        window_s = min(10.0, max(1.0, self.interval_s / 3.0))
        try:
            self.workload.start(reason="autotune", window_s=window_s)
        except CaptureStateError:
            # An operator or burn-trigger capture owns the recorder —
            # never steal an incident capture for a tuning cycle.
            return "skipped", {"reason": "capture_busy"}
        self._stop.wait(window_s)
        try:
            summary = self.workload.stop()
        except CaptureStateError:
            return "skipped", {"reason": "capture_lost"}
        path = summary.get("path")
        if not path:
            return "skipped", {"reason": "no_artifact"}
        wl = load_workload(path)
        arrivals = wl.arrivals()
        if len(arrivals) < self.min_requests:
            return "skipped", {"reason": "too_few_requests",
                               "requests": len(arrivals)}
        model = self.capacity.export().get("dispatch_model") or {}
        a_ms, b_ms = model.get("a_ms"), model.get("b_ms_per_row")
        if a_ms is None or b_ms is None:
            return "skipped", {"reason": "no_dispatch_model"}

        current_wait = float(self.batcher.max_wait_ms)
        candidates = default_policy_candidates(
            self.batcher.max_batch, current_wait, self.batcher.buckets)
        rows = frontier(arrivals, candidates, a_ms=a_ms, b_ms_per_row=b_ms)
        eligible = [r for r in rows
                    if r["duty_cycle"] <= self.duty_cycle_bound
                    and r["p99_ms"] is not None]
        if not eligible:
            return "skipped", {"reason": "no_eligible_candidate"}
        best = min(eligible, key=lambda r: (r["p99_ms"], r["p50_ms"]))
        best_wait = float(best["policy"]["max_wait_ms"])
        detail = {
            "captured_requests": len(arrivals),
            "current_max_wait_ms": current_wait,
            "candidate_max_wait_ms": best_wait,
            "predicted_p99_ms": best["p99_ms"],
            "predicted_duty_cycle": best["duty_cycle"],
        }
        if abs(best_wait - current_wait) < 1e-9:
            return "held", detail

        # Apply-then-prove: the candidate serves the replayed reads; any
        # divergence from the captured digests reverts it on the spot.
        # Reads only (mutations already happened) against the LIVE
        # batcher — the verification load is the captured window itself,
        # compressed, which the window just demonstrated fits.
        replay = self._replay_fn
        if replay is None:
            from knn_tpu.obs.replay import replay_workload as replay
        self.batcher.max_wait_ms = best_wait
        try:
            verdict = replay(wl, batcher=self.batcher, speed=VERIFY_SPEED,
                             replay_mutations=False)
        except Exception as e:  # noqa: BLE001 — an unverifiable
            self.batcher.max_wait_ms = current_wait  # candidate never lands
            detail["error"] = f"{type(e).__name__}: {e}"
            return "refused", detail
        verify = verdict.get("verify") or {}
        divergences = int(verify.get("divergences") or 0)
        detail["replay_divergences"] = divergences
        detail["replay_verified"] = int(verify.get("verified") or 0)
        if divergences > 0:
            self.batcher.max_wait_ms = current_wait
            return "refused", detail
        return "applied", detail

    # -- lifecycle / read side ---------------------------------------------

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def export(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "cycles": self.cycles,
                "outcomes": dict(self.outcomes),
                "duty_cycle_bound": self.duty_cycle_bound,
                "live_max_wait_ms": float(self.batcher.max_wait_ms),
                "audit": list(self._audit),
            }
