"""Brownout ladder: spend quality before availability, reversibly.

When a replica is past its knee, the FIRST things to give up are the ones
nobody's request depends on: observability sampling, recall margin above
the floor, deadline slack. This controller walks a ladder of such
**reversible** knobs under sustained pressure — one step per cooldown,
the :mod:`knn_tpu.index.probe_policy` hysteresis shape — and walks every
step back on recovery, so the post-incident operating point is EXACTLY
the configured one (pinned by ``make overload-soak``: every applied step
must be audited and reverted after the burst).

The ladder the server builds (from whichever layers are actually wired):

1. shadow-scoring sample rate down (quality SLI gets noisier, serving
   gets cheaper — the floor still holds on fewer samples);
2. drift-monitor sample rate down (same trade);
3. ivf ``nprobe`` clamped to base (give back the probe policy's widened
   recall margin — the probe policy resumes control on revert);
4. per-class deadline tightening (queue time stops masking the knee —
   late work 504s instead of occupying batch slots).

Separately from the ladder, :meth:`BrownoutController.defer_background`
reports whether HEADROOM IS NEGATIVE (offered load past sustainable) —
the compactor checks it before kicking a merge, so background index work
schedules into measured headroom instead of competing with overload
traffic (the LSM merge-scheduling shape; explicit ``/admin/compact``
still overrides — an operator's direct order beats the scheduler).

Every step is audited (ring + ``knn_control_brownout_steps_total``
counter + gauge + trace marker). The clock is injectable and
:meth:`tick` is public so tests drive the hysteresis on a fake clock
with no thread and no sleeps.

Env-tunable (read at construction):

======================================  =====  =========================
``KNN_TPU_CONTROL_HEADROOM_FLOOR``      1.0    headroom that engages
``KNN_TPU_CONTROL_RELEASE_HEADROOM``    1.2    headroom that releases
``KNN_TPU_CONTROL_BROWNOUT_BURN``       1.5    burn that engages
``KNN_TPU_CONTROL_RELEASE_BURN``        0.5    burn that allows release
``KNN_TPU_CONTROL_COOLDOWN_MS``         2000   freeze after any step
``KNN_TPU_CONTROL_EVAL_MS``             250    tick interval (thread)
======================================  =====  =========================
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

from knn_tpu import obs
from knn_tpu.control.admission import (
    _COOLDOWN_ENV,
    _EVAL_ENV,
    _FLOOR_ENV,
    _RELEASE_BURN_ENV,
    _RELEASE_HEADROOM_ENV,
    AUDIT_RING,
    _env_float,
)

_BURN_ENV = "KNN_TPU_CONTROL_BROWNOUT_BURN"


class BrownoutStep:
    """One reversible knob on the ladder: ``apply()`` degrades it,
    ``revert()`` restores the exact pre-brownout value (both must be
    idempotent — the controller calls each at most once per engagement,
    but a restart-recovery path may re-revert)."""

    __slots__ = ("name", "apply", "revert")

    def __init__(self, name: str, apply: Callable[[], object],
                 revert: Callable[[], object]):
        self.name = str(name)
        self.apply = apply
        self.revert = revert


class BrownoutController:
    """Hysteretic ladder walker over the capacity/SLO pressure signal.

    ``steps`` — the ordered ladder (first step engages first, reverts
    last); ``slo``/``capacity`` — the signal sources (either may be
    None); ``clock`` — injectable monotonic-seconds callable for tests.
    ``autostart=False`` runs no thread; drive :meth:`tick` directly.
    """

    def __init__(self, steps: List[BrownoutStep], *, slo=None,
                 capacity=None,
                 headroom_floor: Optional[float] = None,
                 release_headroom: Optional[float] = None,
                 engage_burn: Optional[float] = None,
                 release_burn: Optional[float] = None,
                 cooldown_ms: Optional[float] = None,
                 eval_ms: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 autostart: bool = True):
        if not steps:
            raise ValueError("brownout needs at least one ladder step")
        self.steps = list(steps)
        self.slo = slo
        self.capacity = capacity
        self.headroom_floor = (headroom_floor if headroom_floor is not None
                               else _env_float(_FLOOR_ENV, 1.0))
        self.release_headroom = (
            release_headroom if release_headroom is not None
            else _env_float(_RELEASE_HEADROOM_ENV, 1.2))
        self.engage_burn = (engage_burn if engage_burn is not None
                            else _env_float(_BURN_ENV, 1.5))
        self.release_burn = (release_burn if release_burn is not None
                             else _env_float(_RELEASE_BURN_ENV, 0.5))
        if self.release_headroom < self.headroom_floor:
            raise ValueError(
                f"release_headroom ({self.release_headroom}) must be >= "
                f"headroom_floor ({self.headroom_floor}) or the ladder "
                f"would thrash")
        if self.release_burn > self.engage_burn:
            raise ValueError(
                f"release_burn ({self.release_burn}) must be <= "
                f"engage_burn ({self.engage_burn}) or the ladder would "
                f"thrash")
        self.cooldown_ms = (cooldown_ms if cooldown_ms is not None
                            else _env_float(_COOLDOWN_ENV, 2000.0))
        self.eval_ms = (eval_ms if eval_ms is not None
                        else _env_float(_EVAL_ENV, 250.0))
        self.clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self.level = 0  # steps currently applied (0 = fully healthy)
        self._last_move_s = float("-inf")
        self._last_headroom: Optional[float] = None
        self._last_burn = 0.0
        self.moves = {"apply": 0, "revert": 0}
        self._audit: deque = deque(maxlen=AUDIT_RING)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self._thread = threading.Thread(
                target=self._run, name="knn-control-brownout", daemon=True)
            self._thread.start()

    # -- the control loop --------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.eval_ms / 1e3):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a broken signal or a
                pass           # failing knob must not kill the loop

    def tick(self) -> None:
        """One evaluation: read the signals, maybe walk one step. Public
        so tests (and the soak's debug hooks) drive it on a fake clock."""
        now = self.clock()
        headroom = self._headroom()
        burn = self._signal_burn()
        with self._lock:
            self._last_headroom = headroom
            self._last_burn = burn
            if (now - self._last_move_s) < self.cooldown_ms / 1e3:
                return
            pressured = ((headroom is not None
                          and headroom < self.headroom_floor)
                         or burn > self.engage_burn)
            recovered = ((headroom is None
                          or headroom >= self.release_headroom)
                         and burn < self.release_burn)
            if pressured and self.level < len(self.steps):
                step = self.steps[self.level]
                direction = "apply"
                self.level += 1
            elif recovered and self.level > 0:
                self.level -= 1
                step = self.steps[self.level]
                direction = "revert"
            else:
                return
            self._last_move_s = now
            self.moves[direction] += 1
            level = self.level
            self._audit.append({
                "ts": time.time(),
                "step": step.name,
                "action": direction,
                "level": level,
                "headroom_ratio": (round(headroom, 3)
                                   if headroom is not None else None),
                "burn": round(burn, 3),
            })
        # The knob itself runs OUTSIDE the lock: a step that touches a
        # layer's own lock (probe policy, shed queues) must not nest
        # under ours.
        try:
            (step.apply if direction == "apply" else step.revert)()
        except Exception:  # noqa: BLE001 — audit the failure, keep going
            self._audit.append({
                "ts": time.time(), "step": step.name,
                "action": f"{direction}-failed", "level": level,
            })
        obs.counter_add(
            "knn_control_brownout_steps_total",
            help="brownout ladder moves (pressure applies the next "
                 "reversible quality/cost step; recovery reverts it)",
            step=step.name, direction=direction,
        )
        obs.gauge_set(
            "knn_control_brownout_level", level,
            help="brownout ladder steps currently applied "
                 "(0 = fully healthy operating point)",
        )
        with obs.span("control.brownout", step=step.name,
                      direction=direction, level=level,
                      burn=round(burn, 3)):
            pass

    def _headroom(self) -> Optional[float]:
        try:
            return self.capacity.export().get("headroom_ratio") \
                if self.capacity is not None else None
        except Exception:  # noqa: BLE001
            return None

    def _signal_burn(self) -> float:
        """Max availability/latency burn on the shortest window — the
        budgets brownout spends quality to protect."""
        if self.slo is None:
            return 0.0
        try:
            burns = self.slo.burn_rates()
        except Exception:  # noqa: BLE001
            return 0.0
        from knn_tpu.obs.slo import window_label

        label = window_label(min(self.slo.windows_s))
        worst = 0.0
        for objective in ("availability", "latency"):
            per_window = burns.get(objective, {})
            if per_window:
                worst = max(worst, float(
                    per_window.get(label, next(iter(per_window.values())))))
        return worst

    # -- background-work gate ----------------------------------------------

    def defer_background(self) -> bool:
        """True while measured headroom is NEGATIVE (offered load past
        sustainable): compaction and other background index work should
        wait for headroom instead of stealing the worker from overload
        traffic. Reads the last tick's cached signal — O(1) on the
        compactor's path."""
        with self._lock:
            return (self._last_headroom is not None
                    and self._last_headroom < 1.0)

    # -- lifecycle / read side ---------------------------------------------

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def export(self) -> dict:
        with self._lock:
            return {
                "level": self.level,
                "steps": [s.name for s in self.steps],
                "applied": [s.name for s in self.steps[:self.level]],
                "moves": dict(self.moves),
                "headroom_floor": self.headroom_floor,
                "release_headroom": self.release_headroom,
                "engage_burn": self.engage_burn,
                "release_burn": self.release_burn,
                "cooldown_ms": self.cooldown_ms,
                "defer_background": (self._last_headroom is not None
                                     and self._last_headroom < 1.0),
                "last_headroom_ratio": (
                    round(self._last_headroom, 3)
                    if self._last_headroom is not None else None),
                "last_burn": round(self._last_burn, 4),
                "audit": list(self._audit),
            }
