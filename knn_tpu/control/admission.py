"""Priority admission: shed lowest-priority classes first under pressure.

The PR-8 cost layer gave every request a class (``x-knn-class``, validated
and cardinality-capped by :mod:`knn_tpu.obs.accounting`) and the capacity
model gave every replica a headroom ratio — but admission treated a
``bulk`` backfill row exactly like an ``interactive`` user query, so under
overload the queue-full 429s landed uniformly and the high-priority error
budget burned for low-priority load. This module closes that loop.

The operator maps classes to integer priorities (``--priority
interactive=0,bulk=2``; lower number = more important). When the pressure
signal engages — capacity headroom under the floor, or the
availability/latency burn rate over the shed threshold on the shortest SLO
window — a hysteretic cutoff walks DOWN one priority tier per evaluation
(past a cooldown, the :mod:`knn_tpu.index.probe_policy` shape), shedding
the lowest-priority tier first with a typed
:class:`~knn_tpu.resilience.errors.ShedByPolicy` (HTTP 429 +
``Retry-After`` derived from the measured headroom, jittered so a shed
cohort does not retry in lockstep). On recovery the cutoff walks back up,
one tier per cooldown. The **top tier is never shed by policy**: when
pressure persists with only protected classes admitted, the queue-full
backstop (plain :class:`~knn_tpu.resilience.errors.OverloadError`) is the
final limit — that distinction is exactly what the SLO layer uses to keep
a deliberate ``bulk`` shed from reading as an availability incident
(docs/RESILIENCE.md §Degradation order).

The decision path a submitting thread pays is one monotonic read + a
cached cutoff between evaluations; the O(window) capacity/burn aggregation
runs at most once per ``eval_ms``.

Env-tunable (read at construction, like the probe policy):

======================================  =====  =========================
``KNN_TPU_CONTROL_HEADROOM_FLOOR``      1.0    headroom that engages shed
``KNN_TPU_CONTROL_RELEASE_HEADROOM``    1.2    headroom that releases it
``KNN_TPU_CONTROL_SHED_BURN``           2.0    burn that engages shed
``KNN_TPU_CONTROL_RELEASE_BURN``        0.5    burn that allows release
``KNN_TPU_CONTROL_COOLDOWN_MS``         2000   freeze after any move
``KNN_TPU_CONTROL_EVAL_MS``             250    min interval between reads
======================================  =====  =========================
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Dict, Optional

from knn_tpu import obs
from knn_tpu.obs import accounting as acct
from knn_tpu.resilience.errors import ShedByPolicy

_FLOOR_ENV = "KNN_TPU_CONTROL_HEADROOM_FLOOR"
_RELEASE_HEADROOM_ENV = "KNN_TPU_CONTROL_RELEASE_HEADROOM"
_SHED_BURN_ENV = "KNN_TPU_CONTROL_SHED_BURN"
_RELEASE_BURN_ENV = "KNN_TPU_CONTROL_RELEASE_BURN"
_COOLDOWN_ENV = "KNN_TPU_CONTROL_COOLDOWN_MS"
_EVAL_ENV = "KNN_TPU_CONTROL_EVAL_MS"

#: Retry-After bounds (seconds): the header must tell a shed client
#: something actionable — never "retry immediately" into the same
#: overload, never "go away for minutes" for a transient knee crossing.
RETRY_AFTER_MIN_S = 1.0
RETRY_AFTER_MAX_S = 30.0

#: Audit ring size — matches the flight recorder's "recent decisions"
#: scale; the full history is in the counters.
AUDIT_RING = 256


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    raw = os.environ.get(name, "")
    try:
        return max(lo, float(raw)) if raw else default
    except ValueError:
        return default


def parse_priority_map(spec: str) -> Dict[str, int]:
    """Parse ``--priority``'s ``class=prio,class=prio`` spec.

    Classes obey the accounting layer's label grammar (they become
    Prometheus label values through the same pipeline); priorities are
    non-negative ints, lower = more important. Raises :class:`ValueError`
    with the offending token so the CLI can 2-exit with context."""
    out: Dict[str, int] = {}
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        name, sep, prio_s = token.partition("=")
        name = name.strip()
        if not sep:
            raise ValueError(
                f"priority token {token!r} is not class=priority")
        if not acct.valid_request_class(name):
            raise ValueError(
                f"invalid class {name!r} in priority map: want 1-"
                f"{acct.MAX_CLASS_LEN} chars of [a-z0-9_.-]")
        try:
            prio = int(prio_s.strip())
        except ValueError:
            raise ValueError(
                f"priority for class {name!r} must be an integer, "
                f"got {prio_s.strip()!r}") from None
        if prio < 0:
            raise ValueError(
                f"priority for class {name!r} must be >= 0, got {prio}")
        if name in out:
            raise ValueError(f"class {name!r} appears twice in priority map")
        out[name] = prio
    if not out:
        raise ValueError("priority map is empty")
    return out


class PriorityAdmission:
    """Hysteretic priority-tier admission cutoff over the pressure signal.

    ``priority_map`` — class name → priority (lower = more important);
    ``slo``          — an :class:`~knn_tpu.obs.slo.SLOTracker` or None;
    ``capacity``     — a :class:`~knn_tpu.obs.capacity.CapacityTracker`
                       or None. With neither signal the cutoff rests
                       fully open forever (admission is then only the
                       queue bound — a priority map without signals is a
                       labeling, not a policy).
    """

    def __init__(self, priority_map: Dict[str, int], *, slo=None,
                 capacity=None,
                 headroom_floor: Optional[float] = None,
                 release_headroom: Optional[float] = None,
                 shed_burn: Optional[float] = None,
                 release_burn: Optional[float] = None,
                 cooldown_ms: Optional[float] = None,
                 eval_ms: Optional[float] = None):
        if not priority_map:
            raise ValueError("priority_map must not be empty")
        self.priority_map = {str(k): int(v) for k, v in priority_map.items()}
        # Ascending distinct priorities; the LAST tier sheds first, the
        # first tier (the protected one) never sheds by policy.
        self.levels = sorted(set(self.priority_map.values()))
        self.slo = slo
        self.capacity = capacity
        self.headroom_floor = (headroom_floor if headroom_floor is not None
                               else _env_float(_FLOOR_ENV, 1.0))
        self.release_headroom = (
            release_headroom if release_headroom is not None
            else _env_float(_RELEASE_HEADROOM_ENV, 1.2))
        self.shed_burn = (shed_burn if shed_burn is not None
                          else _env_float(_SHED_BURN_ENV, 2.0))
        self.release_burn = (release_burn if release_burn is not None
                             else _env_float(_RELEASE_BURN_ENV, 0.5))
        if self.release_headroom < self.headroom_floor:
            raise ValueError(
                f"release_headroom ({self.release_headroom}) must be >= "
                f"headroom_floor ({self.headroom_floor}) or the cutoff "
                f"would thrash")
        if self.release_burn > self.shed_burn:
            raise ValueError(
                f"release_burn ({self.release_burn}) must be <= shed_burn "
                f"({self.shed_burn}) or the cutoff would thrash")
        self.cooldown_ms = (cooldown_ms if cooldown_ms is not None
                            else _env_float(_COOLDOWN_ENV, 2000.0))
        self.eval_ms = (eval_ms if eval_ms is not None
                        else _env_float(_EVAL_ENV, 250.0))
        self._lock = threading.Lock()
        # How many tiers are currently shed, counted from the BOTTOM
        # (highest priority number). 0 = fully open; capped at
        # len(levels) - 1 so the top tier always admits.
        self._shed_tiers = 0
        self._last_eval_ns = 0
        self._last_move_ns = 0
        self._last_headroom: Optional[float] = None
        self._last_burn = 0.0
        self._rng = random.Random()
        self.moves = {"shed": 0, "restore": 0}
        self._audit: deque = deque(maxlen=AUDIT_RING)

    # -- the decision path (submitting threads) ----------------------------

    def priority_of(self, request_class: Optional[str]) -> int:
        """The priority this class admits at. Unmapped classes inherit the
        default class's mapping when the operator gave one, else priority
        0 — an operator who maps only ``bulk=2`` has said "everything
        else is important", not "everything else is sheddable"."""
        if request_class is not None and request_class in self.priority_map:
            return self.priority_map[request_class]
        return self.priority_map.get(acct.DEFAULT_CLASS, 0)

    def protected(self, request_class: Optional[str]) -> bool:
        """True when this class is in the top tier — never shed by
        policy, and its overload 429s DO spend availability budget
        (docs/RESILIENCE.md: shedding a protected class is an incident,
        shedding a sheddable one is the control plane working)."""
        return self.priority_of(request_class) <= self.levels[0]

    def admit(self, request_class: Optional[str]):
        """One admission decision. Returns None to admit, or a ready
        :class:`ShedByPolicy` (with ``retry_after_s`` priced off the
        current headroom) for the caller to raise — building the error
        here keeps the batcher's hot path to one call."""
        self._evaluate()
        with self._lock:
            shed_tiers = self._shed_tiers
            if shed_tiers == 0:
                return None
            cutoff = self.levels[len(self.levels) - shed_tiers]
            prio = self.priority_of(request_class)
            if prio < cutoff:
                return None
            headroom = self._last_headroom
        retry = self.retry_after_s()
        obs.counter_add(
            "knn_control_shed_total",
            help="requests shed by the priority-admission cutoff "
                 "(deliberate policy 429s, excluded from availability "
                 "burn for non-protected classes)",
            request_class=request_class or acct.DEFAULT_CLASS,
        )
        return ShedByPolicy(
            f"request class {request_class!r} (priority {prio}) shed by "
            f"admission policy: overload cutoff at priority < {cutoff} "
            f"(headroom "
            f"{round(headroom, 3) if headroom is not None else None}); "
            f"retry after backoff",
            request_class=request_class or acct.DEFAULT_CLASS,
            retry_after_s=retry,
        )

    def retry_after_s(self) -> float:
        """The headroom-derived client backoff for a shed/overload
        response: the further past the knee, the longer the ask, jittered
        +-25% so a shed cohort does not come back in lockstep."""
        with self._lock:
            headroom = self._last_headroom
        if headroom is None or headroom >= 1.0:
            base = RETRY_AFTER_MIN_S
        else:
            # headroom 0.5 = offered load is 2x sustainable: asking half
            # the cohort to sit out ~2x the floor is the proportional
            # response.
            base = min(RETRY_AFTER_MAX_S,
                       RETRY_AFTER_MIN_S / max(headroom, 1.0 / 64.0))
        return max(RETRY_AFTER_MIN_S,
                   min(RETRY_AFTER_MAX_S,
                       base * (0.75 + 0.5 * self._rng.random())))

    # -- the control loop (lazy, on the decision path) ---------------------

    def _evaluate(self) -> None:
        """Re-read the pressure signal at most every ``eval_ms`` and walk
        the cutoff one tier per cooldown — the probe policy's cached
        hysteresis, applied to admission."""
        if self.slo is None and self.capacity is None:
            return
        now = time.monotonic_ns()
        with self._lock:
            if (now - self._last_eval_ns) < self.eval_ms * 1e6:
                return
            self._last_eval_ns = now
            headroom = self._headroom()
            burn = self._shed_signal_burn()
            self._last_headroom = headroom
            self._last_burn = burn
            if (now - self._last_move_ns) < self.cooldown_ms * 1e6:
                return
            pressured = ((headroom is not None
                          and headroom < self.headroom_floor)
                         or burn > self.shed_burn)
            recovered = ((headroom is None
                          or headroom >= self.release_headroom)
                         and burn < self.release_burn)
            if pressured and self._shed_tiers < len(self.levels) - 1:
                self._move("shed", headroom, burn, now)
            elif recovered and self._shed_tiers > 0:
                self._move("restore", headroom, burn, now)

    def _headroom(self) -> Optional[float]:
        try:
            return self.capacity.export().get("headroom_ratio") \
                if self.capacity is not None else None
        except Exception:  # noqa: BLE001 — a broken signal must not
            return None    # take admission down; the cutoff just holds

    def _shed_signal_burn(self) -> float:
        """Max of the availability and latency burns on the shortest
        window — the fast signals whose budgets shedding protects."""
        if self.slo is None:
            return 0.0
        try:
            burns = self.slo.burn_rates()
        except Exception:  # noqa: BLE001
            return 0.0
        from knn_tpu.obs.slo import window_label

        label = window_label(min(self.slo.windows_s))
        worst = 0.0
        for objective in ("availability", "latency"):
            per_window = burns.get(objective, {})
            if per_window:
                worst = max(worst, float(
                    per_window.get(label, next(iter(per_window.values())))))
        return worst

    def _move(self, direction: str, headroom, burn: float,
              now_ns: int) -> None:
        self._shed_tiers += 1 if direction == "shed" else -1
        self._last_move_ns = now_ns
        self.moves[direction] += 1
        cutoff = (None if self._shed_tiers == 0
                  else self.levels[len(self.levels) - self._shed_tiers])
        self._audit.append({
            "ts": time.time(),
            "action": direction,
            "shed_tiers": self._shed_tiers,
            "cutoff_priority": cutoff,
            "headroom_ratio": (round(headroom, 3)
                               if headroom is not None else None),
            "burn": round(burn, 3),
        })
        obs.counter_add(
            "knn_control_admission_moves_total",
            help="priority-admission cutoff moves (pressure sheds one "
                 "tier; recovery restores one tier)",
            direction=direction,
        )
        obs.gauge_set(
            "knn_control_admission_shed_tiers", self._shed_tiers,
            help="priority tiers currently shed by admission, counted "
                 "from the lowest-priority tier (0 = fully open)",
        )
        with obs.span("control.admission", direction=direction,
                      shed_tiers=self._shed_tiers,
                      burn=round(burn, 3)):
            pass

    # -- read side ---------------------------------------------------------

    def export(self) -> dict:
        with self._lock:
            cutoff = (None if self._shed_tiers == 0
                      else self.levels[len(self.levels) - self._shed_tiers])
            return {
                "priority_map": dict(self.priority_map),
                "levels": list(self.levels),
                "shed_tiers": self._shed_tiers,
                "cutoff_priority": cutoff,
                "protected_priority": self.levels[0],
                "moves": dict(self.moves),
                "headroom_floor": self.headroom_floor,
                "release_headroom": self.release_headroom,
                "shed_burn": self.shed_burn,
                "release_burn": self.release_burn,
                "cooldown_ms": self.cooldown_ms,
                "last_headroom_ratio": (
                    round(self._last_headroom, 3)
                    if self._last_headroom is not None else None),
                "last_burn": round(self._last_burn, 4),
                "audit": list(self._audit),
            }
