"""Fleet autoscaling policy: grow before any replica has to degrade.

Scale is the FIRST rung of the degradation order (docs/RESILIENCE.md):
booting a replica costs money; shedding a request costs a user. The
router already measures both sides of the decision — its own forwarded
request rate (offered load on the fleet) and, from each replica's
``/healthz`` capacity block, the per-replica modeled ``sustainable_qps``
(:mod:`knn_tpu.obs.capacity`, summed here into fleet capacity).

This module is the POLICY only — a pure, clock-injectable decision
function the router polls (:class:`AutoscalePolicy.decide`) plus the
scale-command runner. The MECHANISM is the operator's ``--scale-cmd``
script (invoked ``<cmd> up <url>`` / ``<cmd> down <url>``), which
starts or stops the serve process behind an already-registered replica
slot; the router's replica registry is the scale bound (``--scale-min``
/ ``--scale-max`` clamp how many slots the policy keeps populated), and
the PR 17 snapshot-bootstrap path does the data plane — a replica the
scale command boots blank is seeded from the primary's current
generation by the router's auto-bootstrap, under live traffic
(``make overload-soak`` proves the whole chain).

Hysteresis: scale UP when offered load exceeds ``up_fraction`` of fleet
sustainable QPS (default 0.8 — grow BEFORE the knee, while there is
still headroom to serve the boot); scale DOWN when offered load would
still fit under ``down_fraction`` (default 0.4) of the fleet MINUS the
candidate replica; a shared cooldown separates any two actions, so a
boot's warmup transient cannot trigger the next decision.
"""

from __future__ import annotations

import subprocess
import time
from typing import Callable, Optional

from knn_tpu.control.admission import _env_float

#: Seconds between any two scale actions (--scale-cooldown-s) — long
#: enough for a booted replica's bootstrap + warmup to register in the
#: fleet capacity sum.
DEFAULT_COOLDOWN_S = 60.0

#: Hysteresis band defaults, env-overridable (read at construction, the
#: control-plane knob idiom) — the overload soak narrows them to drill
#: both directions inside a CI-sized window.
_UP_ENV = "KNN_TPU_SCALE_UP_FRACTION"
_DOWN_ENV = "KNN_TPU_SCALE_DOWN_FRACTION"


class AutoscalePolicy:
    """Pure scale-up/down decision over (offered, sustainable, usable).

    ``scale_min``/``scale_max`` — bounds on populated replica slots;
    ``clock`` — injectable monotonic-seconds callable for tests.
    """

    def __init__(self, scale_min: int, scale_max: int, *,
                 up_fraction: Optional[float] = None,
                 down_fraction: Optional[float] = None,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 clock: Optional[Callable[[], float]] = None):
        if up_fraction is None:
            up_fraction = _env_float(_UP_ENV, 0.8)
        if down_fraction is None:
            down_fraction = _env_float(_DOWN_ENV, 0.4)
        if scale_min < 1:
            raise ValueError(f"scale_min must be >= 1, got {scale_min}")
        if scale_max < scale_min:
            raise ValueError(
                f"scale_max ({scale_max}) must be >= scale_min "
                f"({scale_min})")
        if not 0.0 < down_fraction < up_fraction <= 1.0:
            raise ValueError(
                f"need 0 < down_fraction ({down_fraction}) < up_fraction "
                f"({up_fraction}) <= 1 or the policy would thrash")
        self.scale_min = int(scale_min)
        self.scale_max = int(scale_max)
        self.up_fraction = float(up_fraction)
        self.down_fraction = float(down_fraction)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock if clock is not None else time.monotonic
        self._last_action_s = float("-inf")
        self.decisions = {"up": 0, "down": 0}

    def decide(self, offered_qps: float, sustainable_qps: Optional[float],
               usable: int) -> Optional[str]:
        """``"up"`` / ``"down"`` / None. ``offered_qps`` — the router's
        measured forwarded rate; ``sustainable_qps`` — the fleet sum of
        usable replicas' modeled capacity (None until any replica has a
        dispatch model — no model, no action); ``usable`` — replicas
        currently serving."""
        now = self.clock()
        if (now - self._last_action_s) < self.cooldown_s:
            return None
        if sustainable_qps is None or sustainable_qps <= 0 or usable < 1:
            return None
        if (usable < self.scale_max
                and offered_qps > self.up_fraction * sustainable_qps):
            self._last_action_s = now
            self.decisions["up"] += 1
            return "up"
        per_replica = sustainable_qps / usable
        remaining = sustainable_qps - per_replica
        if (usable > self.scale_min and remaining > 0
                and offered_qps < self.down_fraction * remaining):
            self._last_action_s = now
            self.decisions["down"] += 1
            return "down"
        return None

    def export(self) -> dict:
        return {
            "scale_min": self.scale_min,
            "scale_max": self.scale_max,
            "up_fraction": self.up_fraction,
            "down_fraction": self.down_fraction,
            "cooldown_s": self.cooldown_s,
            "decisions": dict(self.decisions),
        }


def run_scale_cmd(scale_cmd: str, direction: str, url: str,
                  timeout_s: float = 300.0) -> None:
    """Invoke the operator's scale command: ``<cmd> up|down <url>``.

    The command is a shell line (like CI's hook scripts) so operators can
    point at anything from a local launcher script to a cloud API call;
    the target slot URL rides argv, not interpolation. Non-zero exit or
    timeout raises — the router audits the failure and retries after its
    cooldown."""
    subprocess.run(
        [*scale_cmd.split(), direction, url],
        check=True, timeout=timeout_s,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
