"""The control plane: close the loops the observability plane measures.

Every signal this package acts on already exists — capacity headroom
(:mod:`knn_tpu.obs.capacity`), SLO burn (:mod:`knn_tpu.obs.slo`),
per-class cost attribution (:mod:`knn_tpu.obs.accounting`), the what-if
policy frontier (:mod:`knn_tpu.obs.whatif`) — but until this package the
only closed loop was nprobe (:mod:`knn_tpu.index.probe_policy`). Under
overload the server shed blindly: a ``bulk`` batch job could exhaust the
error budget ``interactive`` traffic needed, and the fleet could neither
tighten quality to stay available nor grow itself.

Four controllers, engaged in the **strict degradation order** documented
in docs/RESILIENCE.md (:data:`knn_tpu.resilience.degrade.DEGRADATION_ORDER`):

1. **scale** (:mod:`.autoscale`) — the router boots replicas through the
   snapshot-bootstrap path before any single replica has to degrade;
2. **shed low priority** (:mod:`.admission`) — lowest-priority request
   classes 429 first (typed :class:`~knn_tpu.resilience.errors.ShedByPolicy`
   with a headroom-derived ``Retry-After``) while protected classes keep
   admitting;
3. **brownout quality** (:mod:`.brownout`) — reversible quality/cost
   knobs walk down a hysteretic ladder (shadow/drift sample rates, ivf
   nprobe toward base, deadline tightening) and walk back up on recovery;
4. **availability** is the last thing to go — the pre-existing
   queue-full :class:`~knn_tpu.resilience.errors.OverloadError` backstop,
   which this package exists to make rare.

Every controller is hysteretic with a cooldown (the
:mod:`knn_tpu.index.probe_policy` shape), every action is audited (an
in-memory ring exported over ``/debug/control`` plus ``knn_control_*``
instruments), and every action is REVERSIBLE — recovery restores the
exact pre-brownout operating point.

Zero-cost-when-disabled contract: nothing imports this package unless a
control flag is set (``--priority``, ``--brownout``,
``--autotune-interval-s``, ``--scale-cmd``). Flagless serve/route holds
no controller threads, no ``knn_control_*`` instruments, and no
``knn_tpu.control*`` modules in ``sys.modules``
(``scripts/check_disabled_overhead.py`` pins it).
"""
