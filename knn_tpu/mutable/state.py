"""The mutable view: delta tier + tombstones + the lexicographic merge.

An online-mutable index (ROADMAP item 3) is an LSM-style split — the
Fresh-DiskANN recipe (Singh et al., 2021; PAPERS.md) over the classic
LSM-tree design (O'Neil et al., 1996): the big **base** stays immutable
(every existing retrieval rung, device cache, and compiled executable
keeps working untouched) while writes land in a small mutable tail:

- **delta tier** — recently inserted rows in an amortized-doubling array
  (slots below ``count`` are NEVER mutated, so a reader holding a
  snapshot's array reference sees immutable data with no lock);
- **tombstones** — deleted rows are masked out of candidate sets
  post-selection, never physically removed until compaction folds them
  (``knn_tpu/mutable/compact.py``).

Row identity has two layers. **Positional ids** are what clients see:
``0 .. base_n-1`` address the current generation's base rows (exactly the
indices every exact rung already returns) and ``base_n ..`` address live
delta slots — so base-only retrieval is byte-compatible with today's
responses. **Stable ids** never change across compactions (original base
rows keep ``0..N0-1`` forever; every insert draws a fresh one) and are
what the write-ahead epoch log records, which is what makes replay after
a crash — or after an arbitrary number of compactions — deterministic.

The merge contract (pinned by tests/test_mutable.py):

- an EMPTY view (no delta rows, no tombstones) is never merged at all —
  the serving batcher short-circuits on ``view.empty``, so mutable-on
  serving with no mutations is bit-identical to mutable-off on every
  rung;
- delta distances are computed with the oracle backend's metric formulas
  on the same float32 operands every exact rung shares, and the combined
  candidate set selects through
  :func:`~knn_tpu.models.ordering.lexicographic_topk` — THE
  (distance, index) tie contract — so merged answers replay bit-identical
  from the acknowledged mutation history (scripts/mutable_soak.py);
- tombstone masking **widens for k-coverage**: a base answer whose top-k
  contains a dead row is re-retrieved at ``k + live_base_tombstones``
  for the affected query rows only, so results never come up short
  (deletes that would leave fewer than ``k`` live rows in the whole view
  are refused at admission — ``knn_tpu/mutable/engine.py``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from knn_tpu.models.ordering import lexicographic_topk
from knn_tpu.resilience.errors import DataError


class MutationConflict(DataError):
    """A structurally valid mutation the CURRENT state refuses: deleting
    an unknown/already-deleted row, a delete that would leave fewer than
    ``k`` live rows, or a version precondition that no longer holds. The
    HTTP layer maps this to **409** — retrying the same request verbatim
    will keep failing; the client must re-read state first."""


class ReplicationGap(DataError):
    """A replicated WAL record arrived whose ``seq`` skips past the next
    expected one: applying it would silently drop the missing mutations,
    so the follower refuses typed and reports the seq it HAS applied —
    the primary's shipper resets its cursor there and re-ships the gap
    (``POST /admin/wal-append`` maps this to **409**)."""

    def __init__(self, message: str, *, applied_seq: int):
        super().__init__(message)
        self.applied_seq = applied_seq


class WALDivergence(DataError):
    """A replicated record's ``seq`` overlaps history this replica
    already holds, but its content digest differs — the two write-ahead
    logs tell different stories for the same sequence number (the
    rebooted-ex-primary hazard: an unacknowledged tail applied locally
    before the crash, while the promoted follower assigned those seqs to
    NEW writes). Applying or skipping would be silent corruption; the
    replica must be re-seeded (**409**, never retried)."""


class MutableView(NamedTuple):
    """One immutable snapshot of the mutable tier, taken per dispatch.

    ``features``/``values``/``stable`` are shared array references whose
    slots below ``count`` are append-frozen; ``tomb_pos`` masks
    positional ids (this generation's space) and ``tomb_base``/
    ``tomb_delta_slots`` are the same set pre-split into the two arrays
    the merge actually indexes with. ``seq`` is the snapshot's sequence
    point — the response's ``mutation_seq``, the anchor the soak's
    oracle replay verifies against."""

    features: np.ndarray        # [cap, D] float32, rows < count frozen
    values: np.ndarray          # [cap] float32 (labels or targets)
    stable: np.ndarray          # [cap] int64 stable ids
    count: int                  # delta slots in use (live + tombstoned)
    tomb_pos: frozenset         # positional ids masked from answers
    tomb_base: np.ndarray       # positional base tombstones, int64 sorted
    tomb_delta_slots: np.ndarray  # dead delta slot numbers, int64 sorted
    seq: int                    # last mutation folded into this view
    base_n: int                 # base rows in this generation
    generation: int
    #: Device-resident twin of the delta block
    #: (:class:`~knn_tpu.mutable.device_tail.DeviceTailView`), or None
    #: while the tail is host-only — when present, device rungs merge
    #: base+delta in the same dispatch instead of through the host
    #: merge below (``serve/batcher.py`` decides per rung).
    device: "object | None" = None

    @property
    def empty(self) -> bool:
        return self.count == 0 and not self.tomb_pos

    @property
    def live_delta(self) -> int:
        return self.count - int(self.tomb_delta_slots.shape[0])

    @property
    def sentinel(self) -> int:
        """A positional id strictly greater than every addressable row —
        what masked candidate slots carry so the (distance, index) order
        ranks them after every real +inf candidate."""
        return self.base_n + self.count


def delta_distances(view: MutableView, queries: np.ndarray,
                    metric: str) -> np.ndarray:
    """``[Q, count]`` exact distances from each query row to every delta
    slot, with the oracle backend's metric formulas on float32 operands
    (the bit-identity anchor) and the framework NaN → +inf policy; dead
    slots are masked to +inf."""
    from knn_tpu.backends.oracle import _metric_dists

    if view.count == 0:
        return np.empty((queries.shape[0], 0), np.float32)
    d = _metric_dists(np.asarray(queries, np.float32),
                      view.features[:view.count], metric)
    d = np.asarray(d, np.float32)
    np.nan_to_num(d, copy=False, nan=np.inf)
    if view.tomb_delta_slots.size:
        d[:, view.tomb_delta_slots] = np.inf
    return d


def merge_candidates(view: MutableView, queries: np.ndarray,
                     base_d: np.ndarray, base_i: np.ndarray,
                     k: int, metric: str, wide_fn):
    """Fold the delta tier and tombstones into one rung's base answer.

    ``base_d``/``base_i`` — the rung's ``[Q, k]`` base-only candidates;
    ``wide_fn(feats, k_wide)`` — the rung's wider retrieval, called ONLY
    for the query rows whose top-k contains a tombstoned base row (the
    k-coverage widening; exact rungs pass the oracle, the ivf rung its
    own probed search). Returns ``(dists [Q, k] f32, idx [Q, k] i64)``
    under the shared (distance, index) order, positional ids spanning
    base and delta.
    """
    q = queries.shape[0]
    base_d = np.asarray(base_d, np.float32)
    base_i = np.asarray(base_i, np.int64)
    sentinel = view.sentinel
    mb = base_d.shape[1]
    if view.tomb_base.size:
        dead = np.isin(base_i, view.tomb_base)
        hit = dead.any(axis=1)
        if hit.any():
            k_wide = min(view.base_n, k + int(view.tomb_base.size))
            if k_wide > mb:
                pad_d = np.full((q, k_wide - mb), np.inf, np.float32)
                pad_i = np.full((q, k_wide - mb), sentinel, np.int64)
                base_d = np.concatenate([base_d, pad_d], axis=1)
                base_i = np.concatenate([base_i, pad_i], axis=1)
            wd, wi = wide_fn(queries[hit], k_wide)
            base_d[hit] = np.asarray(wd, np.float32)
            base_i[hit] = np.asarray(wi, np.int64)
            dead = np.isin(base_i, view.tomb_base)
        # Mask every dead candidate: +inf distance AND a past-everything
        # id, so a real +inf-distance candidate (NaN query) still wins
        # the (distance, index) tie against a masked slot.
        base_d = np.where(dead, np.inf, base_d)
        base_i = np.where(dead, sentinel, base_i)
    dd = delta_distances(view, queries, metric)
    if dd.shape[1]:
        di = np.broadcast_to(
            view.base_n + np.arange(view.count, dtype=np.int64),
            (q, view.count),
        ).copy()
        if view.tomb_delta_slots.size:
            di[:, view.tomb_delta_slots] = sentinel
        all_d = np.concatenate([base_d, dd], axis=1)
        all_i = np.concatenate([base_i, di], axis=1)
    else:
        all_d, all_i = base_d, base_i
    return lexicographic_topk(all_d, all_i, k)


def lookup_rows(view: MutableView, base: np.ndarray,
                idx: np.ndarray) -> np.ndarray:
    """Gather per-candidate values across the positional id space:
    ``idx < base_n`` reads ``base``, the rest reads the delta slots.
    ``base`` may be 1-D (labels/targets) or 2-D (features)."""
    idx = np.asarray(idx, np.int64)
    base_part = base[np.minimum(idx, view.base_n - 1)]
    if view.count == 0:
        return base_part
    slot = np.clip(idx - view.base_n, 0, view.count - 1)
    delta_src = (view.features if base.ndim == 2 else
                 view.values)[:view.count]
    delta_part = np.asarray(delta_src)[slot]
    mask = idx >= view.base_n
    if base.ndim == 2:
        return np.where(mask[..., None], delta_part, base_part)
    return np.where(mask, delta_part.astype(base.dtype), base_part)


def predict_from_view(model, view: MutableView, dists: np.ndarray,
                      idx: np.ndarray):
    """The vote/aggregation half of a merged answer: candidate labels or
    targets are gathered across base+delta and fed through the SAME
    first-max / inverse-distance helpers the base-only path uses
    (:func:`~knn_tpu.models.knn.vote_from_labels` /
    :func:`~knn_tpu.models.knn.aggregate_targets`)."""
    from knn_tpu.models.knn import (
        KNNClassifier, aggregate_targets, vote_from_labels,
    )

    train = model.train_
    if isinstance(model, KNNClassifier):
        labels = lookup_rows(view, train.labels, idx)
        return vote_from_labels(dists, labels.astype(train.labels.dtype),
                                train.num_classes, model.weights)
    neigh = lookup_rows(view, train.targets, idx)
    return aggregate_targets(dists, neigh, model.weights)


def merged_oracle_kneighbors(model, view: MutableView,
                             queries: np.ndarray):
    """The exact truth of the LIVE view — oracle base retrieval merged
    through the same delta/tombstone fold. The shadow scorer re-answers
    against this (a served answer that ignored the delta tier — staleness
    — diverges and burns the quality SLI), and the soak's replay oracle
    is an independent re-derivation of the same contract."""
    from knn_tpu.backends.oracle import oracle_kneighbors

    train = model.train_
    base_d, base_i = oracle_kneighbors(train.features, queries, model.k,
                                       model.metric)
    if view.empty:
        return base_d, base_i
    return merge_candidates(
        view, np.asarray(queries, np.float32), base_d, base_i, model.k,
        model.metric,
        lambda feats, kw: oracle_kneighbors(train.features, feats, kw,
                                            model.metric),
    )


def view_true_distances(model, view: MutableView, queries: np.ndarray,
                        served_i: np.ndarray, metric: str) -> np.ndarray:
    """Recompute the ACTUAL distance of every served candidate across the
    base+delta id space — the view-aware twin of
    :func:`~knn_tpu.obs.quality.true_distances` (admissibility never
    trusts served distances). A served id that is not addressable in the
    view (past the sentinel) scores +inf — i.e. always a divergence."""
    from knn_tpu.backends.oracle import _metric_dists

    queries = np.asarray(queries, np.float32)
    served_i = np.asarray(served_i, np.int64)
    out = np.empty(served_i.shape, np.float64)
    for row in range(served_i.shape[0]):
        rows = lookup_rows(view, model.train_.features, served_i[row])
        d = _metric_dists(queries[row:row + 1],
                          np.asarray(rows, np.float32), metric)[0]
        out[row] = np.nan_to_num(d.astype(np.float64), nan=np.inf)
    out[served_i >= view.sentinel] = np.inf
    return out


def validate_insert(model, rows, values) -> "tuple[np.ndarray, np.ndarray]":
    """Shape/label validation for an insert — raises ``ValueError`` (HTTP
    400) before anything is logged or applied. Returns the coerced
    ``(rows f32 [m, D], values f32 [m])``."""
    from knn_tpu.models.knn import KNNClassifier

    train = model.train_
    x = np.ascontiguousarray(rows, dtype=np.float32)
    if x.ndim == 1:
        x = x[None, :]
    if x.ndim != 2 or x.shape[1] != train.num_features:
        raise ValueError(
            f"insert rows must be [m, {train.num_features}], got "
            f"{np.shape(rows)}"
        )
    if x.shape[0] == 0:
        raise ValueError("empty insert (0 rows)")
    v = np.asarray(values, dtype=np.float64)
    if v.ndim != 1 or v.shape[0] != x.shape[0]:
        raise ValueError(
            f"insert needs one label per row: {x.shape[0]} row(s) but "
            f"labels has shape {np.shape(values)}"
        )
    if isinstance(model, KNNClassifier):
        if not np.isfinite(v).all() or (v != np.round(v)).any():
            raise ValueError("classifier labels must be integers")
        if (v < 0).any() or (v >= train.num_classes).any():
            raise ValueError(
                f"classifier labels must be in [0, {train.num_classes}) — "
                f"a new class would change the vote dimensionality; "
                f"rebuild the index to add classes"
            )
    elif not np.isfinite(v).all():
        raise ValueError("regression targets must be finite")
    return x, v.astype(np.float32)


def check_stable_ascending(stable: np.ndarray, where: str) -> np.ndarray:
    """Every generation's positional→stable map is strictly ascending (the
    fold keeps base survivors in order and appends delta stables, which
    are newer than everything before them) — the invariant that lets
    tombstone remapping use ``searchsorted``. A violated map means a
    corrupt artifact: typed, never wrong answers."""
    stable = np.asarray(stable, np.int64)
    if stable.ndim != 1 or (stable.size > 1
                            and not (np.diff(stable) > 0).all()):
        raise DataError(
            f"{where}: mutable stable-id map is not strictly ascending — "
            f"the artifact's mutable block is corrupt; rebuild the index"
        )
    return stable


def stable_to_position(base_stable: np.ndarray,
                       stable_id: int) -> Optional[int]:
    """Positional base id for a stable id, or None when the row is not in
    this generation's base (then it is a delta row or gone)."""
    pos = int(np.searchsorted(base_stable, stable_id))
    if pos < base_stable.shape[0] and int(base_stable[pos]) == stable_id:
        return pos
    return None
