"""Background compaction: fold delta + tombstones into a fresh generation.

The LSM merge step (PAPERS.md: O'Neil et al. 1996; Fresh-DiskANN's
StreamingMerge): a worker folds the sealed delta tier and tombstones into
a brand-new immutable base — surviving base rows in their original order,
then surviving delta rows in insert order (the DETERMINISTIC id
assignment the soak's oracle replay reproduces) — re-runs IVF cell
assignment when the serving index is partitioned, saves the result as an
ordinary artifact generation (``serve/artifact.py``), warms it OFF the
serving path, and swaps it through the existing
``MicroBatcher.swap_model`` machinery with the engine rebase executed
inside the same critical section.

Failure semantics (the hot-reload rollback contract, extended):

- any failure BEFORE the swap leaves the old generation serving and the
  sealed epoch's records on disk — nothing acknowledged is lost, the
  next attempt re-folds from scratch (``knn_mutable_compactions_total
  {outcome="rolled_back"}``);
- the COMMIT POINT is the atomic ``CURRENT.json`` replace: a process
  killed anywhere before it boots from the old base and replays every
  epoch record; killed after it boots from the new generation and
  replays only the records past ``folded_seq``;
- mid-compaction writes land in the fresh epoch the seal opened and are
  re-anchored onto the new base by the rebase — zero acknowledged writes
  lost (the mutable-soak kill test).

A seeded fault point (``mutable.compact``) sits between warmup and swap
so the chaos tooling can prove the rollback path without timing luck.
"""

from __future__ import annotations

import contextlib
import shutil
import threading
import time
from typing import Optional

import numpy as np

import os

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier, KNNRegressor
from knn_tpu.resilience import faults
from knn_tpu.serve import artifact

#: Incremental IVF compaction falls back to a full k-means rebuild when
#: the assignment-only partition's imbalance (largest cell over the
#: balanced size) crosses this — the point where skewed cells make probe
#: work and recall-per-probe visibly worse than a re-clustered field.
#: KNN_TPU_IVF_REBUILD_IMBALANCE overrides.
IVF_REBUILD_IMBALANCE = 4.0


def _rebuild_imbalance() -> float:
    try:
        return float(os.environ.get("KNN_TPU_IVF_REBUILD_IMBALANCE",
                                    IVF_REBUILD_IMBALANCE))
    except ValueError:
        return IVF_REBUILD_IMBALANCE


def rebuild_ivf(old_ivf, new_train: Dataset):
    """The compaction IVF step: ``(new_index, path)`` where ``path``
    names which branch ran — ``"incremental"`` (one same-seed assignment
    of the folded rows to the EXISTING centroids) or ``"rebuild"`` (full
    Lloyd's, taken when the incremental partition's cell imbalance
    crosses the threshold, or the fold shrank the row count below the
    cell count). Every fold used to pay the full rebuild; incremental
    assignment makes steady-state compaction O(rows · cells) instead of
    O(rows · cells · iters)."""
    from knn_tpu.index.ivf import IVFIndex

    cells = min(old_ivf.num_cells, new_train.num_instances)
    if cells == old_ivf.num_cells:
        candidate = IVFIndex.assign_to(new_train.features, old_ivf)
        if candidate.imbalance() <= _rebuild_imbalance():
            return candidate, "incremental"
    rebuilt = IVFIndex.build(
        new_train.features, cells, seed=int(old_ivf.meta.get("seed", 0)))
    return rebuilt, "rebuild"


class CompactionInProgress(Exception):
    """One compaction at a time (the reload-lock rule); /admin/compact
    maps this to HTTP 409."""


class CompactionCommitFailed(Exception):
    """A POST-SWAP step failed (the CURRENT.json commit): the new
    generation IS serving (swap+rebase succeeded) but the on-disk pointer
    still names the old one. NOT a rollback — and must never be reported
    as one. State stays consistent either way: the sealed epoch's records
    are still on disk, so a reboot loads the old base and replays the
    full acknowledged history; the next successful compaction re-folds
    and commits."""


def fold(base_train: Dataset, fold_input: dict,
         base_stable: np.ndarray) -> "tuple[Dataset, np.ndarray, dict]":
    """Pure fold: ``(new_train, new_base_stable, stats)``.

    Survivors keep their relative order — base rows first (ascending
    position), then live delta rows in insert order — so the new
    positional id space is a deterministic function of the acknowledged
    mutation history, which is exactly what lets an oracle replay verify
    post-compaction answers bit-for-bit."""
    count = fold_input["count"]
    tombs = fold_input["tomb_stable"]
    tomb_arr = (np.fromiter(tombs, np.int64, len(tombs)) if tombs
                else np.empty(0, np.int64))
    base_stable = np.asarray(base_stable, np.int64)
    base_keep = ~np.isin(base_stable, tomb_arr)
    delta_stable = np.asarray(fold_input["stable"][:count], np.int64)
    delta_keep = ~np.isin(delta_stable, tomb_arr)
    feats = np.concatenate([
        base_train.features[base_keep],
        np.asarray(fold_input["features"][:count], np.float32)[delta_keep],
    ])
    delta_vals = np.asarray(fold_input["values"][:count],
                            np.float32)[delta_keep]
    labels = np.concatenate([
        base_train.labels[base_keep],
        delta_vals.astype(base_train.labels.dtype),
    ])
    raw_targets = None
    if base_train.raw_targets is not None:
        raw_targets = np.concatenate([
            base_train.raw_targets[base_keep],
            delta_vals.astype(base_train.raw_targets.dtype),
        ])
    elif not np.array_equal(
            delta_vals.astype(base_train.labels.dtype).astype(np.float32),
            delta_vals):
        # Regression targets a sketch-less base stores as int labels
        # (Dataset.targets falls back to labels): a fractional/negative
        # acked target would silently truncate through the int cast and
        # the same read would answer differently after compaction.
        # Promote to raw_targets so the folded train set serves the
        # exact values the delta tier did.
        raw_targets = np.concatenate([
            base_train.labels[base_keep].astype(np.float32), delta_vals])
    new_train = Dataset(
        features=feats, labels=labels, relation=base_train.relation,
        attributes=list(base_train.attributes), raw_targets=raw_targets,
    )
    new_stable = np.concatenate([
        np.asarray(base_stable, np.int64)[base_keep],
        delta_stable[delta_keep],
    ])
    stats = {
        "base_kept": int(base_keep.sum()),
        "base_dropped": int((~base_keep).sum()),
        "delta_folded": int(delta_keep.sum()),
        "delta_dropped": int((~delta_keep).sum()),
        "rows": int(new_stable.shape[0]),
    }
    return new_train, new_stable, stats


def clone_fitted(model, train: Dataset):
    """A fresh model with the serving model's hyperparameters, fitted on
    the folded train set (compaction must not inherit device caches or
    any state tied to the old base)."""
    if isinstance(model, KNNClassifier):
        fresh = KNNClassifier(
            model.k, backend=model.backend_name, metric=model.metric,
            weights=model.weights, **dict(model.backend_opts),
        )
    elif isinstance(model, KNNRegressor):
        fresh = KNNRegressor(
            model.k, weights=model.weights, metric=model.metric,
            engine=model.engine,
        )
    else:
        raise TypeError(f"cannot compact a {type(model).__name__}")
    return fresh.fit(train)


class Compactor:
    """Owns the compaction lock, the optional interval thread, and the
    swap callback into the serving app.

    ``swap`` — ``swap(new_model, version, rebase_hook)``: must execute
    ``rebase_hook()`` inside the batcher's model-swap critical section
    (``ServeApp._mutable_swap`` does); ``warm`` — ``warm(new_model)``
    compiles the serving batch shapes off the serving path.

    ``retention_floor`` — optional zero-arg callable returning the
    lowest WAL cursor any live follower still needs (or None): epoch
    files whose records reach past that floor are NOT pruned after the
    fold, so a merely-lagging follower keeps catching up from the WAL
    instead of being force-parked behind the fold point
    (``FleetReplica.retention_floor`` wires this; a non-replicated serve
    passes nothing and prunes exactly as before).

    ``defer`` — optional zero-arg callable (the control plane's
    ``BrownoutController.defer_background``): while it returns True,
    interval/threshold-triggered folds WAIT — headroom is negative, and
    a fold's warmup + swap stealing cycles from overload traffic is the
    LSM anti-pattern the scheduler exists to avoid. The explicit
    ``/admin/compact`` path (``run_once``) is NOT gated: an operator's
    direct order outranks the scheduler. Pressure keeps accruing while
    deferred (delta-full inserts still 429), so the first post-recovery
    tick folds immediately.
    """

    def __init__(self, engine, *, swap, warm,
                 threshold: int = 1024, interval_s: float = 30.0,
                 retention_floor=None, defer=None):
        if threshold < 1:
            raise ValueError(f"compact threshold must be >= 1, got "
                             f"{threshold}")
        if interval_s < 0:
            raise ValueError(f"compact interval must be >= 0, got "
                             f"{interval_s}")
        self.engine = engine
        self.threshold = int(threshold)
        self.interval_s = float(interval_s)
        self._swap = swap
        self._warm = warm
        self._retention_floor = retention_floor
        self._defer = defer
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.compactions = 0
        engine.on_pressure(self._on_pressure)

    # -- background thread -------------------------------------------------

    def start(self) -> None:
        """Start the interval worker (no thread at ``interval_s == 0`` —
        then only /admin/compact and threshold kicks run, synchronously
        and on demand; the zero-thread embedded mode)."""
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="knn-compactor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _on_pressure(self, pressure: int) -> None:
        if pressure < self.threshold:
            return
        self._kick.set()
        if self._defer is not None and self._defer():
            # Headroom-negative deferral: remember the kick, fold later.
            # Pressure persists, so the next mutation (zero-thread mode)
            # or interval tick re-attempts once headroom returns.
            return
        if self._thread is None and not self._stop.is_set():
            # Zero-thread mode (interval_s == 0) has no interval worker to
            # consume the kick — the CLI promise ("threshold kicks still
            # compact") needs a one-shot worker. run_once's non-blocking
            # lock dedupes concurrent kicks; compacting ON the mutation
            # thread would stall reads for the whole fold.
            threading.Thread(target=self._kick_once, name="knn-compactor",
                             daemon=True).start()

    def _kick_once(self) -> None:
        try:
            self.run_once()
        except CompactionInProgress:
            pass
        except Exception as e:  # noqa: BLE001 — logged, old gen serving
            print(f"warning: compaction failed ({type(e).__name__}: {e}); "
                  f"the previous generation keeps serving", flush=True)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.interval_s)
            if self._stop.is_set():
                return
            if self._defer is not None and self._defer():
                # Negative headroom: leave the kick set and re-check —
                # deferred pressure must fold on the FIRST healthy tick,
                # not wait for a fresh trigger. The bounded sleep (the
                # kick keeps `wait` from sleeping) stops the loop from
                # spinning while deferred.
                self._stop.wait(min(1.0, self.interval_s))
                continue
            kicked = self._kick.is_set()
            self._kick.clear()
            if (self.engine.pressure() >= self.threshold
                    or (kicked and self.engine.pressure() > 0)):
                try:
                    self.run_once()
                except CompactionInProgress:
                    pass
                except Exception as e:  # noqa: BLE001 — logged + counted,
                    # the old generation keeps serving; retried next tick.
                    print(f"warning: compaction failed "
                          f"({type(e).__name__}: {e}); the previous "
                          f"generation keeps serving", flush=True)

    @contextlib.contextmanager
    def exclusive(self):
        """Hold the compaction lock WITHOUT compacting — the snapshot
        bootstrap installer wraps its re-seed swap in this so no
        concurrent fold can seal the abandoned lineage's state and
        re-commit it over the freshly installed generation. Raises
        :class:`CompactionInProgress` (non-blocking, like
        ``run_once``) when a fold is mid-flight."""
        if not self._lock.acquire(blocking=False):
            raise CompactionInProgress(
                "a compaction is already in progress")
        try:
            yield
        finally:
            self._lock.release()

    # -- one compaction ----------------------------------------------------

    def run_once(self, force: bool = False) -> dict:
        """Fold → save generation → warm → swap+rebase → commit pointer.
        Folds whatever exists — threshold gating is the CALLER's job
        (``_run``/``_on_pressure``); ``force`` marks the /admin/compact
        trigger. With nothing to fold it returns ``compacted: False``
        without sealing. Raises :class:`CompactionInProgress` when
        another compaction holds the lock."""
        if not self._lock.acquire(blocking=False):
            raise CompactionInProgress(
                "a compaction is already in progress")
        t0 = time.monotonic()
        swapped = False
        try:
            eng = self.engine
            if eng.pressure() == 0:
                return {"compacted": False, "reason": "nothing to fold"}
            old_model = eng._model
            base_train = old_model.train_
            base_stable = eng._base_stable
            with obs.span("mutable.compact",
                          pressure=eng.pressure()):
                fold_input = eng.seal()
                new_train, new_stable, stats = fold(
                    base_train, fold_input, base_stable)
                new_model = clone_fitted(old_model, new_train)
                new_ivf = None
                ivf_path = None
                old_ivf = getattr(old_model, "ivf_", None)
                if old_ivf is not None:
                    # Re-assign folded rows to cells: incremental (the
                    # existing centroid field, one same-seed assignment
                    # step) unless imbalance demands a full Lloyd's
                    # rebuild — deterministic artifacts either way.
                    from knn_tpu.index.ivf import IVF_ATTR

                    new_ivf, ivf_path = rebuild_ivf(old_ivf, new_train)
                    setattr(new_model, IVF_ATTR, new_ivf)
                generation = fold_input["generation"] + 1
                gen_dir = artifact.generation_path(eng.root, generation)
                artifact.save_index(
                    new_model, gen_dir, ivf=new_ivf,
                    mutable_block=eng.base_manifest_block(
                        fold_input, new_stable),
                )
                version = artifact.index_version(
                    artifact.read_manifest(gen_dir))
                self._warm(new_model)
                # Seeded fault point for the rollback/crash legs of the
                # mutable soak: everything is built and warmed, nothing
                # swapped yet.
                faults.fault_point("mutable.compact")
                previous = self._swap(
                    new_model, version,
                    lambda: eng.rebase(fold_input, new_model, new_stable,
                                       generation, version=version),
                )
                swapped = True
                # COMMIT: after this atomic replace, boots load the new
                # generation and replay only records past folded_seq.
                artifact.write_current(eng.root, {
                    "generation": generation,
                    "base": str(gen_dir.relative_to(eng.root)),
                    "folded_seq": int(fold_input["seq"]),
                    "next_stable": int(eng._next_stable),
                    "active_epoch": int(eng._epoch),
                })
                cleanup = self._cleanup(fold_input, generation)
            wall_ms = (time.monotonic() - t0) * 1e3
            self.compactions += 1
            detail = {
                "generation": generation, "index_version": version,
                "previous_version": previous,
                "folded_seq": int(fold_input["seq"]), **cleanup, **stats,
            }
            if ivf_path is not None:
                # Which IVF branch this fold rode (the compaction
                # verdict's answer to "did we pay a full re-cluster?").
                detail["ivf_compaction"] = ivf_path
                detail["ivf_cell_imbalance"] = new_ivf.imbalance()
            eng.note_compaction("ok", wall_ms, detail)
            return {"compacted": True, "ms": round(wall_ms, 3), **detail}
        except CompactionInProgress:
            raise
        except Exception as e:
            if swapped:
                # The new generation is already serving — saying
                # "rolled_back" here would tell the operator the exact
                # opposite of the truth (e.g. CURRENT.json commit hit a
                # full disk). Reboot-safety holds regardless: the sealed
                # epoch is still on disk, so the old pointer + full
                # replay reconstruct every acknowledged write.
                self.engine.note_compaction(
                    "commit_failed", (time.monotonic() - t0) * 1e3)
                raise CompactionCommitFailed(
                    f"compaction swapped generation in but the pointer "
                    f"commit failed ({type(e).__name__}: {e}); the new "
                    f"generation is serving, a reboot replays onto the "
                    f"old one, and the next compaction re-commits"
                ) from e
            self.engine.note_compaction(
                "rolled_back", (time.monotonic() - t0) * 1e3)
            raise
        finally:
            self._lock.release()

    def _cleanup(self, fold_input: dict, generation: int) -> dict:
        """Best-effort removal of folded epoch files and superseded
        generation directories — AFTER the pointer committed, so a crash
        during cleanup only leaves redundant (skipped-on-replay) files.

        Retention floor: an epoch whose records reach past the lowest
        live follower cursor is HELD, not pruned — the silent hazard
        this closes is a primary compacting a lagging follower straight
        into the terminal behind-the-fold park. Held epochs stay
        eligible (``n <= sealed_epoch``) and are re-examined by the next
        compaction's cleanup once the floor advances; the hold itself is
        counted (``knn_fleet_wal_retention_held_total``) and surfaced in
        the compaction verdict so the router can audit it."""
        floor = None
        if self._retention_floor is not None:
            try:
                floor = self._retention_floor()
            except Exception:  # noqa: BLE001 — advisory; prune as before
                floor = None
        pruned = held = 0
        for n, path in artifact.list_epochs(self.engine.root):
            if n > fold_input["sealed_epoch"]:
                continue
            if floor is not None and floor < fold_input["seq"]:
                try:
                    records, _torn = artifact.read_epoch_records(
                        path, tolerate_torn=True)
                except Exception:  # noqa: BLE001 — unreadable: hold it
                    records = None
                if records is None:
                    last_seq = fold_input["seq"]  # conservative: hold
                elif not records:
                    last_seq = -1  # empty file holds nothing: prune
                else:
                    last_seq = int(records[-1]["seq"])
                if last_seq > floor:
                    held += 1
                    continue
            try:
                path.unlink()
                pruned += 1
            except OSError:
                pass
        if held:
            obs.counter_add(
                "knn_fleet_wal_retention_held_total", held,
                help="epoch files a compaction deferred pruning because "
                     "a live follower's WAL cursor still needs them",
            )
        gen_root = self.engine.root / artifact.GENERATIONS_DIR
        if gen_root.is_dir():
            keep = artifact.generation_path(self.engine.root,
                                            generation).name
            for p in gen_root.iterdir():
                if p.is_dir() and p.name != keep:
                    shutil.rmtree(p, ignore_errors=True)
        out = {"epochs_pruned": pruned, "epochs_held": held}
        if floor is not None:
            out["retention_floor"] = int(floor)
        return out
