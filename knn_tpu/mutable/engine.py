"""The mutable engine: write-ahead log + live state + instruments.

One :class:`MutableEngine` owns everything a mutable-serving process
mutates: the delta arrays, the tombstone sets, the write-ahead epoch log
(``serve/artifact.py`` persistence primitives), and the stable-id
machinery compaction rebases through. Threading contract:

- **mutations are applied ONLY by the batcher worker thread**
  (``MicroBatcher.submit_mutation`` enqueues; the worker drains the
  mutation queue between read dispatches — mutations serialize against
  dispatches for free, and readers never block on a write because read
  ADMISSION never touches the engine);
- **readers** take :meth:`snapshot` — an immutable
  :class:`~knn_tpu.mutable.state.MutableView` of shared append-frozen
  arrays — once per dispatch, under the batcher's own snapshot lock;
- **compaction** (its own thread, ``knn_tpu/mutable/compact.py``) calls
  :meth:`seal` to freeze a fold point (rotating the WAL to a fresh epoch,
  so mid-compaction writes land in the new epoch without loss) and
  :meth:`rebase` inside the batcher's model-swap critical section, so a
  dispatch can never pair the new base with the old delta.

Durability: every mutation is appended + flushed to the epoch log BEFORE
it is applied or acknowledged; boot replays every record newer than the
base generation's ``folded_seq`` (a torn final line is the one in-flight
never-acked append and is dropped). The mutable-soak gate kills a server
mid-compaction and requires zero acknowledged writes lost.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.mutable.state import (
    MutableView,
    MutationConflict,
    ReplicationGap,
    WALDivergence,
    check_stable_ascending,
    stable_to_position,
    validate_insert,
)
from knn_tpu.resilience.errors import DataError, OverloadError
from knn_tpu.serve import artifact

#: Freshness histogram buckets (ms): write-ack to visible-in-snapshots.
FRESHNESS_BUCKETS_MS = (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                        5000)

#: Initial delta allocation; grows by amortized doubling up to the cap.
_INITIAL_SLOTS = 64

#: Content digests kept per applied WAL record for the replication
#: overlap check (fleet/replica.py): enough to cover any realistic
#: shipping window; older seqs fall back to skip-without-check (they are
#: either folded into a generation or far behind every live cursor).
_DIGEST_KEEP = 8192


def wal_record_digest(rec: dict) -> str:
    """Canonical content digest of one WAL record — what the WAL fan-out
    protocol uses to prove that two logs agree about a sequence number
    (``POST /admin/wal-append`` overlap checks). Excludes any ``digest``
    field so a record round-trips."""
    body = {k: v for k, v in rec.items() if k != "digest"}
    payload = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def truncate_wal(root, cap_seq: int) -> int:
    """Drop every epoch-log record with ``seq > cap_seq`` (atomic rewrite
    per file, empty epochs removed) and return how many records were
    dropped. The rejoin primitive: a rebooted ex-primary's WAL tail past
    the promoted follower's takeover point is UNACKNOWLEDGED by
    construction (a write is only acked once a follower holds it), and
    under the new primary those seqs name different mutations — replaying
    the stale tail before following would be silent divergence."""
    dropped = 0
    for _n, path in artifact.list_epochs(root):
        records, _torn = artifact.read_epoch_records(path,
                                                     tolerate_torn=True)
        keep = [r for r in records if int(r.get("seq", 0)) <= cap_seq]
        if len(keep) == len(records):
            continue
        dropped += len(records) - len(keep)
        if keep:
            artifact.repair_epoch(path, keep)
        else:
            path.unlink()
    return dropped


#: ``device_tail="auto"`` activates the device-resident delta buffer
#: (``mutable/device_tail.py``) once this many delta slots are in use —
#: below it, the host merge's numpy scan beats a device dispatch, so the
#: tail would be pure overhead. "on" activates at the first insert,
#: "off" never constructs it. KNN_TPU_DEVICE_TAIL overrides "auto".
DEVICE_TAIL_MIN_ROWS = 256


class _Freshness:
    """Streaming write-to-visible stats + a bounded ring for quantiles
    (the /healthz ``mutable.freshness`` block; the exact distribution
    lives in the ``knn_mutable_freshness_ms`` histogram)."""

    __slots__ = ("count", "sum_ms", "max_ms", "_ring", "_pos")

    def __init__(self, ring: int = 512):
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0
        self._ring = np.zeros(ring, np.float64)
        self._pos = 0

    def note(self, ms: float) -> None:
        self.count += 1
        self.sum_ms += ms
        self.max_ms = max(self.max_ms, ms)
        self._ring[self._pos % self._ring.shape[0]] = ms
        self._pos += 1

    def export(self) -> dict:
        filled = min(self._pos, self._ring.shape[0])
        doc = {
            "count": self.count,
            "mean_ms": (round(self.sum_ms / self.count, 3)
                        if self.count else None),
            "max_ms": round(self.max_ms, 3) if self.count else None,
            "p99_ms": None,
        }
        if filled:
            doc["p99_ms"] = round(
                float(np.percentile(self._ring[:filled], 99)), 3)
        return doc


class MutableEngine:
    """See the module docstring. ``root`` is the artifact directory the
    server booted from (epoch logs and compacted generations live inside
    it); ``model`` is the ALREADY-LOADED base model for the current
    generation (``artifact.resolve_mutable_base`` names the directory).
    Construction replays any existing epoch records newer than the base's
    fold point, then opens a fresh epoch for this process's writes."""

    def __init__(self, model, root, *, delta_cap: int = 4096,
                 current: Optional[dict] = None, base_dir=None,
                 version: Optional[str] = None,
                 device_tail: str = "auto"):
        if delta_cap < 1:
            raise ValueError(f"delta_cap must be >= 1, got {delta_cap}")
        if device_tail == "auto":
            import os

            env = os.environ.get("KNN_TPU_DEVICE_TAIL", "auto")
            device_tail = env if env in ("on", "off") else "auto"
        if device_tail not in ("auto", "on", "off"):
            raise ValueError(
                f"device_tail must be 'auto', 'on', or 'off', got "
                f"{device_tail!r}")
        from pathlib import Path

        self.root = Path(root)
        self.delta_cap = int(delta_cap)
        self._model = model
        self._version = version
        self._k = model.k
        self._metric = model.metric
        train = model.train_
        self._base_n = train.num_instances
        self._d = train.num_features
        self._lock = threading.RLock()
        self._fresh = _Freshness()
        self._last_compaction: Optional[dict] = None
        self._on_pressure = None  # Compactor.kick, wired after build
        self._on_applied = None  # fleet shipper kick, wired after build
        # seq -> content digest for the replication overlap check
        # (wal_record_digest); bounded, pruned oldest-first.
        self._digests: "dict[int, str]" = {}

        base = Path(base_dir) if base_dir is not None else self.root
        block, stable = artifact.read_mutable_block(base)
        if stable is not None:
            if stable.shape[0] != self._base_n:
                raise DataError(
                    f"{base}: mutable_stable_ids spans {stable.shape[0]} "
                    f"rows but the base has {self._base_n}"
                )
            self._base_stable = check_stable_ascending(stable, str(base))
        else:
            self._base_stable = np.arange(self._base_n, dtype=np.int64)
        folded = 0
        self._generation = 0
        if current is not None:
            self._generation = int(current.get("generation", 0))
            folded = int(current.get("folded_seq", 0))
        if block is not None:
            folded = max(folded, int(block.get("folded_seq", 0)))
        self._folded_seq = folded
        self._seq = folded
        self._next_stable = int(self._base_stable[-1]) + 1 if self._base_n \
            else 0
        if block is not None:
            self._next_stable = max(self._next_stable,
                                    int(block.get("next_stable", 0)))
        if current is not None:
            self._next_stable = max(self._next_stable,
                                    int(current.get("next_stable", 0)))

        # Live delta state (slots below _count are append-frozen).
        cap = min(_INITIAL_SLOTS, self.delta_cap)
        self._features = np.zeros((cap, self._d), np.float32)
        self._values = np.zeros(cap, np.float32)
        self._stable = np.zeros(cap, np.int64)
        self._count = 0
        self._tomb_stable: frozenset = frozenset()
        self._tomb_pos: frozenset = frozenset()
        self._tomb_base = np.empty(0, np.int64)
        self._tomb_delta = np.empty(0, np.int64)
        # Device-resident delta tail (mutable/device_tail.py): built
        # LAZILY at the activation threshold so a mutable-on boot with
        # no (or few) mutations constructs zero device machinery and the
        # empty-view byte-identity pin holds trivially.
        self._device_tail_mode = device_tail
        self._dtail = None

        self._replay()
        self._sync_device_tail()
        epochs = artifact.list_epochs(self.root)
        self._epoch = (epochs[-1][0] + 1) if epochs else 1
        self._log = artifact.EpochLog(
            artifact.epoch_path(self.root, self._epoch))
        self._closed = False

    # -- boot replay -------------------------------------------------------

    def _replay(self) -> None:
        epochs = artifact.list_epochs(self.root)
        last = epochs[-1][0] if epochs else None
        for n, path in epochs:
            records, torn = artifact.read_epoch_records(
                path, tolerate_torn=(n == last))
            for rec in records:
                seq = int(rec["seq"])
                if seq <= self._folded_seq:
                    continue
                if seq <= self._seq:
                    raise DataError(
                        f"{path}: epoch log is not seq-monotonic "
                        f"({seq} after {self._seq}); the write-ahead log "
                        f"is corrupt"
                    )
                if seq != self._seq + 1:
                    # A HOLE in the acknowledged history: every record was
                    # acked durable in seq order, so a missing seq means
                    # lost writes — replaying past it would silently serve
                    # a history that never happened (the primary-failover
                    # catch-up path depends on this being typed, never a
                    # skip).
                    raise DataError(
                        f"{path}: epoch stream has a seq gap (expected "
                        f"{self._seq + 1}, found {seq}); the write-ahead "
                        f"log lost acknowledged records"
                    )
                self._replay_one(rec, path)
            if torn:
                print(f"warning: {path}: dropped a torn final record "
                      f"(crash mid-append; that mutation was never "
                      f"acknowledged)", flush=True)
                # Repair NOW: once this boot opens a fresh epoch, this
                # file is no longer last and loses its torn-tolerance —
                # an unrepaired fragment would make the next boot refuse
                # an artifact this boot accepted.
                artifact.repair_epoch(path, records)

    def _replay_one(self, rec: dict, path) -> None:
        op = rec.get("op")
        try:
            if op == "insert":
                rows = np.asarray(rec["rows"], np.float32)
                values = np.asarray(rec["values"], np.float32)
                if rows.ndim != 2 or rows.shape[1] != self._d:
                    raise ValueError(f"bad row shape {rows.shape}")
                # Replay NEVER enforces the cap: every record was
                # acknowledged durable — a smaller --delta-cap on reboot
                # must not lose writes (compaction will fold them).
                self._append_rows(rows, values, int(rec["sid0"]),
                                  enforce_cap=False)
            elif op == "delete":
                sids = [int(s) for s in rec["sids"]]
                self._tombstone_stables(sids, where=str(path))
            else:
                raise ValueError(f"unknown op {op!r}")
        except (KeyError, ValueError, TypeError) as e:
            raise DataError(
                f"{path}: unreplayable epoch record (seq "
                f"{rec.get('seq')}): {e}") from e
        self._seq = int(rec["seq"])
        self._note_digest(self._seq, rec)
        self._next_stable = max(self._next_stable,
                                int(self._stable[:self._count].max(
                                    initial=-1)) + 1)

    def _note_digest(self, seq: int, rec: dict) -> None:
        """Record ``seq``'s content digest for the replication overlap
        check (caller holds the lock or is __init__); bounded by
        ``_DIGEST_KEEP`` — seqs that age out fall back to
        skip-without-check on overlap."""
        self._digests[seq] = wal_record_digest(rec)
        while len(self._digests) > _DIGEST_KEEP:
            # Seqs insert strictly ascending, so dict order IS seq
            # order: the first key is the oldest (O(1), not a key scan).
            self._digests.pop(next(iter(self._digests)))

    # -- shared state primitives (caller holds self._lock or is __init__) --

    def _grow_to(self, want: int) -> None:
        cap = self._features.shape[0]
        if want <= cap:
            return
        new_cap = cap
        while new_cap < want:
            new_cap *= 2
        new_cap = min(new_cap, max(self.delta_cap, want))
        # Amortized doubling with fresh allocations: snapshots holding the
        # OLD arrays keep reading their frozen prefix untouched.
        for name in ("_features", "_values", "_stable"):
            old = getattr(self, name)
            shape = (new_cap,) + old.shape[1:]
            fresh = np.zeros(shape, old.dtype)
            fresh[:self._count] = old[:self._count]
            setattr(self, name, fresh)

    def _append_rows(self, rows: np.ndarray, values: np.ndarray,
                     sid0: int, enforce_cap: bool = True) -> "list[int]":
        m = rows.shape[0]
        if enforce_cap and self._count + m > self.delta_cap:
            raise OverloadError(
                f"delta tier full ({self._count}/{self.delta_cap} slots); "
                f"compaction is behind — retry after backoff or trigger "
                f"/admin/compact"
            )
        self._grow_to(self._count + m)
        s = self._count
        self._features[s:s + m] = rows
        self._values[s:s + m] = values
        self._stable[s:s + m] = np.arange(sid0, sid0 + m, dtype=np.int64)
        self._count = s + m
        self._sync_device_tail(appended=(s, self._count))
        return list(range(self._base_n + s, self._base_n + s + m))

    def _sync_device_tail(self, appended=None) -> None:
        """Keep the device-resident delta buffer in lockstep with the
        host arrays (caller holds the lock). Lazy activation at the mode
        threshold; after that, appends write in place via
        ``dynamic_update_slice`` and a host growth (capacity change)
        triggers a full rebuild inside :meth:`DeviceDeltaTail.append`."""
        mode = self._device_tail_mode
        if mode == "off":
            return
        if self._dtail is None:
            want = 1 if mode == "on" else DEVICE_TAIL_MIN_ROWS
            if self._count < want:
                return
            from knn_tpu.mutable.device_tail import DeviceDeltaTail

            self._dtail = DeviceDeltaTail()
            self._dtail.rebuild(self._features, self._count,
                                self._tomb_delta, self._base_n)
            return
        if appended is not None:
            self._dtail.append(self._features, appended[0], appended[1],
                               self._base_n)
        else:
            self._dtail.rebuild(self._features, self._count,
                                self._tomb_delta, self._base_n)

    def _rebuild_tomb_arrays(self) -> None:
        base, delta = [], []
        for p in self._tomb_pos:
            (base if p < self._base_n else delta).append(p)
        self._tomb_base = np.array(sorted(base), np.int64)
        self._tomb_delta = np.array(
            sorted(p - self._base_n for p in delta), np.int64)

    def _position_of_stable(self, sid: int) -> Optional[int]:
        pos = stable_to_position(self._base_stable, sid)
        if pos is not None:
            return pos
        live = self._stable[:self._count]
        hits = np.nonzero(live == sid)[0]
        if hits.size:
            return self._base_n + int(hits[0])
        return None

    def _validate_tombstones(self, sids: "list[int]",
                             where: str) -> "list[int]":
        """THE one copy of the delete-safety rules (duplicate/unknown/
        already-dead rows, the k-floor) — run by ``apply_delete`` BEFORE
        the WAL append and re-run by :meth:`_tombstone_stables` at apply
        and replay. Two drifting copies would be a WAL hazard: a rule
        relaxed at admission but not at apply acks a record that the
        post-append apply (or a boot replay) then refuses. Returns the
        positional ids."""
        positions = []
        fresh = set()
        for sid in sids:
            if sid in self._tomb_stable or sid in fresh:
                raise MutationConflict(
                    f"{where}: row (stable id {sid}) is already deleted")
            pos = self._position_of_stable(sid)
            if pos is None:
                raise MutationConflict(
                    f"{where}: no such row (stable id {sid})")
            positions.append(pos)
            fresh.add(sid)
        live_total = (self._base_n - int(self._tomb_base.shape[0])
                      + self._count - int(self._tomb_delta.shape[0]))
        if live_total - len(sids) < self._k:
            raise MutationConflict(
                f"{where}: deleting {len(sids)} row(s) would leave "
                f"{live_total - len(sids)} live rows, below k="
                f"{self._k} — the index must always answer full top-k"
            )
        return positions

    def _tombstone_stables(self, sids: "list[int]", where: str) -> "list[int]":
        positions = self._validate_tombstones(sids, where)
        self._tomb_stable = self._tomb_stable | set(sids)
        self._tomb_pos = self._tomb_pos | set(positions)
        self._rebuild_tomb_arrays()
        if self._dtail is not None:
            # Deletes are rare next to reads: a full [cap] mask upload
            # keeps the device tail's tombstones exact.
            self._dtail.set_dead(self._tomb_delta)
        return positions

    # -- mutation application (batcher worker thread) ----------------------

    def apply_insert(self, rows, values, submitted_ns: int) -> dict:
        """Validate → WAL append (flushed) → apply → ack. Raises
        ``ValueError`` (400) for malformed payloads, ``OverloadError``
        (429) when the delta tier is full."""
        rows, values = validate_insert(self._model, rows, values)
        with self._lock:
            if self._closed:
                raise OverloadError("mutable engine is shut down")
            if self._count + rows.shape[0] > self.delta_cap:
                self._note_mutation("insert", "rejected")
                raise OverloadError(
                    f"delta tier full ({self._count}/{self.delta_cap} "
                    f"slots); compaction is behind — retry after backoff "
                    f"or trigger /admin/compact"
                )
            seq = self._seq + 1
            sid0 = self._next_stable
            rec = {
                "seq": seq, "op": "insert", "sid0": sid0,
                "rows": [[float(v) for v in r] for r in rows],
                "values": [float(v) for v in values],
            }
            self._log.append(rec)
            ids = self._append_rows(rows, values, sid0)
            self._seq = seq
            self._note_digest(seq, rec)
            self._next_stable = sid0 + rows.shape[0]
            epoch = self._epoch
            # The version is stamped HERE, under the lock the rebase
            # holds: the ack's positional ids and its version tag must
            # name the same generation, or a client could pair old-space
            # ids with the new tag and satisfy a delete precondition
            # against the wrong rows.
            version = self._version
            pressure = self.pressure()
        self._note_visible(submitted_ns)
        self._note_mutation("insert", "ok", rows.shape[0])
        self._maybe_kick(pressure)
        self._notify_applied()
        return {"op": "insert", "ids": ids, "rows": rows.shape[0],
                "seq": seq, "epoch": epoch, "index_version": version}

    def apply_delete(self, ids, submitted_ns: int,
                     expect_version: Optional[str] = None) -> dict:
        """Delete by positional id (the ids kneighbors responses carry,
        in the CURRENT generation's space). Unknown/already-deleted rows,
        k-floor violations, and a failed ``expect_version`` precondition
        raise :class:`MutationConflict` (409). The precondition is checked
        HERE, under the same lock :meth:`rebase` holds — checking it any
        earlier (e.g. at HTTP admission) races a compaction swap and a
        positional id from the old generation would silently name a
        different row in the new one."""
        try:
            ids = [int(i) for i in np.asarray(ids).ravel()]
        except (TypeError, ValueError) as e:
            raise ValueError(f"delete ids must be integers: {e}") from e
        if not ids:
            raise ValueError("empty delete (0 ids)")
        with self._lock:
            if self._closed:
                raise OverloadError("mutable engine is shut down")
            if (expect_version is not None
                    and expect_version != self._version):
                self._note_mutation("delete", "rejected")
                raise MutationConflict(
                    f"index_version precondition failed: request names "
                    f"{expect_version!r} but {self._version!r} is serving "
                    f"(a compaction re-assigned row ids; re-read before "
                    f"deleting)"
                )
            try:
                # Positional -> stable translation (a concern only this
                # entry point has; replay logs stable ids directly)...
                sids = []
                seen = set()
                for p in ids:
                    if p in seen:
                        raise MutationConflict(
                            f"duplicate id {p} in one delete request")
                    seen.add(p)
                    if p < 0 or p >= self._base_n + self._count:
                        raise MutationConflict(
                            f"no such row: id {p} (addressable: 0.."
                            f"{self._base_n + self._count - 1})")
                    sids.append(int(self._base_stable[p])
                                if p < self._base_n
                                else int(self._stable[p - self._base_n]))
                # ...then the shared safety rules BEFORE anything is
                # durable: a refused delete must leave the write-ahead
                # log untouched, or replay would re-apply a mutation
                # that was never acknowledged.
                self._validate_tombstones(sids, where="delete")
            except MutationConflict:
                self._note_mutation("delete", "rejected")
                raise
            seq = self._seq + 1
            rec = {"seq": seq, "op": "delete", "sids": sids}
            self._log.append(rec)
            self._tombstone_stables(sids, where="delete")
            self._seq = seq
            self._note_digest(seq, rec)
            epoch = self._epoch
            version = self._version  # same-lock pairing as apply_insert
            pressure = self.pressure()
        self._note_visible(submitted_ns)
        self._note_mutation("delete", "ok", len(ids))
        self._maybe_kick(pressure)
        self._notify_applied()
        return {"op": "delete", "deleted": len(ids), "seq": seq,
                "epoch": epoch, "index_version": version}

    # -- replication (fleet/replica.py, docs/SERVING.md §Replica sets) -----

    def apply_replicated(self, rec: dict) -> dict:
        """Apply ONE primary-shipped WAL record through the exact same
        validation path local mutations take — a divergent record (wrong
        width, unknown label, impossible delete) is a typed refusal, never
        silent corruption.

        Contract (what primary-failover catch-up depends on):

        - ``seq == applied + 1`` → validate, append to THIS replica's own
          WAL (flushed — a promoted follower must be able to re-ship and
          to survive its own reboot), apply, return ``applied: True``;
        - ``seq <= applied`` → **idempotent no-op** (the primary re-ships
          from a conservative cursor after a resync) — but only after the
          content digest matches the record already applied at that seq;
          a mismatch raises :class:`WALDivergence` (the two logs disagree
          about history — re-seed, don't retry);
        - ``seq > applied + 1`` → :class:`ReplicationGap` carrying
          ``applied_seq`` so the shipper resets its cursor (never a
          silent skip).
        """
        try:
            seq = int(rec["seq"])
            op = rec["op"]
        except (KeyError, TypeError, ValueError) as e:
            raise DataError(f"unreplayable WAL record: {e}") from e
        with self._lock:
            if self._closed:
                raise OverloadError("mutable engine is shut down")
            if seq <= self._seq:
                known = self._digests.get(seq)
                shipped = wal_record_digest(rec)
                if known is not None and known != shipped:
                    raise WALDivergence(
                        f"seq {seq} is already applied with digest "
                        f"{known} but the primary shipped {shipped} — "
                        f"this replica's log has diverged from the "
                        f"primary's; re-seed it from a fresh copy"
                    )
                return {"applied": False, "seq": self._seq}
            if seq != self._seq + 1:
                raise ReplicationGap(
                    f"record seq {seq} skips past the next expected "
                    f"{self._seq + 1}; re-ship from {self._seq}",
                    applied_seq=self._seq,
                )
            if op == "insert":
                # The full local-insert validation (width, finiteness,
                # label range) — the "divergent record is a typed
                # refusal" half of the fan-out contract.
                rows, values = validate_insert(
                    self._model, rec["rows"], rec.get("values"))
                sid0 = int(rec["sid0"])
                clean = {"seq": seq, "op": "insert", "sid0": sid0,
                         "rows": [[float(v) for v in r] for r in rows],
                         "values": [float(v) for v in values]}
                self._log.append(clean)
                self._append_rows(rows, values, sid0, enforce_cap=False)
                self._next_stable = max(self._next_stable,
                                        sid0 + rows.shape[0])
            elif op == "delete":
                sids = [int(s) for s in rec["sids"]]
                clean = {"seq": seq, "op": "delete", "sids": sids}
                # Validate BEFORE the WAL append (the apply_delete
                # discipline: a refused record must leave this replica's
                # log untouched).
                self._validate_tombstones(sids, where="wal-append")
                self._log.append(clean)
                self._tombstone_stables(sids, where="wal-append")
            else:
                raise DataError(f"unknown op {op!r} in replicated record "
                                f"seq {seq}")
            self._seq = seq
            self._note_digest(seq, clean)
            pressure = self.pressure()
        self._note_mutation(op, "replicated",
                            len(clean.get("rows", clean.get("sids", [0]))))
        self._maybe_kick(pressure)
        self._notify_applied()
        return {"applied": True, "seq": seq}

    def records_since(self, after_seq: int,
                      limit: int = 512) -> "tuple[list[dict], int]":
        """WAL records with ``seq > after_seq`` (ascending, at most
        ``limit``), each stamped with its content ``digest`` — the
        shipping source for the primary's fan-out and for rejoin
        catch-up. Reads the epoch files directly (the appender flushes
        whole lines, and a torn tail is by definition un-acked — skipped
        this round, shipped the next). A cursor behind the fold point is
        still servable while a retention hold (mutable/compact.py) kept
        the folded epochs on disk: the stream is verified gapless from
        ``after_seq + 1`` before shipping, and the typed
        :class:`DataError` re-seed refusal fires only when records are
        actually missing — compacted into a base generation and their
        epochs pruned, so that follower must re-seed from a copy of the
        artifact directory (the snapshot bootstrap path,
        fleet/bootstrap.py). A file vanishing MID-scan (the compactor's
        epoch pruning is not coordinated with this lock-free read) is a
        transient race, re-scanned — and surfaced as a plain ``OSError``
        (retry later, NOT the terminal re-seed state) if it somehow
        persists."""
        for _attempt in range(3):
            with self._lock:
                folded = self._folded_seq
                own_seq = self._seq
            out: "list[dict]" = []
            try:
                epochs = artifact.list_epochs(self.root)
                last = epochs[-1][0] if epochs else None
                for n, path in epochs:
                    if len(out) >= limit:
                        break
                    records, _torn = artifact.read_epoch_records(
                        path, tolerate_torn=(n == last))
                    for rec in records:
                        if (int(rec["seq"]) > after_seq
                                and len(out) < limit):
                            out.append({**rec,
                                        "digest": wal_record_digest(rec)})
            except DataError as e:
                if isinstance(e.__cause__, FileNotFoundError):
                    continue  # pruned mid-scan; re-list and re-read
                raise
            out.sort(key=lambda r: int(r["seq"]))
            if after_seq < folded:
                expect = after_seq
                for rec in out:
                    if int(rec["seq"]) != expect + 1:
                        break
                    expect += 1
                else:
                    if out:  # gapless from the cursor: retention held
                        return out, own_seq
                raise DataError(
                    f"cursor seq {after_seq} predates the fold point "
                    f"{folded}: those records are compacted into a base "
                    f"generation and their epochs pruned — re-seed the "
                    f"follower from a copy of the artifact directory"
                )
            return out, own_seq
        raise OSError(
            "epoch files kept vanishing mid-scan (compaction churn); "
            "transient — retry the shipment"
        )

    def on_applied(self, cb) -> None:
        """Register the fan-out kick: called (outside the lock) after
        every applied mutation so the WAL shippers wake immediately
        instead of on their poll interval."""
        self._on_applied = cb

    def _notify_applied(self) -> None:
        cb = self._on_applied
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — shipping nudge only
                pass

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> MutableView:
        with self._lock:
            return MutableView(
                features=self._features, values=self._values,
                stable=self._stable, count=self._count,
                tomb_pos=self._tomb_pos, tomb_base=self._tomb_base,
                tomb_delta_slots=self._tomb_delta, seq=self._seq,
                base_n=self._base_n, generation=self._generation,
                device=(self._dtail.view() if self._dtail is not None
                        else None),
            )

    @property
    def seq(self) -> int:
        """The last applied mutation sequence number (the replication
        cursor's anchor; /healthz ``fleet.applied_seq``)."""
        with self._lock:
            return self._seq

    @property
    def folded_seq(self) -> int:
        """The fold point: records at or below it live only in compacted
        base generations (their epochs are pruned) — the lowest seq a
        WAL shipper's cursor can meaningfully start from."""
        with self._lock:
            return self._folded_seq

    def pressure(self) -> int:
        """Mutations awaiting compaction: delta slots in use plus live
        tombstones — what ``--compact-threshold`` gates on."""
        with self._lock:
            return self._count + len(self._tomb_stable)

    def delta_full(self) -> bool:
        """Advisory (lock-free) admission pre-check: True when the delta
        tier has no free slot. The authoritative check is the locked one
        in :meth:`apply_insert` — this only spares a doomed insert the
        queue round-trip."""
        return self._count >= self.delta_cap

    # -- compaction interface (knn_tpu/mutable/compact.py) -----------------

    def seal(self) -> dict:
        """Freeze a fold point and rotate the WAL: returns the fold input
        (frozen array refs + tombstones + ``seq``), after which new
        mutations land in a FRESH epoch file and delta slots >= the frozen
        ``count`` — nothing the fold reads can move underneath it."""
        with self._lock:
            fold = {
                "features": self._features, "values": self._values,
                "stable": self._stable, "count": self._count,
                "tomb_stable": self._tomb_stable, "seq": self._seq,
                "generation": self._generation,
                "sealed_epoch": self._epoch,
            }
            self._log.close()
            self._epoch += 1
            self._log = artifact.EpochLog(
                artifact.epoch_path(self.root, self._epoch))
            return fold

    def rebase(self, fold: dict, new_model, new_base_stable: np.ndarray,
               generation: int, version: Optional[str] = None) -> None:
        """Re-anchor the live state on a freshly-compacted base. MUST run
        inside the batcher's model-swap critical section (the hook of
        ``MicroBatcher.swap_model``): the model swap and this rebase are
        one atomic step to every dispatch snapshot. All validation and
        array building happen BEFORE the first assignment, so a raise
        leaves the engine exactly as it was (``swap_model`` restores the
        old model on a hook failure — together that is a true rollback)."""
        with self._lock:
            new_base_stable = check_stable_ascending(
                np.asarray(new_base_stable, np.int64), "rebase")
            new_base_n = int(new_base_stable.shape[0])
            post = list(range(fold["count"], self._count))
            keep_tombs = self._tomb_stable - fold["tomb_stable"]
            cap = min(max(_INITIAL_SLOTS, len(post)), self.delta_cap)
            features = np.zeros((cap, self._d), np.float32)
            values = np.zeros(cap, np.float32)
            stable = np.zeros(cap, np.int64)
            for j, slot in enumerate(post):
                features[j] = self._features[slot]
                values[j] = self._values[slot]
                stable[j] = self._stable[slot]
            positions = set()
            for sid in keep_tombs:
                pos = stable_to_position(new_base_stable, sid)
                if pos is None:
                    hits = np.nonzero(stable[:len(post)] == sid)[0]
                    if not hits.size:
                        raise DataError(
                            f"rebase: post-seal tombstone (stable id "
                            f"{sid}) maps to no row in the new generation "
                            f"— the fold is inconsistent"
                        )
                    pos = new_base_n + int(hits[0])
                positions.add(pos)
            self._model = new_model
            self._version = version
            self._base_stable = new_base_stable
            self._base_n = new_base_n
            self._generation = generation
            self._folded_seq = fold["seq"]
            self._features, self._values, self._stable = (features, values,
                                                          stable)
            self._count = len(post)
            self._tomb_stable = frozenset(keep_tombs)
            self._tomb_pos = frozenset(positions)
            self._rebuild_tomb_arrays()
            # Fresh generation, fresh tail: drop the old device buffer
            # (snapshots holding its view keep reading it — jax arrays
            # are immutable) and lazily re-activate at the threshold.
            self._dtail = None
            self._sync_device_tail()

    def reseed(self, new_model, new_base_stable, current: dict,
               version: Optional[str] = None, commit=None) -> None:
        """Abandon this engine's entire lineage in favor of a freshly
        installed snapshot generation (fleet/bootstrap.py). MUST run
        inside the batcher's model-swap critical section, exactly like
        :meth:`rebase`. Unlike a rebase nothing survives: delta slots,
        tombstones, the digest window, and the WAL cursor all reset to
        the snapshot's fold point — records past it arrive back through
        the normal replication path (the primary holds them).

        ``commit`` — an optional callable run under the engine lock
        AFTER validation but BEFORE any state mutates: the bootstrap
        installer's durable commit (clear old-lineage epochs, atomic
        CURRENT.json replace). Running it here means no mutation can
        append to an epoch file that is about to be abandoned, and a
        raise from it leaves the engine untouched (``swap_model``
        restores the old model — together a true rollback)."""
        with self._lock:
            if new_base_stable is not None:
                stable = check_stable_ascending(
                    np.asarray(new_base_stable, np.int64), "reseed")
            else:
                stable = np.arange(new_model.train_.num_instances,
                                   dtype=np.int64)
            if new_model.train_.num_features != self._d:
                raise DataError(
                    f"reseed: snapshot generation has "
                    f"{new_model.train_.num_features} features but this "
                    f"replica serves {self._d} — wrong fleet"
                )
            folded = int(current.get("folded_seq", 0))
            if commit is not None:
                commit()
            self._model = new_model
            self._version = version
            self._base_stable = stable
            self._base_n = int(stable.shape[0])
            self._generation = int(current.get("generation", 0))
            self._folded_seq = folded
            self._seq = folded
            self._next_stable = max(
                int(stable[-1]) + 1 if self._base_n else 0,
                int(current.get("next_stable", 0)))
            cap = min(_INITIAL_SLOTS, self.delta_cap)
            self._features = np.zeros((cap, self._d), np.float32)
            self._values = np.zeros(cap, np.float32)
            self._stable = np.zeros(cap, np.int64)
            self._count = 0
            self._tomb_stable = frozenset()
            self._tomb_pos = frozenset()
            self._rebuild_tomb_arrays()
            self._digests = {}
            self._dtail = None
            self._sync_device_tail()
            # The old lineage's epoch files are gone (commit cleared
            # them); rotate to a fresh log so new records land in an
            # epoch that postdates the installed fold point.
            self._log.close()
            epochs = artifact.list_epochs(self.root)
            self._epoch = (epochs[-1][0] + 1) if epochs else 1
            self._log = artifact.EpochLog(
                artifact.epoch_path(self.root, self._epoch))

    def note_compaction(self, outcome: str, wall_ms: float,
                        detail: Optional[dict] = None) -> None:
        with self._lock:
            self._last_compaction = {
                "outcome": outcome, "wall_ms": round(wall_ms, 3),
                **(detail or {}),
            }
        obs.counter_add(
            "knn_mutable_compactions_total",
            help="background compactions by outcome (rolled_back = the "
                 "old generation kept serving)",
            outcome=outcome,
        )
        obs.gauge_set(
            "knn_mutable_compaction_wall_ms", round(wall_ms, 3),
            help="wall time of the most recent compaction attempt",
        )

    def base_manifest_block(self, fold: dict,
                            new_base_stable: np.ndarray) -> dict:
        """The ``mutable_block`` the compactor hands ``save_index`` for a
        new generation."""
        return {
            "stable_ids": np.asarray(new_base_stable, np.int64),
            "folded_seq": int(fold["seq"]),
            "next_stable": int(self._next_stable),
            "generation": int(fold["generation"]) + 1,
        }

    # -- instruments / export ----------------------------------------------

    def on_pressure(self, cb) -> None:
        self._on_pressure = cb

    def _maybe_kick(self, pressure: int) -> None:
        cb = self._on_pressure
        if cb is not None:
            try:
                cb(pressure)
            except Exception:  # noqa: BLE001 — compaction nudge only
                pass

    def _note_mutation(self, op: str, outcome: str, rows: int = 1) -> None:
        obs.counter_add(
            "knn_mutable_mutations_total", rows,
            help="acknowledged/rejected mutations by op (rows for "
                 "inserts, ids for deletes)",
            op=op, outcome=outcome,
        )

    def _note_visible(self, submitted_ns: int) -> None:
        ms = (time.monotonic_ns() - submitted_ns) / 1e6
        with self._lock:
            self._fresh.note(ms)
        obs.histogram_observe(
            "knn_mutable_freshness_ms", ms,
            buckets=FRESHNESS_BUCKETS_MS,
            help="write-to-visible latency: mutation submit to applied-"
                 "in-every-subsequent-dispatch-snapshot",
        )

    def export(self) -> dict:
        """Refresh the ``knn_mutable_*`` gauges (scrape-time, the
        ``knn_slo_*`` rule) and return the /healthz ``mutable`` block."""
        with self._lock:
            live_delta = self._count - int(self._tomb_delta.shape[0])
            doc = {
                "delta_rows": live_delta,
                "delta_slots": self._count,
                "delta_cap": self.delta_cap,
                "delta_ratio": (round(live_delta / self._base_n, 6)
                                if self._base_n else None),
                "tombstones": len(self._tomb_stable),
                "seq": self._seq,
                "folded_seq": self._folded_seq,
                "epoch": self._epoch,
                "generation": self._generation,
                "base_rows": self._base_n,
                "freshness": self._fresh.export(),
                "last_compaction": self._last_compaction,
                "device_tail": {
                    "mode": self._device_tail_mode,
                    "active": self._dtail is not None,
                },
            }
        obs.gauge_set(
            "knn_mutable_delta_rows", doc["delta_rows"],
            help="live (non-tombstoned) rows in the delta tier",
        )
        obs.gauge_set(
            "knn_mutable_delta_ratio", doc["delta_ratio"] or 0.0,
            help="live delta rows over base rows (compaction debt)",
        )
        obs.gauge_set(
            "knn_mutable_tombstones", doc["tombstones"],
            help="live tombstones masked out of candidate sets",
        )
        obs.gauge_set(
            "knn_mutable_epoch", doc["epoch"],
            help="active write-ahead epoch number",
        )
        obs.gauge_set(
            "knn_mutable_generation", doc["generation"],
            help="compacted base generation the process serves",
        )
        return doc

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._log.close()
