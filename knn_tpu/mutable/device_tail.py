"""The device-resident delta tail: the mutable tier's block on device.

PR 10's delta tier lives in host numpy, so every mutable-on dispatch
pays a host roundtrip to score the delta block and merge it into the
base answer (``mutable/state.merge_candidates``). This module keeps the
delta features in a pre-allocated DEVICE buffer — grown by doubling in
lockstep with the engine's host arrays, updated in place via
``jax.lax.dynamic_update_slice`` on insert — and merges base+delta in
the SAME device round trip as the base retrieval:

- the exact rungs chain :func:`make_merge_tail`'s jitted two-key sort
  onto the XLA retrieval's device outputs
  (``models/knn._kneighbors_arrays(merge_tail=...)``) — one host sync
  returns the merged candidates;
- the ivf rung fuses the same operands into its segment scorer
  (``ops/segment_score._segment_topk_delta_core``) so probed cells and
  delta rows ride one gather+score+select dispatch.

Snapshot semantics: jax arrays are immutable, so a
:class:`DeviceTailView` taken under the engine lock is a consistent
frozen snapshot for free — the functional ``dynamic_update_slice``
builds a NEW buffer for the appended state while readers keep theirs
(the same append-frozen contract the host arrays honor; this is also
why the update is NOT donated: a donated buffer would be reused under a
live snapshot). Dead slots are a separate ``[cap] bool`` mask rebuilt
on delete (deletes are rare; the mask upload is tiny) and liveness is
``slot < count AND not dead``, so appends never touch the mask.

Bit-identity: the device merge selects top-(k + RERANK_PAD) by device
distances; :func:`rerank_merged` re-scores the delta survivors on the
host with the oracle einsum form (shape-invariant per pair) and
re-selects through ``lexicographic_topk``, so the merged answer is
bit-identical to the host ``merge_candidates`` path. Views with BASE
tombstones keep the host merge — its per-affected-row oracle widening
has no fixed compiled shape (docs/INDEXES.md §On-device scoring).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from knn_tpu.models.ordering import lexicographic_topk

#: Append regions round up to this many slots so the jitted
#: dynamic_update_slice sees a bounded set of row shapes.
_APPEND_QUANTUM = 8


class DeviceTailView(NamedTuple):
    """One frozen device snapshot of the delta tail, carried on
    :class:`~knn_tpu.mutable.state.MutableView.device`."""

    features: jnp.ndarray  # [cap, D] float32, slots >= count undefined
    dead: jnp.ndarray      # [cap] bool — tombstoned delta slots
    count: int             # delta slots in use (live + tombstoned)
    base_n: int            # base rows in this generation


@jax.jit
def _append_core(buf, rows, start):
    return lax.dynamic_update_slice(buf, rows, (start, jnp.int32(0)))


@functools.partial(jax.jit, static_argnames=("kk",))
def _delta_merge_core(base_d, base_i, queries, delta_rows, delta_dead,
                      base_n, count, kk):
    """Fuse the delta block into a base top-k ON DEVICE: score every
    delta slot (subtraction-form squared euclidean), mask dead/unused
    slots to (+inf, sentinel), and run ONE selection over base+delta
    under the ``models/ordering.py`` tie contract
    (``ops/segment_score.margin_select`` — fast distance top-k with the
    exact two-key sort as the on-device tie fallback). Returns the
    top-``kk`` merged survivors for the host re-rank."""
    from knn_tpu.ops.segment_score import delta_columns, margin_select

    dd, di, _sentinel = delta_columns(queries, delta_rows, delta_dead,
                                      base_n, count)
    all_d = jnp.concatenate([base_d, dd], axis=1)
    all_i = jnp.concatenate([base_i.astype(jnp.int32), di], axis=1)
    return margin_select(all_d, all_i, kk)


def make_merge_tail(view: DeviceTailView, k: int):
    """The ``merge_tail`` hook for ``models/knn._kneighbors_arrays``:
    ``(d_dev, i_dev, queries_dev) -> (d_dev, i_dev)`` merging this
    view's delta block into the base top-k on device. The ``sig``
    attribute joins the retrieval executable-cache key."""
    from knn_tpu.ops.segment_score import RERANK_PAD

    cap = view.features.shape[0]
    kk = min(k + RERANK_PAD, k + cap)
    base_n = jnp.asarray(view.base_n, jnp.int32)
    count = jnp.asarray(view.count, jnp.int32)

    def tail(d_dev, i_dev, queries_dev):
        return _delta_merge_core(d_dev, i_dev, queries_dev,
                                 view.features, view.dead, base_n,
                                 count, kk=kk)

    tail.sig = ("delta-merge", cap, kk)
    return tail


def slice_view(view: DeviceTailView, start: int,
               stop: int) -> DeviceTailView:
    """One shard's contiguous slice of a device tail snapshot
    (``knn_tpu/shard/plan.plan_delta`` boundaries): slots
    ``[start, stop)`` become a self-contained view whose ``base_n``
    offset keeps positional ids GLOBAL — slot ``j`` of the slice scores
    as id ``base_n + start + j``, exactly what the unsliced view would
    assign it. The jnp slices are lazy device ops on the frozen buffer
    (no host roundtrip), and every slot below the slice's ``count`` is
    a real slot of the parent (the caller slices within the parent's
    count), so the delta liveness rule needs no new cases.

    Sentinel caveat for callers: a slice that does not reach the
    parent's count has sentinel ``base_n + stop`` — a REAL slot id of
    the next shard — so per-shard survivors must remap their slice
    sentinel to the parent's before any cross-shard merge
    (``knn_tpu/shard/dispatch.py`` owns that rewrite)."""
    start = max(0, int(start))
    stop = max(start, min(int(stop), view.count))
    return DeviceTailView(
        features=view.features[start:stop],
        dead=view.dead[start:stop],
        count=stop - start,
        base_n=view.base_n + start,
    )


def rerank_merged(view, train_x: np.ndarray, queries: np.ndarray,
                  cand: np.ndarray, k: int, metric: str,
                  base_d: Optional[np.ndarray] = None):
    """Host exact re-rank of device-merged survivors, in the view's
    positional id space: delta candidates (``base_n <= id < sentinel``)
    are re-scored with the oracle einsum form on the HOST delta arrays
    (bit-identical to ``mutable/state.delta_distances``), sentinel slots
    mask to +inf, and the final top-k selects through
    ``lexicographic_topk``.

    ``base_d`` — when given (the exact rungs), base candidates keep
    these pass-through distances exactly as the host merge keeps the
    answering rung's values; when None (the ivf fused path), base
    candidates are re-scored with the einsum form too, matching the ivf
    host scorer's exact-distance promise."""
    if metric not in (None, "euclidean"):
        raise ValueError("the device delta merge implements euclidean "
                         "only; the host merge handles other metrics")
    queries = np.asarray(queries, np.float32)
    cand = np.asarray(cand, np.int64)
    base_n, sentinel = view.base_n, view.sentinel
    if base_d is not None:
        d = np.ascontiguousarray(base_d, np.float32).copy()
    else:
        d = np.full(cand.shape, np.inf, np.float32)
        base_mask = cand < base_n
        if base_mask.any():
            qi, ci = np.nonzero(base_mask)
            diff = queries[qi] - train_x[cand[qi, ci]]
            d[qi, ci] = np.einsum("nd,nd->n", diff, diff,
                                  dtype=np.float32)
    delta_mask = (cand >= base_n) & (cand < sentinel)
    if delta_mask.any():
        qi, ci = np.nonzero(delta_mask)
        rows = np.asarray(view.features)[cand[qi, ci] - base_n]
        diff = queries[qi] - rows
        d[qi, ci] = np.einsum("nd,nd->n", diff, diff, dtype=np.float32)
    # NaN -> +inf without touching the pass-through +inf entries
    # (nan_to_num's posinf default would clobber them to float32 max).
    d[np.isnan(d)] = np.inf
    d[cand >= sentinel] = np.inf
    return lexicographic_topk(d, cand, k)


class DeviceDeltaTail:
    """Owns the device buffer + dead mask; driven by the engine under
    its lock (``mutable/engine.py``). All updates are functional — old
    buffers stay valid under any snapshot holding them."""

    __slots__ = ("_buf", "_dead", "_count", "_base_n")

    def __init__(self):
        self._buf = None
        self._dead = None
        self._count = 0
        self._base_n = 0

    @property
    def cap(self) -> int:
        return 0 if self._buf is None else self._buf.shape[0]

    def rebuild(self, host_features: np.ndarray, count: int,
                dead_slots: np.ndarray, base_n: int) -> None:
        """Full (re)upload — activation, growth past the current device
        cap, and compaction rebase all land here."""
        self._buf = jnp.asarray(
            np.ascontiguousarray(host_features, np.float32))
        self._count = int(count)
        self._base_n = int(base_n)
        self.set_dead(dead_slots)

    def append(self, host_features: np.ndarray, start: int,
               end: int, base_n: int) -> None:
        """Write slots ``[start, end)`` in place via
        ``dynamic_update_slice`` (region rounded to the append quantum
        so compiled row shapes stay bounded); a host-side growth
        (capacity change) falls back to a full rebuild."""
        if self._buf is None or self.cap != host_features.shape[0]:
            dead = (np.asarray(self._dead) if self._dead is not None
                    else np.zeros(host_features.shape[0], bool))
            dead_slots = np.flatnonzero(dead[:min(len(dead), end)])
            self.rebuild(host_features, end, dead_slots, base_n)
            return
        s0 = (start // _APPEND_QUANTUM) * _APPEND_QUANTUM
        m = min(-(-(end - s0) // _APPEND_QUANTUM) * _APPEND_QUANTUM,
                self.cap - s0)
        rows = np.ascontiguousarray(host_features[s0:s0 + m], np.float32)
        self._buf = _append_core(self._buf, jnp.asarray(rows),
                                 jnp.asarray(s0, jnp.int32))
        self._count = int(end)
        self._base_n = int(base_n)

    def set_dead(self, dead_slots: np.ndarray) -> None:
        mask = np.zeros(self.cap, bool)
        dead_slots = np.asarray(dead_slots, np.int64)
        if dead_slots.size:
            mask[dead_slots] = True
        self._dead = jnp.asarray(mask)

    def view(self) -> DeviceTailView:
        return DeviceTailView(self._buf, self._dead, self._count,
                              self._base_n)
