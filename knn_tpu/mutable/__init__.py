"""Online mutable indexes: delta tier, tombstones, background compaction.

The LSM-style split (docs/INDEXES.md §Mutable tier): an immutable base —
every existing rung, cache, and compiled executable untouched — plus a
small mutable tail merged into every answer under the shared
(distance, index) contract, folded back into a fresh immutable
generation by background compaction through the live swap path.

- :mod:`knn_tpu.mutable.state`   — the per-dispatch immutable view and
  the lexicographic base+delta+tombstone merge;
- :mod:`knn_tpu.mutable.engine`  — write-ahead epoch log, mutation
  application, boot replay, compaction seal/rebase;
- :mod:`knn_tpu.mutable.compact` — the fold + the background compactor.

Nothing here is imported unless a server boots with ``--mutable on``
(the zero-cost-when-disabled contract,
scripts/check_disabled_overhead.py).
"""

from knn_tpu.mutable.state import MutableView, MutationConflict  # noqa: F401
