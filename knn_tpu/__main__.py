from knn_tpu.cli import main

main()
