"""The IVF (inverted-file) partitioned index — sub-linear retrieval.

Jégou et al.'s IVF family (TPAMI 2011), adapted to this framework's
contracts: the train set is partitioned into ``num_cells`` k-means cells
at ``save-index --ivf-cells N`` time; at query time the centroids are
ranked, the nearest ``nprobe`` cells' rows are gathered through a
cell-sorted row permutation, and EXACT distances + the shared
(distance, index) tie order (:mod:`knn_tpu.models.ordering`) select top-k
over the candidates only. Cost per query is ~``nprobe/num_cells`` of a
full scan; what approximation costs is *recall*, never wrong distances —
every returned candidate carries its true exact distance.

Correctness anchors (pinned by tests/test_ivf.py):

- **nprobe == num_cells is bit-identical to exact retrieval**: the
  candidate set is then every train row, distances are computed with the
  oracle's own einsum form, and selection goes through the same
  ``lexicographic_topk`` — so the full-probe IVF path reproduces
  :func:`~knn_tpu.backends.oracle.oracle_kneighbors` bit-for-bit.
- **Never returns short**: when the probed cells hold fewer than ``k``
  candidates for any query (tiny cells, empty cells, k close to N), the
  probe set WIDENS (doubling) until coverage — counted in
  ``knn_ivf_forced_widenings_total``, never silently truncated.
- **Degenerate partitions serve**: empty cells contribute nothing and
  cost nothing; a single-cell index is exact retrieval with one extra
  centroid compare.

Persistence rides the artifact store (``serve/artifact.py``, format 3):
three arrays (``ivf_centroids``, ``ivf_row_perm``, ``ivf_cell_offsets``)
in ``arrays.npz`` plus an ``ivf`` manifest block; a format-2 artifact
simply has neither and serves exact-only. :class:`IVFServing` is the
serving-side wrapper: the micro-batcher's ``ivf`` rung dispatches through
it, the :class:`~knn_tpu.index.probe_policy.ProbePolicy` supplies the
live ``nprobe``, and the ``knn_ivf_*`` instruments record probes,
candidate rows scanned, and cell imbalance (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.models.ordering import lexicographic_topk
from knn_tpu.resilience.errors import DataError

#: The attribute a fitted model carries its IVF partition on
#: (``artifact.load_index`` attaches it; everything else reads it with
#: ``getattr(model, IVF_ATTR, None)`` so exact-only models stay untouched).
IVF_ATTR = "ivf_"

#: Candidate-scoring chunk bound (elements in the [chunk, M, D] gather +
#: diff blocks) — the oracle's 4e7 halved because this path materializes
#: both the gathered rows and the diff tensor.
_CHUNK_ELEMS = int(2e7)


class IVFSearchStats(NamedTuple):
    """What one :meth:`IVFIndex.search` call actually did."""

    nprobe: int            # probes used (>= requested when widened)
    requested: int         # probes the caller asked for
    forced_widenings: int  # doubling rounds forced by k-coverage
    candidate_rows: int    # total train rows scored across the batch
    cells_probed: int      # queries x nprobe


class IVFIndex:
    """Centroids + cell-sorted row permutation + cell offsets.

    ``row_perm`` lists every train row index grouped by cell (cells in id
    order, rows ascending inside a cell — the build sorts with a stable
    key so artifacts are deterministic); ``cell_offsets [C+1]`` delimits
    each cell's slice. The train rows themselves stay in the dataset —
    the index never copies them.
    """

    __slots__ = ("centroids", "row_perm", "cell_offsets", "meta")

    def __init__(self, centroids: np.ndarray, row_perm: np.ndarray,
                 cell_offsets: np.ndarray, meta: Optional[dict] = None):
        self.centroids = np.ascontiguousarray(centroids, np.float32)
        self.row_perm = np.ascontiguousarray(row_perm, np.int64)
        self.cell_offsets = np.ascontiguousarray(cell_offsets, np.int64)
        self.meta = dict(meta or {})

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, features: np.ndarray, num_cells: int, *, seed: int = 0,
              iters: int = 25) -> "IVFIndex":
        """Partition ``features`` and build the inverted file. Euclidean
        only — the cells are Voronoi regions of the squared-euclidean
        k-means, so probing them under any other metric would rank cells
        by the wrong geometry (the caller validates; docs/INDEXES.md)."""
        from knn_tpu.index.kmeans import kmeans

        features = np.asarray(features, np.float32)
        n = features.shape[0]
        with obs.span("ivf.build", rows=n, cells=num_cells):
            centroids, assign, info = kmeans(
                features, num_cells, seed=seed, iters=iters)
            # Stable sort by cell: rows ascending inside each cell, so
            # the permutation (and the artifact bytes) are deterministic.
            row_perm = np.argsort(assign, kind="stable").astype(np.int64)
            counts = np.bincount(assign, minlength=num_cells)
            cell_offsets = np.zeros(num_cells + 1, np.int64)
            np.cumsum(counts, out=cell_offsets[1:])
        return cls(centroids, row_perm, cell_offsets, meta={
            "num_cells": int(num_cells),
            "seed": int(seed),
            "iterations": int(info["iterations"]),
            "inertia": info["inertia"],
            "empty_cells": int(info["empty_cells"]),
            "metric": "euclidean",
        })

    # -- introspection -----------------------------------------------------

    @property
    def num_cells(self) -> int:
        return self.centroids.shape[0]

    @property
    def num_rows(self) -> int:
        return self.row_perm.shape[0]

    @property
    def cell_sizes(self) -> np.ndarray:
        return np.diff(self.cell_offsets)

    def imbalance(self) -> float:
        """Largest cell vs the perfectly-balanced size (1.0 = uniform;
        10.0 = the worst cell does 10x its share of probe work) — the
        ``knn_ivf_cell_imbalance`` gauge."""
        if self.num_rows == 0:
            return 1.0
        mean = self.num_rows / self.num_cells
        return round(float(self.cell_sizes.max()) / mean, 3) if mean else 1.0

    # -- persistence (serve/artifact.py) -----------------------------------

    def to_arrays(self) -> dict:
        """The ``arrays.npz`` entries (key prefix ``ivf_``)."""
        return {
            "ivf_centroids": self.centroids,
            "ivf_row_perm": self.row_perm,
            "ivf_cell_offsets": self.cell_offsets,
        }

    def manifest_entry(self) -> dict:
        return dict(self.meta)

    @classmethod
    def from_arrays(cls, arrays: dict, manifest_entry: dict,
                    train_rows: int, num_features: int,
                    where: str = "artifact") -> "IVFIndex":
        """Rebuild + validate from a loaded artifact. Every structural
        invariant is checked here so a hand-edited or mismatched artifact
        fails typed (:class:`DataError`) at load, never as wrong answers
        or numpy errors mid-request."""
        try:
            centroids = np.asarray(arrays["ivf_centroids"], np.float32)
            row_perm = np.asarray(arrays["ivf_row_perm"], np.int64)
            cell_offsets = np.asarray(arrays["ivf_cell_offsets"], np.int64)
        except KeyError as e:
            raise DataError(
                f"{where}: manifest declares an ivf partition but "
                f"arrays.npz lacks {e} — the artifact is not from one "
                f"save; rebuild the index") from e
        c = centroids.shape[0]
        if centroids.ndim != 2 or c < 1:
            raise DataError(f"{where}: ivf_centroids must be [C>=1, D], "
                            f"got shape {centroids.shape}")
        if centroids.shape[1] != num_features:
            raise DataError(
                f"{where}: ivf centroid width {centroids.shape[1]} does "
                f"not match the train feature width {num_features}")
        if cell_offsets.shape != (c + 1,):
            raise DataError(
                f"{where}: ivf_cell_offsets must be [C+1={c + 1}], got "
                f"shape {cell_offsets.shape}")
        if (cell_offsets[0] != 0 or cell_offsets[-1] != train_rows
                or (np.diff(cell_offsets) < 0).any()):
            raise DataError(
                f"{where}: ivf_cell_offsets must rise monotonically from "
                f"0 to train_rows={train_rows}")
        if row_perm.shape != (train_rows,) or (
                train_rows and not (
                    np.bincount(row_perm, minlength=train_rows) == 1
                ).all()):
            raise DataError(
                f"{where}: ivf_row_perm must be a permutation of "
                f"[0, {train_rows}) — the cell-sorted row order is "
                f"corrupt; rebuild the index")
        declared = manifest_entry.get("num_cells")
        if declared is not None and int(declared) != c:
            raise DataError(
                f"{where}: manifest ivf.num_cells={declared} but the "
                f"arrays hold {c} centroids")
        return cls(centroids, row_perm, cell_offsets, meta=manifest_entry)

    # -- query -------------------------------------------------------------

    def _gather_candidates(self, sel: np.ndarray, sizes: np.ndarray,
                           counts: np.ndarray) -> np.ndarray:
        """Per-query candidate train indices ``[B, M]`` for the probed
        cells ``sel [B, P]``, padded with ``num_rows`` (the sentinel the
        scorer masks to +inf). Fully vectorized: one searchsorted over
        the flattened (query, cell) segment lengths replaces a Python
        slice loop per probe — the gather was the host hot path."""
        n = self.num_rows
        b, _p = sel.shape
        m = int(counts.max()) if b else 0
        cand = np.full((b, m), n, np.int64)
        starts = self.cell_offsets[:-1][sel]
        lens = sizes[sel]
        total = int(lens.sum())
        if total == 0:
            return cand
        flat_lens = lens.ravel()
        ends = np.cumsum(flat_lens)
        pos = np.arange(total)
        seg = np.searchsorted(ends, pos, side="right")
        src = starts.ravel()[seg] + pos - (ends[seg] - flat_lens[seg])
        qof = seg // sel.shape[1]
        qstart = np.concatenate(([0], np.cumsum(counts)))
        cand[qof, pos - qstart[qof]] = self.row_perm[src]
        return cand

    def search(self, train_x: np.ndarray, queries: np.ndarray, k: int,
               nprobe: int):
        """Probed retrieval: ``(dists [Q,k] f32, indices [Q,k] int64,
        stats)`` under the shared (distance, index) tie order.

        Distances of the probed candidates are EXACT — computed with the
        oracle backend's einsum form on the same float32 operands, which
        is what makes the full-probe path bit-identical to
        ``oracle_kneighbors`` and keeps the shadow scorer's
        distance-divergence check silent on this rung. Queries with NaN
        features follow the framework NaN → +inf policy.
        """
        train_x = np.asarray(train_x, np.float32)
        queries = np.asarray(queries, np.float32)
        n, q = train_x.shape[0], queries.shape[0]
        if n != self.num_rows:
            raise DataError(
                f"ivf index spans {self.num_rows} rows but the train set "
                f"has {n} — index and data are out of sync")
        c = self.num_cells
        k = min(int(k), n)
        requested = min(max(1, int(nprobe)), c)
        nprobe = requested
        with obs.span("ivf.search", rows=q, nprobe=requested, k=k):
            # Rank cells per query (fast matmul form would do — ranking
            # only — but C is small, so keep the oracle's diff form and
            # one less code path).
            diff = queries[:, None, :] - self.centroids[None, :, :]
            cd = np.einsum("qcd,qcd->qc", diff, diff, dtype=np.float32)
            np.nan_to_num(cd, copy=False, nan=np.inf)
            # Stable argsort: equal centroid distances probe the lower
            # cell id first — deterministic probe order.
            order = np.argsort(cd, axis=1, kind="stable")
            sizes = self.cell_sizes
            # k-coverage widening: never return short.
            forced = 0
            while True:
                counts = sizes[order[:, :nprobe]].sum(axis=1)
                if int(counts.min()) >= k or nprobe >= c:
                    break
                nprobe = min(c, nprobe * 2)
                forced += 1
            sel = order[:, :nprobe]
            dists_out = np.empty((q, k), np.float32)
            idx_out = np.empty((q, k), np.int64)
            d_feat = max(train_x.shape[1], 1)
            m_global = int(counts.max()) if q else 0
            chunk = max(1, min(q or 1,
                               _CHUNK_ELEMS // max(m_global * d_feat, 1)))
            for s in range(0, q, chunk):
                e = min(q, s + chunk)
                # Pad slots carry candidate index n (sorts after every
                # real index, so a real +inf-distance candidate still
                # wins the tie) and distance +inf.
                cand = self._gather_candidates(sel[s:e], sizes,
                                               counts[s:e])
                gathered = train_x[np.minimum(cand, n - 1)]
                gdiff = queries[s:e][:, None, :] - gathered
                d = np.einsum("qmd,qmd->qm", gdiff, gdiff,
                              dtype=np.float32)
                np.nan_to_num(d, copy=False, nan=np.inf)
                d[cand == n] = np.inf
                dists_out[s:e], idx_out[s:e] = lexicographic_topk(
                    d, cand, k)
        return dists_out, idx_out, IVFSearchStats(
            nprobe=nprobe, requested=requested, forced_widenings=forced,
            candidate_rows=int(counts.sum()) if q else 0,
            cells_probed=q * nprobe,
        )


class IVFServing:
    """The serving-side IVF rung: probe policy + instruments.

    Holds NO index — it reads the batch's own model snapshot
    (``model.ivf_``), so hot reloads swap the partition with the model
    atomically and a response can never mix one index's rows with
    another's centroids. Constructed only when ``serve --ivf-probes`` is
    given (the zero-cost-when-disabled contract:
    ``scripts/check_disabled_overhead.py`` pins that an exact-only boot
    builds none of this).
    """

    def __init__(self, base_probes: int, num_cells: int, *, slo=None,
                 recall_floor: float = 0.95, policy=None):
        if not 0.0 < recall_floor <= 1.0:
            raise ValueError(
                f"recall_floor must be in (0, 1], got {recall_floor}")
        from knn_tpu.index.probe_policy import ProbePolicy

        self.recall_floor = float(recall_floor)
        self.policy = policy if policy is not None else ProbePolicy(
            base_probes, num_cells, slo=slo)

    def set_num_cells(self, num_cells: int) -> None:
        """Re-bound the policy after a hot reload swapped in an index
        with a different cell count."""
        self.policy.set_num_cells(num_cells)

    def kneighbors(self, model, feats: np.ndarray, k: Optional[int] = None):
        """One ivf-rung dispatch for the micro-batcher: policy-chosen
        ``nprobe``, probed search, instruments. Returns ``(dists, idx)``
        like every other rung closure. ``k`` overrides ``model.k`` for
        the mutable tier's tombstone k-coverage widening
        (``knn_tpu/mutable/state.py``) — the probed search takes k as a
        plain host argument, so widening recompiles nothing and the
        delta rows are searched exhaustively beside the probed cells by
        the merge layer."""
        index = getattr(model, IVF_ATTR, None)
        if index is None:  # reload validation forbids this; stay typed
            raise DataError("serving model has no ivf partition")
        train = model.train_
        dists, idx, stats = index.search(
            train.features, feats, model.k if k is None else k,
            self.policy.current())
        obs.gauge_set(
            "knn_ivf_probes", stats.nprobe,
            help="cells probed per query by the last ivf-rung dispatch "
                 "(the probe policy's live operating point)",
        )
        obs.gauge_set(
            "knn_ivf_cell_imbalance", index.imbalance(),
            help="largest cell size over the balanced size (1.0 = "
                 "uniform partition)",
        )
        obs.counter_add(
            "knn_ivf_queries_total", feats.shape[0],
            help="query rows answered by the ivf rung",
        )
        obs.counter_add(
            "knn_ivf_candidate_rows_total", stats.candidate_rows,
            help="train rows gathered and exactly scored by ivf probes "
                 "(the sub-linear win: compare with train_rows x queries)",
        )
        if stats.forced_widenings:
            obs.counter_add(
                "knn_ivf_forced_widenings_total", stats.forced_widenings,
                help="probe doublings forced because the probed cells "
                     "held fewer than k candidates (the never-return-"
                     "short guarantee)",
            )
        return dists, idx

    def export(self, model=None) -> dict:
        """The ``/healthz`` ivf block."""
        index = getattr(model, IVF_ATTR, None) if model is not None else None
        doc = {
            "recall_floor": self.recall_floor,
            **self.policy.export(),
        }
        if index is not None:
            doc.update(
                num_cells=index.num_cells,
                empty_cells=int((index.cell_sizes == 0).sum()),
                cell_imbalance=index.imbalance(),
            )
        return doc
