"""The IVF (inverted-file) partitioned index — sub-linear retrieval.

Jégou et al.'s IVF family (TPAMI 2011), adapted to this framework's
contracts: the train set is partitioned into ``num_cells`` k-means cells
at ``save-index --ivf-cells N`` time; at query time the centroids are
ranked, the nearest ``nprobe`` cells' rows are gathered through a
cell-sorted row permutation, and EXACT distances + the shared
(distance, index) tie order (:mod:`knn_tpu.models.ordering`) select top-k
over the candidates only. Cost per query is ~``nprobe/num_cells`` of a
full scan; what approximation costs is *recall*, never wrong distances —
every returned candidate carries its true exact distance.

Correctness anchors (pinned by tests/test_ivf.py):

- **nprobe == num_cells is bit-identical to exact retrieval**: the
  candidate set is then every train row, distances are computed with the
  oracle's own einsum form, and selection goes through the same
  ``lexicographic_topk`` — so the full-probe IVF path reproduces
  :func:`~knn_tpu.backends.oracle.oracle_kneighbors` bit-for-bit.
- **Never returns short**: when the probed cells hold fewer than ``k``
  candidates for any query (tiny cells, empty cells, k close to N), the
  probe set WIDENS (doubling) until coverage — counted in
  ``knn_ivf_forced_widenings_total``, never silently truncated.
- **Degenerate partitions serve**: empty cells contribute nothing and
  cost nothing; a single-cell index is exact retrieval with one extra
  centroid compare.

Persistence rides the artifact store (``serve/artifact.py``, format 3):
three arrays (``ivf_centroids``, ``ivf_row_perm``, ``ivf_cell_offsets``)
in ``arrays.npz`` plus an ``ivf`` manifest block; a format-2 artifact
simply has neither and serves exact-only. :class:`IVFServing` is the
serving-side wrapper: the micro-batcher's ``ivf`` rung dispatches through
it, the :class:`~knn_tpu.index.probe_policy.ProbePolicy` supplies the
live ``nprobe``, and the ``knn_ivf_*`` instruments record probes,
candidate rows scanned, and cell imbalance (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.models.ordering import lexicographic_topk
from knn_tpu.resilience.errors import DataError

#: The attribute a fitted model carries its IVF partition on
#: (``artifact.load_index`` attaches it; everything else reads it with
#: ``getattr(model, IVF_ATTR, None)`` so exact-only models stay untouched).
IVF_ATTR = "ivf_"

#: Candidate-scoring chunk bound (elements in the [chunk, M, D] gather +
#: diff blocks) — the oracle's 4e7 halved because this path materializes
#: both the gathered rows and the diff tensor.
_CHUNK_ELEMS = int(2e7)

#: ``scorer="auto"`` routes a search to the device gather+score kernel
#: (``ops/segment_score.py``) once the batch's candidate work
#: (queries x padded-candidates x features) crosses this bound — below
#: it the host scorer wins outright (a jit dispatch costs more than the
#: whole numpy scan). ``KNN_TPU_IVF_SCORER=host|device`` overrides the
#: auto rule process-wide (docs/INDEXES.md §On-device scoring).
DEVICE_SCORER_MIN_ELEMS = int(4e6)

#: Cell counts at or above this rank centroids with ``lax.approx_max_k``
#: (the TPU's hardware-binned approximate selection) instead of an exact
#: host argsort — at ~10k cells the O(Q·C·log C) exact ranking starts to
#: rival the probed scan it is meant to shortcut. Recall stays held to
#: the configured floor by the shadow-scorer ``approx_floors`` machinery
#: exactly as the probed approximation is. KNN_TPU_IVF_APPROX_CELLS
#: overrides (tests force it low to exercise the rung).
APPROX_RANK_MIN_CELLS = 10_000


def _approx_rank_threshold() -> int:
    try:
        return int(os.environ.get("KNN_TPU_IVF_APPROX_CELLS",
                                  APPROX_RANK_MIN_CELLS))
    except ValueError:
        return APPROX_RANK_MIN_CELLS


def _scorer_mode(requested: str) -> str:
    """Resolve the effective scorer mode: an explicit caller choice wins,
    then the KNN_TPU_IVF_SCORER env override, then auto."""
    if requested not in ("auto", "host", "device"):
        raise ValueError(
            f"unknown scorer {requested!r}; choose 'auto', 'host', or "
            f"'device'")
    if requested != "auto":
        return requested
    env = os.environ.get("KNN_TPU_IVF_SCORER", "auto")
    return env if env in ("host", "device") else "auto"


class IVFSearchStats(NamedTuple):
    """What one :meth:`IVFIndex.search` call actually did."""

    nprobe: int            # probes used (>= requested when widened)
    requested: int         # probes the caller asked for
    forced_widenings: int  # doubling rounds forced by k-coverage
    candidate_rows: int    # total train rows scored across the batch
    cells_probed: int      # queries x nprobe
    scorer: str = "host"   # which scorer answered (host | device)
    ranking: str = "exact"  # centroid ranking (exact | approx)
    dead_rows: int = 0     # tombstoned rows occupying probed cells
    padded_candidate_rows: int = 0  # compiled-shape candidate waste
    merged_delta: bool = False      # delta tail fused into this dispatch


class IVFIndex:
    """Centroids + cell-sorted row permutation + cell offsets.

    ``row_perm`` lists every train row index grouped by cell (cells in id
    order, rows ascending inside a cell — the build sorts with a stable
    key so artifacts are deterministic); ``cell_offsets [C+1]`` delimits
    each cell's slice. The train rows themselves stay in the dataset —
    the index never copies them.
    """

    __slots__ = ("centroids", "row_perm", "cell_offsets", "meta", "_cache")

    def __init__(self, centroids: np.ndarray, row_perm: np.ndarray,
                 cell_offsets: np.ndarray, meta: Optional[dict] = None):
        self.centroids = np.ascontiguousarray(centroids, np.float32)
        self.row_perm = np.ascontiguousarray(row_perm, np.int64)
        self.cell_offsets = np.ascontiguousarray(cell_offsets, np.int64)
        self.meta = dict(meta or {})
        # Per-index memo for derived layouts: the device-resident
        # permuted-train operands (built on the first device-scored
        # search, keyed on the train array's identity with a strong ref
        # so the id can never be recycled) and the host inverse
        # permutation the delete-aware accounting reads. The index and
        # its train set are immutable for a generation, so one entry
        # each suffices; compaction swaps in a fresh index+train pair.
        self._cache: dict = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, features: np.ndarray, num_cells: int, *, seed: int = 0,
              iters: int = 25) -> "IVFIndex":
        """Partition ``features`` and build the inverted file. Euclidean
        only — the cells are Voronoi regions of the squared-euclidean
        k-means, so probing them under any other metric would rank cells
        by the wrong geometry (the caller validates; docs/INDEXES.md)."""
        from knn_tpu.index.kmeans import kmeans

        features = np.asarray(features, np.float32)
        n = features.shape[0]
        with obs.span("ivf.build", rows=n, cells=num_cells):
            centroids, assign, info = kmeans(
                features, num_cells, seed=seed, iters=iters)
            # Stable sort by cell: rows ascending inside each cell, so
            # the permutation (and the artifact bytes) are deterministic.
            row_perm = np.argsort(assign, kind="stable").astype(np.int64)
            counts = np.bincount(assign, minlength=num_cells)
            cell_offsets = np.zeros(num_cells + 1, np.int64)
            np.cumsum(counts, out=cell_offsets[1:])
        return cls(centroids, row_perm, cell_offsets, meta={
            "num_cells": int(num_cells),
            "seed": int(seed),
            "iterations": int(info["iterations"]),
            "inertia": info["inertia"],
            "empty_cells": int(info["empty_cells"]),
            "metric": "euclidean",
        })

    @classmethod
    def assign_to(cls, features: np.ndarray,
                  previous: "IVFIndex") -> "IVFIndex":
        """Incremental rebuild: assign ``features`` to the PREVIOUS
        generation's centroids — one deterministic jitted assignment
        step, no Lloyd's — and rebuild the inverted file around them.
        The compaction fast path (``mutable/compact.py``): folding a few
        thousand delta rows into a million-row partition does not move
        the centroid field enough to justify re-clustering; when it
        eventually does, the imbalance check there falls back to a full
        :meth:`build`. Cells are Voronoi regions either way, so
        correctness is untouched — assignment quality only moves
        recall-per-probe."""
        from knn_tpu.index.kmeans import assign_cells

        features = np.asarray(features, np.float32)
        assign = assign_cells(features, previous.centroids)
        num_cells = previous.num_cells
        row_perm = np.argsort(assign, kind="stable").astype(np.int64)
        counts = np.bincount(assign, minlength=num_cells)
        cell_offsets = np.zeros(num_cells + 1, np.int64)
        np.cumsum(counts, out=cell_offsets[1:])
        meta = dict(previous.meta)
        meta.update(
            empty_cells=int((counts == 0).sum()),
            incremental=True,
        )
        return cls(previous.centroids, row_perm, cell_offsets, meta=meta)

    # -- introspection -----------------------------------------------------

    @property
    def num_cells(self) -> int:
        return self.centroids.shape[0]

    @property
    def num_rows(self) -> int:
        return self.row_perm.shape[0]

    @property
    def cell_sizes(self) -> np.ndarray:
        return np.diff(self.cell_offsets)

    def imbalance(self) -> float:
        """Largest cell vs the perfectly-balanced size (1.0 = uniform;
        10.0 = the worst cell does 10x its share of probe work) — the
        ``knn_ivf_cell_imbalance`` gauge."""
        if self.num_rows == 0:
            return 1.0
        mean = self.num_rows / self.num_cells
        return round(float(self.cell_sizes.max()) / mean, 3) if mean else 1.0

    # -- persistence (serve/artifact.py) -----------------------------------

    def to_arrays(self) -> dict:
        """The ``arrays.npz`` entries (key prefix ``ivf_``)."""
        return {
            "ivf_centroids": self.centroids,
            "ivf_row_perm": self.row_perm,
            "ivf_cell_offsets": self.cell_offsets,
        }

    def manifest_entry(self) -> dict:
        return dict(self.meta)

    @classmethod
    def from_arrays(cls, arrays: dict, manifest_entry: dict,
                    train_rows: int, num_features: int,
                    where: str = "artifact") -> "IVFIndex":
        """Rebuild + validate from a loaded artifact. Every structural
        invariant is checked here so a hand-edited or mismatched artifact
        fails typed (:class:`DataError`) at load, never as wrong answers
        or numpy errors mid-request."""
        try:
            centroids = np.asarray(arrays["ivf_centroids"], np.float32)
            row_perm = np.asarray(arrays["ivf_row_perm"], np.int64)
            cell_offsets = np.asarray(arrays["ivf_cell_offsets"], np.int64)
        except KeyError as e:
            raise DataError(
                f"{where}: manifest declares an ivf partition but "
                f"arrays.npz lacks {e} — the artifact is not from one "
                f"save; rebuild the index") from e
        c = centroids.shape[0]
        if centroids.ndim != 2 or c < 1:
            raise DataError(f"{where}: ivf_centroids must be [C>=1, D], "
                            f"got shape {centroids.shape}")
        if centroids.shape[1] != num_features:
            raise DataError(
                f"{where}: ivf centroid width {centroids.shape[1]} does "
                f"not match the train feature width {num_features}")
        if cell_offsets.shape != (c + 1,):
            raise DataError(
                f"{where}: ivf_cell_offsets must be [C+1={c + 1}], got "
                f"shape {cell_offsets.shape}")
        if (cell_offsets[0] != 0 or cell_offsets[-1] != train_rows
                or (np.diff(cell_offsets) < 0).any()):
            raise DataError(
                f"{where}: ivf_cell_offsets must rise monotonically from "
                f"0 to train_rows={train_rows}")
        if row_perm.shape != (train_rows,) or (
                train_rows and not (
                    np.bincount(row_perm, minlength=train_rows) == 1
                ).all()):
            raise DataError(
                f"{where}: ivf_row_perm must be a permutation of "
                f"[0, {train_rows}) — the cell-sorted row order is "
                f"corrupt; rebuild the index")
        declared = manifest_entry.get("num_cells")
        if declared is not None and int(declared) != c:
            raise DataError(
                f"{where}: manifest ivf.num_cells={declared} but the "
                f"arrays hold {c} centroids")
        return cls(centroids, row_perm, cell_offsets, meta=manifest_entry)

    # -- query -------------------------------------------------------------

    def _gather_candidates(self, sel: np.ndarray, sizes: np.ndarray,
                           counts: np.ndarray) -> np.ndarray:
        """Per-query candidate train indices ``[B, M]`` for the probed
        cells ``sel [B, P]``, padded with ``num_rows`` (the sentinel the
        scorer masks to +inf). Fully vectorized: one searchsorted over
        the flattened (query, cell) segment lengths replaces a Python
        slice loop per probe — the gather was the host hot path."""
        n = self.num_rows
        b, _p = sel.shape
        m = int(counts.max()) if b else 0
        cand = np.full((b, m), n, np.int64)
        starts = self.cell_offsets[:-1][sel]
        lens = sizes[sel]
        total = int(lens.sum())
        if total == 0:
            return cand
        flat_lens = lens.ravel()
        ends = np.cumsum(flat_lens)
        pos = np.arange(total)
        seg = np.searchsorted(ends, pos, side="right")
        src = starts.ravel()[seg] + pos - (ends[seg] - flat_lens[seg])
        qof = seg // sel.shape[1]
        qstart = np.concatenate(([0], np.cumsum(counts)))
        cand[qof, pos - qstart[qof]] = self.row_perm[src]
        return cand

    def _device_operands(self, train_x: np.ndarray):
        """The device-resident permuted-train pair for the segment
        scorer, memoized per train array identity (a strong ref keeps
        the id stable)."""
        from knn_tpu.ops import segment_score

        hit = self._cache.get("device")
        if hit is not None and hit[0] is train_x:
            return hit[1], hit[2]
        perm_rows, perm_ids = segment_score.device_operands(
            train_x, self.row_perm)
        self._cache["device"] = (train_x, perm_rows, perm_ids)
        return perm_rows, perm_ids

    def _inverse_perm(self) -> np.ndarray:
        inv = self._cache.get("inv_perm")
        if inv is None:
            inv = np.empty(self.num_rows, np.int64)
            inv[self.row_perm] = np.arange(self.num_rows)
            self._cache["inv_perm"] = inv
        return inv

    def dead_rows_per_cell(self, tomb_base: np.ndarray) -> np.ndarray:
        """``[C]`` tombstoned-but-not-yet-compacted base rows per cell —
        what the delete-aware k-coverage widening subtracts from raw cell
        sizes (a probed cell full of dead rows must not satisfy coverage)
        and the ``knn_ivf_dead_candidate_rows_total`` counter reads."""
        tomb_base = np.asarray(tomb_base, np.int64)
        if tomb_base.size == 0:
            return np.zeros(self.num_cells, np.int64)
        pos = self._inverse_perm()[tomb_base]
        cells = np.searchsorted(self.cell_offsets, pos, side="right") - 1
        return np.bincount(cells, minlength=self.num_cells)

    def _rank_cells(self, queries: np.ndarray, need: int):
        """Top-``need`` cells per query: ``(sel [Q, need], ranking)``.

        Exact (the default): centroid distances in the oracle's diff
        form + a stable argsort, so equal centroid distances probe the
        lower cell id first — deterministic probe order. Approx (at or
        past the APPROX_RANK_MIN_CELLS threshold, and never at full
        probe): ``lax.approx_max_k`` over matmul-form distances on the
        device — ranking only, candidates are still scored exactly, so
        the cost is recall (held to the floor by the shadow scorer),
        never wrong distances."""
        c = self.num_cells
        if c >= _approx_rank_threshold() and need < c:
            try:
                from knn_tpu.ops import segment_score

                cents = self._cache.get("centroids_dev")
                if cents is None:
                    import jax.numpy as jnp

                    cents = jnp.asarray(self.centroids)
                    self._cache["centroids_dev"] = cents
                return segment_score.rank_cells_approx(
                    queries, cents, need), "approx"
            except Exception:  # noqa: BLE001 — ranking must never fail a
                pass           # query; the exact path below always works
        order = self._cache.get("last_order")
        if order is None or order[0] is not queries:
            diff = queries[:, None, :] - self.centroids[None, :, :]
            cd = np.einsum("qcd,qcd->qc", diff, diff, dtype=np.float32)
            np.nan_to_num(cd, copy=False, nan=np.inf)
            order = (queries, np.argsort(cd, axis=1, kind="stable"))
            # Memoized for the widening loop only (same queries object);
            # the next search overwrites it.
            self._cache["last_order"] = order
        return order[1][:, :need], "exact"

    def _coverage(self, queries: np.ndarray, k: int, nprobe: int,
                  dead_per_cell: Optional[np.ndarray]):
        """Rank + k-coverage widening. Returns ``(sel, counts, nprobe,
        forced, ranking, dead_rows)`` where ``counts`` is RAW candidate
        rows per query (the gather shape) and coverage is checked on
        LIVE rows (raw minus tombstoned — the delete-aware rule: a
        tombstoned row still occupies its probed cell until compaction,
        so it cannot count toward k)."""
        c = self.num_cells
        sizes = self.cell_sizes
        live_sizes = (sizes - dead_per_cell if dead_per_cell is not None
                      else sizes)
        forced = 0
        while True:
            sel, ranking = self._rank_cells(queries, nprobe)
            if not sel.size:  # zero queries: nothing to cover
                break
            live = live_sizes[sel].sum(axis=1)
            if int(live.min()) >= k or nprobe >= c:
                break
            nprobe = min(c, nprobe * 2)
            forced += 1
        counts = sizes[sel].sum(axis=1)
        dead_rows = (int(dead_per_cell[sel].sum())
                     if dead_per_cell is not None else 0)
        return sel, counts, nprobe, forced, ranking, dead_rows

    def _exact_rerank(self, train_x: np.ndarray, queries: np.ndarray,
                      cand: np.ndarray, k: int):
        """Host exact re-rank of the device scorer's survivors: the
        oracle einsum form (per-pair values are shape-invariant, so
        these are bit-identical to the host scorer's distances) +
        ``lexicographic_topk`` — the one tie contract."""
        n = self.num_rows
        gathered = train_x[np.minimum(cand, n - 1)]
        gdiff = queries[:, None, :] - gathered
        d = np.einsum("qmd,qmd->qm", gdiff, gdiff, dtype=np.float32)
        np.nan_to_num(d, copy=False, nan=np.inf)
        d[cand >= n] = np.inf
        return lexicographic_topk(d, cand, k)

    def _score_host(self, train_x: np.ndarray, queries: np.ndarray,
                    k: int, sel: np.ndarray, counts: np.ndarray):
        n, q = train_x.shape[0], queries.shape[0]
        sizes = self.cell_sizes
        dists_out = np.empty((q, k), np.float32)
        idx_out = np.empty((q, k), np.int64)
        d_feat = max(train_x.shape[1], 1)
        m_global = int(counts.max()) if q else 0
        chunk = max(1, min(q or 1,
                           _CHUNK_ELEMS // max(m_global * d_feat, 1)))
        for s in range(0, q, chunk):
            e = min(q, s + chunk)
            # Pad slots carry candidate index n (sorts after every
            # real index, so a real +inf-distance candidate still
            # wins the tie) and distance +inf.
            cand = self._gather_candidates(sel[s:e], sizes, counts[s:e])
            gathered = train_x[np.minimum(cand, n - 1)]
            gdiff = queries[s:e][:, None, :] - gathered
            d = np.einsum("qmd,qmd->qm", gdiff, gdiff,
                          dtype=np.float32)
            np.nan_to_num(d, copy=False, nan=np.inf)
            d[cand == n] = np.inf
            dists_out[s:e], idx_out[s:e] = lexicographic_topk(
                d, cand, k)
        return dists_out, idx_out

    def _score_device(self, train_x: np.ndarray, queries: np.ndarray,
                      k: int, sel: np.ndarray, counts: np.ndarray,
                      tail=None, view=None, metric: str = "euclidean"):
        """The device gather+score path (``ops/segment_score.py``): one
        fused dispatch selects top-(k+margin) survivors by device
        distances, the host re-rank restores exact bit-identical
        values/order. ``tail``/``view`` fuse the mutable delta block
        into the same dispatch. Returns ``(dists, idx,
        padded_candidate_rows)``."""
        from knn_tpu.models.knn import candidate_padded_rows
        from knn_tpu.ops import segment_score

        q = queries.shape[0]
        perm_rows, perm_ids = self._device_operands(train_x)
        starts = self.cell_offsets[:-1][sel].astype(np.int32)
        lens = self.cell_sizes[sel].astype(np.int32)
        m_actual = int(counts.max()) if q else 0
        d_dev, cand = segment_score.segment_topk(
            perm_rows, perm_ids, queries, starts, lens, m_actual, k,
            tail=tail)
        waste = q * candidate_padded_rows(m_actual) - int(counts.sum())
        if tail is None:
            d, i = self._exact_rerank(train_x, queries, cand, k)
        else:
            from knn_tpu.mutable.device_tail import rerank_merged

            d, i = rerank_merged(view, train_x, queries, cand, k, metric)
        return d, i, max(waste, 0)

    def search(self, train_x: np.ndarray, queries: np.ndarray, k: int,
               nprobe: int, *, scorer: str = "auto",
               dead_per_cell: Optional[np.ndarray] = None):
        """Probed retrieval: ``(dists [Q,k] f32, indices [Q,k] int64,
        stats)`` under the shared (distance, index) tie order.

        Distances of the probed candidates are EXACT — computed with the
        oracle backend's einsum form on the same float32 operands (the
        device scorer selects survivors on device and re-ranks them
        through the same einsum, so both scorers return identical bits),
        which is what makes the full-probe path bit-identical to
        ``oracle_kneighbors`` and keeps the shadow scorer's
        distance-divergence check silent on this rung. Queries with NaN
        features follow the framework NaN → +inf policy.

        ``scorer``: ``"auto"`` (device once the candidate work crosses
        :data:`DEVICE_SCORER_MIN_ELEMS`, host below — overridable via
        ``KNN_TPU_IVF_SCORER``), ``"host"``, or ``"device"``.
        ``dead_per_cell``: per-cell live-tombstone counts
        (:meth:`dead_rows_per_cell`) — k-coverage widening then counts
        only LIVE rows toward coverage.
        """
        mode = _scorer_mode(scorer)
        train_x = np.asarray(train_x, np.float32)
        queries = np.asarray(queries, np.float32)
        n, q = train_x.shape[0], queries.shape[0]
        if n != self.num_rows:
            raise DataError(
                f"ivf index spans {self.num_rows} rows but the train set "
                f"has {n} — index and data are out of sync")
        c = self.num_cells
        k = min(int(k), n)
        requested = min(max(1, int(nprobe)), c)
        with obs.span("ivf.search", rows=q, nprobe=requested, k=k):
            sel, counts, nprobe, forced, ranking, dead_rows = \
                self._coverage(queries, k, requested, dead_per_cell)
            d_feat = max(train_x.shape[1], 1)
            m_global = int(counts.max()) if q else 0
            use_device = mode == "device" or (
                mode == "auto"
                and q * m_global * d_feat >= DEVICE_SCORER_MIN_ELEMS)
            padded_rows = 0
            if use_device:
                try:
                    dists_out, idx_out, padded_rows = self._score_device(
                        train_x, queries, k, sel, counts)
                except Exception:
                    if mode == "device":
                        raise  # forced: the caller wants the failure
                    use_device = False  # auto: the host path always works
            if not use_device:
                dists_out, idx_out = self._score_host(
                    train_x, queries, k, sel, counts)
        return dists_out, idx_out, IVFSearchStats(
            nprobe=nprobe, requested=requested, forced_widenings=forced,
            candidate_rows=int(counts.sum()) if q else 0,
            cells_probed=q * nprobe,
            scorer="device" if use_device else "host",
            ranking=ranking, dead_rows=dead_rows,
            padded_candidate_rows=padded_rows if use_device else 0,
        )

    def search_merged(self, train_x: np.ndarray, queries: np.ndarray,
                      k: int, nprobe: int, view, *, scorer: str = "auto",
                      dead_per_cell: Optional[np.ndarray] = None,
                      metric: str = "euclidean"):
        """Probed retrieval MERGED with a live mutable view — the fused
        half of the device hot path: when the view's delta block is
        device-resident and no base rows are tombstoned, the delta tail
        is scored beside the probed candidates in the SAME device
        dispatch and the one two-key sort covers base+delta
        (``ops/segment_score._segment_topk_delta_core``). Otherwise the
        host scorer + host merge answer (tombstoned-base views keep the
        host path because the host merge's per-row oracle widening has
        no fixed compiled shape — docs/INDEXES.md §On-device scoring).
        Returns ``(dists, idx, stats)`` in the view's positional id
        space."""
        from knn_tpu.mutable import state as mstate

        mode = _scorer_mode(scorer)
        train_x = np.asarray(train_x, np.float32)
        queries = np.asarray(queries, np.float32)
        q = queries.shape[0]
        # The merged answer can draw from base AND delta slots — clamp k
        # to the combined width (the PR-10 host-merge contract: the base
        # retrieval clamps itself to base rows, lexicographic_topk to
        # the concatenated columns).
        k_eff = min(int(k), self.num_rows + view.count)
        tail = getattr(view, "device", None)
        fuse = (tail is not None and view.tomb_base.size == 0
                and mode != "host")
        if fuse:
            with obs.span("ivf.search", rows=q, nprobe=nprobe, k=k_eff,
                          merged_delta=True):
                # Coverage is a BASE concern: probe for the base share
                # of k (what the host fallback's search would cover),
                # the delta columns ride along regardless.
                sel, counts, nprobe_used, forced, ranking, dead_rows = \
                    self._coverage(queries, min(k_eff, self.num_rows),
                                   min(max(1, int(nprobe)),
                                       self.num_cells), dead_per_cell)
                try:
                    d, i, padded = self._score_device(
                        train_x, queries, k_eff, sel, counts, tail=tail,
                        view=view, metric=metric)
                    return d, i, IVFSearchStats(
                        nprobe=nprobe_used,
                        requested=min(max(1, int(nprobe)),
                                      self.num_cells),
                        forced_widenings=forced,
                        candidate_rows=int(counts.sum()) if q else 0,
                        cells_probed=q * nprobe_used,
                        scorer="device", ranking=ranking,
                        dead_rows=dead_rows,
                        padded_candidate_rows=padded, merged_delta=True,
                    )
                except Exception:
                    if mode == "device":
                        raise
                    # auto: fall through to the host merge below.
        d, i, stats = self.search(
            train_x, queries, k_eff, nprobe, scorer=mode,
            dead_per_cell=dead_per_cell)

        def wide(wfeats, k_wide):
            wd, wi, _ = self.search(
                train_x, wfeats, k_wide, nprobe, scorer=mode,
                dead_per_cell=dead_per_cell)
            return wd, wi

        d, i = mstate.merge_candidates(view, queries, d, i, k_eff,
                                       metric, wide)
        return d, i, stats


class IVFServing:
    """The serving-side IVF rung: probe policy + instruments.

    Holds NO index — it reads the batch's own model snapshot
    (``model.ivf_``), so hot reloads swap the partition with the model
    atomically and a response can never mix one index's rows with
    another's centroids. Constructed only when ``serve --ivf-probes`` is
    given (the zero-cost-when-disabled contract:
    ``scripts/check_disabled_overhead.py`` pins that an exact-only boot
    builds none of this).
    """

    def __init__(self, base_probes: int, num_cells: int, *, slo=None,
                 recall_floor: float = 0.95, policy=None,
                 scorer: str = "auto"):
        if not 0.0 < recall_floor <= 1.0:
            raise ValueError(
                f"recall_floor must be in (0, 1], got {recall_floor}")
        from knn_tpu.index.probe_policy import ProbePolicy

        self.recall_floor = float(recall_floor)
        self.scorer = _scorer_mode(scorer)
        self.policy = policy if policy is not None else ProbePolicy(
            base_probes, num_cells, slo=slo)
        # Per-tombstone-set memo for the delete-aware per-cell dead
        # counts (views share their tomb arrays between mutations, so
        # identity is the cheap and correct key).
        self._dead_cache: Optional[tuple] = None

    def set_num_cells(self, num_cells: int) -> None:
        """Re-bound the policy after a hot reload swapped in an index
        with a different cell count."""
        self.policy.set_num_cells(num_cells)
        self._dead_cache = None

    def _dead_per_cell(self, index: IVFIndex, view):
        """Per-cell live-tombstone counts for the k-coverage widening
        (``IVFIndex.dead_rows_per_cell``), memoized on the view's shared
        tombstone array identity."""
        if view is None or view.tomb_base.size == 0:
            return None
        hit = self._dead_cache
        if (hit is not None and hit[0] is view.tomb_base
                and hit[1] is index):
            return hit[2]
        counts = index.dead_rows_per_cell(view.tomb_base)
        self._dead_cache = (view.tomb_base, index, counts)
        return counts

    def kneighbors(self, model, feats: np.ndarray,
                   k: Optional[int] = None, view=None):
        """One ivf-rung dispatch for the micro-batcher: policy-chosen
        ``nprobe``, probed search, instruments. Returns ``(dists, idx)``
        like every other rung closure. ``k`` overrides ``model.k`` for
        the mutable tier's tombstone k-coverage widening
        (``knn_tpu/mutable/state.py``) — the probed search takes k as a
        plain host argument, so widening recompiles nothing and the
        delta rows are searched exhaustively beside the probed cells by
        the merge layer. ``view`` — a live (non-empty)
        :class:`~knn_tpu.mutable.state.MutableView`: the answer is then
        MERGED with the delta tier + tombstones, fused into the device
        dispatch when the view carries a device-resident tail
        (``IVFIndex.search_merged``), and the delete-aware per-cell dead
        counts feed the coverage widening either way."""
        index = getattr(model, IVF_ATTR, None)
        if index is None:  # reload validation forbids this; stay typed
            raise DataError("serving model has no ivf partition")
        train = model.train_
        kq = model.k if k is None else k
        dead = self._dead_per_cell(index, view)
        if view is not None and not view.empty:
            dists, idx, stats = index.search_merged(
                train.features, feats, kq, self.policy.current(), view,
                scorer=self.scorer, dead_per_cell=dead,
                metric=model.metric)
        else:
            dists, idx, stats = index.search(
                train.features, feats, kq, self.policy.current(),
                scorer=self.scorer, dead_per_cell=dead)
        self._record(index, feats, stats)
        return dists, idx

    def _record(self, index: IVFIndex, feats: np.ndarray,
                stats: IVFSearchStats) -> None:
        obs.gauge_set(
            "knn_ivf_probes", stats.nprobe,
            help="cells probed per query by the last ivf-rung dispatch "
                 "(the probe policy's live operating point)",
        )
        obs.gauge_set(
            "knn_ivf_cell_imbalance", index.imbalance(),
            help="largest cell size over the balanced size (1.0 = "
                 "uniform partition)",
        )
        obs.counter_add(
            "knn_ivf_queries_total", feats.shape[0],
            help="query rows answered by the ivf rung",
        )
        obs.counter_add(
            "knn_ivf_candidate_rows_total", stats.candidate_rows,
            help="train rows gathered and exactly scored by ivf probes "
                 "(the sub-linear win: compare with train_rows x queries)",
        )
        obs.counter_add(
            "knn_ivf_scorer_dispatch_total", 1,
            help="ivf-rung dispatches by the scorer that answered "
                 "(device = the fused gather+score kernel, host = the "
                 "numpy scan) and centroid ranking mode",
            scorer=stats.scorer, ranking=stats.ranking,
        )
        if stats.forced_widenings:
            obs.counter_add(
                "knn_ivf_forced_widenings_total", stats.forced_widenings,
                help="probe doublings forced because the probed cells "
                     "held fewer than k LIVE candidates (the never-"
                     "return-short guarantee, tombstone-aware)",
            )
        if stats.dead_rows:
            obs.counter_add(
                "knn_ivf_dead_candidate_rows_total", stats.dead_rows,
                help="tombstoned rows that occupied probed cells "
                     "(scanned but never returnable until compaction "
                     "folds them — probe-policy-visible dead work)",
            )
        if stats.padded_candidate_rows:
            obs.counter_add(
                "knn_ivf_padded_candidate_rows_total",
                stats.padded_candidate_rows,
                help="compiled-shape candidate rows beyond the gathered "
                     "candidates (the device scorer's bucket-ladder "
                     "pad — the candidate-axis twin of "
                     "knn_cost_padded_rows_total)",
            )

    def export(self, model=None) -> dict:
        """The ``/healthz`` ivf block."""
        index = getattr(model, IVF_ATTR, None) if model is not None else None
        doc = {
            "recall_floor": self.recall_floor,
            "scorer": self.scorer,
            "approx_rank_min_cells": _approx_rank_threshold(),
            **self.policy.export(),
        }
        if index is not None:
            doc.update(
                num_cells=index.num_cells,
                empty_cells=int((index.cell_sizes == 0).sum()),
                cell_imbalance=index.imbalance(),
            )
        return doc
