"""Batched Lloyd's k-means with k-means++ seeding — the IVF cell builder.

Build-time only (``knn_tpu save-index --ivf-cells N``): the serving
process never runs this. Design constraints, in order:

1. **Deterministic.** Seeding uses a ``np.random.default_rng(seed)``
   stream and every tie (assignment, empty-cell reseed) breaks by lowest
   index, so the same (data, num_cells, seed) always yields the same
   partition — on any backend. The artifact records the seed.
2. **Batched.** The assignment step is the O(N·C·D) cost; it runs as a
   jitted JAX matmul-form distance + argmin over row batches
   (``batch_rows`` bounds device memory), so a 10M-row build streams
   instead of materializing [N, C].
3. **Empty cells are handled, not hidden.** An empty cell is reseeded to
   the point currently FARTHEST from its centroid (the standard repair,
   deterministic); when the data has fewer distinct rows than cells the
   repair saturates and the residual empty cells are returned as-is —
   the IVF search layer supports them (they contribute no candidates and
   the k-coverage widening steps past them).

The partition is an *acceleration structure*, not an answer: any cell
assignment yields correct IVF results (probed candidates are re-scored
with exact distances under the shared tie order), so k-means quality
moves recall-per-probe, never correctness.
"""

from __future__ import annotations

import numpy as np

from knn_tpu import obs

#: Assignment-step row batch: bounds the [batch, C] device block.
DEFAULT_BATCH_ROWS = 65536


def _assign_batched(x: np.ndarray, centroids: np.ndarray,
                    batch_rows: int):
    """Nearest-centroid assignment for every row, batched through a jitted
    matmul-form distance. Returns ``(assign [N] int32, min_d2 [N] f32)``
    — ``min_d2`` feeds inertia and the farthest-point reseed. Ties break
    to the lowest cell id (argmin's first-minimum rule)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(xb, cents):
        # ||x-c||^2 = ||x||^2 - 2 x.c + ||c||^2 (the fast form: cell
        # RANKING only — candidates are re-scored exactly at query time).
        x2 = jnp.sum(xb * xb, axis=1, keepdims=True)
        c2 = jnp.sum(cents * cents, axis=1)[None, :]
        d2 = x2 - 2.0 * (xb @ cents.T) + c2
        a = jnp.argmin(d2, axis=1)
        return a.astype(jnp.int32), jnp.take_along_axis(
            d2, a[:, None], axis=1)[:, 0]

    n = x.shape[0]
    assign = np.empty(n, np.int32)
    min_d2 = np.empty(n, np.float32)
    cents = jnp.asarray(centroids)
    for s in range(0, n, batch_rows):
        e = min(n, s + batch_rows)
        a, d = step(jnp.asarray(x[s:e]), cents)
        assign[s:e] = np.asarray(a)
        min_d2[s:e] = np.asarray(d, np.float32)
    np.maximum(min_d2, 0.0, out=min_d2)  # matmul-form negatives clamp to 0
    return assign, min_d2


def _plus_plus_seeds(x: np.ndarray, num_cells: int,
                     rng: np.random.Generator) -> np.ndarray:
    """k-means++ (Arthur & Vassilvitskii 2007): the first center uniform,
    each next drawn with probability proportional to its squared distance
    to the nearest already-chosen center. When the residual D² mass hits
    zero (fewer distinct rows than cells), the remaining seeds fall back
    to uniform draws — duplicates are fine, the resulting cells simply
    start (and may stay) empty."""
    n = x.shape[0]
    seeds = np.empty(num_cells, np.int64)
    seeds[0] = rng.integers(n)
    d2 = ((x - x[seeds[0]]) ** 2).sum(axis=1).astype(np.float64)
    for i in range(1, num_cells):
        total = d2.sum()
        if total > 0:
            seeds[i] = rng.choice(n, p=d2 / total)
        else:
            seeds[i] = rng.integers(n)
        d2 = np.minimum(d2, ((x - x[seeds[i]]) ** 2).sum(axis=1))
    return seeds


def assign_cells(x: np.ndarray, centroids: np.ndarray,
                 batch_rows: int = DEFAULT_BATCH_ROWS) -> np.ndarray:
    """ONE deterministic nearest-centroid assignment step over existing
    centroids — the incremental-compaction primitive
    (``IVFIndex.assign_to``): folded rows get cells without re-running
    Lloyd's. Ties break to the lowest cell id exactly like the builder's
    rounds (argmin first-minimum)."""
    x = np.ascontiguousarray(x, np.float32)
    centroids = np.ascontiguousarray(centroids, np.float32)
    if x.ndim != 2 or centroids.ndim != 2 \
            or x.shape[1] != centroids.shape[1]:
        raise ValueError(
            f"assign_cells wants [N, D] rows and [C, D] centroids, got "
            f"{x.shape} and {centroids.shape}")
    assign, _ = _assign_batched(x, centroids, batch_rows)
    return assign


def kmeans(x: np.ndarray, num_cells: int, *, seed: int = 0,
           iters: int = 25, tol: float = 1e-4,
           batch_rows: int = DEFAULT_BATCH_ROWS):
    """Partition ``x [N, D]`` into ``num_cells`` cells.

    Returns ``(centroids [C, D] float32, assign [N] int32, info)`` where
    ``info`` carries ``iterations``, ``inertia`` (mean squared distance
    to the assigned centroid), and ``empty_cells``. Converges when the
    max squared centroid shift falls below ``tol`` times the mean
    per-feature data variance, or after ``iters`` Lloyd rounds.
    """
    x = np.ascontiguousarray(x, np.float32)
    n = x.shape[0]
    if x.ndim != 2 or n < 1:
        raise ValueError(f"x must be a non-empty [N, D] matrix, got shape "
                         f"{x.shape}")
    if not 1 <= num_cells <= n:
        raise ValueError(
            f"num_cells must be in [1, N={n}], got {num_cells}")
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    rng = np.random.default_rng(seed)
    with obs.span("ivf.kmeans", rows=n, cells=num_cells):
        centroids = x[_plus_plus_seeds(x, num_cells, rng)].astype(np.float64)
        # One float64 view for every Lloyd round's mean update (a float32
        # running sum over millions of rows loses the low bits that decide
        # convergence) — converted ONCE, not per round.
        x64 = x.astype(np.float64)
        scale = float(np.var(x64, axis=0).mean()) or 1.0
        assign = min_d2 = None
        rounds = 0
        for rounds in range(1, iters + 1):
            assign, min_d2 = _assign_batched(
                x, centroids.astype(np.float32), batch_rows)
            counts = np.bincount(assign, minlength=num_cells)
            # Per-feature bincount accumulation: same sequential row-order
            # float64 adds as a scatter, without np.add.at's unbuffered
            # fancy-index path (~50x slower at the 10M-row scale this
            # builder targets).
            sums = np.empty((num_cells, x.shape[1]), np.float64)
            for j in range(x.shape[1]):
                sums[:, j] = np.bincount(assign, weights=x64[:, j],
                                         minlength=num_cells)
            nonempty = counts > 0
            new = centroids.copy()
            new[nonempty] = sums[nonempty] / counts[nonempty, None]
            # Reseed empty cells to the points farthest from their
            # centroids — deterministic (argsort is stable, distinct
            # picks by taking the E worst rows).
            empty = np.flatnonzero(~nonempty)
            if empty.size:
                worst = np.argsort(-min_d2, kind="stable")[:empty.size]
                new[empty] = x[worst].astype(np.float64)
            shift = float(((new - centroids) ** 2).sum(axis=1).max())
            centroids = new
            if shift <= tol * scale and not empty.size:
                break
        # Final assignment against the converged centroids.
        assign, min_d2 = _assign_batched(
            x, centroids.astype(np.float32), batch_rows)
        counts = np.bincount(assign, minlength=num_cells)
    info = {
        "iterations": rounds,
        "inertia": round(float(min_d2.mean()), 6),
        "empty_cells": int((counts == 0).sum()),
    }
    return centroids.astype(np.float32), assign, info
