"""Burn-aware probe policy: trade probes for latency under the quality SLO.

The ivf rung's one knob is ``nprobe`` — more probes mean more exact
distance work and higher recall. A static setting is wrong in both
directions: too low silently burns the quality error budget, too high
pays full-scan latency for recall nobody measures. This controller closes
the loop the ROADMAP asked for ("let the serving ladder trade probes for
latency under SLO burn"): it reads the **quality SLI burn rate**
(:meth:`~knn_tpu.obs.slo.SLOTracker.burn_rates`, fed by the shadow scorer
at its sampling cadence) and moves ``nprobe`` with the same hysteresis
shape as :class:`~knn_tpu.resilience.breaker.CircuitBreaker`:

- burn over ``widen_burn`` on the SHORTEST window (the fast signal) →
  DOUBLE ``nprobe`` toward ``num_cells`` (exact);
- burn under ``narrow_burn`` → HALVE back toward the configured base;
- every move is followed by a ``cooldown_ms`` freeze so the lagging
  shadow signal (samples score seconds after they were served) cannot
  drive oscillation, and the burn windows get time to reflect the move.

The signal only exists while shadow scoring runs (``--shadow-rate`` > 0):
with no scored samples the quality burn reads 0.0, so the policy rests at
(or decays back to) the base — a serve without shadow scoring is simply a
static-``nprobe`` serve, documented in docs/INDEXES.md.

Env-tunable like the breaker (read at construction):

=================================  ======  ============================
``KNN_TPU_PROBE_WIDEN_BURN``       1.0     burn that triggers widening
``KNN_TPU_PROBE_NARROW_BURN``      0.25    burn that allows narrowing
``KNN_TPU_PROBE_COOLDOWN_MS``      2000    freeze after any move
``KNN_TPU_PROBE_EVAL_MS``          250     min interval between burn reads
=================================  ======  ============================

The decision path the batcher pays is one monotonic read + a cached value
between evaluations; the O(window) burn aggregation runs at most once per
``eval_ms``.
"""

from __future__ import annotations

import os
import threading
import time

from knn_tpu import obs

_WIDEN_ENV = "KNN_TPU_PROBE_WIDEN_BURN"
_NARROW_ENV = "KNN_TPU_PROBE_NARROW_BURN"
_COOLDOWN_ENV = "KNN_TPU_PROBE_COOLDOWN_MS"
_EVAL_ENV = "KNN_TPU_PROBE_EVAL_MS"


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    raw = os.environ.get(name, "")
    try:
        return max(lo, float(raw)) if raw else default
    except ValueError:
        return default


class ProbePolicy:
    """Hysteretic ``nprobe`` controller over the quality burn signal.

    ``base``      — the operator-configured floor (``--ivf-probes``);
    ``num_cells`` — the exact-retrieval ceiling;
    ``slo``       — an :class:`~knn_tpu.obs.slo.SLOTracker` (or anything
                    with ``burn_rates()`` / ``windows_s``); None pins the
                    policy at ``base`` forever (embedded static use).
    """

    def __init__(self, base: int, num_cells: int, *, slo=None,
                 widen_burn: "float | None" = None,
                 narrow_burn: "float | None" = None,
                 cooldown_ms: "float | None" = None,
                 eval_ms: "float | None" = None):
        if num_cells < 1:
            raise ValueError(f"num_cells must be >= 1, got {num_cells}")
        if not 1 <= base <= num_cells:
            raise ValueError(
                f"base probes must be in [1, num_cells={num_cells}], "
                f"got {base}")
        self._configured_base = int(base)  # survives reload re-bounding
        self.base = int(base)
        self.num_cells = int(num_cells)
        self.slo = slo
        self.widen_burn = (widen_burn if widen_burn is not None
                           else _env_float(_WIDEN_ENV, 1.0))
        self.narrow_burn = (narrow_burn if narrow_burn is not None
                            else _env_float(_NARROW_ENV, 0.25))
        if self.narrow_burn > self.widen_burn:
            raise ValueError(
                f"narrow_burn ({self.narrow_burn}) must be <= widen_burn "
                f"({self.widen_burn}) or the policy would thrash")
        self.cooldown_ms = (cooldown_ms if cooldown_ms is not None
                            else _env_float(_COOLDOWN_ENV, 2000.0))
        self.eval_ms = (eval_ms if eval_ms is not None
                        else _env_float(_EVAL_ENV, 250.0))
        self._lock = threading.Lock()
        self._current = self.base
        self._last_eval_ns = 0
        self._last_move_ns = 0
        self._brownout = False
        self.moves = {"widen": 0, "narrow": 0}
        self.last_burn = 0.0

    # -- the decision path (batcher worker) --------------------------------

    def current(self) -> int:
        """The ``nprobe`` to dispatch with right now. Re-evaluates the
        burn signal at most every ``eval_ms``; otherwise returns the
        cached operating point."""
        if self.slo is None:
            return self._current
        now = time.monotonic_ns()
        with self._lock:
            if self._brownout:
                # The control plane's brownout holds the operating point
                # at base: widening spends exactly the dispatch cost the
                # brownout exists to reclaim. Quality burn accrued while
                # held is the brownout's documented trade; the policy
                # resumes control the tick the brownout reverts.
                return self._current
            if (now - self._last_eval_ns) < self.eval_ms * 1e6:
                return self._current
            self._last_eval_ns = now
            burn = self._quality_burn()
            self.last_burn = burn
            in_cooldown = (now - self._last_move_ns) < self.cooldown_ms * 1e6
            if in_cooldown:
                return self._current
            if burn > self.widen_burn and self._current < self.num_cells:
                self._move("widen", min(self.num_cells, self._current * 2),
                           burn, now)
            elif burn < self.narrow_burn and self._current > self.base:
                self._move("narrow", max(self.base, self._current // 2),
                           burn, now)
            return self._current

    def _quality_burn(self) -> float:
        """The shortest window's quality burn — the fast signal, same
        choice the breaker makes with its sliding window."""
        try:
            burns = self.slo.burn_rates().get("quality", {})
        except Exception:  # noqa: BLE001 — a broken signal must not
            return 0.0     # take serving down; the policy just holds
        if not burns:
            return 0.0
        from knn_tpu.obs.slo import window_label

        label = window_label(min(self.slo.windows_s))
        return float(burns.get(label, next(iter(burns.values()))))

    def _move(self, direction: str, to: int, burn: float, now_ns: int):
        frm, self._current = self._current, to
        self._last_move_ns = now_ns
        self.moves[direction] += 1
        obs.counter_add(
            "knn_ivf_probe_moves_total",
            help="probe-policy nprobe moves (quality burn over target "
                 "widens toward exact; healthy budget narrows to base)",
            direction=direction,
        )
        obs.gauge_set(
            "knn_ivf_probes", self._current,
            help="cells probed per query by the last ivf-rung dispatch "
                 "(the probe policy's live operating point)",
        )
        # The marker-span idiom the breaker uses: traces show exactly
        # when the quality loop moved the operating point.
        with obs.span("ivf.probe_policy", direction=direction,
                      from_probes=frm, to_probes=to,
                      burn=round(burn, 3)):
            pass

    # -- lifecycle / read side ---------------------------------------------

    def set_brownout(self, active: bool) -> None:
        """Engage/release the control plane's brownout clamp
        (:mod:`knn_tpu.control.brownout`): engaging snaps the operating
        point to ``base`` (giving back every widened probe's dispatch
        cost) and freezes the policy; releasing unfreezes it — the next
        ``current()`` re-reads the burn signal and re-widens if the
        quality budget still demands it (no saved state to restore: the
        burn signal IS the state)."""
        with self._lock:
            active = bool(active)
            if active == self._brownout:
                return
            self._brownout = active
            if active and self._current != self.base:
                self._move("narrow", self.base, self.last_burn,
                           time.monotonic_ns())

    def set_num_cells(self, num_cells: int) -> None:
        """Re-bound after a hot reload (a new index may have a different
        cell count); the operating point and base clamp into range. The
        clamp re-derives from the CONFIGURED base each time, so reloading
        a small index and then the original back restores the operator's
        designed operating point (never a one-way ratchet)."""
        if num_cells < 1:
            raise ValueError(f"num_cells must be >= 1, got {num_cells}")
        with self._lock:
            self.num_cells = int(num_cells)
            self.base = min(self._configured_base, self.num_cells)
            self._current = min(max(self._current, self.base),
                                self.num_cells)

    def export(self) -> dict:
        with self._lock:
            return {
                "nprobe": self._current,
                "base_probes": self.base,
                "max_probes": self.num_cells,
                "moves": dict(self.moves),
                "last_quality_burn": round(self.last_burn, 4),
                "brownout": self._brownout,
                "widen_burn": self.widen_burn,
                "narrow_burn": self.narrow_burn,
                "cooldown_ms": self.cooldown_ms,
            }
