"""Approximate index families (docs/INDEXES.md).

Everything before this package scans every training row per query — even
the hardware approx-top-k rung is linear in index size. ``knn_tpu/index/``
is the sub-linear answer: partition the train set at build time
(``save-index --ivf-cells N``), probe only the nearest cells at query
time, and hold the quality line with the shadow-scored recall SLI
(``obs/quality.py``) plus a burn-aware probe policy.

- :mod:`knn_tpu.index.kmeans`       — batched Lloyd's with k-means++
  seeding (JAX assignment step, seeded, runs on any backend);
- :mod:`knn_tpu.index.ivf`          — the inverted-file index: centroids
  + a cell-sorted row permutation persisted in the artifact (format 3),
  query-time probe of the nearest ``nprobe`` cells with exact distances
  and the shared (distance, index) tie order over the candidates;
- :mod:`knn_tpu.index.probe_policy` — the quality-burn-driven ``nprobe``
  controller (hysteresis templated on ``resilience/breaker.py``).
"""

from knn_tpu.index.ivf import IVFIndex, IVFServing  # noqa: F401
from knn_tpu.index.kmeans import kmeans  # noqa: F401
from knn_tpu.index.probe_policy import ProbePolicy  # noqa: F401
