"""Deterministic, seeded fault injection.

Chaos testing for a numerical stack has a bootstrapping problem: the
interesting failures (device loss, OOM, dead workers) need hardware to
produce, but the recovery logic must be testable in tier-1 on CPU. This
module solves it with *named fault points* — markers compiled into the
production call sites — that a *fault plan* arms from a test or from the
environment. With no plan armed, :func:`fault_point` is one module-global
``None`` check (measured noise on the medium preset), so production code
carries the markers for free.

Fault points (the registry below is closed — a plan naming an unknown
point is an error, so typos fail loudly):

==================  =========================================================
``arff.parse``      dataset load/parse (``knn_tpu/data/arff.py``)
``device.put``      host->device transfer (backends, model retrieval core)
``backend.compile`` kernel trace/compile/first dispatch
``collective.step`` a sharded multi-device dispatch (query/train/ring paths)
``multihost.init``  ``jax.distributed`` cluster init (``parallel/multihost``)
``native.load``     native C++ library load/call (arff + runtime kernels)
``serve.dispatch``  the micro-batcher worker's fast-rung device dispatch
                    (``knn_tpu/serve/batcher.py`` — the serving chaos-soak
                    harness injects here)
==================  =========================================================

Fault-plan syntax (``KNN_TPU_FAULTS`` env var or :func:`inject`):

    point=mode[:kind][,point=mode[:kind]...]

``mode``: ``once`` (fail the first activation, then succeed), ``always``,
an integer ``N`` (fail the first N activations), or ``pF`` (e.g. ``p0.3``
— fail each activation with probability F, drawn from a ``random.Random``
seeded by ``KNN_TPU_FAULT_SEED``/the ``seed`` argument, so a given plan +
seed replays the identical fault sequence).

``kind`` overrides the raised error class: ``oom`` (DeviceError with
``oom=True``), ``data``, ``compile``, ``device``, ``collective``,
``worker``, ``io`` (OSError — exercises the raw-exception classification
path). Default is the point's natural class.

Example::

    KNN_TPU_FAULTS="device.put=once" ./tpu train.arff test.arff 5
    with faults.inject("collective.step=always"): ...

Every triggered fault increments ``knn_fault_injected_total{point,kind}``
through :mod:`knn_tpu.obs` (when enabled) and is marked with
``fault_point=<name>`` on the raised error, so tests can assert the
failure they caused is the failure they saw.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
from typing import Dict, Optional

from knn_tpu.resilience.errors import (
    CollectiveError,
    CompileError,
    DataError,
    DeviceError,
    WorkerLostError,
)

FAULT_ENV = "KNN_TPU_FAULTS"
SEED_ENV = "KNN_TPU_FAULT_SEED"

#: point name -> default error kind
FAULT_POINTS: Dict[str, str] = {
    "arff.parse": "data",
    "device.put": "device",
    "backend.compile": "compile",
    "collective.step": "collective",
    "multihost.init": "worker",
    "native.load": "io",
    "serve.dispatch": "device",
    # Between a compaction's warmup and its swap (knn_tpu/mutable/
    # compact.py): the mutable soak's rollback leg proves a failed
    # compaction leaves the old generation serving with zero
    # acknowledged writes lost.
    "mutable.compact": "device",
    # One router->replica HTTP forward (knn_tpu/fleet/router.py): a
    # fired fault stands in for the wire failing mid-request — reads
    # must retry on a DIFFERENT replica, writes must refuse typed
    # (indeterminate outcomes are never blindly re-sent).
    "fleet.forward": "io",
    # One primary->follower WAL shipment (knn_tpu/fleet/replica.py):
    # the shipper must back off and re-ship without losing its cursor —
    # follower lag grows, then drains, and no record is skipped.
    "fleet.wal_ship": "io",
    # The snapshot bootstrap install path (knn_tpu/fleet/bootstrap.py):
    # fires between download-verify and the atomic CURRENT.json commit,
    # standing in for a torn chunk / full disk mid-install — the
    # follower's prior state must keep serving untouched.
    "fleet.snapshot_ship": "io",
}

_KINDS = ("data", "compile", "device", "collective", "worker", "io", "oom")


def _make_error(point: str, kind: str):
    msg = f"injected fault at {point} ({kind})"
    if kind == "data":
        return DataError(msg, fault_point=point)
    if kind == "compile":
        return CompileError(msg, fault_point=point)
    if kind == "device":
        return DeviceError(msg, transient=True, fault_point=point)
    if kind == "oom":
        return DeviceError(msg, oom=True, fault_point=point)
    if kind == "collective":
        return CollectiveError(msg, fault_point=point)
    if kind == "worker":
        return WorkerLostError(msg, reason="injected", fault_point=point)
    if kind == "io":
        # Raw OSError on purpose: exercises classify_exception / the
        # pre-existing ``except OSError`` degradation paths.
        return OSError(msg)
    raise ValueError(f"unknown fault kind {kind!r}")


class _Rule:
    """One armed fault point: mode state + error kind. ``fire()`` is
    called under the plan lock, so the countdown is race-free."""

    __slots__ = ("point", "kind", "remaining", "prob", "fired", "activations")

    def __init__(self, point: str, mode: str, kind: Optional[str]):
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: "
                f"{sorted(FAULT_POINTS)}"
            )
        kind = kind or FAULT_POINTS[point]
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; known: {sorted(_KINDS)}"
            )
        self.point = point
        self.kind = kind
        self.remaining: Optional[int] = None  # None = unbounded (always/p)
        self.prob: Optional[float] = None
        self.fired = 0
        self.activations = 0
        if mode == "once":
            self.remaining = 1
        elif mode == "always":
            pass
        elif mode.startswith("p"):
            try:
                self.prob = float(mode[1:])
            except ValueError:
                raise ValueError(f"bad probabilistic mode {mode!r}") from None
            if not (0.0 <= self.prob <= 1.0):
                raise ValueError(f"fault probability {self.prob} not in [0, 1]")
        else:
            try:
                self.remaining = int(mode)
            except ValueError:
                raise ValueError(
                    f"bad fault mode {mode!r}; want once|always|<int>|p<float>"
                ) from None
            if self.remaining < 0:
                raise ValueError(f"fault count must be >= 0, got {self.remaining}")

    def fire(self, rng: random.Random) -> bool:
        self.activations += 1
        if self.prob is not None:
            hit = rng.random() < self.prob
        elif self.remaining is None:
            hit = True
        elif self.remaining > 0:
            self.remaining -= 1
            hit = True
        else:
            hit = False
        if hit:
            self.fired += 1
        return hit


class FaultPlan:
    """A parsed fault plan. Construct from a spec string; arm with
    :func:`install` / :func:`inject` (or the env var at import)."""

    def __init__(self, spec: str, seed: Optional[int] = None):
        self.spec = spec
        self.from_env = False  # set by install_from_env; gates auto-disarm
        if seed is None:
            seed = int(os.environ.get(SEED_ENV, "0") or "0")
        self.seed = seed
        self._rng = random.Random(seed)
        self._rules: Dict[str, _Rule] = {}
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"bad fault rule {part!r}; want point=mode[:kind]"
                )
            point, _, rhs = part.partition("=")
            mode, _, kind = rhs.partition(":")
            self._rules[point.strip()] = _Rule(
                point.strip(), mode.strip(), kind.strip() or None
            )

    def check(self, point: str):
        """Return the error to raise at ``point``, or None. Mutates rule
        state; callers hold the plan lock."""
        rule = self._rules.get(point)
        if rule is None or not rule.fire(self._rng):
            return None
        return rule.kind, _make_error(point, rule.kind)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """{point: {fired, activations}} — for tests and post-run reports."""
        return {
            p: {"fired": r.fired, "activations": r.activations}
            for p, r in self._rules.items()
        }


_lock = threading.Lock()
_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def install(plan: Optional[FaultPlan]) -> None:
    """Arm ``plan`` globally (None disarms)."""
    global _ACTIVE
    with _lock:
        _ACTIVE = plan


def install_from_env(strict: bool = True) -> Optional[FaultPlan]:
    """(Re-)read ``KNN_TPU_FAULTS`` and arm the described plan. Called at
    import and again by the CLI entry, so env-driven chaos runs work both
    as subprocesses and in-process.

    When the var is unset/empty, only a plan that *itself came from the
    env* is disarmed — a plan armed programmatically via :func:`inject` /
    :func:`install` stays, so ``cli.run()`` inside an ``inject`` block
    still sees the context-managed faults.

    ``strict=False`` downgrades a malformed spec to a ``RuntimeWarning``
    (and disarms): at import time a typo'd env var must not make the whole
    library unimportable. Strict callers (the CLI) turn the ValueError
    into their one-line usage error instead.
    """
    global _ACTIVE
    spec = os.environ.get(FAULT_ENV, "")
    if not spec:
        with _lock:
            if _ACTIVE is not None and _ACTIVE.from_env:
                _ACTIVE = None
        return _ACTIVE
    try:
        plan = FaultPlan(spec)
    except ValueError:
        if strict:
            raise
        import warnings

        warnings.warn(
            f"ignoring malformed {FAULT_ENV}={spec!r} (fault injection "
            f"disarmed)", RuntimeWarning, stacklevel=2,
        )
        install(None)
        return None
    plan.from_env = True
    install(plan)
    return plan


@contextlib.contextmanager
def inject(spec: str, seed: Optional[int] = None):
    """Context manager arming a fault plan for the enclosed block::

        with faults.inject("device.put=once"):
            model.predict(test)  # first transfer fails, retry recovers

    Yields the :class:`FaultPlan` (read ``plan.stats()`` afterwards to
    assert the fault actually fired). Restores the previously armed plan
    on exit."""
    plan = FaultPlan(spec, seed=seed)
    with _lock:
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = plan
    try:
        yield plan
    finally:
        with _lock:
            _ACTIVE = prev


def fault_point(name: str) -> None:
    """Production-side marker: raise the armed fault for ``name``, if any.

    The disarmed path is one global ``None`` check. Unknown names raise
    even when disarmed-at-call-time plans exist — but only under an armed
    plan (checking the registry unconditionally would put a dict lookup on
    the hot path); tests cover every marker, so typos surface in tier-1.
    """
    plan = _ACTIVE
    if plan is None:
        return
    with _lock:
        if name not in FAULT_POINTS:
            raise ValueError(f"fault_point({name!r}) is not a registered point")
        hit = plan.check(name)
    if hit is None:
        return
    kind, err = hit
    from knn_tpu import obs

    obs.counter_add(
        "knn_fault_injected_total",
        help="faults triggered by the injection harness",
        point=name, kind=kind,
    )
    raise err


# Arm from the environment at import: `KNN_TPU_FAULTS=... ./tpu ...` works
# with no code cooperation beyond the markers. Non-strict: a typo'd env
# var warns and disarms rather than making `import knn_tpu` raise.
install_from_env(strict=False)
