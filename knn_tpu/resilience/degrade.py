"""Graceful backend degradation: the ladder.

The reference's MPI backend dies wholesale when any rank fails; a serving
stack must instead answer every query it can with the best backend still
standing. Each backend degrades along a fixed ladder toward the rung that
cannot fail for device reasons (the NumPy oracle):

    tpu-sharded / tpu-train-sharded / tpu-ring       (sharded → single-device)
        → tpu → tpu-pallas → native → oracle
    native-mt → native → oracle

Because every rung implements the same reference-exact contract
(SURVEY.md §3.5), degradation changes *where* the answer is computed, not
*what* it is — predictions are bit-identical down the ladder (pinned by
the chaos suite).

Failure handling per rung:

- transient faults are retried in place (:mod:`knn_tpu.resilience.retry`,
  inside the backend call sites);
- ``DeviceError(oom=True)`` on a rung that streams queries (``tpu``)
  halves ``query_batch`` and re-executes the same rung — degrading batch
  size before backend;
- any other typed failure (CompileError / DeviceError / CollectiveError)
  moves down the ladder, warning on stderr and counting
  ``knn_fallback_total{from_backend,to}``;
- a rung that rejects the *options* (e.g. ``--metric cosine`` on the
  native kernel) is skipped the same way — but only when it is a
  fallback rung; the user's explicitly chosen backend still reports its
  own option errors verbatim.

``no_fallback=True`` (the CLI's ``--no-fallback``) disables ladder moves
AND batch-halving: the first typed failure propagates, so operators who
would rather page than degrade get exactly that.
"""

from __future__ import annotations

import sys
from typing import Callable, Dict, Optional, Tuple

from knn_tpu import obs
from knn_tpu.resilience.errors import DataError, DeviceError, ResilienceError

#: The SERVING ladder's canonical rung order (``serve/batcher.py``
#: walks it ivf → fast → xla → oracle; "ivf" exists only when the served
#: artifact carries an IVF partition AND ``serve --ivf-probes`` enabled
#: approximate serving, and "xla" is skipped when it IS the fast engine).
#: The exact rungs below ivf are the truth anchor: a typed failure on the
#: ivf rung degrades to bit-exact retrieval, so approximation can only
#: ever be traded away, never silently substituted. Shared here so every
#: layer that attributes work to a rung — the batcher's
#: ``knn_serve_fallback_total`` labels, the shadow scorer's
#: ``knn_quality_recall{rung}`` / ``knn_quality_divergence_total{rung,...}``
#: (obs/quality.py), and ``/debug/quality``'s fast-to-degraded row order —
#: agrees on one vocabulary; a rung label outside this tuple is an
#: instrumentation bug.
SERVING_RUNGS: Tuple[str, ...] = ("ivf", "fast", "xla", "oracle")

#: The OVERLOAD degradation order (docs/RESILIENCE.md §Degradation
#: order) — the contract the control plane (knn_tpu/control/) enforces
#: when a replica is past its knee, strictly in this sequence:
#:
#: 1. ``scale``              — the fleet grows (router autoscaler boots
#:                             a replica through snapshot bootstrap)
#:                             before any single replica degrades;
#: 2. ``shed_low_priority``  — the lowest-priority request classes 429
#:                             (typed ShedByPolicy, Retry-After from
#:                             headroom) while protected classes admit;
#: 3. ``brownout_quality``   — reversible quality/cost knobs walk down
#:                             (sampling rates, nprobe to base, deadline
#:                             tightening), audited and reverted;
#: 4. ``availability``       — the queue-full OverloadError backstop:
#:                             the LAST resort, and the only stage that
#:                             spends protected classes' error budget.
#:
#: Shared as data so the controllers, their tests, and the overload soak
#: assert the same sequence instead of each encoding its own.
DEGRADATION_ORDER: Tuple[str, ...] = (
    "scale", "shed_low_priority", "brownout_quality", "availability")

#: backend -> fallback rungs, most-capable first.
LADDER: Dict[str, Tuple[str, ...]] = {
    "tpu-sharded": ("tpu", "tpu-pallas", "native", "oracle"),
    "tpu-train-sharded": ("tpu", "tpu-pallas", "native", "oracle"),
    "tpu-ring": ("tpu", "tpu-pallas", "native", "oracle"),
    "tpu": ("tpu-pallas", "native", "oracle"),
    "tpu-pallas": ("native", "oracle"),
    "native-mt": ("native", "oracle"),
    "native": ("oracle",),
    "oracle": (),
}

#: options meaningful only to specific rungs — stripped when degrading so
#: a fallback rung isn't rejected over a knob it never had.
_RUNG_ONLY_OPTS = {
    "approx": ("tpu",),
    "recall_target": ("tpu",),
    "query_batch": ("tpu",),
    "num_threads": ("native-mt",),
    "num_devices": ("tpu-sharded", "tpu-train-sharded", "tpu-ring"),
}


def fallback_for(backend: str, available) -> Optional[str]:
    """First ladder rung for ``backend`` present in ``available`` — the
    static unavailable-backend substitution (CLI startup)."""
    for rung in LADDER.get(backend, ()):
        if rung in available:
            return rung
    return None


def known_backend(backend: str) -> bool:
    """Whether ``backend`` is a name the ladder knows (i.e. a real backend
    that may merely be unbuilt/unregistered on this install, as opposed to
    a typo)."""
    return backend in LADDER


def opts_for_rung(rung: str, origin: str, opts: dict) -> dict:
    """Sanitize ``opts`` for a fallback ``rung``: drop knobs owned by
    other rungs and map ring-only engine names to auto. The origin rung
    (``rung == origin``) keeps its opts verbatim."""
    if rung == origin:
        return dict(opts)
    out = {
        name: value
        for name, value in opts.items()
        if rung in _RUNG_ONLY_OPTS.get(name, (rung,))
    }
    if out.get("engine") in ("full", "tiled") and rung != "tpu-ring":
        out["engine"] = "auto"
    return out


def _default_warn(msg: str) -> None:
    print(f"warning: {msg}", file=sys.stderr)


def _record_fallback(frm: str, to: str, reason: str) -> None:
    obs.counter_add(
        "knn_fallback_total",
        help="degradation-ladder moves (backend -> fallback backend)",
        from_backend=frm, to=to, reason=reason,
    )
    # Also lands in any active request contexts (a laddered predict run
    # inside a traced serving dispatch); one predicate otherwise.
    from knn_tpu.obs import reqtrace

    reqtrace.emit("fallback", from_backend=frm, to=to, reason=reason)


class LadderResult:
    """Outcome of a laddered predict: the predictions plus where (and with
    what options) they were actually computed — so a caller timing repeat
    runs can start from the surviving rung instead of re-walking failures."""

    __slots__ = ("predictions", "backend", "opts", "degraded")

    def __init__(self, predictions, backend: str, opts: dict, degraded: bool):
        self.predictions = predictions
        self.backend = backend
        self.opts = opts
        self.degraded = degraded


def predict_with_ladder(
    backend: str,
    train,
    test,
    k: int,
    opts: Optional[dict] = None,
    *,
    no_fallback: bool = False,
    warn: Optional[Callable[[str], None]] = None,
) -> LadderResult:
    """Classify through ``backend``, degrading down the ladder on typed
    failures. Returns a :class:`LadderResult`; raises the last typed error
    when every rung fails (or the first one under ``no_fallback``)."""
    from knn_tpu.backends import available_backends, get_backend

    if opts is None:
        opts = {}
    if warn is None:
        warn = _default_warn
    available = set(available_backends())
    rungs = [backend] + [r for r in LADDER.get(backend, ()) if r in available]
    if backend not in available:
        rungs = rungs[1:]
        if not rungs:
            raise DeviceError(f"backend '{backend}' unavailable and no "
                              f"fallback rung is registered")
    last_err: Optional[Exception] = None
    degraded = False
    for pos, rung in enumerate(rungs):
        rung_opts = opts_for_rung(rung, backend, opts)
        while True:  # OOM batch-halving loop (same rung, smaller batches)
            try:
                fn = get_backend(rung)
                preds = fn(train, test, k, **rung_opts)
                return LadderResult(preds, rung, rung_opts, degraded)
            except DeviceError as e:
                if (
                    e.oom
                    and not no_fallback
                    and rung == "tpu"
                    and (rung_opts.get("query_batch")
                         or test.num_instances) > 1
                ):
                    prev = rung_opts.get("query_batch") or test.num_instances
                    rung_opts = dict(rung_opts, query_batch=max(1, prev // 2))
                    warn(
                        f"backend '{rung}' out of memory; retrying with "
                        f"query_batch={rung_opts['query_batch']}"
                    )
                    _record_fallback(rung, rung, "oom_halve_batch")
                    degraded = True
                    continue
                last_err = e
            except DataError:
                # Bad input is bad input on every rung: switching backends
                # cannot fix it, so don't walk the ladder pretending it might.
                raise
            except ResilienceError as e:
                last_err = e
            except ValueError as e:
                # Option/validation rejection. On the user's chosen rung
                # this is their error to see; on a fallback rung it means
                # "this rung can't serve these opts" — skip it.
                if pos == 0:
                    raise
                last_err = e
            break
        if no_fallback:
            raise last_err
        nxt = rungs[pos + 1] if pos + 1 < len(rungs) else None
        if nxt is not None:
            warn(
                f"backend '{rung}' failed "
                f"({type(last_err).__name__}: {last_err}); "
                f"falling back to '{nxt}'"
            )
            _record_fallback(rung, nxt, type(last_err).__name__)
            degraded = True
    assert last_err is not None
    raise last_err
