"""Retry with exponential backoff and deadlines.

The transfer/compile/collective call sites wrap their device work in
:func:`guarded_call`, which composes the three resilience primitives in
the right order:

1. :func:`knn_tpu.resilience.faults.fault_point` — the injection marker
   (first, so a planned fault replaces the real call);
2. the real call, with raw exceptions classified into the typed taxonomy
   (:func:`knn_tpu.resilience.errors.classify_exception`);
3. retry: transient failures re-attempt with exponential backoff
   (``base * 2**attempt``, capped) until the attempt budget or the
   deadline runs out. Non-transient failures (malformed data, OOM)
   propagate immediately — the degradation ladder, not the retry loop,
   owns those.

Every re-attempt increments ``knn_retry_total{site=...}`` and opens a
``retry`` span through :mod:`knn_tpu.obs` (no-ops while obs is off).

Backoff timing is env-tunable so the chaos suite runs at full speed:
``KNN_TPU_RETRY_BASE_MS`` (default 25), ``KNN_TPU_RETRY_MAX_MS`` (default
2000), ``KNN_TPU_RETRY_ATTEMPTS`` (default 3 total attempts),
``KNN_TPU_RETRY_DEADLINE_MS`` (default none). Tests set the base to 0.

``KNN_TPU_RETRY_JITTER`` (default **off**) multiplies each backoff sleep
by a uniform draw from ``[0.5, 1.0]`` — enough spread to de-synchronize
the serving process's concurrent handler threads (a fault that fails N
threads at once would otherwise have all N re-attempt in lockstep, an
in-process retry storm), while staying below the deterministic schedule so
the ``max_ms`` cap and deadline arithmetic keep holding. It defaults off
because the chaos suite replays fault plans deterministically and jittered
sleeps would vary the interleaving; when on, the draw sequence comes from
a PRNG seeded by ``KNN_TPU_FAULT_SEED`` (the fault harness's seed), so a
single-threaded replay is still reproducible.
"""

from __future__ import annotations

import errno
import os
import random
import threading
import time
from typing import Callable, Optional, TypeVar

from knn_tpu import obs
from knn_tpu.resilience import faults
from knn_tpu.resilience.errors import ResilienceError, classify_exception

T = TypeVar("T")

_BASE_ENV = "KNN_TPU_RETRY_BASE_MS"
_MAX_ENV = "KNN_TPU_RETRY_MAX_MS"
_ATTEMPTS_ENV = "KNN_TPU_RETRY_ATTEMPTS"
_DEADLINE_ENV = "KNN_TPU_RETRY_DEADLINE_MS"
_JITTER_ENV = "KNN_TPU_RETRY_JITTER"

# Jitter PRNG: one shared, lock-protected stream so concurrent handler
# threads draw DIFFERENT values (that difference is the whole point —
# per-call reseeding would hand every thread the identical first draw and
# re-synchronize the storm). Seeded lazily from KNN_TPU_FAULT_SEED.
_jitter_lock = threading.Lock()
_jitter_rng: Optional[random.Random] = None


def jitter_enabled() -> bool:
    return os.environ.get(_JITTER_ENV, "") not in ("", "0", "off", "false")


def _seed_from_env() -> int:
    from knn_tpu.resilience.faults import SEED_ENV

    return int(os.environ.get(SEED_ENV, "0") or "0")


def reset_jitter(seed: Optional[int] = None) -> None:
    """(Re-)seed the jitter stream — tests use this to pin replay
    determinism; ``None`` re-reads ``KNN_TPU_FAULT_SEED``."""
    global _jitter_rng
    with _jitter_lock:
        _jitter_rng = random.Random(
            seed if seed is not None else _seed_from_env()
        )


def apply_jitter(sleep_ms: float) -> float:
    """One seeded draw: ``sleep_ms * U[0.5, 1.0]``. Bounded below at half
    the deterministic sleep (backoff must keep backing off) and above at
    the deterministic value (the ``max_ms`` cap and the caller's deadline
    check stay valid)."""
    global _jitter_rng
    with _jitter_lock:
        if _jitter_rng is None:
            _jitter_rng = random.Random(_seed_from_env())
        u = _jitter_rng.random()
    return sleep_ms * (0.5 + 0.5 * u)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def backoff_schedule(
    attempts: int, base_ms: float, max_ms: float,
) -> "list[float]":
    """Sleep (ms) before re-attempt i (i = 1..attempts-1): capped binary
    exponential. Deterministic — no jitter — so chaos tests replay
    identically; the single-process CLI has no thundering-herd peer to
    de-synchronize from."""
    return [min(base_ms * (2.0 ** i), max_ms) for i in range(attempts - 1)]


# errno values that are deterministic facts about the filesystem, not
# blips: retrying a missing path re-stats the same absence.
_DETERMINISTIC_ERRNOS = frozenset(
    getattr(errno, name)
    for name in ("ENOENT", "EISDIR", "ENOTDIR", "EACCES", "EPERM", "ENAMETOOLONG")
    if hasattr(errno, name)
)


def _is_transient(exc: BaseException) -> bool:
    if getattr(exc, "_retry_exhausted", False):
        # A nested guarded_call already spent its attempt budget on this
        # failure; re-retrying it at the outer guard would multiply the
        # attempts (3x3) and double-count knn_retry_total.
        return False
    if isinstance(exc, ResilienceError):
        return exc.transient
    # Raw OSError (e.g. an injected or real IO blip) is worth one more try —
    # unless its errno says the failure is deterministic.
    return isinstance(exc, OSError) and exc.errno not in _DETERMINISTIC_ERRNOS


def guarded_call(
    site: str,
    fn: Callable[[], T],
    *,
    attempts: Optional[int] = None,
    base_ms: Optional[float] = None,
    max_ms: Optional[float] = None,
    deadline_ms: Optional[float] = None,
    classify: bool = True,
) -> T:
    """Run ``fn()`` under fault point ``site`` with transient-failure retry.

    Raises the *typed* error (original as ``__cause__``) when attempts or
    the deadline are exhausted, or immediately for non-transient failures.
    ``classify=False`` propagates non-``ResilienceError`` exceptions
    unchanged (for sites whose callers already handle raw errors).
    """
    if attempts is None:
        attempts = max(1, int(_env_float(_ATTEMPTS_ENV, 3)))
    if base_ms is None:
        base_ms = _env_float(_BASE_ENV, 25.0)
    if max_ms is None:
        max_ms = _env_float(_MAX_ENV, 2000.0)
    if deadline_ms is None:
        raw = _env_float(_DEADLINE_ENV, 0.0)
        deadline_ms = raw if raw > 0 else None
    sleeps = backoff_schedule(attempts, base_ms, max_ms)
    t0 = time.monotonic()

    last: BaseException = RuntimeError(f"guarded_call({site!r}): no attempts")
    for attempt in range(attempts):
        try:
            faults.fault_point(site)
            return fn()
        except Exception as e:  # noqa: BLE001 — classified and re-raised below
            last = e
            if not _is_transient(e):
                break
            obs.counter_add(
                "knn_retry_total",
                help="transient-failure re-attempts at guarded call sites",
                site=site,
            )
            if attempt + 1 >= attempts:
                break
            sleep_ms = sleeps[attempt]
            if sleep_ms > 0 and jitter_enabled():
                sleep_ms = apply_jitter(sleep_ms)
            elapsed_ms = (time.monotonic() - t0) * 1e3
            if deadline_ms is not None and elapsed_ms + sleep_ms >= deadline_ms:
                break
            with obs.span("retry", site=site, attempt=attempt + 1):
                if sleep_ms > 0:
                    time.sleep(sleep_ms / 1e3)
    try:
        # Mark so an enclosing guarded_call (the nested transfer+compile
        # guards) propagates instead of re-running this whole attempt loop.
        last._retry_exhausted = True
    except AttributeError:
        pass  # exceptions with __slots__: worst case the outer guard retries
    if classify and not isinstance(last, ResilienceError):
        err = classify_exception(last, site)
        err._retry_exhausted = True
        raise err from last
    raise last
