"""Resilience subsystem: fault injection, retry/backoff, degradation.

The reference's failure model is the course assignment's: any failure —
a bad ARFF token, a lost MPI rank, an OOM — is a crash (or undefined
behavior). A serving stack needs the opposite property: every failure
mode is *recovered* (transient faults retried), *degraded around* (the
backend ladder, batch halving, multihost → solo), or *reported* as a
typed, actionable error. This package is that property, woven through
the backends, the sharded paths, multihost, and the CLI:

- :mod:`knn_tpu.resilience.errors`  — the typed taxonomy (``DataError``,
  ``CompileError``, ``DeviceError``, ``CollectiveError``,
  ``WorkerLostError``) callers branch on instead of string-matching JAX
  internals;
- :mod:`knn_tpu.resilience.faults`  — deterministic, seeded fault
  injection at named points (``arff.parse``, ``device.put``,
  ``backend.compile``, ``collective.step``, ``multihost.init``,
  ``native.load``), armed by ``KNN_TPU_FAULTS`` or
  :func:`~knn_tpu.resilience.faults.inject` — chaos tests run in tier-1
  on CPU;
- :mod:`knn_tpu.resilience.retry`   — :func:`guarded_call`, the
  fault-point + classify + exponential-backoff-retry wrapper on the
  transfer/compile/collective call sites (``knn_retry_total``);
- :mod:`knn_tpu.resilience.degrade` — the graceful-degradation ladder
  (``tpu → tpu-pallas → native → oracle``, sharded → single-device,
  OOM → halve ``query_batch``), with the CLI's ``--no-fallback`` escape
  hatch (``knn_fallback_total``);
- :mod:`knn_tpu.resilience.breaker` — the circuit breaker
  (closed/open/half-open over a sliding failure window, Nygard's
  *Release It!* pattern) the serving micro-batcher wraps its device
  dispatch in: persistent failure short-circuits to the degraded rung,
  half-open probes re-promote when the device recovers
  (``knn_breaker_*`` metrics — docs/RESILIENCE.md).

Everything is measured-zero-cost when idle: an unarmed fault point is one
``None`` check, and the retry wrapper sits only at per-predict
granularity (docs/RESILIENCE.md).
"""

from __future__ import annotations

from knn_tpu.resilience.errors import (
    CollectiveError,
    CompileError,
    DataError,
    DeadlineExceededError,
    DeviceError,
    OverloadError,
    ResilienceError,
    WorkerLostError,
    classify_exception,
)
from knn_tpu.resilience.faults import FaultPlan, fault_point, inject, install_from_env
from knn_tpu.resilience.retry import guarded_call
from knn_tpu.resilience.breaker import CircuitBreaker
from knn_tpu.resilience.degrade import (
    LADDER,
    LadderResult,
    fallback_for,
    known_backend,
    predict_with_ladder,
)

__all__ = [
    "ResilienceError", "DataError", "CompileError", "DeviceError",
    "CollectiveError", "WorkerLostError", "DeadlineExceededError",
    "OverloadError", "classify_exception",
    "FaultPlan", "fault_point", "inject", "install_from_env",
    "guarded_call", "CircuitBreaker",
    "LADDER", "LadderResult", "fallback_for", "known_backend",
    "predict_with_ladder",
]
