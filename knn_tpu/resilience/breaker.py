"""Circuit breaker: stop hammering a broken dependency, probe, re-promote.

Nygard's pattern (*Release It!*) adapted to the serving loop: the
micro-batcher's fast-rung device dispatch is the guarded dependency. When
it fails persistently, every batch would otherwise pay the full failure +
ladder walk (retries, a doomed dispatch, the fallback) before answering —
exactly the "slow component dominates latency" failure mode *The Tail at
Scale* warns about. The breaker makes the degraded state cheap and the
recovery automatic:

- **closed**  — normal operation; outcomes feed a sliding window of the
  last ``window`` results. ``threshold`` failures inside the window trip
  the breaker open.
- **open**    — the guarded call is skipped entirely (the batcher
  short-circuits straight to its degraded rung) for ``cooldown_ms``.
- **half-open** — after the cooldown one probe call is allowed through;
  ``probe_successes`` consecutive successes re-close (re-promoting the
  fast rung), any probe failure re-opens and restarts the cooldown.

Env-tunable (read at construction, so a serving process configures itself
from its environment):

================================  =======  =================================
``KNN_TPU_BREAKER_WINDOW``        20       sliding window size (outcomes)
``KNN_TPU_BREAKER_THRESHOLD``     5        failures in window that trip open
``KNN_TPU_BREAKER_COOLDOWN_MS``   1000     open -> half-open delay
``KNN_TPU_BREAKER_PROBES``        2        half-open successes to re-close
================================  =======  =================================

Metrics (through :mod:`knn_tpu.obs`, no-ops while disabled):
``knn_breaker_state{breaker}`` gauge (0 closed / 1 open / 2 half-open),
``knn_breaker_transitions_total{breaker,from_state,to_state}``, and
``knn_breaker_short_circuits_total{breaker}`` (calls refused while open).
State transitions also emit a zero-length ``breaker.transition`` span so
a trace shows exactly when the serving loop degraded and recovered.

The decision path is O(1) and lock-cheap: one monotonic read plus deque
arithmetic — measured noise next to a device dispatch.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from knn_tpu import obs
from knn_tpu.obs import reqtrace

_WINDOW_ENV = "KNN_TPU_BREAKER_WINDOW"
_THRESHOLD_ENV = "KNN_TPU_BREAKER_THRESHOLD"
_COOLDOWN_ENV = "KNN_TPU_BREAKER_COOLDOWN_MS"
_PROBES_ENV = "KNN_TPU_BREAKER_PROBES"

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"
_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


def _env_int(name: str, default: int, lo: int = 1) -> int:
    raw = os.environ.get(name, "")
    try:
        return max(lo, int(float(raw))) if raw else default
    except ValueError:
        return default


def _env_float(name: str, default: float, lo: float = 0.0) -> float:
    raw = os.environ.get(name, "")
    try:
        return max(lo, float(raw)) if raw else default
    except ValueError:
        return default


class CircuitBreaker:
    """Thread-safe closed/open/half-open breaker over a sliding outcome
    window. The caller drives it with three calls per guarded dispatch::

        decision = breaker.decide()       # "closed" | "probe" | "open"
        if decision == "open":
            ...skip the guarded call (short-circuit)...
        else:
            try:    ...guarded call...;  breaker.record_success()
            except: ...;                 breaker.record_failure()

    ``decide()`` returning ``"probe"`` means the call is a half-open
    recovery probe (the caller may want to mark it in traces); it is
    otherwise identical to ``"closed"``.
    """

    def __init__(self, name: str, *, window: "int | None" = None,
                 threshold: "int | None" = None,
                 cooldown_ms: "float | None" = None,
                 probe_successes: "int | None" = None):
        self.name = name
        self.window = window if window is not None else _env_int(_WINDOW_ENV, 20)
        self.threshold = (threshold if threshold is not None
                          else _env_int(_THRESHOLD_ENV, 5))
        self.cooldown_ms = (cooldown_ms if cooldown_ms is not None
                            else _env_float(_COOLDOWN_ENV, 1000.0))
        self.probe_successes = (probe_successes if probe_successes is not None
                                else _env_int(_PROBES_ENV, 2))
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if not 1 <= self.threshold <= self.window:
            raise ValueError(
                f"threshold ({self.threshold}) must be in [1, window="
                f"{self.window}] or the breaker could never (or always) trip"
            )
        self._lock = threading.Lock()
        self._state = CLOSED
        self._outcomes: "deque[bool]" = deque(maxlen=self.window)  # True=fail
        self._failures = 0  # failures currently inside the window
        self._opened_at_ns = 0
        self._probes_ok = 0
        self.transitions = 0
        self.short_circuits = 0

    # -- state machine -----------------------------------------------------

    def _transition(self, to: str) -> None:
        frm, self._state = self._state, to
        self.transitions += 1
        self._outcomes.clear()
        self._failures = 0
        self._probes_ok = 0
        if to == OPEN:
            self._opened_at_ns = time.monotonic_ns()
        obs.counter_add(
            "knn_breaker_transitions_total",
            help="circuit-breaker state transitions",
            breaker=self.name, from_state=frm, to_state=to,
        )
        obs.gauge_set(
            "knn_breaker_state", _STATE_CODE[to],
            help="circuit-breaker state (0 closed / 1 open / 2 half-open)",
            breaker=self.name,
        )
        # A zero-length marker span: traces show when serving degraded —
        # and the same marker lands in every request context the current
        # dispatch is serving (one thread-local predicate when none are).
        reqtrace.emit("breaker.transition", breaker=self.name,
                      from_state=frm, to_state=to)
        with obs.span("breaker.transition", breaker=self.name,
                      from_state=frm, to_state=to):
            pass

    def decide(self) -> str:
        """``"closed"`` (call normally), ``"probe"`` (call as a half-open
        recovery probe), or ``"open"`` (skip the call — short-circuit)."""
        with self._lock:
            if self._state == CLOSED:
                return CLOSED
            if self._state == OPEN:
                elapsed_ms = (time.monotonic_ns() - self._opened_at_ns) / 1e6
                if elapsed_ms < self.cooldown_ms:
                    self.short_circuits += 1
                    obs.counter_add(
                        "knn_breaker_short_circuits_total",
                        help="guarded calls skipped while the breaker was "
                             "open (served degraded instead)",
                        breaker=self.name,
                    )
                    return OPEN
                self._transition(HALF_OPEN)
            return "probe"

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._probes_ok += 1
                if self._probes_ok >= self.probe_successes:
                    self._transition(CLOSED)
                return
            if self._state == CLOSED:
                self._observe(False)
            # success while OPEN (a call that raced the trip): ignore.

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._transition(OPEN)  # failed probe: back to cooldown
                return
            if self._state == CLOSED:
                self._observe(True)
                if self._failures >= self.threshold:
                    self._transition(OPEN)

    def _observe(self, failed: bool) -> None:
        if len(self._outcomes) == self._outcomes.maxlen and self._outcomes[0]:
            self._failures -= 1  # the aged-out outcome was a failure
        self._outcomes.append(failed)
        if failed:
            self._failures += 1

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        """State for /healthz and tests."""
        with self._lock:
            return {
                "state": self._state,
                "window_failures": self._failures,
                "threshold": self.threshold,
                "transitions": self.transitions,
                "short_circuits": self.short_circuits,
            }
