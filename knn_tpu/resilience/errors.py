"""Typed error taxonomy for the resilience layer.

The reference's failure story is a raw crash: a malformed ARFF aborts
mid-parse (libarff THROW), an MPI rank failure kills the whole job, and
anything else is undefined behavior. Our reproduction inherited the JAX
flavor of the same problem — callers had to string-match
``XlaRuntimeError`` messages to tell an OOM from a compile failure from a
dead worker. This module gives every failure mode a class so callers (the
CLI, the degradation ladder in :mod:`knn_tpu.resilience.degrade`, tests)
branch on type, not text:

- :class:`DataError`       — input data is unusable (parse failures with
  file:line context, missing files surfaced at load, invalid shapes).
- :class:`CompileError`    — tracing/compiling a kernel failed.
- :class:`DeviceError`     — moving data to/from a device or executing on
  it failed; ``oom=True`` marks resource exhaustion (the ladder answers
  OOM by halving ``query_batch``, not by switching backends).
- :class:`CollectiveError` — a sharded/multi-device step failed (the MPI
  analogue of a lost rank mid-collective).
- :class:`WorkerLostError` — a multihost worker/cluster is gone or never
  materialized (``jax.distributed`` init failure, dead coordinator).
- :class:`DeadlineExceededError` — a request's deadline elapsed before its
  result was ready (the serving path's 504; also raised by
  ``AsyncResult.result(timeout=...)``).
- :class:`OverloadError`  — admission control rejected work because a
  bounded queue is full (the serving path's 429).

``DataError`` subclasses ``ValueError`` and every class subclasses
``ResilienceError`` (itself an ``Exception``), so pre-existing
``except (OSError, ValueError)`` handling keeps working while new code
catches the taxonomy.

``transient`` marks errors worth retrying (:mod:`knn_tpu.resilience.retry`
only re-attempts those): an interrupted transfer is transient, a malformed
file or an OOM is not — retrying a deterministic failure wastes the
deadline, and retrying OOM at the same batch size re-OOMs.
"""

from __future__ import annotations


class ResilienceError(Exception):
    """Base of the taxonomy. ``transient`` gates retry; ``fault_point``
    records which named injection point raised it (None for real errors)."""

    #: class default; instances may override via the constructor
    transient = False

    def __init__(self, message: str, *, transient: "bool | None" = None,
                 fault_point: "str | None" = None):
        super().__init__(message)
        if transient is not None:
            self.transient = transient
        self.fault_point = fault_point


class DataError(ResilienceError, ValueError):
    """Unusable input data: parse failures (with file:line context where
    the parser has it), missing/unreadable files surfaced at load time,
    unknown nominal/class labels, shape mismatches. Never transient —
    re-reading a malformed file yields the same bytes."""


class CompileError(ResilienceError):
    """Tracing or compiling a kernel failed (XLA compile error, Pallas
    lowering failure). Transient by default: real compile infrastructure
    does fail transiently (compile-server hiccups), and one retry is cheap
    next to abandoning the fast backend."""

    transient = True


class DeviceError(ResilienceError):
    """A device transfer or on-device execution failed. ``oom=True`` marks
    resource exhaustion, which is NOT transient (same inputs re-exhaust
    the same memory) — the ladder's answer is a smaller ``query_batch``."""

    def __init__(self, message: str, *, oom: bool = False,
                 transient: "bool | None" = None,
                 fault_point: "str | None" = None):
        if transient is None:
            transient = not oom
        super().__init__(message, transient=transient, fault_point=fault_point)
        self.oom = oom


class CollectiveError(ResilienceError):
    """A multi-device collective step failed — the single-controller
    analogue of losing an MPI rank mid-``MPI_Gatherv``. Transient by
    default (ICI/DCN links flap); persistent failures degrade to the
    single-device rung."""

    transient = True


class WorkerLostError(CollectiveError):
    """A multihost worker or the cluster itself is unavailable:
    ``jax.distributed`` init failed, the coordinator died, or a peer
    process disappeared. ``reason`` carries the original failure class
    name for logs/metrics."""

    def __init__(self, message: str, *, reason: str = "unknown",
                 transient: "bool | None" = None,
                 fault_point: "str | None" = None):
        super().__init__(message, transient=transient, fault_point=fault_point)
        self.reason = reason


class DeadlineExceededError(ResilienceError):
    """A deadline elapsed before the work finished: a queued serving request
    expired before dispatch, or ``AsyncResult.result(timeout=...)`` ran out
    of time waiting for an in-flight computation. Never transient under the
    retry machinery — the work is usually still running; re-submitting it
    would double the load exactly when the system is slowest. The serving
    front-end maps this to HTTP 504."""


class OverloadError(ResilienceError):
    """Admission control rejected new work: a bounded request queue is full
    (or the component is shutting down). Not transient for the *internal*
    retry loop — an immediate in-process re-attempt re-hits the same full
    queue; the right retry is the CLIENT's, after backoff, which is exactly
    what the serving front-end's HTTP 429 tells it."""


class ShedByPolicy(OverloadError):
    """Admission DELIBERATELY refused this request because its priority
    class is below the control plane's current admission cutoff
    (:mod:`knn_tpu.control.admission`) — overload pressure, not a full
    queue. Distinct from the base :class:`OverloadError` so the serving
    layer can label the outcome ``shed`` (not ``rejected``) and the SLO
    availability SLI can exclude policy sheds of non-protected classes: a
    planned ``bulk`` shed is the control plane working, not an incident.
    ``retry_after_s`` is the headroom-derived client backoff the 429's
    ``Retry-After`` header carries; ``request_class`` names the shed
    class."""

    def __init__(self, message: str, *, request_class: str,
                 retry_after_s: float,
                 fault_point: "str | None" = None):
        super().__init__(message, fault_point=fault_point)
        self.request_class = request_class
        self.retry_after_s = float(retry_after_s)


# Substrings that mark an XLA runtime failure as resource exhaustion. XLA
# surfaces OOM as XlaRuntimeError("RESOURCE_EXHAUSTED: ..."); host-side
# allocation failure is MemoryError.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def classify_exception(exc: BaseException, site: str) -> ResilienceError:
    """Map a raw exception from a guarded call site to the taxonomy.

    ``site`` is the fault-point name of the call site (``device.put``,
    ``backend.compile``, ``collective.step``, ...) — it decides the class
    for generic runtime errors, because at the raw-exception level an XLA
    failure during a collective dispatch is indistinguishable from one
    during a single-device dispatch. Already-typed errors pass through
    unchanged. The original exception is preserved as ``__cause__`` by the
    raising caller (``raise classify_exception(e, site) from e``).
    """
    if isinstance(exc, ResilienceError):
        return exc
    text = f"{type(exc).__name__}: {exc}"
    if isinstance(exc, MemoryError) or any(m in str(exc) for m in _OOM_MARKERS):
        return DeviceError(f"[{site}] {text}", oom=True)
    if site == "backend.compile":
        return CompileError(f"[{site}] {text}")
    if site == "multihost.init":
        return WorkerLostError(f"[{site}] {text}", reason=type(exc).__name__)
    if site == "collective.step":
        return CollectiveError(f"[{site}] {text}")
    if site == "arff.parse":
        return DataError(f"[{site}] {text}")
    # device.put, native.load, and any future execution-flavored site.
    return DeviceError(f"[{site}] {text}", transient=isinstance(exc, OSError))
