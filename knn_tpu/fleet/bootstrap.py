"""Snapshot-shipping follower bootstrap: how a blank or stale replica
joins the fleet without anyone copying directories by hand.

The LSM recipe's missing distributed leg: component state (a generation
artifact — ``manifest.json`` + ``arrays.npz``) ships by snapshot over a
chunked, digest-verified ``/admin/snapshot`` transfer, then the WAL
catches the follower up through the normal ``wal-append`` path. Two
entry points share the machinery:

- **boot-time** (``knn-tpu serve --follower-of URL`` over a blank
  directory, cli.py): :func:`install_snapshot` pulls, verifies, and
  commits before the engine ever boots — "add a replica" is one
  command;
- **in-process** (``POST /admin/bootstrap`` on a running follower,
  serve/server.py): :func:`download_snapshot` stages and verifies while
  the old state keeps serving, then :func:`commit_snapshot` runs inside
  the engine's reseed critical section — clear the abandoned lineage's
  epochs, rename the staged generation in, atomically replace
  ``CURRENT.json``.

Failure contract: every byte is verified (whole-file sha256 against the
source manifest) before anything durable changes, the staged directory
lives inside the artifact root (same filesystem — the final rename is
atomic), and the ``fleet.snapshot_ship`` fault point fires before the
first destructive step — any failure leaves the prior state serving.
Crash windows are stale-but-consistent: removing the old epochs before
the pointer commit can only roll a *diverged-or-behind* follower back
to its own fold point, never replay another lineage's records.
"""

from __future__ import annotations

import hashlib
import shutil
from pathlib import Path
from typing import Optional

from knn_tpu.resilience import faults
from knn_tpu.resilience.errors import DataError
from knn_tpu.serve import artifact
from knn_tpu.fleet.wire import forward_bytes, request_json

#: Per-request transfer unit. Small enough that one chunk never trips
#: the serve handler's body ceiling, large enough that arrays ship in a
#: handful of round trips.
CHUNK_BYTES = 4 << 20

#: The only files a generation artifact consists of — the snapshot
#: manifest lists exactly these, and the chunk endpoint refuses
#: anything else (no path traversal surface).
SNAPSHOT_FILES = (artifact.MANIFEST_NAME, artifact.ARRAYS_NAME)


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


# -- primary side ----------------------------------------------------------


def snapshot_manifest(root) -> dict:
    """What ``GET /admin/snapshot`` returns: the current base
    generation's file list (name/size/sha256 each) plus the WAL cursor a
    follower resumes shipping from after installing it. Read purely from
    disk — the committed state — so the snapshot is self-consistent
    even while the live engine runs ahead of the fold point (the WAL
    ships the difference)."""
    root = Path(root)
    base_dir, current = artifact.resolve_mutable_base(root)
    block, _stable = artifact.read_mutable_block(base_dir)
    generation, wal_cursor, next_stable = 0, 0, 0
    if block is not None:
        generation = int(block.get("generation", 0))
        wal_cursor = int(block.get("folded_seq", 0))
        next_stable = int(block.get("next_stable", 0))
    if current is not None:
        generation = int(current.get("generation", generation))
        wal_cursor = max(wal_cursor, int(current.get("folded_seq", 0)))
        next_stable = max(next_stable, int(current.get("next_stable", 0)))
    files = []
    for name in SNAPSHOT_FILES:
        p = base_dir / name
        if not p.exists():
            raise DataError(
                f"{base_dir}: {name} missing — the serving base is not a "
                f"complete artifact; cannot snapshot"
            )
        files.append({"name": name, "size": p.stat().st_size,
                      "sha256": _sha256(p)})
    manifest = artifact.read_manifest(base_dir)
    return {
        "generation": generation,
        "wal_cursor": wal_cursor,
        "next_stable": next_stable,
        "index_version": artifact.index_version(manifest),
        "files": files,
    }


def read_chunk(root, name: str, offset: int, length: int,
               generation: int) -> bytes:
    """One chunk of a snapshot file, or a typed refusal. ``generation``
    is the client's precondition: a compaction swapping the base
    mid-transfer must surface as a 409-able error, never as a file
    stitched from two generations (the sha256 would catch it anyway —
    this catches it cheaply and with a name)."""
    root = Path(root)
    base_dir, current = artifact.resolve_mutable_base(root)
    block, _stable = artifact.read_mutable_block(base_dir)
    live_gen = 0
    if block is not None:
        live_gen = int(block.get("generation", 0))
    if current is not None:
        live_gen = int(current.get("generation", live_gen))
    if live_gen != generation:
        raise DataError(
            f"snapshot generation {generation} superseded by "
            f"{live_gen} (a compaction landed mid-transfer); re-fetch "
            f"the snapshot manifest and restart"
        )
    if name not in SNAPSHOT_FILES:
        raise DataError(
            f"{name!r} is not a snapshot file; a snapshot ships exactly "
            f"{list(SNAPSHOT_FILES)}"
        )
    if offset < 0 or length <= 0:
        raise DataError(f"bad chunk range offset={offset} length={length}")
    with open(base_dir / name, "rb") as f:
        f.seek(offset)
        return f.read(length)


# -- follower side ---------------------------------------------------------


class SnapshotInstallError(DataError):
    """A bootstrap transfer or install failed with the prior state still
    serving — retryable from scratch, nothing durable changed."""


def download_snapshot(primary_url: str, root, *, timeout_s: float = 30.0,
                      chunk_bytes: int = CHUNK_BYTES,
                      attempts: int = 3) -> dict:
    """Pull the primary's current generation into a staging directory
    under ``root`` and verify every file's sha256. Returns the staged
    plan (consumed by :func:`commit_snapshot`); raises
    :class:`SnapshotInstallError` with the staging directory removed on
    any failure. Restart-from-manifest on a generation-superseded 409:
    a compaction mid-transfer costs a retry, never a torn install."""
    primary_url = primary_url.rstrip("/")
    root = Path(root)
    last: Optional[str] = None
    for _attempt in range(attempts):
        try:
            status, man = request_json(
                "GET", primary_url + "/admin/snapshot", timeout=timeout_s)
        except OSError as e:
            last = f"snapshot manifest fetch failed: {e}"
            continue
        if status != 200:
            raise SnapshotInstallError(
                f"{primary_url}/admin/snapshot returned HTTP {status}: "
                f"{man.get('error', man)}"
            )
        try:
            generation = int(man["generation"])
            files = list(man["files"])
        except (KeyError, TypeError, ValueError) as e:
            raise SnapshotInstallError(
                f"{primary_url}: malformed snapshot manifest: {e}"
            ) from e
        tmp = root / f".bootstrap-gen-{generation:06d}.tmp"
        try:
            return _fetch_into(primary_url, tmp, man, generation, files,
                               timeout_s, chunk_bytes, root)
        except _GenerationSuperseded as e:
            shutil.rmtree(tmp, ignore_errors=True)
            last = str(e)
            continue
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    raise SnapshotInstallError(
        f"bootstrap from {primary_url} did not converge after "
        f"{attempts} attempts: {last}"
    )


class _GenerationSuperseded(Exception):
    pass


def _fetch_into(primary_url: str, tmp: Path, man: dict, generation: int,
                files: list, timeout_s: float, chunk_bytes: int,
                root: Path) -> dict:
    shutil.rmtree(tmp, ignore_errors=True)
    tmp.mkdir(parents=True)
    total = 0
    for entry in files:
        name, size = str(entry["name"]), int(entry["size"])
        want = str(entry["sha256"])
        if name not in SNAPSHOT_FILES:
            raise SnapshotInstallError(
                f"{primary_url}: snapshot manifest lists unexpected file "
                f"{name!r}"
            )
        dest = tmp / name
        with open(dest, "wb") as out:
            offset = 0
            while offset < size:
                length = min(chunk_bytes, size - offset)
                url = (f"{primary_url}/admin/snapshot?file={name}"
                       f"&offset={offset}&length={length}"
                       f"&generation={generation}")
                status, body = forward_bytes("GET", url, None,
                                             timeout=timeout_s)
                if status == 409:
                    raise _GenerationSuperseded(
                        f"generation {generation} superseded mid-transfer")
                if status != 200:
                    raise SnapshotInstallError(
                        f"{url}: HTTP {status} mid-transfer"
                    )
                if len(body) != length:
                    # A torn chunk: the wire delivered fewer bytes than
                    # the range asked for — refuse now rather than let
                    # the digest check name it less precisely.
                    raise SnapshotInstallError(
                        f"{url}: torn chunk ({len(body)} bytes of "
                        f"{length})"
                    )
                out.write(body)
                offset += length
        got = _sha256(dest)
        if got != want:
            raise SnapshotInstallError(
                f"{name}: digest mismatch after transfer (want {want[:16]}…, "
                f"got {got[:16]}…) — refusing to install a corrupt snapshot"
            )
        total += size
    # Staged and fully verified; nothing durable has changed yet.
    return {
        "tmp_dir": tmp,
        "root": root,
        "generation": generation,
        "wal_cursor": int(man.get("wal_cursor", 0)),
        "next_stable": int(man.get("next_stable", 0)),
        "index_version": man.get("index_version"),
        "bytes": total,
        "files": [e["name"] for e in files],
    }


def plan_install_dir(staged: dict) -> Path:
    """Where the staged generation will live: ``generations/gen-NNNNNN``,
    or a ``-rsK`` suffixed sibling when that name is already taken by
    this replica's own (abandoned, possibly divergent) lineage —
    CURRENT.json's ``base`` is a relative path, so the name only has to
    be unique, and never clobbering the serving base keeps every crash
    window consistent."""
    root: Path = staged["root"]
    final = artifact.generation_path(root, staged["generation"])
    k = 0
    while final.exists():
        k += 1
        final = final.with_name(
            f"gen-{staged['generation']:06d}-rs{k}")
    return final


def commit_snapshot(staged: dict) -> dict:
    """The durable flip, in crash-safe order: fault point (the injected
    stand-in for disk-full mid-install) → rename the staged generation
    in (additive) → remove the old lineage's epoch files (so no record
    from an abandoned history can ever replay onto the new base) →
    atomic ``CURRENT.json`` replace (the commit point). A crash between
    epoch removal and the pointer commit boots the OLD base at its own
    fold point — stale but consistent, recoverable through replication.

    In-process callers run this inside the engine's reseed critical
    section (no concurrent append can land in an epoch being cleared);
    the boot-time path has no engine yet, so ordering alone suffices."""
    root: Path = staged["root"]
    faults.fault_point("fleet.snapshot_ship")
    final = plan_install_dir(staged)
    final.parent.mkdir(parents=True, exist_ok=True)
    import os

    os.replace(staged["tmp_dir"], final)
    for _n, path in artifact.list_epochs(root):
        try:
            path.unlink()
        except OSError:
            pass
    current = {
        "generation": staged["generation"],
        "base": str(final.relative_to(root)),
        "folded_seq": staged["wal_cursor"],
        "next_stable": staged["next_stable"],
        "active_epoch": 0,
    }
    artifact.write_current(root, current)
    return {**current, "bytes": staged["bytes"],
            "files": staged["files"],
            "index_version": staged["index_version"]}


def install_snapshot(root, primary_url: str, *, timeout_s: float = 30.0,
                     chunk_bytes: int = CHUNK_BYTES) -> dict:
    """The boot-time one-shot: download, verify, commit. Used by the CLI
    when ``--follower-of`` points a blank directory at a live primary —
    after this returns, the normal mutable boot path resolves the
    installed generation like any compacted artifact and the WAL
    shipper catches the replica up from ``wal_cursor``."""
    root = Path(root)
    root.mkdir(parents=True, exist_ok=True)
    staged = download_snapshot(primary_url, root, timeout_s=timeout_s,
                               chunk_bytes=chunk_bytes)
    try:
        return commit_snapshot(staged)
    except Exception:
        shutil.rmtree(staged["tmp_dir"], ignore_errors=True)
        raise


def artifact_present(root) -> bool:
    """Does ``root`` already hold something bootable? (Either a plain
    artifact at the top or a CURRENT.json pointer.) The CLI's
    auto-bootstrap gate: never overwrite an existing lineage at boot —
    a *stale* follower re-seeds through the in-process path, where the
    decision is explicit."""
    root = Path(root)
    return ((root / artifact.MANIFEST_NAME).exists()
            or (root / artifact.CURRENT_NAME).exists())


def summary_line(doc: dict) -> str:
    return (f"bootstrap: installed generation {doc['generation']} "
            f"({doc['bytes']} bytes, {len(doc['files'])} files) at WAL "
            f"cursor {doc['folded_seq']}")
