"""The replica-side fleet role: WAL fan-out, follower apply, promotion.

One :class:`FleetReplica` rides a mutable ``ServeApp``:

- **primary** (``serve --replicate-to URL,...``): after every locally
  acknowledged mutation, one :class:`WALShipper` per follower pushes the
  ordered record stream over ``POST /admin/wal-append`` (cursor per
  follower, gap resync via the follower's reported ``applied_seq``,
  divergence is terminal). With ``ack_mode="any"`` (the default) a
  mutation's HTTP 200 waits until at least one follower holds its seq —
  that is the invariant that makes "promote the most-caught-up follower"
  lose zero acknowledged writes.
- **follower** (``serve --follower-of URL``): read-only for clients;
  applies shipped records through
  :meth:`~knn_tpu.mutable.engine.MutableEngine.apply_replicated` (the
  exact local-mutation validation path — a divergent record is a typed
  refusal, not silent corruption). ``POST /admin/promote`` flips the
  role in place and starts shipping to the surviving peers.

Rejoin (docs/SERVING.md §Running a replica set): a rebooted ex-primary
boots ``--follower-of NEW_PRIMARY``; :func:`reconcile_wal_with_primary`
truncates its WAL past the new primary's takeover point — that tail is
unacknowledged by construction (see above), and under the new lineage
those seqs name different mutations.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Optional

from knn_tpu import obs
from knn_tpu.fleet.wire import request_json
from knn_tpu.mutable.state import (
    MutationConflict,
    ReplicationGap,
    WALDivergence,
)
from knn_tpu.resilience.errors import DataError
from knn_tpu.resilience.retry import guarded_call

#: Shipper states an operator reads in /healthz ``fleet.followers``.
SHIP_OK = "ok"
SHIP_UNREACHABLE = "unreachable"
SHIP_DIVERGED = "diverged"        # parked: re-seed the follower
SHIP_BEHIND_FOLD = "behind_fold"  # parked: re-seed the follower
SHIP_REJECTED = "rejected"

#: ``knn_fleet_shipper_state`` gauge encoding — the numeric mirror of
#: the states above so a dashboard can alert on "any follower parked"
#: without string labels: 0 shipping/idle, 1 unreachable, 2 rejected,
#: 3 parked awaiting re-seed (behind the fold), 4 parked diverged.
SHIP_STATE_CODE = {
    SHIP_OK: 0,
    SHIP_UNREACHABLE: 1,
    SHIP_REJECTED: 2,
    SHIP_BEHIND_FOLD: 3,
    SHIP_DIVERGED: 4,
}

#: Replication-lag clock bound: stamped apply instants kept while no
#: follower has confirmed them (writes-in-flight, not history).
_MAX_SEQ_STAMPS = 4096

#: How long a parked (diverged/behind-fold) shipper waits before
#: re-probing its follower. Parking — not dying — is what makes the
#: documented recovery work WITHOUT a primary restart: once the operator
#: re-seeds and reboots the follower, the next probe resyncs (gap-409 →
#: cursor reset, digest overlap clean) and shipping resumes; until then
#: each probe is one cheap refused batch per interval. The env override
#: exists for the soak/drill harnesses (scripts/fleet_soak.py) which run
#: whole park→re-seed→resume cycles in seconds, not for production.
TERMINAL_RETRY_S = float(os.environ.get("KNN_TPU_SHIP_RETRY_S") or 30.0)


class WALShipper(threading.Thread):
    """One ordered push cursor: this primary -> one follower."""

    def __init__(self, fleet: "FleetReplica", url: str, *,
                 interval_s: float = 0.05, batch: int = 512,
                 timeout_s: float = 10.0):
        super().__init__(daemon=True,
                         name=f"knn-fleet-ship-{url.split('//')[-1]}")
        self.fleet = fleet
        self.url = url.rstrip("/")
        self.interval_s = interval_s
        self.batch = batch
        self.timeout_s = timeout_s
        # Start the cursor AT the fold point: records at or below it
        # live only in compacted generations (records_since would refuse
        # cursor 0 on any ever-compacted artifact). A follower that is
        # genuinely behind the fold answers the first shipment with a
        # gap-409 naming its real seq; the resync then lands below the
        # fold and records_since's typed refusal marks it re-seed —
        # exactly the one case that SHOULD be terminal.
        self.acked_seq = fleet.engine.folded_seq
        self.state = SHIP_OK
        self.last_error: Optional[str] = None
        self.shipped = 0
        self._halt = threading.Event()
        self._kick = threading.Event()

    def kick(self) -> None:
        self._kick.set()

    def stop(self) -> None:
        self._halt.set()
        self._kick.set()

    def lag(self) -> int:
        return max(0, self.fleet.engine.seq - self.acked_seq)

    def run(self) -> None:
        while not self._halt.is_set():
            self._kick.wait(self.interval_s)
            self._kick.clear()
            if self._halt.is_set():
                break
            try:
                self._ship_pending()
            except (WALDivergence, DataError) as e:
                # PARK this follower (its log diverged, or it is behind
                # the fold point): shipping more records could only
                # corrupt it further. The state is surfaced in /healthz
                # for the operator to re-seed + reboot the follower —
                # after which the slow re-probe below resyncs and
                # resumes, with no primary restart needed.
                if isinstance(e, WALDivergence):
                    self.state = SHIP_DIVERGED
                else:
                    self.state = SHIP_BEHIND_FOLD
                    # Re-anchor at the fold so the re-probe SHIPS
                    # instead of re-raising: a re-seeded follower
                    # (seq >= fold) then resyncs cleanly; one still
                    # genuinely behind answers gap-409 below the fold
                    # and parks here again.
                    self.acked_seq = self.fleet.engine.folded_seq
                self.last_error = str(e)
                self._note("parked")
                self._export_state()
                self._halt.wait(TERMINAL_RETRY_S)
                self._kick.clear()
            except Exception as e:  # noqa: BLE001 — a shipper must
                # never die on a transport blip; next interval retries.
                self.state = SHIP_UNREACHABLE
                self.last_error = f"{type(e).__name__}: {e}"
                self._note("error")
                self._export_state()

    def _ship_pending(self) -> None:
        while not self._halt.is_set():
            if self.fleet.engine.seq <= self.acked_seq:
                # Caught up: don't touch the epoch files at all — an
                # idle shipper would otherwise re-read and re-parse the
                # whole WAL every poll tick.
                if self.state is SHIP_UNREACHABLE:
                    self.state = SHIP_OK
                self._export_lag()
                return
            records, own_seq = self.fleet.engine.records_since(
                self.acked_seq, limit=self.batch)
            if not records:
                if self.state is SHIP_UNREACHABLE:
                    self.state = SHIP_OK
                self._export_lag()
                return
            status, doc = guarded_call(
                "fleet.wal_ship",
                lambda: request_json(
                    "POST", self.url + "/admin/wal-append",
                    {"records": records, "primary_seq": own_seq},
                    timeout=self.timeout_s,
                ),
            )
            if status == 200:
                self.acked_seq = int(doc.get("applied_seq", self.acked_seq))
                self.shipped += int(doc.get("applied", 0))
                self.state = SHIP_OK
                self.last_error = None
                self._note("ok")
                self.fleet.note_follower_ack(self.url, self.acked_seq)
            elif status == 409 and doc.get("diverged"):
                raise WALDivergence(
                    f"{self.url}: {doc.get('error', 'diverged')}")
            elif status == 409 and "applied_seq" in doc:
                # Seq gap from the follower's perspective (it rebooted,
                # or a prior batch was lost): resync the cursor to what
                # it reports and re-ship from there — never skip.
                self.acked_seq = int(doc["applied_seq"])
                self._note("resync")
                self.fleet.note_follower_ack(self.url, self.acked_seq)
            else:
                self.state = SHIP_REJECTED
                self.last_error = (f"HTTP {status}: "
                                   f"{doc.get('error', doc)}")
                self._note("rejected")
                return
            self._export_lag()

    def _note(self, outcome: str) -> None:
        obs.counter_add(
            "knn_fleet_wal_ship_total",
            help="WAL shipment batches by follower and outcome",
            follower=self.url, outcome=outcome,
        )

    def _export_lag(self) -> None:
        obs.gauge_set(
            "knn_fleet_replication_lag_seq", self.lag(),
            help="primary applied_seq minus this follower's acked seq",
            follower=self.url,
        )
        self._export_state()

    def _export_state(self) -> None:
        obs.gauge_set(
            "knn_fleet_shipper_state", SHIP_STATE_CODE.get(self.state, 1),
            help="per-follower shipper state: 0 shipping/idle, "
                 "1 unreachable, 2 rejected, 3 parked-reseed (behind "
                 "the fold), 4 parked-diverged",
            follower=self.url,
        )

    def export(self) -> dict:
        self._export_state()
        lag_ms = self.fleet.follower_lag_ms(self.url)
        return {
            "acked_seq": self.acked_seq,
            "lag": self.lag(),
            "lag_ms": lag_ms,
            "state": self.state,
            "last_error": self.last_error,
            "shipped": self.shipped,
        }


class FleetReplica:
    """This process's role in a replica set (``/healthz`` ``fleet``
    block). Built ONLY when ``--follower-of`` or ``--replicate-to`` was
    given — a plain serve constructs nothing from this package."""

    def __init__(self, engine, *, role: str,
                 primary_url: Optional[str] = None,
                 replicate_to=(), ack_mode: str = "any",
                 ack_timeout_s: float = 5.0,
                 ship_interval_s: float = 0.05):
        if role not in ("primary", "follower"):
            raise ValueError(f"fleet role must be primary or follower, "
                             f"got {role!r}")
        if ack_mode not in ("any", "none"):
            raise ValueError(f"ack_mode must be 'any' or 'none', got "
                             f"{ack_mode!r}")
        self.engine = engine
        self.role = role
        self.primary_url = primary_url
        self.ack_mode = ack_mode
        self.ack_timeout_s = float(ack_timeout_s)
        self.ship_interval_s = float(ship_interval_s)
        self.promoted_at_seq: Optional[int] = None
        self.promotions = 0
        #: Highest ``primary_seq`` a shipped batch has carried (follower
        #: side): how far ahead the primary reported being when it last
        #: shipped here — the read-staleness reference the serve layer
        #: annotates lagging answers with.
        self.primary_seq_seen = 0
        self._lock = threading.Lock()
        self._ack_cond = threading.Condition(self._lock)
        self._shippers: "dict[str, WALShipper]" = {}
        self._closed = False
        # The replication-lag clock (primary side): stamp each applied
        # seq's wall instant; a follower's ack of seq s then measures
        # apply->confirmed-replicated in milliseconds. Bounded: seqs at
        # or below every follower's ack are dropped on each ack.
        self._seq_stamps: "collections.OrderedDict[int, float]" = \
            collections.OrderedDict()
        self._lag_ms: "dict[str, float]" = {}
        engine.on_applied(self._on_applied)
        if role == "primary":
            for url in replicate_to:
                self._start_shipper(url)

    # -- primary side ------------------------------------------------------

    def _start_shipper(self, url: str) -> None:
        url = url.rstrip("/")
        existing = self._shippers.get(url)
        if existing is not None:
            if existing.is_alive():
                return
            existing.stop()  # a dead thread is replaced, never kept
        shipper = WALShipper(self, url, interval_s=self.ship_interval_s)
        self._shippers[url] = shipper
        shipper.start()

    def _on_applied(self) -> None:
        if self.role == "primary":
            with self._ack_cond:
                self._seq_stamps[self.engine.seq] = time.monotonic()
                while len(self._seq_stamps) > _MAX_SEQ_STAMPS:
                    self._seq_stamps.popitem(last=False)
        for s in list(self._shippers.values()):
            s.kick()

    def note_follower_ack(self, url: str, seq: int) -> None:
        now = time.monotonic()
        with self._ack_cond:
            # The newest stamped seq this ack covers gives the lag clock:
            # apply-instant -> replicated-confirmed for that write. Acks
            # usually confirm the latest seq, so the reversed scan is
            # O(1) in the common case.
            stamp = None
            for s in reversed(self._seq_stamps):
                if s <= seq:
                    stamp = self._seq_stamps[s]
                    break
            if stamp is not None:
                lag_ms = round((now - stamp) * 1e3, 3)
                self._lag_ms[url.rstrip("/")] = lag_ms
                obs.gauge_set(
                    "knn_fleet_replication_lag_ms", lag_ms,
                    help="ms from a write's primary apply to this "
                         "follower's ack of it (the replication-delay "
                         "SLI; seq-lag 0 with a stale clock means idle, "
                         "not behind)",
                    follower=url.rstrip("/"),
                )
            # Stamps every follower has confirmed can never clock a
            # future ack; drop them so the dict stays ack-bounded.
            floor = min((sh.acked_seq
                         for sh in self._shippers.values()), default=seq)
            while self._seq_stamps:
                first = next(iter(self._seq_stamps))
                if first > floor:
                    break
                del self._seq_stamps[first]
            self._ack_cond.notify_all()

    def follower_lag_ms(self, url: str) -> Optional[float]:
        """Last measured replication delay for one follower (ms), or
        None before the first confirmed ack."""
        with self._ack_cond:
            return self._lag_ms.get(url.rstrip("/"))

    def max_follower_seq(self) -> int:
        shippers = list(self._shippers.values())
        return max((s.acked_seq for s in shippers), default=0)

    def retention_floor(self) -> Optional[int]:
        """The lowest WAL cursor a LIVE follower still needs — the
        compactor's epoch-pruning floor (``Compactor(retention_floor=
        ...)``), closing the hazard where a fold silently strands a
        merely-lagging follower behind the fold point. Parked shippers
        (diverged / behind the fold) are excluded on purpose: they
        recover through the snapshot bootstrap path, not the WAL, and
        holding epochs for them would pin the log forever. None when
        there is nothing to hold for (follower role, or no shippers)."""
        if self.role != "primary":
            return None
        live = [s.acked_seq for s in self._shippers.values()
                if s.state not in (SHIP_DIVERGED, SHIP_BEHIND_FOLD)]
        if not live:
            return None
        return min(live)

    def wait_replicated(self, seq: int,
                        timeout_s: Optional[float] = None) -> bool:
        """Block until at least one follower has acknowledged ``seq``
        (the semi-synchronous half of the durability story). True
        immediately for ``ack_mode="none"`` or a primary with no
        followers configured (single-replica durability is then the
        local WAL, exactly as before this layer existed)."""
        if self.role != "primary" or self.ack_mode == "none":
            return True
        if not self._shippers:
            return True
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.ack_timeout_s)
        with self._ack_cond:
            while self.max_follower_seq() < seq:
                left = deadline - time.monotonic()
                if left <= 0 or self._closed:
                    return False
                self._ack_cond.wait(min(left, 0.1))
        return True

    # -- follower side -----------------------------------------------------

    def apply_wal_records(self, records, primary_seq=None) -> dict:
        """Apply one shipped batch in seq order (the ``/admin/wal-append``
        body). Raises the engine's typed taxonomy unchanged —
        :class:`ReplicationGap` carries the seq to resync from,
        :class:`WALDivergence`/validation errors mean the batch (and this
        replica) must not be trusted."""
        with self._lock:
            if self.role != "follower":
                raise MutationConflict(
                    "this replica is the primary; it ships WAL records, "
                    "it does not accept them (a second primary would be "
                    "a split brain)"
                )
        if not isinstance(records, list) or not records:
            raise ValueError('wal-append body needs a non-empty '
                             '"records" list')
        if primary_seq is not None:
            # The primary's own seq when it shipped this batch: the
            # freshest "how far behind am I" reference a follower has,
            # annotated onto lagging reads (staleness_seq).
            self.primary_seq_seen = max(self.primary_seq_seen,
                                        int(primary_seq))
        applied = skipped = 0
        for rec in sorted(records, key=lambda r: int(r.get("seq", 0))):
            result = self.engine.apply_replicated(rec)
            if result["applied"]:
                applied += 1
            else:
                skipped += 1
        return {"applied_seq": self.engine.seq, "applied": applied,
                "skipped": skipped}

    def promote(self, replicate_to=()) -> dict:
        """Follower -> primary, in place: record the takeover seq (the
        truncation point a rebooted ex-primary reconciles against),
        start shipping to the surviving peers, accept writes from the
        next request on."""
        with self._lock:
            if self.role == "primary":
                raise MutationConflict(
                    "already the primary; promote a FOLLOWER")
            self.role = "primary"
            self.primary_url = None
            self.promoted_at_seq = self.engine.seq
            self.promotions += 1
            for url in replicate_to or ():
                self._start_shipper(url)
        obs.counter_add(
            "knn_fleet_promotions_total",
            help="follower->primary promotions this process served",
        )
        return {"role": self.role, "seq": self.engine.seq,
                "promoted_at_seq": self.promoted_at_seq,
                "followers": sorted(self._shippers)}

    def staleness_seq(self) -> int:
        """How many acknowledged primary writes this follower has not yet
        applied, judged by the freshest shipped ``primary_seq`` (0 when
        caught up, when never shipped to, or on the primary itself)."""
        if self.role != "follower":
            return 0
        return max(0, self.primary_seq_seen - self.engine.seq)

    # -- shared ------------------------------------------------------------

    def export(self) -> dict:
        doc = {
            "role": self.role,
            "applied_seq": self.engine.seq,
            "ack_mode": self.ack_mode,
            "promoted_at_seq": self.promoted_at_seq,
        }
        if self.role == "follower":
            doc["primary_url"] = self.primary_url
            doc["primary_seq_seen"] = self.primary_seq_seen
            doc["staleness_seq"] = self.staleness_seq()
        else:
            doc["followers"] = {url: s.export()
                                for url, s in self._shippers.items()}
        return doc

    def close(self) -> None:
        with self._ack_cond:
            self._closed = True
            self._ack_cond.notify_all()
        for s in self._shippers.values():
            s.stop()
        for s in self._shippers.values():
            s.join(timeout=5)


def reconcile_wal_with_primary(root, primary_url: str, *,
                               timeout_s: float = 2.0,
                               attempts: int = 5) -> Optional[dict]:
    """The rejoin step, run BEFORE the engine boots and replays: ask the
    new primary for its takeover point and truncate this artifact's WAL
    past it (see :func:`knn_tpu.mutable.engine.truncate_wal` for why that
    tail is safe — and necessary — to drop). Best-effort: an unreachable
    primary returns None and boot proceeds on the local log alone (the
    wal-append digest overlap check still catches divergence later,
    typed)."""
    from knn_tpu.mutable.engine import truncate_wal

    last_err: Optional[str] = None
    for attempt in range(attempts):
        try:
            status, doc = request_json(
                "GET", primary_url.rstrip("/") + "/healthz",
                timeout=timeout_s)
        except OSError as e:
            last_err = f"{type(e).__name__}: {e}"
            time.sleep(min(0.2 * (attempt + 1), 1.0))
            continue
        fleet = doc.get("fleet") if isinstance(doc, dict) else None
        if not isinstance(fleet, dict):
            return {"reconciled": False,
                    "reason": f"primary /healthz ({status}) carries no "
                              f"fleet block"}
        cap = fleet.get("promoted_at_seq")
        if cap is None:
            # Never-promoted primary: the shared lineage IS its whole
            # log; nothing local can be divergent.
            return {"reconciled": True, "dropped": 0, "cap": None}
        dropped = truncate_wal(root, int(cap))
        return {"reconciled": True, "dropped": dropped, "cap": int(cap)}
    return {"reconciled": False,
            "reason": f"primary unreachable ({last_err})"}
