"""The router's view of its replicas: polled + passively demoted health.

One :class:`ReplicaSet` owns N replica base URLs. A background thread
polls each replica's ``/healthz`` (the capacity/quality/slo document the
serve process already exports) on an interval; a replica is **usable**
when that poll returned HTTP 200 (ready, not draining). Two demotion
paths, one promotion path:

- **active**: a poll that fails to connect, times out, or returns non-200
  marks the replica unusable;
- **passive**: a connection error during a live forward marks it unusable
  IMMEDIATELY (``note_failure``) — the drain path closes its listener
  before flipping healthz exactly so this fires on the first refused
  connect, not a poll interval later;
- a replica only becomes usable again through a successful poll (a lucky
  forward is not evidence of health — the poll reads the whole document).

**Shard groups** (mesh-sharded serving, docs/SERVING.md §Sharded
serving): a replica spec ``url1+url2+...`` declares that one logical
"replica" is N cooperating serve processes (a multi-process shard
group). The FIRST member is the head — the only URL the router forwards
to — and the group is usable only while EVERY member's poll is healthy:
a shard group missing one process cannot answer from its whole index,
so a partial group must look down to routing (the kill-one-member
drill degrades typed instead of serving wrong-shard answers). Role,
seq, and version read from the head's document.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from knn_tpu import obs
from knn_tpu.fleet.wire import request_json


class ReplicaState:
    """Everything the router knows about one replica (exported verbatim
    into the router's ``/healthz`` and ``/debug/fleet``)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.healthy = False
        self.ever_seen = False
        self.consecutive_failures = 0
        self.last_error: Optional[str] = None
        self.last_poll_unix: Optional[float] = None
        self.draining = False
        self.index_version: Optional[str] = None
        self.role: Optional[str] = None       # primary|follower|None
        self.applied_seq = 0
        self.promoted_at_seq: Optional[int] = None
        self.compaction_pressure: Optional[int] = None
        #: The replica's self-reported read capacity (its /healthz
        #: ``capacity.sustainable_qps``, from the fitted cost model) —
        #: the supply side of the router's autoscale comparison.
        self.sustainable_qps: Optional[float] = None
        #: The primary's own view of its shippers (``fleet.followers``
        #: from its /healthz): ``{follower_url: {state, acked_seq}}`` —
        #: how the router learns a follower parked behind the fold or
        #: diverged, i.e. the auto-bootstrap trigger.
        self.followers: Optional[dict] = None

    def export(self) -> dict:
        return {
            "healthy": self.healthy,
            "ever_seen": self.ever_seen,
            "consecutive_failures": self.consecutive_failures,
            "last_error": self.last_error,
            "draining": self.draining,
            "index_version": self.index_version,
            "role": self.role,
            "applied_seq": self.applied_seq,
            "compaction_pressure": self.compaction_pressure,
            "sustainable_qps": self.sustainable_qps,
            "followers": self.followers,
        }


class ReplicaSet:
    def __init__(self, urls, *, interval_s: float = 1.0,
                 poll_timeout_s: float = 2.0, on_poll=None, events=None):
        if not urls:
            raise ValueError("a replica set needs at least one replica "
                             "base URL")
        #: head url -> every member url (heads included), for specs of
        #: the ``url1+url2`` shard-group form; singleton replicas are
        #: absent (the common case pays one dict miss, nothing else).
        self.groups: "dict[str, tuple[str, ...]]" = {}
        heads, members_all = [], []
        for spec in urls:
            members = [u.rstrip("/") for u in str(spec).split("+") if u]
            if not members:
                raise ValueError(f"empty replica spec in {urls!r}")
            heads.append(members[0])
            members_all.extend(members)
            if len(members) > 1:
                self.groups[members[0]] = tuple(members)
        self.urls = heads
        if len(set(members_all)) != len(members_all):
            raise ValueError(f"duplicate replica URLs: {members_all}")
        self._members = members_all
        self.interval_s = float(interval_s)
        self.poll_timeout_s = float(poll_timeout_s)
        self._on_poll = on_poll
        #: Optional :class:`knn_tpu.fleet.events.FleetEventLog`: health
        #: TRANSITIONS (demote / passive-demote / rejoin) are audit
        #: events; steady states are not.
        self.events = events
        self._lock = threading.Lock()
        self._states = {u: ReplicaState(u) for u in self._members}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.poll_once()  # the router answers its first request informed
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="knn-fleet-health")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the poller must survive
                pass

    # -- polling -----------------------------------------------------------

    def poll_once(self) -> None:
        for url in self._members:
            self._poll(url)
        cb = self._on_poll
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — advisory (failover) hook
                pass

    def _poll(self, url: str) -> None:
        try:
            status, doc = request_json("GET", url + "/healthz",
                                       timeout=self.poll_timeout_s)
        except OSError as e:
            self._mark_down(url, f"{type(e).__name__}: {e}")
            return
        with self._lock:
            s = self._states[url]
            was_healthy, was_seen = s.healthy, s.ever_seen
            s.ever_seen = True
            s.last_poll_unix = time.time()
            s.draining = bool(doc.get("draining"))
            s.index_version = doc.get("index_version", s.index_version)
            fleet = doc.get("fleet")
            if isinstance(fleet, dict):
                s.role = fleet.get("role")
                s.applied_seq = int(fleet.get("applied_seq") or 0)
                s.promoted_at_seq = fleet.get("promoted_at_seq")
                followers = fleet.get("followers")
                if isinstance(followers, dict):
                    s.followers = {
                        u: {"state": d.get("state"),
                            "acked_seq": d.get("acked_seq"),
                            "lag": d.get("lag")}
                        for u, d in followers.items()
                        if isinstance(d, dict)
                    }
                else:
                    s.followers = None
            mutable = doc.get("mutable")
            if isinstance(mutable, dict):
                s.compaction_pressure = (int(mutable.get("delta_slots", 0))
                                         + int(mutable.get("tombstones", 0)))
            capacity = doc.get("capacity")
            if isinstance(capacity, dict):
                s.sustainable_qps = capacity.get("sustainable_qps")
            if status == 200:
                s.healthy = True
                s.consecutive_failures = 0
                s.last_error = None
            else:
                s.healthy = False
                s.consecutive_failures += 1
                s.last_error = (f"HTTP {status}"
                                + (" (draining)" if s.draining else ""))
            role, err = s.role, s.last_error
        if self.events is not None:
            if status == 200 and was_seen and not was_healthy:
                # First-ever success is boot discovery, not a rejoin:
                # the transition the audit log wants is down -> up on a
                # replica this router had already met.
                self.events.emit("rejoin", replica=url, role=role)
            elif status != 200 and was_healthy:
                self.events.emit("demote", replica=url, role=role,
                                 error=err)
        self._export_gauge(url)

    def _mark_down(self, url: str, err: str, *, event: str = "demote",
                   request_id=None) -> None:
        with self._lock:
            s = self._states[url]
            was_healthy = s.healthy
            role = s.role
            s.healthy = False
            s.consecutive_failures += 1
            s.last_error = err
            s.last_poll_unix = time.time()
        if self.events is not None and was_healthy:
            self.events.emit(event, request_id=request_id, replica=url,
                             role=role, error=err)
        self._export_gauge(url)

    def note_failure(self, url: str, err: str, request_id=None) -> None:
        """Passive demotion: a forward just failed at the transport layer
        — don't wait for the next poll to stop routing there.
        ``request_id`` (when the failing forward had one) stamps the
        audit event so the demotion joins back to the request that
        surfaced it."""
        self._mark_down(url.rstrip("/"), err, event="passive-demote",
                        request_id=request_id)

    def _group_ok(self, head: str) -> bool:
        """Caller holds ``self._lock``. A shard group is usable only
        while EVERY member is healthy — a partial group cannot answer
        from its whole index."""
        return all(self._states[m].healthy
                   for m in self.groups.get(head, (head,)))

    def is_healthy(self, url: str) -> bool:
        url = url.rstrip("/")
        with self._lock:
            if url not in self._states:
                return False
            return self._group_ok(url) if url in self.groups \
                else self._states[url].healthy

    def _export_gauge(self, url: str) -> None:
        obs.gauge_set(
            "knn_fleet_replica_healthy",
            1 if self._states[url].healthy else 0,
            help="1 while the replica's /healthz poll returns ready",
            replica=url,
        )

    # -- queries -----------------------------------------------------------

    def state(self, url: str) -> ReplicaState:
        return self._states[url.rstrip("/")]

    def usable_urls(self, start: int = 0) -> "list[str]":
        """Healthy replicas, rotated by ``start`` (the router's
        round-robin cursor) so consecutive reads spread the load."""
        with self._lock:
            up = [u for u in self.urls if self._group_ok(u)]
        if not up:
            return []
        k = start % len(up)
        return up[k:] + up[:k]

    def primary_url(self) -> Optional[str]:
        """The healthy replica reporting role=primary, or None (failover
        window, or an immutable fleet with no roles at all)."""
        return (self.primaries() or [None])[0]

    def primaries(self) -> "list[str]":
        with self._lock:
            return [u for u in self.urls
                    if self._group_ok(u)
                    and self._states[u].role == "primary"]

    def down_primary(self) -> Optional[str]:
        """The replica whose LAST seen role was primary but which is now
        unusable — the failover trigger (None while a healthy primary
        exists)."""
        with self._lock:
            healthy_primary = any(
                self._group_ok(u)
                and self._states[u].role == "primary" for u in self.urls)
            if healthy_primary:
                return None
            for u in self.urls:
                if self._states[u].role == "primary":
                    return u
        return None

    def most_caught_up(self, exclude=()) -> Optional[str]:
        """The healthy follower with the highest ``applied_seq`` — with
        semi-synchronous ack it holds every acknowledged write, which is
        what makes promoting it lossless."""
        exclude = {u.rstrip("/") for u in exclude}
        with self._lock:
            candidates = [
                (self._states[u].applied_seq, u) for u in self.urls
                if u not in exclude and self._group_ok(u)
                and self._states[u].role == "follower"
            ]
        if not candidates:
            return None
        return max(candidates)[1]

    def export(self) -> dict:
        with self._lock:
            states = {u: self._states[u].export() for u in self.urls}
            for head, members in self.groups.items():
                states[head]["shard_group"] = {
                    "members": list(members),
                    "unhealthy": [m for m in members
                                  if not self._states[m].healthy],
                }
                # The exported health of a grouped replica is the
                # GROUP's usability, not just the head's poll.
                states[head]["healthy"] = self._group_ok(head)
        primaries = [u for u, s in states.items()
                     if s["healthy"] and s["role"] == "primary"]
        primary_seq = max((s["applied_seq"] for s in states.values()
                           if s["role"] == "primary"), default=None)
        lag = None
        if primary_seq is not None:
            lag = {u: max(0, primary_seq - s["applied_seq"])
                   for u, s in states.items() if s["role"] == "follower"}
            for u, v in lag.items():
                obs.gauge_set(
                    "knn_fleet_replication_lag_seq", v,
                    help="primary applied_seq minus this follower's "
                         "acked seq",
                    follower=u,
                )
        return {
            "replicas": states,
            "usable": sum(1 for s in states.values() if s["healthy"]),
            "primary": primaries[0] if len(primaries) == 1 else None,
            "split_brain": primaries if len(primaries) > 1 else None,
            "lag": lag,
        }
