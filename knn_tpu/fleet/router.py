"""The fleet router (`knn_tpu route`): a thin HTTP front-end over N
replicas (docs/SERVING.md §Running a replica set).

Routing rules (each one line of the robustness story):

- **reads** (``/predict``, ``/kneighbors``) go to a usable replica
  (round-robin); a transport failure or retryable status (429/5xx)
  retries on a DIFFERENT replica — reads are idempotent, so they retry
  freely. Optionally a tail read is **hedged**: if the first replica has
  not answered within a p99-derived delay, a second attempt races it on
  another replica and the first acceptable answer wins.
- **writes** (``/insert``, ``/delete``) go to the ONE primary. A
  connect-refused forward (proven never sent) demotes the primary and
  returns a typed 503 — the failover window; anything that reached the
  wire is NEVER blindly re-sent (an indeterminate mutation re-sent is a
  duplicate). No primary (or two — split brain) is a typed 503.
- **503 with a JSON body is the only total-failure answer**: the router
  returns it exactly when ZERO replicas are usable (or no primary, for
  writes) — never a traceback.
- ``POST /admin/reload`` flips ``index_version`` on EVERY replica or
  none: replicas reload sequentially through their own validated
  rollback path; the first failure rolls the already-flipped replicas
  back to the previous fleet-wide target.
- ``POST /admin/compact`` runs on at most ONE replica at a time, chosen
  by compaction debt (the ``/debug/capacity`` mutable block).
- ``POST /admin/promote`` (and ``--auto-failover``) promotes the
  most-caught-up usable follower.
- ``POST /admin/bootstrap`` (and, with ``--auto-failover``, the health
  poll) drives a parked follower — one the primary reports diverged or
  behind the fold — through the snapshot bootstrap
  (``knn_tpu.fleet.bootstrap``): the follower re-seeds from the
  primary's current generation and its shipper resumes on the next
  re-probe. This is the self-healing leg: a replica never stays
  terminally parked while a healthy primary can re-seed it.
- ``--scale-cmd`` arms the fleet autoscaler
  (:mod:`knn_tpu.control.autoscale`): each health poll compares the
  router's 30s offered read load against the fleet's summed
  self-reported ``sustainable_qps`` and, past the hysteresis bands,
  runs the operator's scale command to boot or drain one replica slot
  — the FIRST rung of the degradation order (docs/RESILIENCE.md):
  grow the fleet before any replica sheds or browns out.
- every 429/503 overload answer (relayed or originated) carries a
  ``Retry-After`` hint, and the access log records the request's
  admission ``class`` — the client-facing half of the overload
  control plane (docs/SERVING.md §Surviving an overload).

The router holds no model and no index — it is restartable at any time
with zero state loss (its only state is health, a round-robin cursor,
and the confirmed reload target).
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from knn_tpu import obs
from knn_tpu.fleet.events import FleetEventLog
from knn_tpu.fleet.health import ReplicaSet
from knn_tpu.fleet.wire import forward_bytes, request_json
from knn_tpu.obs import aggregate, reqtrace
from knn_tpu.resilience.retry import guarded_call

#: Statuses a READ may retry on another replica: the replica refused or
#: failed the request without serving it (429 overload, 503 draining,
#: 5xx failure). 4xx client errors pass through — a malformed body is
#: malformed everywhere.
_READ_RETRYABLE = frozenset({429, 500, 502, 503, 504})

#: Request bodies past this are rejected before buffering (the serve
#: process's own bound).
MAX_BODY_BYTES = 64 * 1024 * 1024

#: Hedge latency ring size (p99 over the last N read forwards).
_LATENCY_RING = 512

#: Minimum seconds between auto-bootstrap attempts on the SAME follower:
#: a re-seed that keeps failing (full disk, crashing follower) must not
#: be re-driven at health-poll rate. Matches the parked shipper's own
#: 30s re-probe cadence, so a successful re-seed is picked up within one
#: cooldown anyway. Env override is for the drill harness only.
_BOOTSTRAP_COOLDOWN_S = float(
    os.environ.get("KNN_TPU_BOOTSTRAP_COOLDOWN_S") or 30.0)

#: Shipper states that mean "this follower needs a snapshot re-seed, the
#: WAL alone cannot catch it up" (knn_tpu.fleet.replica park states).
_PARKED_STATES = frozenset({"behind_fold", "diverged"})


class RouterBusy(Exception):
    """A fleet-wide admin operation (reload/compact) is already running;
    mapped to HTTP 409."""


class RouterApp:
    def __init__(self, replicas, *, health_interval_s: float = 1.0,
                 poll_timeout_s: float = 2.0,
                 forward_timeout_s: float = 30.0,
                 admin_timeout_s: float = 300.0,
                 hedge: str = "off",
                 auto_failover: bool = False,
                 failover_after_s: float = 3.0,
                 flight_recorder_size: int = 256, slowest_k: int = 32,
                 access_log: Optional[str] = None,
                 event_log=None,
                 scale_cmd: Optional[str] = None,
                 scale_min: int = 1,
                 scale_max: Optional[int] = None,
                 scale_cooldown_s: float = 60.0,
                 history_dir: Optional[str] = None,
                 history_interval_s: float = 5.0,
                 history_retention_s: float = 3600.0,
                 alert_rules=None):
        # The fleet event audit log: None unless asked for — a router
        # booted without --event-log constructs no writer, no ring
        # (the zero-cost-when-off contract the overhead check pins).
        # ``event_log=True`` keeps the ring without a file (tests).
        self.events = (FleetEventLog(None if event_log is True
                                     else event_log)
                       if event_log else None)
        # The router's own flight recorder (same default as serve: on,
        # bounded, disable with flight_recorder_size=0). Its timelines
        # are the router tier of every stitched cross-tier trace.
        self.recorder = (reqtrace.FlightRecorder(flight_recorder_size,
                                                 slowest_k)
                         if flight_recorder_size > 0 else None)
        self.access_log = None
        if access_log:
            # Serve's AccessLog IS the contract (same line shape, same
            # off-hot-path discipline); imported lazily so a plain
            # router never touches the serve module.
            from knn_tpu.serve.server import AccessLog

            self.access_log = AccessLog(access_log)
        self.set = ReplicaSet(replicas, interval_s=health_interval_s,
                              poll_timeout_s=poll_timeout_s,
                              on_poll=self._on_poll,
                              events=self.events)
        self.forward_timeout_s = float(forward_timeout_s)
        self.admin_timeout_s = float(admin_timeout_s)
        self.hedge = self._parse_hedge(hedge)
        self.auto_failover = bool(auto_failover)
        self.failover_after_s = float(failover_after_s)
        self.started_unix = time.time()
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._lat_ring = np.zeros(_LATENCY_RING, np.float64)
        self._lat_pos = 0
        self._lat_lock = threading.Lock()
        self._admin_lock = threading.Lock()   # one reload/compact at a time
        self._confirmed_index: Optional[str] = None
        self._failover_lock = threading.Lock()
        self._primary_down_since: Optional[float] = None
        self._failover_inflight = False
        # Failover-window SLI: (monotonic, unix, request_id) of the first
        # failover-typed write 503; cleared by the first write 200, which
        # observes the span into knn_fleet_failover_window_ms.
        self._fo_onset = None
        self.failovers = 0
        self.reloads = 0
        self.reseeds = 0
        # Auto-bootstrap state (plain containers — a flagless router
        # constructs no threads and no instruments for this): which
        # followers have a re-seed inflight, and when each last started
        # (the cooldown that keeps a failing bootstrap from hot-looping
        # at poll rate).
        self._bootstrap_lock = threading.Lock()
        self._bootstrap_inflight: "set[str]" = set()
        self._bootstrap_last: "dict[str, float]" = {}
        # Fleet autoscaler (knn_tpu/control/autoscale.py,
        # docs/SERVING.md §Surviving an overload): the DEGRADATION-ORDER
        # first resort — grow the fleet before any replica sheds or
        # browns out. No --scale-cmd (the default) constructs NOTHING:
        # no control import, no offered-load ring, no autoscale state
        # (scripts/check_disabled_overhead.py pins it).
        self.scale_cmd = scale_cmd
        self.scales = 0
        if scale_cmd is not None:
            from knn_tpu.control.autoscale import AutoscalePolicy
            from knn_tpu.obs.slo import SecondRing

            self.autoscale = AutoscalePolicy(
                scale_min, scale_max or len(self.set.urls),
                cooldown_s=scale_cooldown_s)
            # Offered READ load, counted at forward time (before any
            # shed/failure): the demand side the fleet's summed
            # sustainable QPS is compared against.
            self._offered = SecondRing(1, 60)
            self._scale_lock = threading.Lock()
            self._scale_inflight = False
        else:
            self.autoscale = None
            self._offered = None
        # Durable fleet history + alerting (obs/history.py, obs/alerts.py):
        # the router's recorder scrapes every usable member's /metrics
        # into its OWN segment ring with a {replica} label, so fleet-wide
        # history survives member death. Neither flag (the default)
        # constructs NOTHING — no obs.history/alerts import, no
        # knn_history_*/knn_alerts_* instruments, no knn-history/
        # knn-alerts thread (scripts/check_disabled_overhead.py pins it).
        if history_dir is not None or alert_rules:
            from knn_tpu.obs.alerts import AlertEngine
            from knn_tpu.obs.history import HistoryRecorder

            # slo=None: a router has no request-SLO tracker, so
            # burn_rate rules are a typed boot error here.
            self.alerts = (AlertEngine(
                alert_rules, slo=None, workload=None,
                recorder=self.recorder, events=self.events,
                history_dir=history_dir,
            ) if alert_rules else None)
            self.history = HistoryRecorder(
                history_dir, interval_s=history_interval_s,
                retention_s=history_retention_s, source="route",
                sample_fn=self._history_sample,
                on_sample=(
                    (lambda ts, view: self.alerts.evaluate(ts, view))
                    if self.alerts is not None else None),
            )
        else:
            self.history = None
            self.alerts = None
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="knn-fleet-hedge")
        self.set.start()

    def _history_sample(self) -> list:
        """One history snapshot: the router's own registry plus every
        usable member's scraped snapshot, each member record tagged with
        its ``{replica}`` label (the federated_metrics convention — raw
        per-replica values, never a lossy pre-sum). A member that fails
        its scrape is simply absent from this snapshot; an absence rule
        can page on exactly that."""
        records = list(aggregate.snapshot_registry(obs.registry()))
        for url in self.set.usable_urls():
            st, doc, _err = self._admin_call(
                "GET", url + "/metrics?format=json", None,
                timeout=self.set.poll_timeout_s)
            if st != 200 or not isinstance(doc.get("snapshot"), list):
                continue
            for rec in doc["snapshot"]:
                records.append(
                    {**rec, "labels": {**(rec.get("labels") or {}),
                                       "replica": url}})
        return records

    @staticmethod
    def _parse_hedge(hedge) -> Optional[float]:
        """``None`` = off, ``0.0`` = auto (p99-derived), >0 = fixed ms."""
        if hedge in (None, "off", "", False):
            return None
        if hedge == "auto":
            return 0.0
        ms = float(hedge)
        if ms <= 0:
            raise ValueError(f"hedge delay must be > 0 ms, got {ms}")
        return ms

    def close(self) -> None:
        if self.history is not None:
            # First, while the pool + replica set still answer: close()
            # takes a final snapshot for the post-mortem record.
            self.history.close()
        if self.alerts is not None:
            self.alerts.close()
        self.set.close()
        self._pool.shutdown(wait=False)
        if self.access_log is not None:
            self.access_log.close()
        if self.events is not None:
            self.events.close()

    # -- latency / hedging -------------------------------------------------

    def _note_latency(self, ms: float) -> None:
        with self._lat_lock:
            self._lat_ring[self._lat_pos % _LATENCY_RING] = ms
            self._lat_pos += 1

    def hedge_delay_s(self) -> Optional[float]:
        """The wait before firing a hedge: the configured fixed delay, or
        (auto) the observed read p99 — a hedge should only ever fire for
        genuine tail requests, so it costs ~1% duplicate work. Auto with
        under 50 observations returns None (no evidence, no hedging)."""
        if self.hedge is None:
            return None
        if self.hedge > 0:
            return self.hedge / 1e3
        with self._lat_lock:
            n = min(self._lat_pos, _LATENCY_RING)
            if n < 50:
                return None
            p99 = float(np.percentile(self._lat_ring[:n], 99))
        return max(p99, 1.0) / 1e3

    # -- forwarding --------------------------------------------------------

    def _next_rr(self) -> int:
        with self._rr_lock:
            self._rr += 1
            return self._rr

    def _attempt(self, url: str, path: str, body: Optional[bytes],
                 headers: dict, timeout_s: float, trace=None, hop: int = 1):
        """One forward to one replica. Returns ``("ok"|"retryable",
        url, status, raw_body)`` or ``("transport", url, error, None)``
        — and passively demotes the replica on a transport failure.
        ``hop`` numbers this attempt within its request and rides the
        ``x-knn-hop`` header, so the replica's own timeline records
        WHICH router attempt reached it; ``trace`` (when the router's
        recorder is on) gets one attempt record with the forward wall,
        the outcome, and the retry reason."""
        if trace is not None or hop != 1:
            headers = dict(headers, **{"x-knn-hop": str(hop)})
        t0 = time.monotonic()
        try:
            status, raw = guarded_call(
                "fleet.forward",
                lambda: forward_bytes("POST", url + path, body,
                                      timeout_s, headers),
                attempts=1, classify=False,
            )
        except Exception as e:  # noqa: BLE001 — transport taxonomy below
            ms = (time.monotonic() - t0) * 1e3
            rid = trace.request_id if trace is not None else None
            self.set.note_failure(url, f"{type(e).__name__}: {e}",
                                  request_id=rid)
            self._count_forward(url, "transport_error")
            if trace is not None:
                trace.attempt(url, False, ms, hop=hop,
                              error=f"{type(e).__name__}: {e}")
            return ("transport", url, e, None)
        ms = (time.monotonic() - t0) * 1e3
        if status in _READ_RETRYABLE:
            self._count_forward(url, f"http_{status}")
            if trace is not None:
                trace.attempt(url, False, ms, hop=hop, status=status,
                              error=f"retryable HTTP {status}")
            return ("retryable", url, status, raw)
        self._note_latency(ms)
        self._count_forward(url, "ok" if status == 200 else
                            f"http_{status}")
        if trace is not None:
            trace.attempt(url, status == 200, ms, hop=hop, status=status)
        return ("ok", url, status, raw)

    @staticmethod
    def _count_forward(url: str, outcome: str) -> None:
        obs.counter_add(
            "knn_fleet_forward_total",
            help="router->replica forwards by replica and outcome",
            replica=url, outcome=outcome,
        )

    def forward_read(self, path: str, body: Optional[bytes],
                     headers: dict, trace=None):
        """Route one read; returns ``(status, raw_json_body, replica)``.
        Walks the usable replicas (round-robin start), retrying transport
        failures and retryable statuses on the NEXT replica; optionally
        hedges the first attempt. 503 typed only when zero replicas are
        usable or every one failed.

        ``trace`` (the router's own :class:`RequestTrace`) records two
        phases — ``route`` (candidate selection) and ``dispatch`` (the
        whole forward walk) — with one attempt record per replica tried,
        so the phase walls sum to ~the router-observed request wall (the
        invariant the fleet soak's forensics phase pins)."""
        if self._offered is not None:
            # Offered-load sample for the autoscaler: counted before any
            # shed/failure so demand the fleet turned away still counts.
            self._offered.add(1)
        if trace is not None:
            trace.phase_start("route")
        candidates = self.set.usable_urls(start=self._next_rr())
        if trace is not None:
            trace.phase_end("route")
        if not candidates:
            return self._none_usable("read")
        failures = []
        hedge_s = self.hedge_delay_s()
        if trace is not None:
            trace.phase_start("dispatch")
        try:
            i = 0
            while i < len(candidates):
                url = candidates[i]
                if i == 0 and hedge_s is not None and len(candidates) > 1:
                    result = self._hedged_attempt(candidates, path, body,
                                                  headers, hedge_s,
                                                  trace=trace)
                    i += 2  # the hedged round consumed candidates[0] AND [1]
                else:
                    result = self._attempt(url, path, body, headers,
                                           self.forward_timeout_s,
                                           trace=trace, hop=i + 1)
                    i += 1
                kind, where, detail, raw = result
                if kind == "ok":
                    return detail, raw, where
                failures.append(f"{where}: "
                                f"{detail if kind == 'retryable' else f'{type(detail).__name__}: {detail}'}")
                obs.counter_add(
                    "knn_fleet_retries_total",
                    help="reads re-routed to a different replica after a "
                         "transient failure",
                    kind="read",
                )
                if kind == "retryable" and len(candidates) == 1:
                    # Nothing to retry on; surface the replica's own
                    # status.
                    return detail, raw, where
            return 503, _json_body({
                "error": f"every usable replica failed the read: "
                         f"{'; '.join(failures[:4])}",
                "replicas_tried": len(candidates),
            }), None
        finally:
            if trace is not None:
                trace.phase_end("dispatch")

    def _hedged_attempt(self, candidates, path, body, headers,
                        hedge_s: float, trace=None):
        """Race the first two candidates: fire #1, wait ``hedge_s``, fire
        #2 if #1 is still out — OR if #1 failed fast (the backup then
        doubles as the cross-replica retry: the caller consumed both
        candidates, so skipping #2 on a fast failure would silently
        shrink the retry walk). Returns the first acceptable answer.

        The losing attempt is never silently dropped: a done-callback
        drains its result (the worker already read the whole response
        off the socket) and counts
        ``knn_fleet_hedge_wasted_total{outcome}`` — the duplicate
        downstream work the hedge bought, i.e. the cost side of the
        hedging SLI."""
        rid = trace.request_id if trace is not None else None
        f1 = self._pool.submit(self._attempt, candidates[0], path, body,
                               headers, self.forward_timeout_s,
                               trace=trace, hop=1)
        first_failure = None
        hedged = False
        try:
            result = f1.result(timeout=hedge_s)
            if result[0] == "ok":
                return result
            first_failure = result
        except concurrent.futures.TimeoutError:
            hedged = True
            obs.counter_add("knn_fleet_hedges_total",
                            help="hedged tail reads by outcome",
                            outcome="fired")
            if trace is not None:
                trace.event("hedge-fired", slow_replica=candidates[0],
                            hedge_replica=candidates[1])
            if self.events is not None:
                self.events.emit("hedge-fired", request_id=rid,
                                 slow_replica=candidates[0],
                                 hedge_replica=candidates[1])
        f2 = self._pool.submit(self._attempt, candidates[1], path, body,
                               headers, self.forward_timeout_s,
                               trace=trace, hop=2)
        pending = {f2} if first_failure is not None else {f1, f2}
        last = first_failure
        while pending:
            done, pending = concurrent.futures.wait(
                pending, return_when=concurrent.futures.FIRST_COMPLETED)
            for fut in done:
                result = fut.result()
                if result[0] == "ok":
                    if hedged:
                        won = fut is f2
                        obs.counter_add("knn_fleet_hedges_total",
                                        help="hedged tail reads by "
                                             "outcome",
                                        outcome="won" if won else "lost")
                        if trace is not None:
                            trace.event("hedge-won" if won
                                        else "hedge-lost")
                    for p in pending:
                        self._drain_loser(p)
                    return result
                last = result
        return last

    @staticmethod
    def _drain_loser(fut) -> None:
        """The race decided; the other attempt still owns a socket and a
        worker. ``cancel()`` only helps if it never started — otherwise
        the attempt runs to completion and its outcome used to vanish.
        A done-callback consumes the result (``_attempt`` returns, never
        raises) so the duplicate work is drained, closed, and COUNTED
        instead of silently discarded."""

        def _consume(f):
            if f.cancelled():
                outcome = "cancelled"
            else:
                try:
                    kind = f.result()[0]
                except Exception:  # noqa: BLE001 — belt for future edits
                    kind = "transport"
                outcome = "completed" if kind == "ok" else "failed"
            obs.counter_add(
                "knn_fleet_hedge_wasted_total",
                help="losing hedge attempts by how they ended — the "
                     "duplicate replica work hedging paid for",
                outcome=outcome,
            )

        fut.add_done_callback(_consume)
        fut.cancel()

    def forward_write(self, path: str, body: Optional[bytes],
                      headers: dict, trace=None):
        """Route one mutation to the primary — exactly once on the wire.
        Retry policy: only a PROVEN-not-applied failure (the connect was
        refused, so no byte reached the primary) is safe to re-send, and
        even then the primary is demoted and the answer is the typed 503
        failover window — the client (or the soak's writer loop) retries
        after the promote, against a new primary. Anything indeterminate
        (timeout mid-request, connection reset after send) returns a
        typed 502: re-sending could apply the mutation twice.

        Failover-window SLI: the FIRST failover-typed 503 (primary
        refused, or no usable primary — NOT split brain, which an
        operator must resolve) arms an onset clock; the first write 200
        after it observes ``knn_fleet_failover_window_ms`` and stamps a
        ``failover-window`` audit event — the measured span writes were
        actually refused, as a client saw it."""
        rid = (trace.request_id if trace is not None
               else headers.get("x-request-id"))
        if trace is not None:
            trace.phase_start("route")
        primaries = self.set.primaries()  # cheap: no export()/gauge
        # churn on the per-write hot path
        if trace is not None:
            trace.phase_end("route")
        if len(primaries) > 1:
            return 503, _json_body({
                "error": f"split brain: {primaries} both claim primary; "
                         f"refusing writes until an operator demotes "
                         f"one",
            }), None
        primary = primaries[0] if primaries else None
        if primary is None:
            self._arm_failover_onset(rid)
            return 503, _json_body({
                "error": "no usable primary (failover in progress or "
                         "the fleet is read-only); retry after promote",
                "down_primary": self.set.down_primary(),
            }), None
        if trace is not None:
            trace.phase_start("dispatch")
            headers = dict(headers, **{"x-knn-hop": "1"})
        t0 = time.monotonic()
        try:
            try:
                status, raw = guarded_call(
                    "fleet.forward",
                    lambda: forward_bytes("POST", primary + path, body,
                                          self.forward_timeout_s,
                                          headers),
                    attempts=1, classify=False,
                )
            except ConnectionRefusedError as e:
                # Proven never sent: the listener is gone (the drain
                # path closes it first, a SIGKILL'd process loses it
                # with the process). Demote now so the failover clock
                # starts.
                self.set.note_failure(primary,
                                      f"ConnectionRefusedError: {e}",
                                      request_id=rid)
                self._count_forward(primary, "refused")
                self._arm_failover_onset(rid)
                if trace is not None:
                    trace.attempt(primary, False,
                                  (time.monotonic() - t0) * 1e3, hop=1,
                                  error=f"ConnectionRefusedError: {e}")
                return 503, _json_body({
                    "error": f"primary {primary} refused the "
                             f"connection; write not applied — retry "
                             f"after failover",
                }), primary
            except Exception as e:  # noqa: BLE001 — indeterminate
                refused = isinstance(getattr(e, "reason", None),
                                     ConnectionRefusedError)
                self.set.note_failure(primary, f"{type(e).__name__}: {e}",
                                      request_id=rid)
                self._count_forward(primary, "refused" if refused
                                    else "transport_error")
                if trace is not None:
                    trace.attempt(primary, False,
                                  (time.monotonic() - t0) * 1e3, hop=1,
                                  error=f"{type(e).__name__}: {e}")
                if refused:
                    self._arm_failover_onset(rid)
                    return 503, _json_body({
                        "error": f"primary {primary} refused the "
                                 f"connection; write not applied — "
                                 f"retry after failover",
                    }), primary
                return 502, _json_body({
                    "error": f"write to {primary} failed mid-flight "
                             f"({type(e).__name__}: {e}); the outcome "
                             f"is INDETERMINATE — re-read before "
                             f"re-sending (a blind retry could apply "
                             f"it twice)",
                }), primary
        finally:
            if trace is not None:
                trace.phase_end("dispatch")
        self._count_forward(primary, "ok" if status == 200
                            else f"http_{status}")
        if trace is not None:
            trace.attempt(primary, status == 200,
                          (time.monotonic() - t0) * 1e3, hop=1,
                          status=status)
        if status == 200:
            self._close_failover_window(rid)
        return status, raw, primary

    def _arm_failover_onset(self, rid) -> None:
        with self._failover_lock:
            if self._fo_onset is None:
                self._fo_onset = (time.monotonic(), time.time(), rid)

    def _close_failover_window(self, rid) -> None:
        """A write succeeded: if a failover-typed 503 opened a window,
        this 200 closes it — observe the span and stamp the audit event
        that joins onset request to recovery request."""
        with self._failover_lock:
            onset = self._fo_onset
            self._fo_onset = None
        if onset is None:
            return
        window_ms = round((time.monotonic() - onset[0]) * 1e3, 3)
        obs.histogram_observe(
            "knn_fleet_failover_window_ms", window_ms,
            help="write unavailability span: first failover-typed 503 "
                 "to the first write 200 after it (as a client saw it)",
        )
        if self.events is not None:
            self.events.emit("failover-window", request_id=rid,
                             window_ms=window_ms, onset_unix=onset[1],
                             onset_request_id=onset[2])

    def _none_usable(self, kind: str):
        export = self.set.export()
        detail = {u: s["last_error"]
                  for u, s in export["replicas"].items()}
        return 503, _json_body({
            "error": f"zero usable replicas for this {kind}",
            "replicas": detail,
        }), None

    # -- coordinated admin -------------------------------------------------

    def coordinated_reload(self, index: Optional[str],
                           rollback_to: Optional[str] = None,
                           request_id: Optional[str] = None) -> dict:
        """Flip every replica's index or none. Sequential prepare/confirm
        over each replica's own validated reload: the Nth failure rolls
        replicas 1..N-1 back to the previous fleet-wide target — the
        last CONFIRMED reload this router drove, overridable per-call
        with ``rollback_to`` (the operator's lever after a router
        restart, which loses the in-memory confirmed target and would
        otherwise fall back to each replica's boot index), else their
        boot index. All-or-nothing is judged over the WHOLE set — an
        unreachable replica aborts, so a crash-stop mid-reload leaves
        the survivors consistent. A fleet that is ALREADY divergent
        (replicas reporting different versions) refuses the reload
        before flipping anything: rolling back from an unknown mixed
        state could only compound the divergence."""
        if not self._admin_lock.acquire(blocking=False):
            raise RouterBusy("a fleet-wide reload or compaction is "
                             "already in progress")
        try:
            if self.events is not None:
                self.events.emit("coordinated-reload-begin",
                                 request_id=request_id, index=index)
            targets = list(self.set.urls)
            # Divergence pre-check over the replicas that ANSWER — an
            # unreachable one is not evidence of divergence (the flip
            # sequence aborts + rolls back on it anyway, which is the
            # crash-stop contract the fleet soak pins).
            pre = {}
            for url in targets:
                st, doc, _err = self._admin_call("GET", url + "/healthz",
                                                 None)
                if st is not None and doc.get("index_version"):
                    pre[url] = doc["index_version"]
            if len(set(pre.values())) > 1:
                return {"status": 409, "body": {
                    "error": f"fleet versions already diverge: {pre} — "
                             f"fix the stragglers (or remove them from "
                             f"the set) before a coordinated reload",
                    "rolled_back": False,
                }}
            if rollback_to is not None:
                self._confirmed_index = rollback_to
            flipped: "list[str]" = []
            versions: "dict[str, str]" = {}
            payload = {"index": index} if index else {}
            for url in targets:
                st, doc, err = self._admin_call(
                    "POST", url + "/admin/reload", payload)
                if st != 200:
                    rollback = self._rollback_reload(flipped)
                    obs.counter_add("knn_fleet_reloads_total",
                                    help="coordinated fleet reloads by "
                                         "outcome",
                                    outcome="rolled_back")
                    if self.events is not None:
                        self.events.emit("coordinated-reload-rollback",
                                         request_id=request_id,
                                         failed_on=url,
                                         flipped=list(flipped))
                    return {
                        "status": 502,
                        "body": {
                            "error": f"reload failed on {url}: "
                                     f"{err or doc.get('error', doc)}",
                            "rolled_back": True,
                            "flipped_then_rolled_back": flipped,
                            "rollback": rollback,
                        },
                    }
                flipped.append(url)
                versions[url] = doc.get("index_version")
            if len(set(versions.values())) > 1:
                rollback = self._rollback_reload(flipped)
                obs.counter_add("knn_fleet_reloads_total",
                                help="coordinated fleet reloads by "
                                     "outcome",
                                outcome="rolled_back")
                if self.events is not None:
                    self.events.emit("coordinated-reload-rollback",
                                     request_id=request_id,
                                     reason="divergent versions",
                                     versions=versions)
                return {"status": 502, "body": {
                    "error": f"replicas flipped to DIFFERENT versions "
                             f"{versions} — the artifact paths do not "
                             f"name one build; rolled back",
                    "rolled_back": True, "rollback": rollback,
                }}
            self._confirmed_index = index
            self.reloads += 1
            obs.counter_add("knn_fleet_reloads_total",
                            help="coordinated fleet reloads by outcome",
                            outcome="ok")
            if self.events is not None:
                self.events.emit(
                    "coordinated-reload-commit", request_id=request_id,
                    index_version=next(iter(versions.values()), None),
                    replicas=len(flipped))
            return {"status": 200, "body": {
                "index_version": next(iter(versions.values()), None),
                "replicas": len(flipped),
            }}
        finally:
            self._admin_lock.release()

    def _admin_call(self, method: str, url: str, payload,
                    timeout: Optional[float] = None):
        try:
            st, doc = request_json(
                method, url, payload,
                timeout=timeout if timeout is not None
                else self.admin_timeout_s)
            return st, doc, None
        except OSError as e:
            return None, {}, f"{type(e).__name__}: {e}"

    def _rollback_reload(self, flipped) -> dict:
        """Re-point already-flipped replicas at the previous confirmed
        target (their boot index when none): best-effort, per-replica
        outcome reported — a replica that ALSO fails rollback is left
        marked unhealthy for the operator."""
        payload = ({"index": self._confirmed_index}
                   if self._confirmed_index else {})
        out = {}
        for url in flipped:
            st, doc, err = self._admin_call(
                "POST", url + "/admin/reload", payload)
            out[url] = "ok" if st == 200 else (err or
                                               doc.get("error", f"HTTP {st}"))
            if st != 200:
                self.set.note_failure(url, f"rollback reload failed: "
                                           f"{out[url]}")
        return out

    def coordinated_compact(self, replica: Optional[str] = None,
                            request_id: Optional[str] = None) -> dict:
        """Run one compaction on ONE replica: the named one, else the
        highest compaction debt (delta slots + tombstones from each
        usable replica's ``/debug/capacity``). Serialized fleet-wide —
        compaction doubles a replica's working set while it folds, and
        one replica at a time is the capacity contract."""
        if not self._admin_lock.acquire(blocking=False):
            raise RouterBusy("a fleet-wide reload or compaction is "
                             "already in progress")
        try:
            target = replica
            debts = {}
            if target is None:
                for url in self.set.usable_urls():
                    st, doc, err = self._admin_call(
                        "GET", url + "/debug/capacity", None)
                    blk = doc.get("mutable") if st == 200 else None
                    if isinstance(blk, dict):
                        debts[url] = (int(blk.get("delta_slots", 0))
                                      + int(blk.get("tombstones", 0)))
                if not debts:
                    return {"status": 503, "body": {
                        "error": "no usable mutable replica reports "
                                 "compaction debt",
                    }}
                target = max(debts, key=debts.get)
            st, doc, err = self._admin_call(
                "POST", target + "/admin/compact", {})
            if st is None:
                return {"status": 502, "body": {
                    "error": f"compaction on {target} failed at the "
                             f"transport layer: {err}",
                    "replica": target,
                }}
            body = {**doc, "replica": target, "debts": debts or None}
            if st == 200 and doc.get("compacted"):
                if int(doc.get("epochs_held") or 0) > 0 \
                        and self.events is not None:
                    # The primary deferred WAL pruning for a lagging
                    # follower — audit it so "why is disk growing"
                    # joins to the follower holding the floor.
                    self.events.emit(
                        "epoch-retention-hold",
                        request_id=request_id, replica=target,
                        epochs_held=int(doc["epochs_held"]),
                        retention_floor=doc.get("retention_floor"),
                        folded_seq=doc.get("folded_seq"))
                body["propagated"] = self._propagate_fold(target, doc)
            return {"status": st, "body": body}
        finally:
            self._admin_lock.release()

    def _propagate_fold(self, compacted: str, doc: dict):
        """After a PRIMARY compaction, fold the same point into each
        usable follower whose replication cursor has already passed it
        (so its own compaction folds a superset — the fleet's fold
        points advance together instead of each follower carrying an
        ever-longer WAL tail). Best-effort and per-follower reported: a
        follower that declines (mid-reload, still behind) just compacts
        later. Compacting a FOLLOWER propagates nothing."""
        if self.set.state(compacted).role != "primary":
            return None
        fold_seq = doc.get("folded_seq")
        if fold_seq is None:
            return None
        out = {}
        self.set.poll_once()  # applied_seq must be current, not stale
        for url in self.set.usable_urls():
            if url == compacted:
                continue
            s = self.set.state(url)
            if s.role != "follower":
                continue
            if s.applied_seq < int(fold_seq):
                out[url] = {"skipped": f"cursor {s.applied_seq} behind "
                                       f"fold point {fold_seq}"}
                continue
            pst, pdoc, perr = self._admin_call(
                "POST", url + "/admin/compact", {})
            out[url] = {"status": pst,
                        "compacted": bool((pdoc or {}).get("compacted")),
                        "folded_seq": (pdoc or {}).get("folded_seq")}
            if pst != 200:
                out[url]["error"] = perr or (pdoc or {}).get(
                    "error", f"HTTP {pst}")
        return out or None

    def promote(self, replica: Optional[str] = None,
                trigger: str = "manual",
                request_id: Optional[str] = None) -> dict:
        """Promote ``replica`` (default: the most-caught-up usable
        follower) and hand it the surviving peers to ship to. The
        promote call itself is bounded short — it flips a role in place,
        no index work — so a stalled target cannot pin the caller (the
        auto-failover path runs this; see :meth:`_maybe_failover`)."""
        target = replica.rstrip("/") if replica else None
        if target is None:
            target = self.set.most_caught_up(
                exclude=[u for u in (self.set.down_primary(),) if u])
        if target is None:
            return {"status": 503, "body": {
                "error": "no usable follower to promote",
            }}
        peers = [u for u in self.set.urls if u != target]
        st, doc, err = self._admin_call(
            "POST", target + "/admin/promote", {"replicate_to": peers},
            timeout=min(self.admin_timeout_s, 10.0))
        if st != 200:
            return {"status": 502 if st is None else st, "body": {
                "error": f"promote on {target} failed: "
                         f"{err or doc.get('error', doc)}",
                "replica": target,
            }}
        self.failovers += 1
        obs.counter_add("knn_fleet_failovers_total",
                        help="promotions the router drove, by trigger",
                        trigger=trigger)
        if self.events is not None:
            self.events.emit(
                "auto-failover" if trigger == "auto" else "promote",
                request_id=request_id, replica=target,
                promoted_at_seq=doc.get("promoted_at_seq"),
                trigger=trigger)
        self.set.poll_once()  # writes resume as soon as the poll sees it
        return {"status": 200, "body": {**doc, "replica": target,
                                        "trigger": trigger}}

    def _maybe_failover(self) -> None:
        """Poll hook: with ``--auto-failover``, promote once the primary
        has been unusable for ``failover_after_s`` straight. The promote
        runs OFF the poll thread: health polling is the only path that
        re-promotes replicas to usable, so a stalled promote call must
        never freeze it."""
        if not self.auto_failover:
            return
        with self._failover_lock:
            down = self.set.down_primary()
            if down is None:
                self._primary_down_since = None
                return
            now = time.monotonic()
            if self._primary_down_since is None:
                self._primary_down_since = now
                return
            if now - self._primary_down_since < self.failover_after_s:
                return
            if self._failover_inflight:
                return
            self._failover_inflight = True
            self._primary_down_since = None

        def work():
            try:
                result = self.promote(trigger="auto")
                if result["status"] != 200:
                    # Nothing promotable yet; the next poll re-arms the
                    # clock.
                    obs.counter_add("knn_fleet_failovers_total",
                                    help="promotions the router drove, "
                                         "by trigger",
                                    trigger="auto_failed")
            finally:
                with self._failover_lock:
                    self._failover_inflight = False

        threading.Thread(target=work, daemon=True,
                         name="knn-fleet-failover").start()

    def _on_poll(self) -> None:
        """The health poller's advisory hook: both self-healing legs run
        here, each internally gated on ``--auto-failover`` and each
        moving real work off the poll thread."""
        self._maybe_failover()
        self._maybe_bootstrap()
        self._maybe_autoscale()

    def _maybe_bootstrap(self) -> None:
        """Poll hook, the re-seed leg: with ``--auto-failover``, a
        HEALTHY follower whose shipper the primary reports parked
        (behind the fold after a compaction outran its cursor, or
        diverged after a partition) is driven through the snapshot
        bootstrap. One inflight re-seed per follower, with a cooldown so
        a bootstrap that keeps failing cannot hot-loop; the work runs
        off the poll thread — a slow snapshot transfer must never
        freeze health polling."""
        if not self.auto_failover:
            return
        primary = self.set.primary_url()
        if primary is None:
            return  # no source to re-seed from (failover window)
        followers = self.set.state(primary).followers
        if not followers:
            return
        now = time.monotonic()
        target = None
        with self._bootstrap_lock:
            for url, info in followers.items():
                u = url.rstrip("/")
                if not isinstance(info, dict) \
                        or info.get("state") not in _PARKED_STATES:
                    continue
                if u in self._bootstrap_inflight:
                    continue
                if now - self._bootstrap_last.get(u, -1e9) \
                        < _BOOTSTRAP_COOLDOWN_S:
                    continue
                # The follower itself must be serving: bootstrap is an
                # admin call into a LIVE process. A crashed follower is
                # the operator's problem (or a fresh boot's --bootstrap
                # auto), not this hook's.
                if not self.set.is_healthy(u):
                    continue
                target = u
                self._bootstrap_inflight.add(u)
                self._bootstrap_last[u] = now
                break
        if target is None:
            return

        def work():
            try:
                self.bootstrap(follower=target, source=primary,
                               trigger="auto")
            finally:
                with self._bootstrap_lock:
                    self._bootstrap_inflight.discard(target)

        threading.Thread(target=work, daemon=True,
                         name="knn-fleet-bootstrap").start()

    def _fleet_capacity(self):
        """Sum the fleet's self-reported read capacity: each usable
        replica's ``sustainable_qps`` (its /healthz capacity block,
        captured by the health poller). Returns ``(sum_or_None,
        usable_count)`` — None until at least one replica has a fitted
        capacity model, so the autoscaler holds instead of acting on a
        cold fleet."""
        total = None
        usable = 0
        for url in self.set.usable_urls():
            usable += 1
            qps = self.set.state(url).sustainable_qps
            if qps is not None:
                total = (total or 0.0) + float(qps)
        return total, usable

    def _maybe_autoscale(self) -> None:
        """Poll hook, the capacity leg (``--scale-cmd``): compare the
        30s offered read load against the fleet's summed sustainable
        QPS and walk the fleet size toward demand — the FIRST rung of
        the degradation order (grow before any replica sheds). One
        scale op inflight at a time, cooldown inside the policy; the
        operator's command runs off the poll thread."""
        if self.autoscale is None or self._scale_inflight:
            return
        offered = self._offered.window_sums(30)[0] / 30.0
        sustainable, usable = self._fleet_capacity()
        direction = self.autoscale.decide(offered, sustainable, usable)
        if direction is None:
            return
        target = (self._scale_up_target() if direction == "up"
                  else self._scale_down_target())
        if target is None:
            return
        with self._scale_lock:
            if self._scale_inflight:
                return
            self._scale_inflight = True
        if self.events is not None:
            self.events.emit(f"scale-{direction}-begin", replica=target,
                             offered_qps=round(offered, 2),
                             sustainable_qps=(
                                 None if sustainable is None
                                 else round(sustainable, 2)),
                             usable=usable)

        def work():
            ok = False
            err = None
            try:
                from knn_tpu.control.autoscale import run_scale_cmd
                run_scale_cmd(self.scale_cmd, direction, target,
                              timeout_s=self.admin_timeout_s)
                ok = True
            except Exception as e:  # the operator's command, any failure
                err = str(e)
            finally:
                obs.counter_add(
                    "knn_fleet_scale_total",
                    help="autoscaler scale operations by direction and "
                         "outcome",
                    direction=direction,
                    outcome="ok" if ok else "failed")
                if self.events is not None:
                    if ok:
                        self.events.emit(f"scale-{direction}-complete",
                                         replica=target)
                    else:
                        self.events.emit(f"scale-{direction}-failed",
                                         replica=target, error=err)
                if ok:
                    self.scales += 1
                    self.set.poll_once()
                with self._scale_lock:
                    self._scale_inflight = False

        threading.Thread(target=work, daemon=True,
                         name="knn-control-autoscale").start()

    def _scale_up_target(self) -> Optional[str]:
        """The slot to fill: the first REGISTERED url that is not
        currently usable — the router's replica list is the fleet's
        address space, so scale-up re-animates a down slot (the scale
        command boots a process there; --bootstrap auto seeds it)."""
        usable = set(self.set.usable_urls())
        for url in self.set.urls:
            if url not in usable:
                return url
        return None

    def _scale_down_target(self) -> Optional[str]:
        """The replica to drain: the LAST usable non-primary — never
        the primary (writes), never below the policy floor (the policy
        already enforced min)."""
        primary = self.set.primary_url()
        for url in reversed(self.set.usable_urls()):
            if url != primary:
                return url
        return None

    def bootstrap(self, follower: Optional[str] = None,
                  source: Optional[str] = None,
                  trigger: str = "manual",
                  request_id: Optional[str] = None) -> dict:
        """Drive ONE snapshot bootstrap: tell ``follower`` (default: the
        first follower the primary reports parked) to re-seed itself
        from ``source`` (default: the healthy primary) via its
        ``POST /admin/bootstrap``. The transfer and install run inside
        the follower; this call blocks until it commits (bounded by the
        admin timeout) and audits begin/complete/failed either way."""
        src = (source or self.set.primary_url() or "").rstrip("/")
        if not src:
            return {"status": 503, "body": {
                "error": "no healthy primary to bootstrap from",
            }}
        target = follower.rstrip("/") if follower else None
        if target is None:
            followers = self.set.state(src).followers or {}
            for url, info in followers.items():
                if isinstance(info, dict) \
                        and info.get("state") in _PARKED_STATES:
                    target = url.rstrip("/")
                    break
        if target is None:
            return {"status": 409, "body": {
                "error": "no parked follower to re-seed (the primary "
                         "reports none behind_fold or diverged; name "
                         'one explicitly with {"follower": URL})',
            }}
        if self.events is not None:
            self.events.emit("reseed-begin", request_id=request_id,
                             follower=target, source=src,
                             trigger=trigger)
        st, doc, err = self._admin_call(
            "POST", target + "/admin/bootstrap", {"from": src})
        ok = st == 200
        obs.counter_add(
            "knn_fleet_reseeds_total",
            help="snapshot bootstraps the router drove, by trigger and "
                 "outcome",
            trigger=trigger, outcome="ok" if ok else "failed")
        if self.events is not None:
            if ok:
                self.events.emit(
                    "reseed-complete", request_id=request_id,
                    follower=target, source=src, trigger=trigger,
                    generation=doc.get("generation"),
                    wal_cursor=doc.get("folded_seq"))
            else:
                self.events.emit(
                    "reseed-failed", request_id=request_id,
                    follower=target, source=src, trigger=trigger,
                    error=err or doc.get("error", f"HTTP {st}"))
        if not ok:
            return {"status": 502 if st is None else st, "body": {
                "error": f"bootstrap on {target} failed: "
                         f"{err or doc.get('error', doc)}",
                "replica": target, "source": src,
            }}
        self.reseeds += 1
        # The re-seeded follower's next shipper re-probe (<=30s) resumes
        # shipping; the poll below refreshes the router's view now.
        self.set.poll_once()
        return {"status": 200, "body": {**doc, "replica": target,
                                        "source": src,
                                        "trigger": trigger}}

    # -- export ------------------------------------------------------------

    def health(self) -> dict:
        export = self.set.export()
        return {
            "ready": export["usable"] > 0,
            "uptime_s": round(time.time() - self.started_unix, 1),
            "primary": export["primary"],
            "split_brain": export["split_brain"],
            "lag": export["lag"],
            "usable": export["usable"],
            "replicas": export["replicas"],
            "hedge": ("off" if self.hedge is None else
                      ("auto" if self.hedge == 0 else f"{self.hedge}ms")),
            "auto_failover": self.auto_failover,
            "failovers": self.failovers,
            "reloads": self.reloads,
            "reseeds": self.reseeds,
            "confirmed_index": self._confirmed_index,
            "flight_recorder": (self.recorder.stats()
                                if self.recorder is not None else None),
            "event_log": (self.events.export()
                          if self.events is not None else None),
            "access_log": self.access_log is not None,
            # The autoscaler's operating point; None (the DISTINCT
            # "no autoscaler" state) while --scale-cmd is unset.
            "autoscale": self._autoscale_block(),
            # Durable metrics history + alert engine; None while
            # --history-dir/--alert-rules are unset.
            "history": (self.history.status()
                        if self.history is not None else None),
            "alerts": ({"firing": self.alerts.export()["firing"],
                        "rules": len(self.alerts.rules)}
                       if self.alerts is not None else None),
        }

    def _autoscale_block(self) -> Optional[dict]:
        if self.autoscale is None:
            return None
        offered = self._offered.window_sums(30)[0] / 30.0
        sustainable, usable = self._fleet_capacity()
        return dict(self.autoscale.export(),
                    offered_qps=round(offered, 2),
                    sustainable_qps=(None if sustainable is None
                                     else round(sustainable, 2)),
                    usable=usable,
                    inflight=self._scale_inflight,
                    scales=self.scales)

    def overload_retry_after_s(self) -> float:
        """Retry-After for the router's own overload answers (zero
        usable replicas, no primary): a small jittered constant — the
        router has no queue model of its own, and the jitter de-syncs a
        thundering herd of retriers."""
        return 1.0 + random.random()

    # -- fleet observability -----------------------------------------------

    def federated_metrics(self) -> str:
        """The whole fleet in ONE scrape: every usable replica's registry
        snapshot merged with a ``{replica=…}`` label (values stay
        per-replica — the multihost merge machinery, not a lossy
        pre-sum), the router's own ``knn_fleet_*`` instruments overlaid
        unlabeled. A replica that fails its scrape is skipped (and
        counted) — a slow replica must not take /metrics down with it."""
        snaps = {}
        for url in self.set.usable_urls():
            st, doc, _err = self._admin_call(
                "GET", url + "/metrics?format=json", None,
                timeout=self.set.poll_timeout_s)
            ok = st == 200 and isinstance(doc.get("snapshot"), list)
            obs.counter_add(
                "knn_fleet_scrape_total",
                help="federated /metrics scrapes of replica registries "
                     "by outcome",
                replica=url, outcome="ok" if ok else "error")
            if ok:
                snaps[url] = doc["snapshot"]
        merged = aggregate.merge_snapshots(snaps, label="replica")
        # The router's own registry last: its scrape counters above are
        # in this snapshot, so the scrape self-reports.
        aggregate.merge_snapshots(
            {"router": aggregate.snapshot_registry(obs.registry())},
            merged, label=None)
        return merged.to_prometheus()

    def fleet_debug(self) -> dict:
        """The one-stop incident document (``GET /debug/fleet``): the
        router's own health/routing state joined with each replica's
        LIVE healthz / capacity / quality documents and the audit-event
        tail — what an operator would otherwise assemble by hand from
        3N curls mid-incident."""
        doc = self.health()
        live = {}
        for url in self.set.urls:
            entry = {}
            for name, path in (("healthz", "/healthz"),
                               ("capacity", "/debug/capacity"),
                               ("quality", "/debug/quality")):
                st, body, err = self._admin_call(
                    "GET", url + path, None,
                    timeout=self.set.poll_timeout_s)
                entry[name] = (body if st is not None
                               else {"error": err})
                if st is not None and st != 200:
                    entry[name] = {"status": st, **body} \
                        if isinstance(body, dict) else {"status": st}
            live[url] = entry
        doc["live"] = live
        doc["events"] = (self.events.recent(32)
                         if self.events is not None else None)
        return doc

    def stitched_request(self, request_id: str) -> Optional[dict]:
        """One request's CROSS-TIER story: the router's own timeline for
        ``request_id`` plus, fetched LIVE from each replica an attempt
        touched, that replica's timeline for the same id (hedge losers
        included — their replica-side work is part of the request's
        cost). Returns ``{"request_id", "router", "replicas": {url:
        timeline|None}}`` or None when the router never recorded the id
        (evicted, or traced before the recorder was enabled)."""
        if self.recorder is None:
            return None
        tl = self.recorder.find(request_id)
        if tl is None:
            return None
        replicas: "dict[str, Optional[dict]]" = {}
        for a in tl.get("attempts", ()):
            url = a.get("rung")
            if not url or url in replicas:
                continue
            st, doc, _err = self._admin_call(
                "GET", url + "/debug/requests?id=" + request_id, None,
                timeout=self.set.poll_timeout_s)
            reqs = doc.get("requests") if st == 200 else None
            replicas[url] = reqs[0] if reqs else None
        return {"request_id": request_id, "router": tl,
                "replicas": replicas}

    @staticmethod
    def stitched_to_chrome_trace(stitched: dict) -> dict:
        """The :meth:`stitched_request` document as one Perfetto trace:
        the router tier first, then one process per replica that
        answered — load at ui.perfetto.dev and the tiers line up on the
        shared wall clock."""
        tiers = [("router", [stitched["router"]])]
        for url, tl in stitched["replicas"].items():
            tiers.append((url, [tl] if tl else []))
        return reqtrace.stitch_chrome_trace(tiers)


def _json_body(doc: dict) -> bytes:
    return (json.dumps(doc) + "\n").encode()


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "knn-tpu-route/1"
    protocol_version = "HTTP/1.1"
    timeout = 60

    @property
    def app(self) -> RouterApp:
        return self.server.app  # type: ignore[attr-defined]

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        pass  # /metrics is the log (the serve handler's rule)

    def _send_raw(self, status: int, raw: bytes,
                  content_type="application/json",
                  retry_after: "Optional[float]" = None):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        rid = getattr(self, "_rid", None)
        if rid is not None:
            self.send_header("x-request-id", rid)
        if retry_after is not None:
            # RFC 9110 delay-seconds: integral, floor 1 — a client that
            # honors it backs off instead of hammering an overloaded
            # fleet.
            self.send_header("Retry-After",
                             str(max(1, int(round(retry_after)))))
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _send(self, status: int, payload: dict):
        self._send_raw(status, _json_body(payload))

    def _begin(self) -> bool:
        raw = self.headers.get("x-request-id")
        if raw is None:
            self._rid = reqtrace.gen_request_id()
            return True
        raw = raw.strip()
        if not reqtrace.valid_request_id(raw):
            self._rid = reqtrace.gen_request_id()
            self.close_connection = True
            self._send(400, {"error": "invalid x-request-id header"})
            return False
        self._rid = raw
        return True

    def _read_body(self):
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return None, "a body with Content-Length is required"
        if length > MAX_BODY_BYTES:
            return None, (f"body {length} B exceeds the "
                          f"{MAX_BODY_BYTES} B bound")
        return (self.rfile.read(length) if length > 0 else b""), None

    def do_GET(self):  # noqa: N802 — stdlib dispatch name
        if not self._begin():
            return
        route = urlparse(self.path).path
        if route == "/healthz":
            h = self.app.health()
            self._send(200 if h["ready"] else 503, h)
        elif route == "/debug/fleet":
            self._send(200, self.app.fleet_debug())
        elif route == "/debug/requests":
            self._do_debug_requests()
        elif route == "/debug/events":
            self._do_debug_events()
        elif route == "/debug/history":
            self._do_history()
        elif route == "/debug/alerts":
            self._do_alerts()
        elif route == "/metrics":
            self._send_raw(200, self.app.federated_metrics().encode(),
                           "text/plain; version=0.0.4")
        else:
            self._send(404, {"error": f"no such endpoint: {self.path}"})

    def _do_history(self) -> None:
        """The fleet history window (serve's /debug/history contract:
        always 200, ``enabled: false`` while the layer is off). Series
        scraped from members carry their ``{replica}`` label."""
        app = self.app
        if app.history is None:
            self._send(200, {"enabled": False, "series": []})
            return
        from knn_tpu.obs.history import parse_window

        q = parse_qs(urlparse(self.path).query)
        metric = q.get("metric", [None])[0]
        labels = {}
        for item in q.get("label", []):
            k, sep, v = item.partition("=")
            if not sep or not k:
                self._send(400, {"error": f"bad label={item!r}: want k=v"})
                return
            labels[k] = v
        window_s = None
        if q.get("window", [None])[0] is not None:
            try:
                window_s = parse_window(q["window"][0])
            except ValueError as exc:
                self._send(400, {"error": str(exc)})
                return
        self._send(200, {"enabled": True, "status": app.history.status(),
                         **app.history.query(metric=metric, labels=labels,
                                             window_s=window_s)})

    def _do_alerts(self) -> None:
        app = self.app
        if app.alerts is None:
            self._send(200, {"enabled": False, "rules": [], "firing": [],
                             "recent": []})
            return
        self._send(200, {"enabled": True, **app.alerts.export()})

    def _do_debug_requests(self) -> None:
        """The router tier of per-request debugging: no ``id`` lists the
        router's own recent timelines (serve's contract); ``?id=`` goes
        CROSS-TIER — the router timeline joined with the answering (and
        hedge-losing) replicas' timelines for the same request_id,
        fetched live; ``&format=perfetto`` renders the stitched trace
        with one Perfetto process per tier."""
        rec = self.app.recorder
        if rec is None:
            self._send(404, {"error": "request tracing is disabled "
                                      "(--flight-recorder-size 0)"})
            return
        q = parse_qs(urlparse(self.path).query)
        fmt = q.get("format", ["json"])[0]
        if fmt not in ("json", "perfetto"):
            self._send(400, {"error": f"bad format={fmt!r}: want json "
                                      f"or perfetto"})
            return
        rid = q.get("id", [None])[0]
        if rid is not None:
            stitched = self.app.stitched_request(rid)
            if stitched is None:
                self._send(404, {"error": f"request_id {rid!r} not in "
                                          f"the router's flight "
                                          f"recorder (evicted or never "
                                          f"traced)"})
                return
            if fmt == "perfetto":
                self._send(200,
                           self.app.stitched_to_chrome_trace(stitched))
            else:
                self._send(200, stitched)
            return
        try:
            n = int(q["n"][0]) if "n" in q else None
        except ValueError:
            self._send(400, {"error": f"bad n={q['n'][0]!r}: want an "
                                      f"integer"})
            return
        timelines = rec.recent(n)
        if fmt == "perfetto":
            self._send(200, rec.to_chrome_trace(timelines))
        else:
            self._send(200, {"requests": timelines, **rec.stats()})

    def _do_debug_events(self) -> None:
        ev = self.app.events
        if ev is None:
            self._send(404, {"error": "the fleet event audit log is "
                                      "disabled (--event-log)"})
            return
        q = parse_qs(urlparse(self.path).query)
        try:
            n = int(q["n"][0]) if "n" in q else None
        except ValueError:
            self._send(400, {"error": f"bad n={q['n'][0]!r}: want an "
                                      f"integer"})
            return
        self._send(200, {"events": ev.recent(n), **ev.export()})

    def do_POST(self):  # noqa: N802 — stdlib dispatch name
        if not self._begin():
            return
        route = urlparse(self.path).path
        body, err = self._read_body()
        if err is not None:
            self.close_connection = True
            self._send(413 if "exceeds" in err else 400, {"error": err})
            return
        headers = {"Content-Type": "application/json",
                   "x-request-id": self._rid}
        cls = self.headers.get("x-knn-class")
        if cls is not None:
            headers["x-knn-class"] = cls
        trace = self._new_trace(route)
        try:
            if route in ("/predict", "/kneighbors"):
                status, raw, replica = self.app.forward_read(
                    route, body, headers, trace=trace)
                self._note(route, status, replica, trace, req_class=cls)
                self._send_raw(status, raw,
                               retry_after=self._retry_after(status))
            elif route in ("/insert", "/delete"):
                status, raw, replica = self.app.forward_write(
                    route, body, headers, trace=trace)
                self._note(route, status, replica, trace, req_class=cls)
                self._send_raw(status, raw,
                               retry_after=self._retry_after(status))
            elif route == "/admin/promote":
                self._do_admin(body, self._admin_promote)
            elif route == "/admin/reload":
                self._do_admin(body, self._admin_reload)
            elif route == "/admin/compact":
                self._do_admin(body, self._admin_compact)
            elif route == "/admin/bootstrap":
                self._do_admin(body, self._admin_bootstrap)
            else:
                self.close_connection = True
                self._send(404, {"error": f"no such endpoint: "
                                          f"{self.path}"})
        except Exception as e:  # noqa: BLE001 — the router's last line:
            # typed JSON for EVERY terminal outcome, never a traceback.
            if trace is not None and not trace.finished:
                trace.annotate(error=f"{type(e).__name__}: {e}")
                trace.finish("error")
            self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def _new_trace(self, route: str):
        """The router's own timeline for one forwarded request — created
        when EITHER consumer exists (the flight recorder, or the access
        log, whose line is derived from the finished trace). Row count
        is 0: the body is opaque bytes here; the replica's timeline
        carries the real shape."""
        if route not in ("/predict", "/kneighbors", "/insert",
                         "/delete"):
            return None
        app = self.app
        if app.recorder is not None:
            return app.recorder.new_trace(route.lstrip("/"), 0,
                                          request_id=self._rid)
        if app.access_log is not None:
            return reqtrace.RequestTrace(route.lstrip("/"), 0,
                                         request_id=self._rid)
        return None

    def _retry_after(self, status: int) -> "Optional[float]":
        """Retry-After for every overload/unavailable answer the router
        relays or originates (429 shed/rejected at a replica, 503 zero
        usable / failover window) — the forward path strips replica
        headers, so the router re-derives the hint here."""
        if status not in (429, 503):
            return None
        return self.app.overload_retry_after_s()

    def _note(self, route: str, status: int, replica,
              trace=None, req_class=None) -> None:
        obs.counter_add(
            "knn_fleet_router_requests_total",
            help="client requests answered by the router, by endpoint "
                 "and status",
            endpoint=route, status=str(status),
        )
        if trace is None:
            return
        trace.annotate(status=status, replica=replica)
        if not trace.finished:
            trace.finish("ok" if status == 200 else f"http_{status}")
        log = self.app.access_log
        if log is not None:
            tl = trace.to_dict()
            entry = {
                "ts": round(time.time(), 6),
                "request_id": self._rid,
                "kind": route.lstrip("/"),
                "status": status,
                "outcome": tl["outcome"],
                "ms": tl["request_ms"],
                "replica": replica,
                "replicas_tried": len({a["rung"]
                                       for a in tl["attempts"]}),
                "hedged": any(e["event"] == "hedge-fired"
                              for e in tl["events"]),
            }
            if req_class is not None:
                # Which admission class asked — overload forensics needs
                # to join sheds back to the traffic that drove them.
                entry["class"] = req_class
            phases: dict = {}
            for p in tl["phases"]:
                phases[p["phase"]] = round(
                    phases.get(p["phase"], 0.0) + (p["ms"] or 0.0), 3)
            entry["phases"] = phases
            if tl["attempts"]:
                entry["attempts"] = [
                    f"{a['rung']}:{'ok' if a['ok'] else a.get('error', 'fail')}"
                    for a in tl["attempts"]
                ]
            log.write(entry)

    def _do_admin(self, body: bytes, fn) -> None:
        try:
            doc = json.loads(body) if body else {}
            if not isinstance(doc, dict):
                raise ValueError("the request body must be a JSON object")
        except ValueError as e:
            self._send(400, {"error": f"bad request body: {e}"})
            return
        try:
            result = fn(doc)
        except RouterBusy as e:
            self._send(409, {"error": str(e)})
            return
        self._send(result["status"], result["body"])

    def _admin_promote(self, doc: dict) -> dict:
        return self.app.promote(doc.get("replica"), trigger="manual",
                                request_id=self._rid)

    def _admin_reload(self, doc: dict) -> dict:
        return self.app.coordinated_reload(doc.get("index"),
                                           doc.get("rollback_to"),
                                           request_id=self._rid)

    def _admin_compact(self, doc: dict) -> dict:
        return self.app.coordinated_compact(doc.get("replica"),
                                            request_id=self._rid)

    def _admin_bootstrap(self, doc: dict) -> dict:
        return self.app.bootstrap(doc.get("follower"),
                                  source=doc.get("from"),
                                  trigger="manual",
                                  request_id=self._rid)


class RouterServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, address, app: RouterApp):
        super().__init__(address, _RouterHandler)
        self.app = app

    def handle_error(self, request, client_address):
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
            return
        super().handle_error(request, client_address)


def make_router_server(app: RouterApp, host: str = "127.0.0.1",
                       port: int = 0) -> RouterServer:
    return RouterServer((host, port), app)


def router_forever(server: RouterServer, *, banner=None) -> int:
    """Run until SIGINT/SIGTERM. The router holds no in-flight state
    worth draining (every request is a synchronous forward on its own
    handler thread), so both signals stop it the simple way."""
    import signal

    def on_stop(signum, frame):
        threading.Thread(target=server.shutdown, daemon=True).start()

    previous = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            previous[sig] = signal.signal(sig, on_stop)
        except ValueError:
            pass  # not the main thread (embedded use)
    if banner:
        print(banner, flush=True)
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        server.server_close()
        server.app.close()
    return 0
