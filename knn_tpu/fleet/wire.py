"""One tiny stdlib HTTP-JSON client shared by the router and the WAL
shipper (no new deps — the serve stack's own rule).

Transport failures propagate as ``OSError`` (``urllib.error.URLError``
subclasses it), which is exactly what the resilience classifier treats
as transient at the ``fleet.*`` call sites; HTTP error statuses return
normally as ``(status, body)`` so callers can apply the routing rules
(retry a read elsewhere, never blindly re-send a write).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional


def request_json(method: str, url: str, payload: Optional[dict] = None,
                 timeout: float = 10.0,
                 headers: Optional[dict] = None) -> "tuple[int, dict]":
    """``(status, parsed-json-body)``; a non-JSON body comes back as
    ``{"raw": <first 400 chars>}`` so a misbehaving replica still yields
    a typed, loggable outcome rather than a parse traceback."""
    data = None
    hdrs = dict(headers or {})
    if payload is not None:
        data = json.dumps(payload).encode()
        hdrs.setdefault("Content-Type", "application/json")
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, _parse(r.read())
    except urllib.error.HTTPError as e:
        return e.code, _parse(e.read())


def forward_bytes(method: str, url: str, body: Optional[bytes],
                  timeout: float,
                  headers: Optional[dict] = None) -> "tuple[int, bytes]":
    """Raw pass-through for the router's proxy path: the replica's JSON
    body is already exactly what the client should see — re-encoding it
    would only cost time and risk reordering."""
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=dict(headers or {}))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _parse(raw: bytes) -> dict:
    try:
        doc = json.loads(raw)
        if isinstance(doc, dict):
            return doc
        return {"raw": str(doc)[:400]}
    except ValueError:
        return {"raw": raw[:400].decode("utf-8", "replace")}
