"""The fleet event audit log: an append-only record of every control-plane
transition the router observed or drove.

Metrics answer "how often"; request timelines answer "why was this request
slow"; neither answers "what HAPPENED to the fleet between 14:02 and
14:03". This module is that third surface — one bounded in-memory ring of
typed events plus an optional JSONL file (``--event-log``), exposed at
``GET /debug/events``:

- ``demote`` / ``passive-demote`` — a replica left the usable set (health
  poll vs. a live forward's transport failure);
- ``rejoin``                       — a previously-down replica polled
  healthy again;
- ``promote`` / ``auto-failover``  — the router drove a follower to
  primary (operator vs. ``--auto-failover``);
- ``failover-window``              — the first post-promote write 200,
  carrying the measured typed-503 span in ms;
- ``hedge-fired``                  — a tail read's backup attempt was
  launched;
- ``coordinated-reload-begin`` / ``-commit`` / ``-rollback``;
- ``reseed-begin`` / ``reseed-complete`` / ``reseed-failed`` — the
  router drove a snapshot bootstrap on a parked follower (the
  self-healing leg; ``trigger`` distinguishes auto from operator);
- ``epoch-retention-hold``         — a coordinated compaction reported
  deferring WAL epoch pruning because a live follower's cursor still
  needs those records (the retention floor);
- ``scale-up-begin`` / ``scale-down-begin`` / ``-complete`` /
  ``-failed`` — the autoscaler (``--scale-cmd``) drove the operator's
  scale command at a replica slot; the begin event carries the
  offered/sustainable QPS comparison that justified the move.

Every event is stamped with the ``request_id`` that triggered it where one
exists (a hedge, a passive demotion, an operator admin call), so the audit
log joins against ``/debug/requests`` — the incident-forensics contract
``scripts/fleet_soak.py`` pins.

Cost contract: the log is constructed ONLY when ``--event-log`` (or the
``event_log=`` ctor arg) asks for it — a router booted without it carries
``events = None`` and every emit site pays one ``is None`` predicate
(scripts/check_disabled_overhead.py). Events are control-plane-rate (a
handful per incident, ~1% of tail reads for hedges), so the file write is
a single line-buffered append under one lock, the access-log discipline.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque
from typing import List, Optional


class FleetEventLog:
    """Bounded ring + optional JSONL appender. ``path=None`` keeps the
    ring only (embedded/test use); ``path='-'`` writes lines to stderr;
    anything else appends to the file (created if missing)."""

    def __init__(self, path: Optional[str] = None, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.path = path
        self.capacity = int(capacity)
        self.emitted = 0
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=self.capacity)
        self._file = None
        if path == "-":
            self._file = sys.stderr
        elif path:
            self._file = open(path, "a", buffering=1, encoding="utf-8")

    def emit(self, event: str, request_id: Optional[str] = None,
             **fields) -> dict:
        """Append one event. ``request_id`` is stamped only when the
        trigger had one (an auto-failover driven by the health poller
        does not). Returns the record for callers that echo it."""
        rec = {"ts": round(time.time(), 6), "event": event}
        if request_id is not None:
            rec["request_id"] = request_id
        rec.update(fields)
        line = None
        if self._file is not None:
            line = json.dumps(rec, separators=(",", ":"), default=str)
        with self._lock:
            self._ring.append(rec)
            self.emitted += 1
            if line is not None:
                try:
                    self._file.write(line + "\n")
                except (OSError, ValueError):
                    pass  # a full disk must never fail a control action
        return rec

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """The retained events in chronological order; ``n`` bounds to
        the newest n (still chronological — an audit log reads forward)."""
        with self._lock:
            out = list(self._ring)
        if n is not None:
            out = out[-max(0, int(n)):]
        return [dict(r) for r in out]

    def find(self, event: str) -> List[dict]:
        """All retained events of one type, chronological."""
        return [r for r in self.recent() if r["event"] == event]

    def export(self) -> dict:
        return {
            "capacity": self.capacity,
            "retained": len(self._ring),
            "emitted": self.emitted,
            "path": self.path,
        }

    def close(self) -> None:
        with self._lock:
            if self._file is not None and self._file is not sys.stderr:
                try:
                    self._file.close()
                except OSError:
                    pass
            self._file = None
