"""Replica sets and fault-tolerant routing (docs/SERVING.md §Running a
replica set).

Three layers, each importable on its own:

- :mod:`knn_tpu.fleet.replica` — the replica-side role: a PRIMARY fans
  every acknowledged WAL record out to its followers (``WALShipper``,
  one ordered cursor per follower, semi-synchronous ack), a FOLLOWER
  applies shipped records through the exact local-mutation validation
  path and can be promoted in place.
- :mod:`knn_tpu.fleet.health` — the router's view of N replicas: active
  ``/healthz`` polling plus passive demotion on connection errors.
- :mod:`knn_tpu.fleet.router` — the thin HTTP front-end (`knn_tpu
  route`): reads routed to healthy replicas with cross-replica retry and
  optional tail hedging, writes routed to the one primary, coordinated
  reload (all-or-nothing), serialized compaction, optional auto-failover.

Everything here is OPT-IN: a plain ``knn_tpu serve`` (no
``--follower-of``, no ``--replicate-to``) never imports this package
(scripts/check_disabled_overhead.py pins it).
"""
