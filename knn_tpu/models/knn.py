"""High-level model API.

The reference exposes no reusable API — each backend's ``main()`` is the whole
surface (main.cpp:114). ``KNNClassifier`` is the framework's model-layer
equivalent: fit/predict/score with a pluggable execution backend.
"""

from __future__ import annotations

import contextlib
import math
from typing import Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.backends import get_backend
from knn_tpu.data.dataset import Dataset
from knn_tpu.utils.evaluate import confusion_matrix, accuracy

#: Query rows pad to this quantum on the XLA retrieval path when no
#: bucket ladder is configured (one warm executable then serves every
#: batch size up to it). The ONE definition: :func:`query_padded_rows`
#: below is what the pad, the executable-cache key, and the cost layer's
#: padded-row accounting (obs/accounting.py) all resolve from, so they
#: can never silently diverge from the pad that really happens.
QUERY_PAD_QUANTUM = 128

#: The serving default for ``serve --batch-buckets auto`` (a geometric
#: ladder — each bucket is one compiled executable; a batch pads to the
#: smallest bucket >= its rows, so the measured padded-row waste tracks
#: the batch the traffic actually formed instead of the single
#: pad-to-quantum shape). docs/SERVING.md §Tuning the bucket ladder.
DEFAULT_BATCH_BUCKETS = (16, 32, 64, 128, 256)

#: Process-wide compiled-shape bucket ladder for XLA query padding.
#: ``None`` (the default, and always outside a bucketed serve) keeps the
#: legacy pad-to-``QUERY_PAD_QUANTUM`` behavior byte-identical.
_QUERY_BUCKETS: "tuple[int, ...] | None" = None

#: The candidate-count bucket ladder for the device IVF gather+score
#: kernel (``ops/segment_score.py``): the probed candidate axis ``M``
#: varies with every (nprobe, cell-size) combination, so without buckets
#: every dispatch would compile a fresh executable. A fixed geometric
#: ladder keeps the compiled-shape set small; past the top bucket the
#: shape steps in top-bucket multiples (the ``query_padded_rows`` rule).
DEFAULT_CANDIDATE_BUCKETS = (256, 512, 1024, 2048, 4096, 8192, 16384)


def normalize_buckets(buckets) -> "tuple[int, ...]":
    """Validate + canonicalize a bucket ladder: positive ints, sorted,
    deduplicated. Raises ``ValueError`` on anything else."""
    try:
        out = tuple(sorted({int(b) for b in buckets}))
    except (TypeError, ValueError):
        raise ValueError(f"batch buckets must be integers, got {buckets!r}")
    if not out or out[0] < 1:
        raise ValueError(f"batch buckets must be positive, got {buckets!r}")
    return out


def set_query_buckets(buckets) -> "tuple[int, ...] | None":
    """Install (or with ``None`` clear) the process-wide compiled-shape
    bucket ladder. Padding NEVER changes answers (padded query rows are
    sliced off every output — the bit-identity contract), only which
    executable shapes exist; the serving boot sets this once from
    ``--batch-buckets`` BEFORE warmup so every bucket pre-compiles.
    Returns the normalized ladder (or None)."""
    global _QUERY_BUCKETS
    _QUERY_BUCKETS = None if buckets is None else normalize_buckets(buckets)
    return _QUERY_BUCKETS


def query_buckets() -> "tuple[int, ...] | None":
    """The active compiled-shape bucket ladder (None = legacy quantum)."""
    return _QUERY_BUCKETS


@contextlib.contextmanager
def query_bucket_ladder(buckets):
    """Scoped :func:`set_query_buckets` — tests and bench configs install
    a ladder for one block and are guaranteed the previous state back."""
    previous = _QUERY_BUCKETS
    set_query_buckets(buckets)
    try:
        yield _QUERY_BUCKETS
    finally:
        set_query_buckets(previous)


def query_padded_rows(rows: int) -> int:
    """THE compiled-shape query-row count for an XLA retrieval dispatch
    of ``rows`` actual rows — the one definition shared by the pad below,
    the executable-cache key, and ``obs/accounting.padded_query_rows``
    (the PR-8 hardening contract). With a bucket ladder installed: the
    smallest bucket >= rows, and past the top bucket the next multiple of
    it (so oversized one-shot calls still hit a bounded shape set);
    without one: the next multiple of :data:`QUERY_PAD_QUANTUM`."""
    rows = int(rows)
    if rows <= 0:
        return 0
    b = _QUERY_BUCKETS
    if b:
        for size in b:
            if rows <= size:
                return size
        top = b[-1]
        return -(-rows // top) * top
    return -(-rows // QUERY_PAD_QUANTUM) * QUERY_PAD_QUANTUM


def candidate_padded_rows(rows: int) -> int:
    """THE compiled-shape candidate-row count for one device IVF
    gather+score dispatch of ``rows`` actual candidates per query — the
    ``query_padded_rows`` twin for the candidate axis, and the one
    definition shared by the segment-score pad (``ops/segment_score.py``),
    its executable-cache key, and the cost layer's candidate-waste
    accounting (``obs/accounting.padded_candidate_rows``), so the waste
    metrics can never silently diverge from the pad that really happens
    (the PR-8/PR-12 one-definition contract). Smallest ladder bucket
    >= rows; past the top bucket, the next multiple of it."""
    rows = int(rows)
    if rows <= 0:
        return 0
    for size in DEFAULT_CANDIDATE_BUCKETS:
        if rows <= size:
            return size
    top = DEFAULT_CANDIDATE_BUCKETS[-1]
    return -(-rows // top) * top


def _kneighbors_arrays(
    train_x: np.ndarray,
    test_x: np.ndarray,
    k: int,
    metric: str = "euclidean",
    engine: str = "auto",
    cache: "dict | None" = None,
    deferred: bool = False,
    prefetched_queries=None,
    merge_tail=None,
):
    """Shared retrieval core for both model families: ``(dists [Q,k],
    indices [Q,k])`` sorted by (distance, train index). Pure geometry — no
    label semantics, so the regressor can use it with negative/float targets
    that the classifier's label validation would reject.

    ``merge_tail`` (the mutable tier's device-resident delta tail,
    ``knn_tpu/mutable/device_tail.py``): a callable
    ``(d_dev, i_dev, queries_dev) -> (d_dev, i_dev)`` applied to the XLA
    path's DEVICE outputs before the host copy starts — the delta block
    is scored and merged into the base top-k in the same device round
    trip as the base retrieval (one host sync for base+delta instead of
    a per-batch host merge). XLA engine only (the stripe kernel pads and
    fetches inside its own entry); its ``sig`` attribute joins the
    executable-cache key so a merged dispatch never aliases an unmerged
    one.

    ``prefetched_queries`` (the serving batcher's double-buffered upload,
    ``serve/batcher.py``): an already-on-device array of the PADDED query
    block — shape ``[query_padded_rows(Q), D]``, rows ``[:Q]`` equal to
    ``test_x`` and the tail zero, exactly what the pad below would build.
    The XLA path consumes it instead of re-staging + re-uploading, so
    batch N+1's host→device transfer can overlap batch N's compute; a
    shape/dtype mismatch (or the stripe engine, which pads inside its own
    entry) silently falls back to the normal pad — never wrong data.

    ``engine`` mirrors the backend knob (VERDICT r1 #6): ``auto`` hands exact
    euclidean narrow-feature problems on a real TPU to the lane-striped
    Pallas kernel — the same engine selection ``predict`` gets — so
    ``kneighbors``/``predict_proba``/regression run at the framework's own
    perf bar; ``xla`` keeps the tiled candidate scan; ``stripe`` forces the
    kernel (interpret mode off-TPU). ``cache`` (normally the train
    ``Dataset.device_cache``) memoizes the device-side train layout so
    repeat retrievals skip the host pad/transpose/upload.

    ``deferred`` returns a zero-arg resolve closure instead of the arrays:
    device work is dispatched (host copies started asynchronously) before
    this returns, and the blocking host sync happens at resolve time — the
    engine-uniform primitive under ``kneighbors_async`` (VERDICT r4 #6)."""
    import jax.numpy as jnp

    from knn_tpu.backends.tpu import knn_forward_candidates
    from knn_tpu.ops.distance import resolve_form
    from knn_tpu.ops.pallas_knn import stripe_auto_eligible
    from knn_tpu.utils.padding import pad_axis_to_multiple, pad_axis_to_size

    if engine not in ("auto", "stripe", "xla"):
        raise ValueError(
            f"unknown engine {engine!r}; choose 'auto', 'stripe', or 'xla'"
        )
    form = resolve_form("exact", metric)
    euclidean = metric in (None, "euclidean")
    if engine == "auto" and euclidean and stripe_auto_eligible(
        "exact", train_x.shape[1], k
    ):
        engine = "stripe"
    if obs.enabled():
        from knn_tpu.obs import devprof

        # Executable-cache attribution for the retrieval core — the path
        # every serving dispatch (batcher -> kneighbors) rides, so the
        # serve /healthz cache block reflects live traffic. The XLA path
        # pads queries to 128 and train to its tile, so the key uses the
        # PADDED shapes — the executable's real operand shapes; otherwise
        # every coalesced serving batch size would read as a fresh miss
        # while XLA reuses one executable. (Stripe pads inside its own
        # entry; its raw-shape key is conservative, never the reverse.)
        if engine == "stripe":
            sig = (engine, train_x.shape, train_x.dtype.str, test_x.shape,
                   k, form)
        else:
            n_tile = max(min(2048, train_x.shape[0]), k)
            sig = (
                engine,
                -(-train_x.shape[0] // n_tile) * n_tile, train_x.shape[1],
                train_x.dtype.str,
                query_padded_rows(test_x.shape[0]),
                k, form,
            )
        if merge_tail is not None:
            # The fused delta merge is a second executable chained onto
            # the retrieval: its shape (delta capacity, merged width) is
            # part of what compiles, so it is part of the key.
            sig = sig + (getattr(merge_tail, "sig", "merge_tail"),)
        devprof.record_executable_lookup("retrieval", sig)
    if engine == "stripe":
        if not euclidean:
            raise ValueError("the stripe engine implements euclidean only")
        if merge_tail is not None:
            raise ValueError(
                "merge_tail is an XLA-path hook; the stripe kernel pads "
                "and fetches inside its own entry (the caller routes "
                "stripe dispatches through the host merge instead)"
            )
        from knn_tpu.ops.pallas_knn import stripe_candidates_arrays
        from knn_tpu.resilience.retry import guarded_call

        span_attrs = {}
        if obs.enabled():
            # Compiled-shape rows alongside the actual rows: the stripe
            # kernel pads queries to its resolved block_q grid, and that
            # padding is dispatch cost the span should own up to — the
            # same helper the serving cost layer attributes with, so the
            # two can never silently diverge
            # (docs/OBSERVABILITY.md §Cost & capacity).
            from knn_tpu.obs.accounting import padded_query_rows

            span_attrs = dict(
                rows=test_x.shape[0],
                padded_rows=padded_query_rows(
                    "stripe", test_x.shape[0],
                    num_features=train_x.shape[1], k=k,
                ),
            )
        with obs.span("distance", engine="stripe", note="fused distance+top-k",
                      **span_attrs):
            out = guarded_call("device.put", lambda: guarded_call(
                "backend.compile", lambda: stripe_candidates_arrays(
                    train_x, test_x, k, precision="exact", cache=cache,
                    deferred=deferred,
                )))
        if deferred and obs.enabled():
            def resolve_stripe(inner=out):
                with obs.span("fetch", engine="stripe"):
                    return inner()

            return resolve_stripe
        return out
    from knn_tpu.ops.pallas_knn import memo_device

    n, q = train_x.shape[0], test_x.shape[0]
    train_tile = max(min(2048, n), k)

    def make():
        tx, _ = pad_axis_to_multiple(train_x, train_tile, axis=0)
        # Placeholder labels: the candidate core wants them but pure
        # retrieval never reads the gathered values.
        return jnp.asarray(tx), jnp.asarray(np.zeros(tx.shape[0], np.int32))

    from knn_tpu.resilience.retry import guarded_call

    with obs.span("prepare", engine="xla"):
        txj, tyj = guarded_call("device.put", lambda: memo_device(
            cache, ("xla_candidates_train", train_tile), make
        ))
        q_target = query_padded_rows(q)
        qx = None
        if prefetched_queries is not None:
            # The batcher's double-buffered upload: consume only when the
            # prefetched block really is this dispatch's padded shape (the
            # batcher staged it from the same request rows through the
            # same query_padded_rows definition, so a match means same
            # content + zero tail by construction).
            pq_shape = getattr(prefetched_queries, "shape", None)
            pq_dtype = getattr(prefetched_queries, "dtype", None)
            if (pq_shape == (q_target, test_x.shape[1])
                    and str(pq_dtype) == str(test_x.dtype)):
                qx = prefetched_queries
        if qx is None:
            qx = pad_axis_to_size(test_x, q_target, axis=0)
    import jax

    # The fused distance + running-top-k dispatch (one executable; the two
    # logical phases are inseparable on the XLA path — docs/OBSERVABILITY.md).
    # rows vs padded_rows: the bucket/quantum query pad is dispatch cost
    # this span owns up to (docs/OBSERVABILITY.md §Cost & capacity).
    # Query tile: the kernel sweeps queries in static tiles, so the tile
    # must divide the padded shape. The legacy 128-quantum pad keeps the
    # 128-row tile; a bucket below it IS its own (single) tile, and a
    # non-dividing bucket falls back to the largest common tile — every
    # bucket stays one compiled executable either way.
    query_tile = q_target if q_target < 128 else math.gcd(q_target, 128)
    with obs.span("distance", engine="xla", note="fused distance+top-k",
                  rows=q, padded_rows=qx.shape[0]):
        qxj = jnp.asarray(qx)
        d, i, _ = guarded_call("backend.compile", lambda: knn_forward_candidates(
            txj, tyj, qxj,
            jnp.asarray(n, jnp.int32),
            k=k, train_tile=train_tile, precision=form,
            query_tile=query_tile,
        ))
        if merge_tail is not None:
            # Device-resident delta tail: score + merge the delta block
            # on device, chained onto the base retrieval's outputs —
            # base+delta come back in the ONE host sync below.
            d, i = guarded_call("backend.compile",
                                lambda: merge_tail(d, i, qxj))
        for leaf in (d, i):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()

    def resolve():
        # One batched fetch — two sequential np.asarray calls each pay a full
        # device->host round trip (~100 ms on a tunneled device).
        with obs.span("fetch", engine="xla"):
            d_h, i_h = jax.device_get((d, i))
        return d_h[:q], i_h[:q]

    return resolve if deferred else resolve()


class AsyncResult:
    """Handle for an in-flight retrieval/predict (``kneighbors_async`` /
    ``predict_async``, and the serving batcher's request futures): the
    device work and its device->host copies are already dispatched when the
    handle is returned; :meth:`result` performs the one blocking host sync
    and memoizes. On a tunneled device every blocking sync costs a fixed
    ~100 ms round trip regardless of compute, so M calls made through
    futures and resolved together pay ~one round trip where M synchronous
    calls pay M (VERDICT r4 #6 — measured 102.8 ms/call on a 0.75 ms
    kernel step).

    The handle is single-consumer: resolve it from one thread.

    ``meta`` is an optional side-channel dict the producer may attach
    (the serving batcher records ``index_version`` and the degradation
    rung that answered there); it never affects :meth:`result`."""

    __slots__ = ("_finish", "_value", "_waiter", "_outcome", "meta")

    def __init__(self, finish, meta: "dict | None" = None):
        self._finish = finish
        self._value = None
        self._waiter = None
        self._outcome = None
        self.meta = meta

    def result(self, timeout: "float | None" = None):
        """Block until the result is ready and return it (memoized).

        ``timeout`` (seconds) bounds the wait: on expiry a typed
        :class:`~knn_tpu.resilience.errors.DeadlineExceededError` is raised
        and the in-flight work keeps running — a later ``result()`` call
        can still collect it. Two resolution strategies:

        - a finish closure marked ``__accepts_timeout__ = True`` (the
          serving batcher's event-backed futures) is called as
          ``finish(timeout=...)`` and owns its own bounded wait;
        - a generic closure (the deferred device fetches, which block in
          jax) is moved to a daemon waiter thread the first time a timeout
          is requested, and the caller joins it with the timeout.
        """
        if self._waiter is not None:
            return self._join_waiter(timeout)
        if self._finish is None:
            return self._value
        if timeout is None:
            self._value = self._finish()
            self._finish = None
            return self._value
        if getattr(self._finish, "__accepts_timeout__", False):
            # The closure raises DeadlineExceededError itself on expiry,
            # leaving the handle resolvable later.
            self._value = self._finish(timeout=timeout)
            self._finish = None
            return self._value
        import threading

        fn, self._finish = self._finish, None
        box = []

        def run():
            try:
                box.append(("ok", fn()))
            except BaseException as e:  # delivered to the consumer below
                box.append(("err", e))

        self._outcome = box
        self._waiter = threading.Thread(
            target=run, name="knn-async-result", daemon=True
        )
        self._waiter.start()
        return self._join_waiter(timeout)

    def _join_waiter(self, timeout):
        from knn_tpu.resilience.errors import DeadlineExceededError

        self._waiter.join(timeout)
        if self._waiter.is_alive():
            raise DeadlineExceededError(
                f"async result not ready within {timeout * 1e3:.0f} ms; the "
                f"work continues — call result() again to collect it"
            )
        kind, payload = self._outcome[0]
        if kind == "err":
            # Memoized failure: the dead waiter is kept so every later
            # result() joins instantly and re-raises the same error.
            raise payload
        self._value = payload
        self._waiter = None
        self._outcome = None
        return self._value


def _host_counts(labels: np.ndarray, num_classes: int) -> np.ndarray:
    """[Q, C] neighbor-label histogram on host. One flattened bincount
    (np.add.at's unbuffered scatter is ~10x slower at scale)."""
    nq, c = labels.shape[0], num_classes
    return np.bincount(
        (np.arange(nq)[:, None] * c + labels).ravel(), minlength=nq * c
    ).reshape(nq, c)


def _host_vote(labels: np.ndarray, num_classes: int) -> np.ndarray:
    """NumPy twin of ops/vote.py: per-row class counts, argmax with ties to
    the LOWEST class id (np.argmax returns the first maximum — the same
    first-max rule, main.cpp:70-74)."""
    return np.argmax(_host_counts(labels, num_classes), axis=1).astype(np.int32)


def _inverse_distance_weights(dists: np.ndarray):
    """Shared inverse-distance weighting for both model families: float64
    weights (1/d on tiny f32 distances overflows), exact-distance-0 matches
    claim all the weight, and rows whose weights all vanish (all-inf
    distances) are flagged for a uniform fallback. Returns ``(w, degenerate)``
    where ``degenerate`` marks rows needing the uniform treatment."""
    dists = dists.astype(np.float64)
    exact = dists == 0.0
    any_exact = exact.any(axis=1)
    with np.errstate(divide="ignore"):
        w = np.where(exact, 0.0, 1.0 / dists)
    w = np.where(any_exact[:, None], exact.astype(np.float64), w)
    degenerate = w.sum(axis=1) == 0
    return w, degenerate


def vote_from_labels(dists: np.ndarray, labels: np.ndarray,
                     num_classes: int, weights: str) -> np.ndarray:
    """Classifier vote from an EXPLICIT per-candidate label matrix
    ``labels [Q, k]`` — the label-lookup-agnostic half of
    :meth:`KNNClassifier.predict_from_candidates`. The serving mutable
    tier (``knn_tpu/mutable/``) votes through this with labels gathered
    from the base+delta id space, so a delta-row neighbor votes with its
    OWN label instead of a clamped base row's; both callers share the one
    first-max / inverse-distance contract (SURVEY.md §3.5)."""
    if weights == "distance":
        w, degenerate = _inverse_distance_weights(np.asarray(dists))
        w = np.where(degenerate[:, None], 1.0, w)
        scores = np.zeros((labels.shape[0], num_classes))
        for c in range(num_classes):
            scores[:, c] = np.where(labels == c, w, 0.0).sum(axis=1)
        return np.argmax(scores, axis=1).astype(np.int32)
    return _host_vote(labels, num_classes)


def aggregate_targets(dists: np.ndarray, neigh: np.ndarray,
                      weights: str) -> np.ndarray:
    """Regression aggregation from an EXPLICIT neighbor-target matrix
    ``neigh [Q, k]`` — the target-lookup-agnostic half of
    :meth:`KNNRegressor.predict`, shared with the serving mutable tier
    for the same reason as :func:`vote_from_labels`."""
    if weights == "uniform":
        return neigh.mean(axis=1).astype(np.float32)
    w, degenerate = _inverse_distance_weights(dists)
    w_sum = w.sum(axis=1)
    weighted = (w * neigh).sum(axis=1) / np.where(degenerate, 1.0, w_sum)
    # All-inf distances (e.g. NaN queries) zero every weight; fall back to
    # the uniform mean rather than emitting 0/0.
    return np.where(degenerate, neigh.mean(axis=1), weighted).astype(np.float32)


def radius_neighbors_arrays(
    train_x: np.ndarray,
    test_x: np.ndarray,
    radius: float,
    max_neighbors: int = 128,
    metric: str = "euclidean",
    engine: str = "auto",
    cache: "dict | None" = None,
):
    """All train rows within ``radius`` of each query, as fixed-shape masked
    arrays — the TPU-friendly formulation (variable-length results defeat
    static shapes): ``(dists [Q,m], indices [Q,m], mask [Q,m])`` where
    ``m = min(max_neighbors, N)``, candidates sorted by (distance, index),
    ``mask`` marking the within-radius entries. Euclidean radii are compared
    against *squared* distances, matching the framework's distance values.

    Raises when a query's neighborhood might exceed ``max_neighbors`` (every
    returned candidate in-radius with more train rows unseen) rather than
    silently truncating.
    """
    n = train_x.shape[0]
    m = min(max_neighbors, n)
    d, i = _kneighbors_arrays(
        train_x, test_x, m, metric=metric, engine=engine, cache=cache
    )
    mask = d <= radius
    full = mask.all(axis=1)
    if m < n and bool(full.any()):
        rows = np.nonzero(full)[0][:5]
        raise ValueError(
            f"queries {rows.tolist()} have at least {m} neighbors within "
            f"radius {radius}; raise max_neighbors (or shrink the radius) to "
            f"get complete neighborhoods"
        )
    return d, i, mask


def sweep_k(train: Dataset, test: Dataset, ks, metric="euclidean", engine="auto"):
    """Predictions for EVERY k in ``ks`` from one shared retrieval.

    The reference's own benchmark workflow reruns the whole binary per k
    (BASELINE.json runs k=1/5/10 as separate jobs, re-reading and re-scanning
    the train set each time). Here the candidate list is computed once for
    ``max(ks)`` and each k votes over its prefix — correct because candidates
    are sorted ascending by (distance, train index), so the first k entries
    ARE that k's exact neighbor set under the reference's tie rule
    (SURVEY.md §3.5). Returns ``{k: [Q] int32 predictions}``; each entry is
    identical to an individual ``predict`` at that k.
    """
    import jax.numpy as jnp

    from knn_tpu.ops.vote import vote

    ks = sorted({int(k) for k in ks})
    if not ks or ks[0] < 1:
        raise ValueError(f"ks must be positive integers, got {sorted(ks)}")
    kmax = ks[-1]
    train.validate_for_knn(kmax, test)
    with obs.span("sweep_k", kmax=kmax, num_ks=len(ks)):
        _, idx = _kneighbors_arrays(
            train.features, test.features, kmax, metric=metric, engine=engine,
            cache=train.device_cache,
        )
        import jax

        with obs.span("vote", num_ks=len(ks)):
            labels = jnp.asarray(
                train.labels[np.minimum(idx, train.num_instances - 1)]
            )
            # One batched fetch for every k's vote — per-k np.asarray would
            # pay a device->host round trip per k (~100 ms each on a
            # tunneled device).
            return jax.device_get(
                {k: vote(labels[:, :k], train.num_classes) for k in ks}
            )


class KNNClassifier:
    """k-nearest-neighbor classifier with reference-exact tie semantics
    (SURVEY.md §3.5) and a pluggable execution strategy.

    >>> model = KNNClassifier(k=5, backend="tpu")
    >>> model.fit(train_ds)
    >>> preds = model.predict(test_ds)
    >>> model.score(test_ds)
    """

    def __init__(
        self, k: int, backend: str = "tpu", metric: str = "euclidean",
        weights: str = "uniform", **backend_opts,
    ):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if weights not in ("uniform", "distance"):
            raise ValueError(f"weights must be 'uniform' or 'distance', got {weights!r}")
        if weights == "distance" and (
            backend != "tpu" or set(backend_opts) - {"engine"}
        ):
            # ``engine`` is exempt: the weighted vote runs on the candidate
            # kernel, which honors engine selection (VERDICT r1 #6).
            raise ValueError(
                "weights='distance' computes its vote from the JAX candidate "
                "kernel; a backend choice or backend options (except "
                "'engine') would be silently ignored — drop them or use "
                "weights='uniform'"
            )
        from knn_tpu.ops.distance import resolve_form

        resolve_form("exact", metric)  # validate early
        self.k = k
        self.backend_name = backend
        self.metric = metric
        self.weights = weights
        self.backend_opts = backend_opts
        self._train: Optional[Dataset] = None

    def fit(self, train: Dataset) -> "KNNClassifier":
        with obs.span("fit", k=self.k):
            train.validate_for_knn(self.k)
            self._train = train
        return self

    @property
    def train_(self) -> Dataset:
        if self._train is None:
            raise RuntimeError("call fit() before predict()/score()")
        return self._train

    def predict(self, test: Dataset) -> np.ndarray:
        if self.weights == "distance":
            # Weighted vote (opt-in extension; the reference vote is an
            # unweighted bincount, main.cpp:65-67): per-class inverse-distance
            # weight sums, ties to the lowest class id like the reference.
            scores = self._weighted_class_scores(test)
            with obs.span("vote", weighted=True):
                return np.argmax(scores, axis=1).astype(np.int32)
        fn = get_backend(self.backend_name)
        return fn(self.train_, test, self.k, metric=self.metric, **self.backend_opts)

    def _weighted_class_scores(
        self, test: Optional[Dataset] = None, neighbors=None
    ) -> np.ndarray:
        train = self.train_
        dists, idx = neighbors if neighbors is not None else self.kneighbors(test)
        labels = train.labels[np.minimum(idx, train.num_instances - 1)]
        w, degenerate = _inverse_distance_weights(dists)
        w = np.where(degenerate[:, None], 1.0, w)  # degenerate rows: uniform
        scores = np.zeros((dists.shape[0], train.num_classes))
        for c in range(train.num_classes):
            scores[:, c] = np.where(labels == c, w, 0.0).sum(axis=1)
        return scores

    def predict_from_candidates(
        self, dists: np.ndarray, idx: np.ndarray
    ) -> np.ndarray:
        """Predictions from an already-retrieved candidate set — the vote
        half of :meth:`predict_async`, shared with the serving micro-batcher
        (``knn_tpu/serve/batcher.py``), which retrieves candidates for a
        whole coalesced batch and votes per request slice. Identical
        predictions to :meth:`predict` by the shared (distance, train-index,
        first-max vote) contracts (SURVEY.md §3.5)."""
        train = self.train_
        labels = train.labels[np.minimum(idx, train.num_instances - 1)]
        if self.weights == "distance":
            with obs.span("vote", weighted=True):
                return vote_from_labels(dists, labels, train.num_classes,
                                        "distance")
        with obs.span("vote"):
            return vote_from_labels(dists, labels, train.num_classes,
                                    "uniform")

    def kneighbors(self, test: Dataset):
        """Per-query neighbor candidates: ``(dists [Q,k], indices [Q,k])``
        sorted ascending by (distance, train index) — the framework's
        tie-break order. No reference analogue (its kernel discards the
        candidate set after voting, main.cpp:64-78); standard retrieval API.
        """
        train = self.train_
        train.validate_for_knn(self.k, test)
        return _kneighbors_arrays(
            train.features, test.features, self.k, metric=self.metric,
            engine=self._retrieval_engine(), cache=train.device_cache,
        )

    def kneighbors_async(self, test: Dataset) -> AsyncResult:
        """:meth:`kneighbors` with the blocking host sync deferred: device
        work (and the device->host copies) are in flight when this returns;
        ``.result()`` on the handle blocks once and returns the identical
        ``(dists, indices)`` (pinned by tests/test_models_engine.py). Use
        for interactive/many-call workloads: the fixed per-sync tunnel
        round trip amortizes across every handle resolved afterward."""
        train = self.train_
        train.validate_for_knn(self.k, test)
        return AsyncResult(_kneighbors_arrays(
            train.features, test.features, self.k, metric=self.metric,
            engine=self._retrieval_engine(), cache=train.device_cache,
            deferred=True,
        ))

    def predict_async(self, test: Dataset) -> AsyncResult:
        """:meth:`predict` as a future. Computed from the candidate kernel
        (same engine selection as :meth:`kneighbors`) with the host-side
        vote twin — identical predictions to ``predict`` by the shared
        (distance, train-index, first-max vote) contracts (SURVEY.md §3.5;
        pinned by tests), independent of the fitted ``backend`` name, which
        an async dispatch cannot honor for host backends (oracle/native)."""
        train = self.train_
        train.validate_for_knn(self.k, test)
        resolve = _kneighbors_arrays(
            train.features, test.features, self.k, metric=self.metric,
            engine=self._retrieval_engine(), cache=train.device_cache,
            deferred=True,
        )

        def finish():
            return self.predict_from_candidates(*resolve())

        return AsyncResult(finish)

    def _retrieval_engine(self) -> str:
        """The backend ``engine`` opt translated for the candidate kernel:
        ring-only per-step scorers ('full'/'tiled') have no retrieval
        counterpart, so they defer to auto selection."""
        engine = self.backend_opts.get("engine", "auto")
        return "auto" if engine in ("full", "tiled") else engine

    def radius_neighbors(
        self, test: Dataset, radius: float, max_neighbors: int = 128
    ):
        """Within-radius retrieval (``(dists, indices, mask)`` fixed-shape
        masked arrays — see :func:`radius_neighbors_arrays`)."""
        train = self.train_
        train.validate_for_knn(1, test)
        return radius_neighbors_arrays(
            train.features, test.features, radius, max_neighbors, self.metric,
            engine=self._retrieval_engine(), cache=train.device_cache,
        )

    def predict_proba(self, test: Dataset) -> np.ndarray:
        """[Q, num_classes] neighbor-vote fractions: counts/k for uniform
        weights, normalized inverse-distance weight sums otherwise."""
        train = self.train_
        if self.weights == "distance":
            scores = self._weighted_class_scores(test)
            return scores / scores.sum(axis=1, keepdims=True)
        _, idx = self.kneighbors(test)
        labels = train.labels[np.minimum(idx, train.num_instances - 1)]
        return _host_counts(labels, train.num_classes).astype(np.float64) / self.k

    def confusion_matrix(self, test: Dataset, predictions: Optional[np.ndarray] = None) -> np.ndarray:
        if predictions is None:
            predictions = self.predict(test)
        return confusion_matrix(predictions, test.labels, test.num_classes)

    def score(self, test: Dataset, predictions: Optional[np.ndarray] = None) -> float:
        return accuracy(self.confusion_matrix(test, predictions))


class KNNRegressor:
    """k-nearest-neighbor regression — a model family the reference does not
    have (its pipeline casts the class column to int unconditionally,
    main.cpp:57); the framework keeps the uncast column
    (``Dataset.raw_targets``) so numeric targets survive ingest.

    Neighbor selection is identical to the classifier (squared Euclidean,
    lexicographic (distance, train-index) order — SURVEY.md §3.5), so the
    same TPU candidate kernel backs both models. ``weights``:

    - ``"uniform"``: mean of the k neighbor targets.
    - ``"distance"``: inverse-distance weighting; when a query coincides
      exactly with train rows (distance 0), the prediction is the mean of
      those exact matches only.
    """

    def __init__(
        self, k: int, weights: str = "uniform", metric: str = "euclidean",
        engine: str = "auto",
    ):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if weights not in ("uniform", "distance"):
            raise ValueError(f"weights must be 'uniform' or 'distance', got {weights!r}")
        if engine not in ("auto", "stripe", "xla"):
            raise ValueError(
                f"unknown engine {engine!r}; choose 'auto', 'stripe', or 'xla'"
            )
        from knn_tpu.ops.distance import resolve_form

        resolve_form("exact", metric)  # validate early
        self.k = k
        self.weights = weights
        self.metric = metric
        self.engine = engine
        self._train: Optional[Dataset] = None

    def fit(self, train: Dataset) -> "KNNRegressor":
        with obs.span("fit", k=self.k):
            if self.k > train.num_instances:
                raise ValueError(
                    f"k={self.k} exceeds the number of train instances "
                    f"({train.num_instances})"
                )
            self._train = train
        return self

    @property
    def train_(self) -> Dataset:
        if self._train is None:
            raise RuntimeError("call fit() before predict()/score()")
        return self._train

    def _check_features(self, test: Dataset) -> Dataset:
        train = self.train_
        if test.num_features != train.num_features:
            raise ValueError(
                f"train has {train.num_features} features but test has "
                f"{test.num_features}"
            )
        return train

    def radius_neighbors(
        self, test: Dataset, radius: float, max_neighbors: int = 128
    ):
        """Within-radius retrieval — see :func:`radius_neighbors_arrays`."""
        train = self._check_features(test)
        return radius_neighbors_arrays(
            train.features, test.features, radius, max_neighbors, self.metric,
            engine=self.engine, cache=train.device_cache,
        )

    def kneighbors(self, test: Dataset):
        """Same candidate kernel as the classifier, without its label
        validation (regression targets may be negative/non-integer)."""
        train = self._check_features(test)
        return _kneighbors_arrays(
            train.features, test.features, self.k, metric=self.metric,
            engine=self.engine, cache=train.device_cache,
        )

    def kneighbors_async(self, test: Dataset) -> AsyncResult:
        """:meth:`kneighbors` as a future — see the classifier's
        :meth:`KNNClassifier.kneighbors_async` for the round-trip
        amortization this buys."""
        train = self._check_features(test)
        return AsyncResult(_kneighbors_arrays(
            train.features, test.features, self.k, metric=self.metric,
            engine=self.engine, cache=train.device_cache, deferred=True,
        ))

    def predict_async(self, test: Dataset) -> AsyncResult:
        """:meth:`predict` as a future (identical values: same retrieval,
        same host-side aggregation)."""
        handle = self.kneighbors_async(test)
        return AsyncResult(lambda: self._predict_from(handle.result()))

    def predict(self, test: Dataset) -> np.ndarray:
        return self._predict_from(self.kneighbors(test))

    def _predict_from(self, neighbors) -> np.ndarray:
        train = self.train_
        dists, idx = neighbors
        neigh = train.targets[np.minimum(idx, train.num_instances - 1)]
        return aggregate_targets(dists, neigh, self.weights)

    def score(self, test: Dataset, predictions: Optional[np.ndarray] = None) -> float:
        """Coefficient of determination R^2 against ``test.targets``."""
        if predictions is None:
            predictions = self.predict(test)
        y = test.targets.astype(np.float64)
        p = predictions.astype(np.float64)
        ss_res = float(((y - p) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return 1.0 - ss_res / ss_tot if ss_tot else (1.0 if ss_res == 0 else 0.0)
