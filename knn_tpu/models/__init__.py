from knn_tpu.models.knn import KNNClassifier

__all__ = ["KNNClassifier"]
