from knn_tpu.models.knn import KNNClassifier, KNNRegressor

__all__ = ["KNNClassifier", "KNNRegressor"]
