"""THE (distance, index) lexicographic tie-order contract, in one place.

Every retrieval rung in the framework returns candidates sorted ascending
by ``(distance, train index)`` — the reference's strict ``<`` insertion
keeps the first-scanned candidate among equal distances (main.cpp:46-61),
and a stable lexicographic sort over (distance, index) reproduces exactly
that (SURVEY.md §3.5). Until PR 9 the host-side realization of the rule
lived only inside the oracle backend's loop; the IVF index family added a
second host consumer, so the contract moved here:

- :func:`~knn_tpu.backends.oracle.oracle_kneighbors` (the serving
  ladder's truth anchor) selects through :func:`lexicographic_topk`;
- the IVF candidate scorer (``knn_tpu/index/ivf.py``) selects its probed
  candidates through the same call — which is what makes
  ``nprobe == num_cells`` *bit-identical* to exact retrieval;
- the device kernels (XLA tiled scan, stripe Pallas kernel, approx guard)
  implement the rule in-kernel for shape reasons and are pinned AGAINST
  this helper by tests/test_ivf.py::TestTieOrderEveryRung — the helper is
  the executable spec they must match, not a path they share.

NaN handling is the caller's job (the framework-wide NaN → +inf policy is
applied where distances are computed); this module only orders.
"""

from __future__ import annotations

import numpy as np


def lexicographic_topk(dists: np.ndarray, indices: np.ndarray, k: int):
    """Select each row's ``k`` best candidates under the (distance, index)
    lexicographic order.

    ``dists``   — ``[Q, M]`` candidate distances (any float dtype; the
                  output keeps it);
    ``indices`` — ``[Q, M]`` candidate train indices, or ``[M]`` shared by
                  every row (the oracle's full-scan case);
    ``k``       — clamped to ``M``.

    Returns ``(dists [Q, k], indices [Q, k] int64)`` sorted ascending by
    (distance, index) — equal distances break to the LOWEST train index,
    reproducing the reference's first-seen-wins insertion.

    Two realizations of the ONE order: non-negative float32 distances
    (every metric in the framework produces them — squared euclidean,
    L1/L∞, 1-cosine, with NaN already mapped to +inf) take a vectorized
    packed-key path — the IEEE bit pattern of a non-negative float is
    monotone as an unsigned integer, so ``(distance_bits << 32) | index``
    is ONE uint64 key whose integer order IS the lexicographic
    (distance, index) order, letting argpartition + argsort select top-k
    with no per-row Python. Anything else (float64 scores, negative
    values) falls back to a stable per-row ``np.lexsort``. Both paths are
    pinned equal on adversarial tie data by tests/test_ivf.py.
    """
    dists = np.asarray(dists)
    if dists.ndim != 2:
        raise ValueError(f"dists must be [Q, M], got shape {dists.shape}")
    q, m = dists.shape
    k = min(int(k), m)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    indices = np.asarray(indices)
    shared = indices.ndim == 1
    if (shared and indices.shape[0] != m) or (
            not shared and indices.shape != dists.shape):
        raise ValueError(
            f"indices must be [M] or [Q, M] matching dists {dists.shape}, "
            f"got {indices.shape}"
        )
    if (dists.dtype == np.float32 and m and indices.size
            and int(indices.min()) >= 0 and int(indices.max()) < 2 ** 32
            and not bool((dists < 0).any())):
        return _packed_topk_f32(dists, indices, k, shared)
    d_out = np.empty((q, k), dists.dtype)
    i_out = np.empty((q, k), np.int64)
    for row in range(q):
        row_idx = indices if shared else indices[row]
        # Stable (distance, index) ordering == first-seen-wins insertion.
        order = np.lexsort((row_idx, dists[row]))[:k]
        i_out[row] = row_idx[order]
        d_out[row] = dists[row][order]
    return d_out, i_out


def lexicographic_topk_jax(dists, indices, k: int, *payload):
    """The DEVICE realization of the same contract (traced; callers jit):
    one two-key ``lax.sort`` over (distance, index), ascending, equal
    distances breaking to the lowest index — exactly
    :func:`lexicographic_topk`'s order, on device arrays.

    ``dists``/``indices`` — ``[..., M]`` candidate arrays (indices must be
    a sortable integer dtype); ``k`` — static slice width (clamped to M by
    the slice itself); ``payload`` — extra ``[..., M]`` operands carried
    through the permutation WITHOUT participating in the key (the
    train-sharded merge rides its gathered labels here). Returns the
    sorted ``k``-prefix of every operand: ``(d, i)`` or
    ``(d, i, *payload)``.

    This is the one definition the in-kernel consumers share —
    ``ops/segment_score.margin_select``'s exact tie branch and
    ``parallel/train_sharded.merge_candidates_vote`` both select through
    it — and it is pinned against the host twin on adversarial tie
    plateaus by tests/test_shard.py.
    """
    from jax import lax

    ordered = lax.sort((dists, indices, *payload), dimension=-1,
                       num_keys=2)
    return tuple(o[..., :k] for o in ordered)


def _packed_topk_f32(dists: np.ndarray, indices: np.ndarray, k: int,
                     shared: bool):
    """The vectorized realization: uint64 keys ``(f32 bits << 32) | idx``.

    Key equality implies (distance, index) equality, so the unstable
    argsort under the keys cannot reorder anything observable; key order
    equals lexicographic order because non-negative IEEE-754 bit patterns
    compare like the floats they encode (+0.0 is the only zero a squared
    or absolute distance produces, so the -0.0 wrinkle never arises).
    """
    q, m = dists.shape
    bits = np.ascontiguousarray(dists).view(np.uint32).astype(np.uint64)
    keys = (bits << np.uint64(32)) | indices.astype(np.uint64)
    if k == m:
        final = np.argsort(keys, axis=1)
    else:
        part = np.argpartition(keys, k - 1, axis=1)[:, :k]
        pk = np.take_along_axis(keys, part, axis=1)
        final = np.take_along_axis(part, np.argsort(pk, axis=1), axis=1)
    d_out = np.take_along_axis(dists, final, axis=1)
    if shared:
        i_out = np.broadcast_to(indices, (q, m))
        i_out = np.take_along_axis(i_out, final, axis=1).astype(np.int64)
    else:
        i_out = np.take_along_axis(indices, final, axis=1).astype(np.int64)
    return d_out, i_out
