"""Query-drift detection: streaming distribution sketches vs a baseline.

A KNN index answers from the training distribution; when the live query
distribution walks away from it (new feature scaling upstream, a client
sending unnormalized rows, a population shift), answer quality degrades
with NO error signal — every request still returns 200 with k neighbors.
This module gives the serving stack the missing signal:

- :class:`P2Quantile` — the P² algorithm (Jain & Chlamtac 1985): one
  quantile estimated online with five markers, O(1) memory and O(1) per
  observation, no sample retention. Accuracy is pinned against numpy on
  fixed seeds in tests/test_quality.py.
- :class:`StreamSketch` — a per-feature distribution sketch: Welford
  mean/variance (the numerically-stable streaming moments) plus P²
  estimates of the quartiles. :meth:`StreamSketch.from_data` computes the
  same summary EXACTLY from a full matrix — that is what ``save-index``
  stores in the artifact manifest as the reference (training)
  distribution, so the baseline costs one pass at build time and nothing
  at serve time.
- :class:`DriftMonitor` — the serving-side consumer: probabilistically
  samples query rows (seeded, ``--drift-rate``, default off) into a
  bounded shed-on-overload queue drained by a background worker (the
  same never-block-the-batcher contract as
  :class:`~knn_tpu.obs.quality.ShadowScorer`), folds them into a live
  :class:`StreamSketch`, and scores the live sketch against the
  reference: per-feature mean shift in reference-σ units and quartile
  shift in reference-IQR units, the max over both exposed as
  ``knn_drift_score{stat=max|mean}`` gauges and joined with recall in
  ``GET /debug/quality``.

No-baseline contract (the artifact back-compat guard): a pre-sketch
artifact (format 1) loads cleanly and the monitor reports a distinct
``baseline: "absent"`` state — ``knn_drift_baseline_present`` 0 and NO
drift-score gauges (score gauges already exported under a previous
baseline are zeroed, since the registry has no instrument removal) —
rather than fabricating scores against nothing. A malformed or
wrong-width manifest sketch fails loudly at boot/reload time
(``ValueError`` → CLI exit 2 / reload rolled back), never as a numpy
error inside a scrape.

Like every obs layer: not constructed (rate 0 / no ``--drift-rate``) →
the batcher pays one ``is None`` predicate and nothing is recorded.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

from knn_tpu import obs
from knn_tpu.obs.shedqueue import ShedQueue

#: Quantiles every sketch tracks (the quartiles: location + spread without
#: moment sensitivity to tails).
SKETCH_QUANTILES = (0.25, 0.5, 0.75)

#: Guard against zero-variance reference features: shifts are reported in
#: units of max(reference scale, this floor) so a constant train column
#: cannot make every live deviation an infinite score.
_SCALE_FLOOR = 1e-6


class P2Quantile:
    """One quantile estimated online by the P² algorithm.

    Five markers track (min, p/2, p, (1+p)/2, max); each observation moves
    the marker heights by a piecewise-parabolic interpolation toward their
    desired positions. Until five observations arrive, :attr:`value` is
    the exact sample quantile of what has been seen.
    """

    __slots__ = ("p", "n", "_heights", "_pos", "_desired", "_inc")

    def __init__(self, p: float):
        if not 0.0 < p < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {p}")
        self.p = float(p)
        self.n = 0
        self._heights: List[float] = []
        self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
        self._desired = [1.0, 1 + 2 * p, 1 + 4 * p, 3 + 2 * p, 5.0]
        self._inc = [0.0, p / 2, p, (1 + p) / 2, 1.0]

    def update(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if self.n <= 5:
            self._heights.append(x)
            self._heights.sort()
            return
        h, pos = self._heights, self._pos
        # Locate the cell and bump marker positions above it.
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while x >= h[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._desired[i] += self._inc[i]
        # Adjust the three interior markers toward their desired positions.
        for i in (1, 2, 3):
            d = self._desired[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (
                    d <= -1.0 and pos[i - 1] - pos[i] < -1.0):
                d = 1.0 if d >= 1.0 else -1.0
                cand = self._parabolic(i, d)
                if h[i - 1] < cand < h[i + 1]:
                    h[i] = cand
                else:  # parabolic estimate left the bracket: linear step
                    j = i + int(d)
                    h[i] = h[i] + d * (h[j] - h[i]) / (pos[j] - pos[i])
                pos[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        h, q = self._pos, self._heights
        return q[i] + d / (h[i + 1] - h[i - 1]) * (
            (h[i] - h[i - 1] + d) * (q[i + 1] - q[i]) / (h[i + 1] - h[i])
            + (h[i + 1] - h[i] - d) * (q[i] - q[i - 1]) / (h[i] - h[i - 1])
        )

    @property
    def value(self) -> Optional[float]:
        if self.n == 0:
            return None
        if self.n <= 5:
            # Exact quantile of the few samples seen (linear interpolation,
            # numpy's default convention).
            return float(np.quantile(self._heights, self.p))
        return self._heights[2]


class StreamSketch:
    """Per-feature distribution sketch: Welford mean/var + P² quartiles.

    :meth:`update` folds a ``[rows, D]`` block in (moments vectorized via
    Chan's parallel-update form; P² markers per value). :meth:`to_dict` /
    :meth:`from_dict` serialize the summary (counts, moments, quantiles —
    never samples) for the artifact manifest.
    """

    def __init__(self, num_features: int,
                 quantiles: Sequence[float] = SKETCH_QUANTILES):
        if num_features < 1:
            raise ValueError(f"num_features must be >= 1, got {num_features}")
        self.num_features = int(num_features)
        self.quantile_ps = tuple(float(p) for p in quantiles)
        self.count = 0
        self._mean = np.zeros(self.num_features, np.float64)
        self._m2 = np.zeros(self.num_features, np.float64)
        self._p2 = [[P2Quantile(p) for p in self.quantile_ps]
                    for _ in range(self.num_features)]

    def update(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows, np.float64)
        if rows.ndim == 1:
            rows = rows[None, :]
        if rows.shape[1] != self.num_features:
            raise ValueError(
                f"sketch expects {self.num_features} features, got "
                f"{rows.shape[1]}"
            )
        b = rows.shape[0]
        if b == 0:
            return
        # Chan's parallel moment merge: exact for any block size.
        b_mean = rows.mean(axis=0)
        b_m2 = ((rows - b_mean) ** 2).sum(axis=0)
        delta = b_mean - self._mean
        n = self.count + b
        self._mean += delta * (b / n)
        self._m2 += b_m2 + delta ** 2 * (self.count * b / n)
        self.count = n
        for j in range(self.num_features):
            col = rows[:, j]
            for est in self._p2[j]:
                for v in col:
                    est.update(v)

    # -- summaries ---------------------------------------------------------

    def mean(self) -> np.ndarray:
        return self._mean.copy()

    def variance(self) -> np.ndarray:
        if self.count < 2:
            return np.zeros(self.num_features, np.float64)
        return self._m2 / (self.count - 1)

    def quantile(self, p: float) -> List[Optional[float]]:
        i = self.quantile_ps.index(float(p))
        return [ests[i].value for ests in self._p2]

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "count": self.count,
            "num_features": self.num_features,
            "mean": [round(float(v), 8) for v in self._mean],
            "var": [round(float(v), 8) for v in self.variance()],
            "quantiles": {
                str(p): [None if v is None else round(float(v), 8)
                         for v in self.quantile(p)]
                for p in self.quantile_ps
            },
        }

    @classmethod
    def from_data(cls, features: np.ndarray) -> "StreamSketch":
        """EXACT summary of a full matrix in sketch form — the reference
        (training) sketch ``save-index`` computes: one numpy pass, no P²
        approximation on the baseline side."""
        features = np.asarray(features, np.float64)
        if features.ndim != 2:
            raise ValueError(f"features must be [rows, D], got "
                             f"{features.shape}")
        s = cls(features.shape[1])
        s.count = int(features.shape[0])
        if s.count:
            s._mean = features.mean(axis=0)
            s._m2 = ((features - s._mean) ** 2).sum(axis=0)
            for i, p in enumerate(s.quantile_ps):
                qs = np.quantile(features, p, axis=0)
                for j in range(s.num_features):
                    est = s._p2[j][i]
                    est.n = s.count
                    # Exact value carried in the P² slot the consumers read.
                    est._heights = [float(qs[j])] * 5
        return s


def sketch_summary(doc: dict) -> dict:
    """Validate + normalize a serialized sketch (manifest field or live
    :meth:`StreamSketch.to_dict`); raises ``ValueError`` on malformed
    documents so a hand-edited manifest fails loudly at boot, not with a
    numpy broadcast error at the first scrape."""
    if not isinstance(doc, dict):
        raise ValueError(f"drift sketch must be an object, got "
                         f"{type(doc).__name__}")
    try:
        d = int(doc["num_features"])
        out = {
            "count": int(doc["count"]),
            "num_features": d,
            "mean": np.asarray(doc["mean"], np.float64),
            "var": np.asarray(doc["var"], np.float64),
            "quantiles": {
                float(p): np.asarray(
                    [np.nan if v is None else v for v in vals], np.float64)
                for p, vals in (doc.get("quantiles") or {}).items()
            },
        }
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"malformed drift sketch: {e!r}") from e
    if out["mean"].shape != (d,) or out["var"].shape != (d,):
        raise ValueError("drift sketch moment arrays do not match "
                         "num_features")
    for p, vals in out["quantiles"].items():
        if vals.shape != (d,):
            raise ValueError(f"drift sketch quantile {p} does not match "
                             f"num_features")
    return out


def drift_scores(reference: dict, live: dict) -> np.ndarray:
    """Per-feature drift score between two normalized sketch summaries:
    the max of (|Δmean| in reference-σ units) and (|Δquantile| in
    reference-IQR units, over the shared quantiles). 0 = identical
    distributions; ~1 = the live distribution moved by a full reference
    scale unit — worth an operator's attention; >>1 = a different
    distribution entirely."""
    sigma = np.sqrt(np.maximum(reference["var"], 0.0))
    sigma = np.maximum(sigma, _SCALE_FLOOR)
    score = np.abs(live["mean"] - reference["mean"]) / sigma
    ref_q, live_q = reference["quantiles"], live["quantiles"]
    if 0.25 in ref_q and 0.75 in ref_q:
        iqr = np.maximum(ref_q[0.75] - ref_q[0.25], _SCALE_FLOOR)
    else:
        iqr = sigma
    for p, ref_vals in ref_q.items():
        if p not in live_q:
            continue
        d = np.abs(live_q[p] - ref_vals) / iqr
        score = np.maximum(score, np.nan_to_num(d, nan=0.0))
    return score


class DriftMonitor:
    """The serving-side drift layer: sampled query rows → background
    sketch update → scored against the reference sketch at scrape time.

    ``offer`` is the batcher tap: one seeded RNG draw per request; a
    sampled row block is appended to a bounded queue (full → dropped and
    counted, NEVER blocking the batcher worker). The background worker
    folds samples into the live sketch. ``reference`` is the normalized
    manifest sketch (:func:`sketch_summary`) or None — the no-baseline
    state (pre-sketch artifacts) is reported distinctly, never scored.
    """

    def __init__(self, reference: Optional[dict], *, rate: float,
                 num_features: int, queue_cap: int = 256, seed: int = 0,
                 autostart: bool = True):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"drift rate must be in [0, 1], got {rate}")
        if queue_cap < 1:
            raise ValueError(f"queue_cap must be >= 1, got {queue_cap}")
        self.rate = float(rate)
        self.num_features = int(num_features)
        self._reference = self._normalize_reference(reference)
        # The sketch lock guards the live sketch + sample counter: a
        # per-value P² update can take milliseconds and must never stall
        # an admission, so the queue the batcher touches lives in the
        # ShedQueue (its own O(1)-critical-section lock).
        self._sketch_lock = threading.Lock()
        self._scores_exported = False
        self.live = StreamSketch(self.num_features)
        self.sampled_rows = 0
        self._sq = ShedQueue(
            rate=rate, queue_cap=queue_cap, seed=seed,
            consume=self._ingest, thread_name="knn-drift-monitor",
            on_shed=lambda: obs.counter_add(
                "knn_drift_shed_total",
                help="sampled query blocks dropped because the drift "
                     "queue was full (shed-on-overload — the batcher "
                     "worker never blocks on drift)",
            ),
            on_error=lambda: obs.counter_add(
                "knn_drift_errors_total",
                help="drift sketch updates that raised (dropped)",
            ),
            autostart=autostart,
        )

    @property
    def queue_cap(self) -> int:
        return self._sq.queue_cap

    @property
    def shed(self) -> int:
        return self._sq.shed

    def set_rate(self, rate: float) -> None:
        """Move the live sampling rate (the control plane's brownout
        knob — :mod:`knn_tpu.control.brownout`)."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"drift rate must be in [0, 1], got {rate}")
        self.rate = float(rate)
        self._sq.rate = float(rate)

    def set_defer(self, defer) -> None:
        """Install (or clear, with None) the brownout's headroom gate —
        see :meth:`knn_tpu.obs.quality.ShadowScorer.set_defer`."""
        self._sq.defer = defer

    # -- producer side (the batcher worker thread) -------------------------

    def offer(self, features: np.ndarray) -> bool:
        """Sample one request's query rows; O(1), never blocks (the
        :class:`~knn_tpu.obs.shedqueue.ShedQueue` contract). Returns
        whether the rows were queued."""
        return self._sq.offer(lambda: features)

    # -- worker side -------------------------------------------------------

    def _ingest(self, rows: np.ndarray) -> None:
        with self._sketch_lock:
            self.live.update(rows)
            self.sampled_rows += rows.shape[0]
        obs.counter_add(
            "knn_drift_rows_total", int(rows.shape[0]),
            help="query rows folded into the live drift sketch",
        )

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Block until the queue is empty (tests + the soak gate); the
        serving path never calls this."""
        return self._sq.drain(timeout_s)

    def close(self) -> None:
        self._sq.close()

    # -- read side ---------------------------------------------------------

    @property
    def baseline_present(self) -> bool:
        return self._reference is not None

    def _normalize_reference(self, reference: Optional[dict]):
        """Validate a manifest sketch at BOOT/RELOAD time — a malformed or
        wrong-width sketch must fail loudly here (ValueError, exit 2 from
        the CLI), never as a numpy broadcast error inside the first
        /metrics scrape."""
        if reference is None:
            return None
        ref = sketch_summary(reference)
        if ref["num_features"] != self.num_features:
            raise ValueError(
                f"drift sketch covers {ref['num_features']} features but "
                f"the index serves {self.num_features} — the manifest "
                f"sketch does not describe this index's training set"
            )
        return ref

    def set_reference(self, reference: Optional[dict]) -> None:
        """Swap the baseline (the hot-reload path: a new artifact may add,
        change, or — for a pre-sketch rollback — remove the sketch).
        Raises ``ValueError`` on a malformed/mismatched sketch, leaving
        the previous baseline in place."""
        ref = self._normalize_reference(reference)
        with self._sketch_lock:
            self._reference = ref

    def scores(self) -> Optional[np.ndarray]:
        """Per-feature drift scores, or None while there is no baseline or
        no live sample yet."""
        with self._sketch_lock:
            ref = self._reference
            if ref is None or self.live.count == 0:
                return None
            live = {
                "mean": self.live.mean(),
                "var": self.live.variance(),
                "quantiles": {
                    p: np.asarray(
                        [np.nan if v is None else v
                         for v in self.live.quantile(p)], np.float64)
                    for p in self.live.quantile_ps
                },
            }
        return drift_scores(ref, live)

    def export(self) -> dict:
        """Refresh the ``knn_drift_*`` gauges (scrape-time, like
        ``knn_slo_*``) and return the summary ``/healthz`` and
        ``/debug/quality`` embed. The no-baseline state is DISTINCT:
        ``baseline: "absent"`` with no scores, never fabricated zeros."""
        obs.gauge_set(
            "knn_drift_baseline_present",
            1 if self.baseline_present else 0,
            help="1 when the serving artifact carries a reference "
                 "(training) drift sketch; 0 = pre-sketch artifact, drift "
                 "scoring disabled",
        )
        with self._sketch_lock:
            sampled = self.sampled_rows
        summary = {
            "rate": self.rate,
            "baseline": "present" if self.baseline_present else "absent",
            "sampled_rows": sampled,
            "shed": self.shed,
            "queue_depth": self._sq.depth(),
        }
        s = self.scores()
        if s is None:
            summary["scores"] = None
            if self._scores_exported:
                # A reload removed the baseline after scores had been
                # exported: the registry has no instrument removal, so
                # zero the gauges rather than leave the PREVIOUS index's
                # scores frozen in every future scrape
                # (knn_drift_baseline_present 0 marks them meaningless).
                obs.gauge_set("knn_drift_score", 0.0, stat="mean")
                obs.gauge_set("knn_drift_score", 0.0, stat="max")
            return summary
        mean_s, max_s = float(np.mean(s)), float(np.max(s))
        obs.gauge_set(
            "knn_drift_score", round(mean_s, 4),
            help="query-distribution drift vs the training sketch "
                 "(reference-scale units; ~0 = same distribution)",
            stat="mean",
        )
        obs.gauge_set("knn_drift_score", round(max_s, 4), stat="max")
        self._scores_exported = True
        worst = np.argsort(s)[::-1][:5]
        summary["scores"] = {
            "mean": round(mean_s, 4),
            "max": round(max_s, 4),
            "worst_features": [
                {"feature": int(j), "score": round(float(s[j]), 4)}
                for j in worst
            ],
        }
        return summary
