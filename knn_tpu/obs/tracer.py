"""Nested, thread-safe span tracing with Chrome/Perfetto export.

A :class:`SpanTracer` records :class:`Span` intervals — wall-clock epoch
time for humans, ``time.monotonic_ns()`` for durations and ordering — on a
per-thread span stack, so concurrently-traced threads nest independently
while all spans land in one shared buffer. The buffer exports as
Chrome ``trace_event`` JSON (the ``{"traceEvents": [...]}`` wrapper with
matched ``B``/``E`` duration events), which both ``chrome://tracing`` and
https://ui.perfetto.dev open directly.

Spans carry optional attributes (rendered as Perfetto ``args``) and an
optional ``jax.profiler.TraceAnnotation`` pass-through so host-side phases
line up with device timelines when a jax profiler trace is being taken
(``utils/timing.py::maybe_profile``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional


class Span:
    """One recorded interval. Created open by :meth:`SpanTracer.span`;
    ``dur_ns`` is set at exit. Context-manager use is the normal API."""

    __slots__ = (
        "name", "attrs", "start_ns", "dur_ns", "wall_start_s", "parent",
        "depth", "tid", "_tracer", "_annotation",
    )

    def __init__(self, tracer: "SpanTracer", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self.name = name
        self.attrs = attrs or {}
        self._tracer = tracer
        self.start_ns = 0
        self.dur_ns: Optional[int] = None
        self.wall_start_s = 0.0
        self.parent: Optional["Span"] = None
        self.depth = 0
        self.tid = 0
        self._annotation = None

    def __enter__(self) -> "Span":
        self._tracer._enter(self)
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer._exit(self)
        return False

    @property
    def dur_ms(self) -> float:
        return (self.dur_ns or 0) / 1e6


class SpanTracer:
    """Thread-safe collector of nested spans.

    One instance is process-global (``knn_tpu.obs.tracer()``); independent
    instances are cheap and fully isolated, which is what the tests use.
    """

    # Buffer bound for long-lived enabled processes (KNN_TPU_OBS=1 servers):
    # ~100k spans is hours of predict traffic at tens of spans/call; past it
    # new spans are counted in ``dropped`` instead of retained, so memory
    # stays bounded and the truncation is visible in the exported artifacts.
    DEFAULT_MAX_SPANS = 100_000

    def __init__(self, jax_annotations: bool = False,
                 max_spans: int = DEFAULT_MAX_SPANS):
        self.jax_annotations = jax_annotations
        self.max_spans = max_spans
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._local = threading.local()
        # Epoch anchor so monotonic timestamps export as one consistent
        # clock across threads.
        self._epoch_wall = time.time()
        self._epoch_ns = time.monotonic_ns()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _enter(self, s: Span) -> None:
        stack = self._stack()
        s.parent = stack[-1] if stack else None
        s.depth = len(stack)
        s.tid = threading.get_ident()
        stack.append(s)
        if self.jax_annotations:
            import jax

            s._annotation = jax.profiler.TraceAnnotation(s.name)
            s._annotation.__enter__()
        s.wall_start_s = time.time()
        s.start_ns = time.monotonic_ns()  # last: excludes setup from dur

    def _exit(self, s: Span) -> None:
        end_ns = time.monotonic_ns()  # first: excludes teardown from dur
        s.dur_ns = end_ns - s.start_ns
        if s._annotation is not None:
            s._annotation.__exit__(None, None, None)
            s._annotation = None
        stack = self._stack()
        if stack and stack[-1] is s:
            stack.pop()
        else:  # tolerate misnested exits rather than corrupting the stack
            try:
                stack.remove(s)
            except ValueError:
                pass
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(s)
            else:
                self.dropped += 1

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # -- queries -----------------------------------------------------------

    def spans(self) -> List[Span]:
        """Completed spans, in completion order (children before parents)."""
        with self._lock:
            return list(self._spans)

    def aggregate(self, parent: Optional[Span] = None) -> Dict[str, dict]:
        """``{name: {"count": n, "total_ms": x}}`` over completed spans.

        ``parent`` restricts the aggregation to that span's DIRECT children
        — the per-phase breakdown of one region. Children of a sequential
        region partition its extent, so their totals sum to ~its duration.
        """
        out: Dict[str, dict] = {}
        for s in self.spans():
            if parent is not None and s.parent is not parent:
                continue
            agg = out.setdefault(s.name, {"count": 0, "total_ms": 0.0})
            agg["count"] += 1
            agg["total_ms"] += s.dur_ms
        for agg in out.values():
            agg["total_ms"] = round(agg["total_ms"], 3)
        return out

    def phase_totals(self, parent: Optional[Span]) -> Dict[str, float]:
        """``{phase: total_ms}`` over ``parent``'s direct children — THE
        per-phase breakdown shape every consumer shares (CLI ``--json``
        ``phases``, the ``--metrics-out`` document, bench's per-config
        ``span_breakdown``), so the artifacts stay plain-equality
        comparable."""
        return {
            name: agg["total_ms"]
            for name, agg in self.aggregate(parent=parent).items()
        }

    def find(self, name: str) -> Optional[Span]:
        """The most recently completed span with ``name`` (None if absent)."""
        for s in reversed(self.spans()):
            if s.name == name:
                return s
        return None

    # -- export ------------------------------------------------------------

    def _ts_us(self, mono_ns: int) -> float:
        """Monotonic ns -> trace microseconds on the tracer's epoch anchor."""
        return (mono_ns - self._epoch_ns) / 1e3

    def trace_events(self) -> List[dict]:
        """Chrome ``trace_event`` duration events: one matched B/E pair per
        completed span. Events are emitted by a depth-first walk of the
        span tree (per thread, subtrees in start order), which guarantees
        structurally matched nesting — a child's B/E always falls between
        its parent's B and E — even when coarse clocks produce equal
        timestamps, where a pure timestamp sort could misnest. Within a
        thread timestamps are non-decreasing in emission order because a
        child's interval lies inside its parent's by construction."""
        done = [s for s in self.spans() if s.dur_ns is not None]
        children: Dict[Optional[int], List[Span]] = {}
        for s in done:
            children.setdefault(
                id(s.parent) if s.parent is not None else None, []
            ).append(s)
        for subs in children.values():
            subs.sort(key=lambda s: s.start_ns)
        known = {id(s) for s in done}
        # Roots: no parent, or a parent still open / recorded elsewhere.
        roots = [
            s for s in done
            if s.parent is None or id(s.parent) not in known
        ]
        roots.sort(key=lambda s: (s.tid, s.start_ns))

        events: List[dict] = []

        def emit(s: Span) -> None:
            common = {"name": s.name, "cat": "knn_tpu", "pid": 1, "tid": s.tid}
            b = dict(common, ph="B", ts=self._ts_us(s.start_ns))
            if s.attrs:
                b["args"] = {k: _jsonable(v) for k, v in s.attrs.items()}
            events.append(b)
            for child in children.get(id(s), ()):
                emit(child)
            events.append(
                dict(common, ph="E", ts=self._ts_us(s.start_ns + s.dur_ns))
            )

        for root in roots:
            emit(root)
        return events

    def to_chrome_trace(self) -> dict:
        """The Perfetto-loadable JSON object (``json.dump`` it to a file)."""
        other = {
            "producer": "knn_tpu.obs",
            "epoch_unix_s": self._epoch_wall,
        }
        if self.dropped:
            other["spans_dropped"] = self.dropped
        return {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
            "otherData": other,
        }


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)
