"""Workload capture: record the serving traffic itself, replayably.

Every other obs layer summarizes traffic (histograms, burn rates, cost
totals); none can RE-DRIVE it. This module records the arrival process —
per-request arrival time, kind, class, query rows, deadline, outcome,
answering rung, ``index_version``/``mutation_seq``, and the acknowledged
mutation stream — into a versioned on-disk **workload artifact** that
``knn_tpu replay`` re-drives open-loop (:mod:`knn_tpu.obs.replay`) and
the what-if simulator (:mod:`knn_tpu.obs.whatif`) costs candidate
batching policies against. Johnson et al. size replicas and batch shapes
from measured query traces, and Fresh-DiskANN evaluates against replayed
insert/delete streams (PAPERS.md) — this is the machinery that makes
both possible here.

The artifact is a directory, schema-hash pinned like
``serve/artifact.py``:

    workload-<t0_ms>/
    ├── manifest.json — format version, capture window metadata (reason,
    │                   rate, policy, index_version at arm time), event/
    │                   row counts, content digests, and a schema hash
    │                   over all of it — a hand-edited manifest or a
    │                   swapped array file fails typed (DataError),
    │                   never replays wrong traffic
    ├── queries.npz   — one float32 ``rows`` matrix: every captured
    │                   request's (and insert's) query rows concatenated;
    │                   each event names its ``(row_off, rows)`` slice
    └── events.jsonl  — one JSON record per captured request/mutation,
                        sorted by arrival time

Capture contract (the :mod:`knn_tpu.obs.shedqueue` rule both quality
layers already ride): the serving-path tap is one predicate while the
layer is idle and one seeded RNG draw + one O(1) bounded-queue append
while a window is armed — a full queue SHEDS the record (counted) and
never blocks the worker. Everything with real cost (answer digests,
array conversion, file IO) happens on the capture consumer thread.
With no ``--capture-dir`` configured, NOTHING is constructed — no queue,
no thread, no instruments, no per-request work
(scripts/check_disabled_overhead.py pins it).

Windows are armed three ways: at the operator's request
(``POST /admin/capture``), by serve boot flags, or **burn-triggered** —
when the configured SLO objective's short-window burn rate crosses a
threshold, a window arms itself, so an incident's traffic is on disk at
workload granularity before anyone is paged (complementing the flight
recorder's last-N request timelines; docs/OBSERVABILITY.md §Workload
capture & replay).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from pathlib import Path
from typing import Optional

import numpy as np

from knn_tpu import obs
from knn_tpu.obs.shedqueue import ShedQueue
from knn_tpu.resilience.errors import DataError

#: Bumped on any incompatible change to the manifest or event layout.
WORKLOAD_FORMAT = 1
MANIFEST_NAME = "manifest.json"
QUERIES_NAME = "queries.npz"
EVENTS_NAME = "events.jsonl"

#: Fields a read event carries (events.jsonl). Mutations carry
#: ``op``/``seq`` plus ``values`` (insert) or ``ids`` (delete) instead of
#: the request fields.
READ_EVENT_FIELDS = (
    "id", "t_ms", "kind", "rows", "row_off", "class", "deadline_ms",
    "outcome", "rung", "index_version", "mutation_seq", "request_id", "ms",
    "digest",
)


def answer_digest(kind: str, value) -> str:
    """Digest of one answer in a transport-independent canonical form.

    Everything is hashed as float64: int32 predictions/indices and
    float32 distances both convert exactly, and float64 survives a JSON
    round trip bit-exactly (shortest-repr serialization) — so a digest
    computed by the in-process capture consumer matches one recomputed
    by the replay driver from a live server's JSON body whenever the
    answers are bit-identical.
    """
    h = hashlib.sha256()
    arrays = (value,) if kind == "predict" else (value[0], value[1])
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a, dtype=np.float64))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def _schema_hash(payload: dict) -> str:
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()[:32]


class CaptureStateError(Exception):
    """A capture start/stop that contradicts the current window state
    (start while capturing, stop while idle) — the admin endpoint maps
    this to HTTP 409, mirroring ReloadInProgress."""


class WorkloadCapture:
    """The serving-path workload recorder. One instance per server.

    ``out_dir``        — artifacts land here (one subdirectory per
                         finalized window); created at construction so an
                         unwritable path fails at boot, not mid-incident.
    ``num_features``   — the serving schema width (stamped + validated).
    ``rate``           — per-request sampling probability while a window
                         is armed. Mutations are NEVER sampled: replay
                         needs the complete acknowledged stream for
                         ``mutation_seq`` alignment, so every mutation is
                         offered (a shed mutation marks the artifact's
                         stream incomplete instead of silently thinning
                         it).
    ``max_requests``   — a window finalizes itself at this many captured
                         events (bounded memory, bounded artifact).
    ``slo`` / ``burn_threshold`` / ``burn_objective`` / ``burn_window_s``
                       — the burn trigger: while idle, the tap checks the
                         objective's SHORTEST-window burn rate at most
                         once per ``burn_check_interval_s``; crossing the
                         threshold arms a window (reason
                         ``burn:<objective>``) that auto-stops after
                         ``burn_window_s``. ``burn_threshold=None``
                         disables the trigger entirely.
    ``policy``         — the live batching policy (max_batch/max_wait_ms)
                         recorded in the manifest so replay and the
                         what-if simulator know what produced the trace.
    ``autostart``      — tests pin shed/queue mechanics with the consumer
                         held off; serving always autostarts.
    """

    def __init__(self, out_dir, *, num_features: int, k: Optional[int] = None,
                 rate: float = 1.0, max_requests: int = 65536,
                 queue_cap: int = 1024, seed: int = 0, slo=None,
                 burn_threshold: Optional[float] = None,
                 burn_objective: str = "availability",
                 burn_window_s: float = 60.0,
                 burn_check_interval_s: float = 1.0,
                 policy: Optional[dict] = None,
                 index_version: Optional[str] = None,
                 autostart: bool = True):
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"capture rate must be in (0, 1], got {rate}")
        if max_requests < 1:
            raise ValueError(
                f"max_requests must be >= 1, got {max_requests}")
        if burn_threshold is not None and burn_threshold <= 0:
            raise ValueError(
                f"burn_threshold must be > 0, got {burn_threshold}")
        if burn_window_s <= 0:
            raise ValueError(
                f"burn_window_s must be > 0, got {burn_window_s}")
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self.num_features = int(num_features)
        self.k = k
        self.rate = float(rate)
        self.max_requests = int(max_requests)
        self.policy = dict(policy) if policy else None
        self.index_version = index_version
        self._slo = slo
        self.burn_threshold = (float(burn_threshold)
                               if burn_threshold is not None else None)
        self.burn_objective = burn_objective
        self.burn_window_s = float(burn_window_s)
        self._burn_check_interval_s = float(burn_check_interval_s)
        self._burn_next = 0.0
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # Window state. `_capturing` is read lock-free on the tap's fast
        # path (one attribute load; a racy read costs at most one extra
        # offer into a window that just closed — the generation check
        # drops it).
        self._capturing = False
        self._stop_pending: Optional[str] = None
        self._gen = 0
        self._t0_ns = 0
        self._t0_unix = 0.0
        self._reason = None
        self._deadline_ns: Optional[int] = None
        self._window_max: int = self.max_requests
        # Capture buffers (consumer-thread writes, finalize swaps).
        self._events: list = []
        self._blocks: list = []
        self._total_rows = 0
        self._next_id = 0
        self._shed_window = 0
        self._mut_shed_window = 0
        self._captures_done = 0
        self._last: Optional[dict] = None
        self._queue = ShedQueue(
            # The sampling draw lives HERE (mutations must bypass it), so
            # the queue itself admits everything offered; it contributes
            # the bounded-append + shed-never-block half of the contract.
            rate=1.0, queue_cap=queue_cap, seed=seed,
            consume=self._consume, thread_name="knn-workload-capture",
            on_shed=self._on_shed, autostart=autostart,
        )

    # -- producer side (batcher worker / handler threads) -------------------

    def note_request(self, req, outcome: str) -> Optional[int]:
        """Tap one terminal request outcome. O(1), never blocks; returns
        the workload record id when the request was captured (the batcher
        annotates it onto the request trace so access-log lines and
        flight-recorder timelines resolve back to this record), else
        None. ``req`` is the batcher's request object (features, kind,
        enqueued_ns, deadline_ns, meta, request_class, trace)."""
        if not self._capturing:
            if self.burn_threshold is not None:
                self._maybe_burn_arm()
            if not self._capturing:
                return None
        now_ns = time.monotonic_ns()
        if self._deadline_ns is not None and now_ns > self._deadline_ns:
            self._request_stop("window_elapsed")
            return None
        t0 = self._t0_ns
        if req.enqueued_ns < t0:
            return None  # arrived before the window armed
        if self._rng.random() >= self.rate:
            return None
        meta = req.meta
        trace = req.trace
        ev = {
            "t_ms": round((req.enqueued_ns - t0) / 1e6, 3),
            "kind": req.kind,
            "rows": int(req.rows),
            "class": req.request_class,
            "deadline_ms": (round((req.deadline_ns - req.enqueued_ns) / 1e6,
                                  3)
                            if req.deadline_ns is not None else None),
            "outcome": outcome,
            "rung": meta.get("rung"),
            "index_version": meta.get("index_version"),
            "mutation_seq": meta.get("mutation_seq"),
            "request_id": (meta.get("request_id")
                           or (trace.request_id if trace is not None
                               else None)),
            "ms": round((now_ns - req.enqueued_ns) / 1e6, 3),
        }
        gen = self._gen
        value = req.value if outcome == "ok" else None
        holder = []

        def make():
            rec_id = self._next_id
            self._next_id += 1
            holder.append(rec_id)
            return ("req", gen, rec_id, ev, req.features, req.kind, value)

        if not self._queue.offer(make):
            return None
        rec_id = holder[0]
        if trace is not None:
            # The linkage satellite: a replayed divergence resolves to its
            # original request via access log / flight recorder. (Known
            # slack: a record admitted in the last instants of a window
            # that finalizes at max_requests can be dropped by the
            # generation check after this annotation was written — a log
            # line may then name a record just past the artifact's cap,
            # never a record of a DIFFERENT window: ids are process-
            # monotonic across windows.)
            trace.annotate(workload_record=rec_id)
        return rec_id

    def note_mutation(self, op: str, payload: dict, seq,
                      enqueued_ns: int) -> None:
        """Tap one ACKNOWLEDGED mutation (worker thread, after the epoch
        log flush). Never sampled — see the class docstring."""
        if not self._capturing:
            return
        t0 = self._t0_ns
        if enqueued_ns < t0:
            return
        ev = {
            "t_ms": round((enqueued_ns - t0) / 1e6, 3),
            "op": op,
            "seq": int(seq) if seq is not None else None,
        }
        gen = self._gen
        if op == "insert":
            rows, values = payload.get("rows"), payload.get("values")
        else:
            rows, values = None, None
            ev["ids"] = [int(i) for i in payload.get("ids", ())]

        def make():
            rec_id = self._next_id
            self._next_id += 1
            return ("mut", gen, rec_id, ev, rows, None, values)

        if not self._queue.offer(make):
            self._mut_shed_window += 1

    def _on_shed(self) -> None:
        self._shed_window += 1
        obs.counter_add(
            "knn_workload_shed_total",
            help="workload records dropped because the capture queue was "
                 "full (shed-on-overload — the serving worker never "
                 "blocks on capture)",
        )

    # -- burn trigger --------------------------------------------------------

    def _maybe_burn_arm(self) -> None:
        now = time.monotonic()
        if now < self._burn_next or self._slo is None:
            return
        self._burn_next = now + self._burn_check_interval_s
        try:
            from knn_tpu.obs.slo import window_label

            label = window_label(self._slo.windows_s[0])
            burn = (self._slo.burn_rates().get(self.burn_objective)
                    or {}).get(label, 0.0)
        except Exception:  # noqa: BLE001 — a trigger bug must not fail serving
            return
        if burn > self.burn_threshold:
            if self._stop_pending is not None:
                # A previous window still awaits finalization (file IO) —
                # that belongs on a status/admin thread, never the serving
                # worker this check runs on; the next scrape finalizes it
                # and a still-burning SLO re-arms on a later check.
                return
            try:
                self.start(reason=f"burn:{self.burn_objective}",
                           window_s=self.burn_window_s)
            except CaptureStateError:
                pass  # raced another arm

    # -- window control ------------------------------------------------------

    def start(self, reason: str = "manual",
              max_requests: Optional[int] = None,
              window_s: Optional[float] = None) -> dict:
        """Arm a capture window. Raises :class:`CaptureStateError` when
        one is already armed (409 at the admin endpoint)."""
        self._maybe_finalize_pending()
        with self._lock:
            if self._capturing or self._stop_pending is not None:
                raise CaptureStateError(
                    "a capture window is already armed; stop it first "
                    "(POST /admin/capture {\"action\": \"stop\"})"
                )
            self._t0_ns = time.monotonic_ns()
            self._t0_unix = time.time()
            self._reason = reason
            self._window_max = int(max_requests or self.max_requests)
            self._deadline_ns = (
                self._t0_ns + int(window_s * 1e9)
                if window_s is not None else None
            )
            self._shed_window = 0
            self._mut_shed_window = 0
            self._capturing = True
        obs.counter_add(
            "knn_workload_captures_total",
            help="capture windows armed, by reason", reason=reason,
        )
        return {"capturing": True, "reason": reason,
                "max_requests": self._window_max,
                "window_s": window_s,
                "t0_unix": round(self._t0_unix, 3)}

    def stop(self) -> dict:
        """Finalize the armed window: drain the capture queue so every
        admitted record is included, write the artifact, return its
        summary. Raises :class:`CaptureStateError` when idle."""
        with self._lock:
            if not self._capturing and self._stop_pending is None:
                raise CaptureStateError("no capture window is armed")
            self._capturing = False
            if self._stop_pending is None:
                self._stop_pending = "manual"
        return self._finalize(drain=True)

    def _request_stop(self, why: str) -> None:
        """Flag the window for finalization WITHOUT doing file IO on the
        calling (serving) thread; the consumer, the next status read, or
        close() completes it."""
        with self._lock:
            if not self._capturing:
                return
            self._capturing = False
            self._stop_pending = why

    def _maybe_finalize_pending(self) -> None:
        # A timed window whose traffic CEASED (so no tap ever sees the
        # deadline pass) is expired here instead: every status read —
        # /healthz, /metrics, /debug/capture, start/stop/close — runs
        # this, so a monitored server finalizes the artifact within one
        # scrape interval even at zero traffic.
        if (self._capturing and self._deadline_ns is not None
                and time.monotonic_ns() > self._deadline_ns):
            self._request_stop("window_elapsed")
        with self._lock:
            pending = self._stop_pending is not None and not self._capturing
        if pending:
            try:
                self._finalize(drain=True)
            except CaptureStateError:
                pass  # another thread finalized first

    # -- consumer side -------------------------------------------------------

    def _consume(self, sample) -> None:
        tag, gen, rec_id, ev, rows, kind, value = sample
        finalize = False
        with self._lock:
            if gen != self._gen:
                return  # belongs to an already-finalized window
            ev = dict(ev, id=rec_id)
            if tag == "req":
                ev["row_off"] = self._total_rows
                block = np.ascontiguousarray(rows, dtype=np.float32)
                self._blocks.append(block)
                self._total_rows += int(block.shape[0])
                self._events.append(ev)
            else:
                if rows is not None:  # insert: rows + values persist
                    block = np.ascontiguousarray(rows, dtype=np.float32)
                    if block.ndim == 1:
                        block = block[None, :]
                    ev["row_off"] = self._total_rows
                    ev["rows"] = int(block.shape[0])
                    self._blocks.append(block)
                    self._total_rows += int(block.shape[0])
                    ev["values"] = (np.asarray(value).tolist()
                                    if value is not None else None)
                else:
                    ev["row_off"], ev["rows"] = self._total_rows, 0
                self._events.append(ev)
            if (len(self._events) >= self._window_max
                    and self._stop_pending is None):
                self._capturing = False
                self._stop_pending = "max_requests"
                finalize = True
        if tag == "req" and value is not None:
            # The one O(rows·k) cost, off the serving path: hash the
            # answer so replay can verify bit-identity.
            digest = answer_digest(kind, value)
            with self._lock:
                if gen == self._gen:
                    ev["digest"] = digest
        obs.counter_add(
            "knn_workload_captured_total",
            help="workload records captured (requests + mutations)",
            type=("request" if tag == "req" else "mutation"),
        )
        if finalize:
            # Consumer-initiated (cap reached): no drain — the consumer
            # cannot wait on itself; later same-gen samples are dropped
            # by the generation check (the window is full anyway).
            try:
                self._finalize(drain=False)
            except CaptureStateError:
                pass

    # -- finalization --------------------------------------------------------

    def _finalize(self, drain: bool) -> dict:
        if drain:
            self._queue.drain(timeout_s=10.0)
        with self._lock:
            if self._stop_pending is None:
                raise CaptureStateError("no finalization pending")
            events = self._events
            blocks = self._blocks
            total_rows = self._total_rows
            reason = self._reason
            stop_reason = self._stop_pending
            t0_unix = self._t0_unix
            t0_ns = self._t0_ns
            shed = self._shed_window
            mut_shed = self._mut_shed_window
            self._events, self._blocks, self._total_rows = [], [], 0
            # Record ids stay globally monotonic across windows: a
            # workload_record annotation in an access log / timeline
            # names exactly one record process-wide, never "record N of
            # whichever window".
            self._stop_pending = None
            self._reason = None
            self._deadline_ns = None
            self._gen += 1
        duration_ms = round((time.monotonic_ns() - t0_ns) / 1e6, 3)
        events = sorted(events, key=lambda e: (e["t_ms"], e["id"]))
        rows = (np.concatenate(blocks) if blocks
                else np.zeros((0, self.num_features), np.float32))
        n_req = sum(1 for e in events if "kind" in e)
        n_mut = len(events) - n_req
        out = self.out_dir / f"workload-{int(t0_unix * 1000)}"
        events_text = "".join(
            json.dumps(e, separators=(",", ":")) + "\n" for e in events
        )
        events_sha = hashlib.sha256(events_text.encode()).hexdigest()[:32]
        rows_sha = hashlib.sha256(
            np.ascontiguousarray(rows).tobytes()).hexdigest()[:32]
        schema = {
            "format": WORKLOAD_FORMAT,
            "num_features": self.num_features,
            "k": self.k,
            "requests": n_req,
            "mutations": n_mut,
            "total_rows": int(rows.shape[0]),
            "rows_dtype": str(rows.dtype),
            "events_sha": events_sha,
            "rows_sha": rows_sha,
        }
        manifest = {
            **schema,
            "created_unix": round(time.time(), 3),
            "t0_unix": round(t0_unix, 6),
            "reason": reason,
            "stop_reason": stop_reason,
            "rate": self.rate,
            "policy": self.policy,
            "index_version": self.index_version,
            "duration_ms": duration_ms,
            "shed": shed,
            "mutations_dropped": mut_shed,
            "mutation_stream_complete": mut_shed == 0,
            "schema_hash": _schema_hash(schema),
        }
        out.mkdir(parents=True, exist_ok=True)
        (out / EVENTS_NAME).write_text(events_text, encoding="utf-8")
        np.savez(out / QUERIES_NAME, rows=rows)
        tmp = out / (MANIFEST_NAME + ".tmp")
        tmp.write_text(json.dumps(manifest, indent=2, sort_keys=True) + "\n")
        # Manifest lands last and atomically: a crashed capture leaves a
        # directory load_workload rejects, never a half-artifact.
        os.replace(tmp, out / MANIFEST_NAME)
        summary = {
            "path": str(out),
            "reason": reason,
            "stop_reason": stop_reason,
            "requests": n_req,
            "mutations": n_mut,
            "total_rows": int(rows.shape[0]),
            "duration_ms": duration_ms,
            "shed": shed,
        }
        with self._lock:
            self._captures_done += 1
            self._last = summary
        return summary

    # -- read side -----------------------------------------------------------

    @property
    def capturing(self) -> bool:
        return self._capturing

    def export(self) -> dict:
        """The status block for ``GET /debug/capture`` and ``/healthz``;
        also completes any deferred auto-stop finalization and refreshes
        the ``knn_workload_*`` gauges."""
        self._maybe_finalize_pending()
        with self._lock:
            out = {
                "capturing": self._capturing,
                "reason": self._reason,
                "captured_events": len(self._events),
                "window_max_requests": self._window_max,
                "rate": self.rate,
                "shed": self._shed_window,
                "queue_depth": self._queue.depth(),
                "out_dir": str(self.out_dir),
                "captures": self._captures_done,
                "burn_trigger": (
                    {"objective": self.burn_objective,
                     "threshold": self.burn_threshold,
                     "window_s": self.burn_window_s}
                    if self.burn_threshold is not None else None
                ),
                "last": self._last,
            }
        obs.gauge_set(
            "knn_workload_capturing", 1.0 if out["capturing"] else 0.0,
            help="1 while a workload capture window is armed",
        )
        return out

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Tests + gates: block until every offered record was consumed."""
        return self._queue.drain(timeout_s)

    def close(self) -> None:
        """Shutdown: finalize any armed window first (an incident capture
        must survive the process that triggered it), then stop the
        consumer."""
        try:
            self.stop()
        except CaptureStateError:
            pass
        self._queue.close()


# -- the artifact's read side ------------------------------------------------


class Workload:
    """A loaded, validated workload artifact."""

    def __init__(self, manifest: dict, events: list, rows: np.ndarray,
                 path: Path):
        self.manifest = manifest
        self.events = events
        self.rows = rows
        self.path = path

    @property
    def read_events(self) -> list:
        return [e for e in self.events if "kind" in e]

    @property
    def mutation_events(self) -> list:
        return [e for e in self.events if "op" in e]

    def rows_for(self, ev: dict) -> np.ndarray:
        off, n = ev["row_off"], ev["rows"]
        return self.rows[off:off + n]

    def arrivals(self) -> "list[tuple[float, int]]":
        """``[(t_ms, rows)]`` of the read arrival process, sorted — the
        what-if simulator's input."""
        return [(e["t_ms"], e["rows"]) for e in self.read_events]

    def captured_latency_summary(self) -> dict:
        """p50/p99/QPS of the ok reads AS RECORDED — the baseline a
        replay verdict compares against."""
        ok = [e["ms"] for e in self.read_events
              if e.get("outcome") == "ok" and e.get("ms") is not None]
        dur_s = max(self.manifest.get("duration_ms", 0.0), 1e-3) / 1e3
        out = {
            "requests": len(self.read_events),
            "ok": len(ok),
            "qps": round(len(self.read_events) / dur_s, 2),
        }
        if ok:
            arr = np.asarray(sorted(ok))
            out["p50_ms"] = round(float(np.percentile(arr, 50)), 3)
            out["p99_ms"] = round(float(np.percentile(arr, 99)), 3)
        else:
            out["p50_ms"] = out["p99_ms"] = None
        return out


def load_workload(path) -> Workload:
    """Load + validate a workload artifact. Any corruption — missing
    files, a newer format, a hand-edited manifest, swapped/truncated
    arrays or events — raises a typed :class:`DataError`, never replays
    wrong traffic."""
    root = Path(path)
    mf = root / MANIFEST_NAME
    if not root.exists():
        raise DataError(f"{root}: workload artifact not found")
    if not root.is_dir() or not mf.exists():
        raise DataError(
            f"{root}: not a workload artifact (no {MANIFEST_NAME}); "
            f"capture one with `POST /admin/capture` or serve "
            f"--capture-dir"
        )
    try:
        manifest = json.loads(mf.read_text())
    except (OSError, ValueError) as e:
        raise DataError(f"{mf}: unreadable manifest: {e}") from e
    fmt = manifest.get("format")
    if not isinstance(fmt, int) or fmt < 1:
        raise DataError(f"{mf}: missing/invalid format field: {fmt!r}")
    if fmt > WORKLOAD_FORMAT:
        raise DataError(
            f"{mf}: workload format {fmt} is newer than this build "
            f"supports ({WORKLOAD_FORMAT}); upgrade or re-capture"
        )
    try:
        events_text = (root / EVENTS_NAME).read_text(encoding="utf-8")
    except OSError as e:
        raise DataError(f"{root / EVENTS_NAME}: unreadable events: {e}") from e
    import zipfile

    try:
        with np.load(root / QUERIES_NAME, allow_pickle=False) as z:
            rows = np.ascontiguousarray(z["rows"])
    except (OSError, KeyError, ValueError, zipfile.BadZipFile) as e:
        raise DataError(
            f"{root / QUERIES_NAME}: unreadable query rows: {e}") from e
    schema = {
        "format": fmt,
        "num_features": manifest.get("num_features"),
        "k": manifest.get("k"),
        "requests": manifest.get("requests"),
        "mutations": manifest.get("mutations"),
        "total_rows": manifest.get("total_rows"),
        "rows_dtype": manifest.get("rows_dtype"),
        "events_sha": hashlib.sha256(events_text.encode()).hexdigest()[:32],
        "rows_sha": hashlib.sha256(rows.tobytes()).hexdigest()[:32],
    }
    if manifest.get("schema_hash") != _schema_hash(schema):
        raise DataError(
            f"{root}: schema hash mismatch — the manifest, events.jsonl "
            f"and queries.npz are not from the same capture; re-capture "
            f"the workload"
        )
    if rows.shape != (manifest["total_rows"],
                      manifest["num_features"]) \
            or str(rows.dtype) != manifest["rows_dtype"]:
        raise DataError(
            f"{root}: query rows shape {rows.shape} ({rows.dtype}) does "
            f"not match the manifest schema"
        )
    events = []
    for n, line in enumerate(events_text.splitlines()):
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
            if not isinstance(ev, dict) or "t_ms" not in ev:
                raise ValueError("not a workload event")
        except ValueError as e:
            raise DataError(
                f"{root / EVENTS_NAME}:{n + 1}: corrupt event record: {e}"
            ) from e
        off, r = ev.get("row_off", 0), ev.get("rows", 0)
        if not (0 <= off and off + r <= rows.shape[0]):
            raise DataError(
                f"{root / EVENTS_NAME}:{n + 1}: event rows "
                f"[{off}, {off + r}) out of bounds for the "
                f"{rows.shape[0]}-row query matrix"
            )
        events.append(ev)
    if len(events) != manifest["requests"] + manifest["mutations"]:
        raise DataError(
            f"{root}: {len(events)} events but the manifest declares "
            f"{manifest['requests']} requests + {manifest['mutations']} "
            f"mutations"
        )
    events.sort(key=lambda e: (e["t_ms"], e.get("id", 0)))
    return Workload(manifest, events, rows, root)
