"""Pipelined-slope measurement primitives.

THE timing methodology shared by ``bench.py`` and every ``scripts/tune_*`` /
``scripts/probe_*`` sweep (formerly private copies inside ``bench.py``): on
a tunneled device each blocking host sync costs a fixed ~75-100 ms round
trip regardless of compute, so per-step device time is measured as the
SLOPE between two pipelined batch sizes (one drain each), with the
stall-artifact guards the bench rounds accumulated:

- :func:`timed_batch`              — one pipelined batch, one drain.
- :func:`pipelined_slope`          — marginal seconds/dispatch from two
  batch sizes (best-of-3 each).
- :func:`interleaved_slope_trials` — R independent slope trials with the
  compared cases interleaved inside each trial (device-load drift hits
  all cases alike) and non-positive trials dropped loudly.
- :func:`slope_trials`             — the one-case wrapper.
- :func:`drop_superroofline`       — discard trials whose implied Tflop/s
  beats the chip peak (host-stall artifacts by definition).
- :func:`median` / :func:`spread`  — the summary reducers every BENCH
  record uses.

Kept dependency-free (no jax import at module level) so host-only tools
can use it.
"""

from __future__ import annotations

import sys
import time


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


def timed_batch(step, bufs, reps, block_fn=None):
    """One pipelined batch: ``reps`` dispatches cycling the distinct buffer
    pool, one drain, wall seconds. ``block_fn(out)`` drains; the default
    pulls the (first) output to host via np.asarray (jax.block_until_ready
    proved unreliable on the tunneled device). THE timing primitive — the
    slope estimators and the tuning scripts all ride it so their ms/step
    numbers stay methodology-comparable."""
    if block_fn is None:
        import numpy as np

        def block_fn(out):
            np.asarray(out if not isinstance(out, (tuple, list)) else out[0])

    t0 = time.monotonic()
    out = None
    for i in range(reps):
        out = step(bufs[i % len(bufs)])
    block_fn(out)
    return time.monotonic() - t0


def pipelined_slope(mkstep, bufs, r_lo, r_hi, block_fn=None):
    """Marginal per-dispatch seconds: time r_lo and r_hi pipelined dispatches
    (one drain each, best of 3) and take the slope — subtracts the fixed
    host-sync/tunnel round-trip that has nothing to do with device compute.
    Returns ``(per_step_seconds, fixed_overhead_seconds)``."""
    def timed(reps):
        return min(
            timed_batch(mkstep, bufs, reps, block_fn) for _ in range(3)
        )

    t_lo, t_hi = timed(r_lo), timed(r_hi)
    per_step = (t_hi - t_lo) / (r_hi - r_lo)
    return per_step, t_lo - r_lo * per_step


def interleaved_slope_trials(cases, r_lo, r_hi, trials=5, rounds=2):
    """Per-case slope TRIALS with the cases INTERLEAVED inside each trial:
    every round times each case once at r_lo and r_hi dispatches before the
    next round starts, so device-load drift (observed ~1.5x run-to-run on
    the tunneled v5e) hits all cases alike instead of erasing a comparison
    measured minutes apart. Within a trial the slope is taken between the
    per-batch-size MINIMA over ``rounds`` rounds — NOT between paired
    single timings, which a load spike during the r_lo batch would bias
    low (fast), exactly the trials a min-of-R summary then cherry-picks.
    ``cases`` maps name -> (step_fn, bufs); returns name -> list of
    per-step seconds, one per trial (run order preserved). Batch order
    alternates (lo,hi)/(hi,lo) per round so a position-correlated stall
    (tunnel hiccup, GC) cannot systematically inflate one batch size —
    an inflated t_lo reads as an impossibly FAST slope (observed beating
    the chip's bf16 roofline), which a min-of-trials summary then
    selects. Consumers should treat the MEDIAN as the central estimate
    and sanity-check any min against the roofline."""
    out = {name: [] for name in cases}
    for _ in range(trials):
        lo = {name: float("inf") for name in cases}
        hi = {name: float("inf") for name in cases}
        for r in range(rounds):
            for name, (step, bufs) in cases.items():
                if r % 2 == 0:
                    lo[name] = min(lo[name], timed_batch(step, bufs, r_lo))
                    hi[name] = min(hi[name], timed_batch(step, bufs, r_hi))
                else:
                    hi[name] = min(hi[name], timed_batch(step, bufs, r_hi))
                    lo[name] = min(lo[name], timed_batch(step, bufs, r_lo))
        for name in cases:
            out[name].append((hi[name] - lo[name]) / (r_hi - r_lo))
    # A load spike spanning every r_lo batch of a trial can push that
    # trial's slope to <= 0; min() would then select the garbage and turn
    # the whole record negative. Drop such trials loudly; a session where
    # EVERY trial is non-positive has no usable signal at all.
    for name, vals in out.items():
        good = [v for v in vals if v > 0]
        if not good:
            raise RuntimeError(
                f"all {len(vals)} slope trials for {name!r} are non-positive "
                f"({vals}); device load noise swamped the measurement"
            )
        if len(good) < len(vals):
            _log(f"dropped {len(vals) - len(good)} non-positive slope "
                 f"trial(s) for {name!r}: {vals}")
            from knn_tpu import obs

            obs.counter_add(
                "bench_nonpositive_trials_dropped_total",
                len(vals) - len(good),
                help="slope trials discarded for non-positive slope "
                     "(device-load spikes during the r_lo batch)",
            )
        out[name] = good
    return out


def slope_trials(step, bufs, r_lo, r_hi, trials=5, inner=2):
    """R independent slope estimates for ONE case (VERDICT r3 #1: one number
    per session made every regression-vs-variance call guesswork). Thin
    wrapper over interleaved_slope_trials — see there for the
    slope-of-minima rationale and the non-positive-trial guard."""
    return interleaved_slope_trials(
        {"case": (step, bufs)}, r_lo, r_hi, trials=trials, rounds=inner,
    )["case"]


# Chip-peak filter bounds for drop_superroofline, per operand precision:
# the v5e bf16 MXU peak (197 TF) plus 5% margin, and the f32 peak at
# roughly half of it (the MXU decomposes f32 contractions — ADVICE r5 #3:
# filtering an f32 trial against the bf16 peak admits physically
# impossible f32 slopes). Callers pass the peak matching the CASE's
# operand dtype, not one blanket number.
PEAK_TF_BF16 = 207.0
PEAK_TF_F32 = 104.0


def drop_superroofline(trials_s, flops, peak_tf=PEAK_TF_BF16):
    """Drop slope trials whose implied Tflop/s exceeds the chip's peak —
    nothing computes faster than the hardware, so such a trial is a
    measurement artifact by definition (a host stall inflating the r_lo
    batch reads as an impossibly fast slope; observed 247-412 "Tflop/s"
    on a 197-peak chip, and in one r5 session 3 of 5 trials stalled this
    way and poisoned the MEDIAN too). ``peak_tf`` is the v5e bf16 peak
    plus 5% margin. Returns the surviving trials; if none survive, the
    raw list comes back (no signal beats fake signal, and the consumer's
    min/median at least stays visibly absurd)."""
    good = [s for s in trials_s if flops / s / 1e12 <= peak_tf]
    if good and len(good) < len(trials_s):
        _log(f"dropped {len(trials_s) - len(good)} super-roofline slope "
             f"trial(s): {[round(flops / s / 1e12) for s in trials_s]} "
             f"Tflop/s")
        from knn_tpu import obs

        obs.counter_add(
            "bench_superroofline_trials_dropped_total",
            len(trials_s) - len(good),
            help="slope trials discarded for implying > chip-peak Tflop/s "
                 "(host-stall artifacts)",
        )
    return good or trials_s


def median(trials):
    srt = sorted(trials)
    m = len(srt)
    return srt[m // 2] if m % 2 else (srt[m // 2 - 1] + srt[m // 2]) / 2


def spread(trials_s, scale=1e3, digits=3):
    """Summary fields for a list of per-trial per-step seconds: best (min),
    median, and the full list, in milliseconds. The MEDIAN is the central
    estimate every headline value derives from (r4: minority stall-biased
    trials produced minima past the chip's roofline — see
    interleaved_slope_trials); the min and full list stay recorded so
    stability and best-case are visible."""
    ms = [s * scale for s in trials_s]
    return {
        "step_ms": round(min(ms), digits),
        "step_ms_median": round(median(ms), digits),
        # run order preserved so drift across a session stays visible
        "step_ms_trials": [round(v, digits) for v in ms],
    }
