"""Noise-aware perf-regression comparison: best-of-mins with MAD tolerance.

Five ``BENCH_r0*.json`` records exist with no automated regression
detection — a kernel-speed loss would ship silently. This module is the
decision rule behind ``scripts/bench_gate.py`` / ``make bench-gate``:

- Each gated metric is a list of per-trial measurements (the repo's
  timing methodology already records trial lists everywhere —
  ``obs/bench_timing.py``).
- The central comparison is **best-of-mins**: the minimum trial is the
  least-noise estimate of the true cost on a contended box (stalls only
  ever ADD time), so ``fresh_best`` vs ``baseline_best``.
- The tolerance is **noise-aware**: ``max(rel_tol · baseline_best,
  mad_k · MAD(baseline_trials), abs_floor_ms)``. The MAD (median absolute
  deviation) of the baseline's own trials measures how noisy this metric
  is ON THIS BOX — a metric whose baseline spread is wide gets a wide
  gate, a tight one gets a tight gate, and the absolute floor keeps
  microsecond-scale metrics from failing on scheduler jitter.
- A metric regresses when the fresh best exceeds (lower-is-better) or
  undercuts (higher-is-better) the baseline best by more than the
  tolerance. Improvements never fail the gate; they are reported so a
  suspicious 10x "win" is visible too.

The verdict JSON (``compare_records``) is the machine-readable artifact
CI uploads; ``pass`` is the single gate bit.
"""

from __future__ import annotations

from typing import Dict, List

#: Default gate knobs: 5% relative, 5 baseline-MADs, 0.5 ms floor. mad_k=5
#: is deliberately loose — this gate exists to catch real regressions
#: (tens of percent), not to flag every breeze on a shared CI box.
DEFAULT_REL_TOL = 0.05
DEFAULT_MAD_K = 5.0
DEFAULT_ABS_FLOOR = 0.5


def median(xs: List[float]) -> float:
    srt = sorted(xs)
    m = len(srt)
    return srt[m // 2] if m % 2 else (srt[m // 2 - 1] + srt[m // 2]) / 2


def mad(xs: List[float]) -> float:
    """Median absolute deviation — the robust spread estimate (a single
    stalled trial cannot inflate it the way it inflates a stddev)."""
    if len(xs) < 2:
        return 0.0
    med = median(xs)
    return median([abs(x - med) for x in xs])


def compare_metric(
    name: str,
    baseline_trials: List[float],
    fresh_trials: List[float],
    direction: str = "lower",
    rel_tol: float = DEFAULT_REL_TOL,
    mad_k: float = DEFAULT_MAD_K,
    abs_floor: float = DEFAULT_ABS_FLOOR,
    unit: str = "ms",
) -> dict:
    """One metric's verdict dict. ``direction`` is "lower" (latencies) or
    "higher" (throughputs); best-of is min/max respectively, and the
    regression test points the matching way."""
    if direction not in ("lower", "higher"):
        raise ValueError(f"direction must be 'lower' or 'higher', got "
                         f"{direction!r}")
    if not baseline_trials or not fresh_trials:
        return {
            "metric": name, "regressed": True, "unit": unit,
            "reason": "missing trials "
                      f"(baseline={len(baseline_trials or [])}, "
                      f"fresh={len(fresh_trials or [])})",
        }
    best = min if direction == "lower" else max
    base_best = float(best(baseline_trials))
    fresh_best = float(best(fresh_trials))
    base_mad = mad([float(x) for x in baseline_trials])
    tol = max(rel_tol * abs(base_best), mad_k * base_mad, abs_floor)
    delta = (fresh_best - base_best if direction == "lower"
             else base_best - fresh_best)
    return {
        "metric": name,
        "direction": direction,
        "unit": unit,
        "baseline_best": round(base_best, 4),
        "fresh_best": round(fresh_best, 4),
        "baseline_mad": round(base_mad, 4),
        "tolerance": round(tol, 4),
        "delta": round(delta, 4),  # positive = worse, by `direction`
        "regressed": delta > tol,
        "improved": delta < -tol,
    }


def compare_records(
    baseline: dict,
    fresh: dict,
    rel_tol: float = DEFAULT_REL_TOL,
    mad_k: float = DEFAULT_MAD_K,
    abs_floor: float = DEFAULT_ABS_FLOOR,
) -> dict:
    """Compare two gate records' ``metrics`` maps (``{name: {"trials":
    [...], "direction": ..., "unit": ...}}`` — the shape
    ``bench.bench_gate_config`` emits). A metric present in the baseline
    but missing from the fresh record is a failure (a silently dropped
    measurement must not read as a pass); metrics only the fresh record
    has are reported as ``new`` and do not gate."""
    checks = []
    base_metrics: Dict[str, dict] = baseline.get("metrics", {})
    fresh_metrics: Dict[str, dict] = fresh.get("metrics", {})
    for name in sorted(base_metrics):
        b = base_metrics[name]
        f = fresh_metrics.get(name)
        if f is None:
            checks.append({
                "metric": name, "regressed": True,
                "reason": "metric missing from the fresh record",
            })
            continue
        checks.append(compare_metric(
            name, b.get("trials", []), f.get("trials", []),
            direction=b.get("direction", "lower"),
            rel_tol=rel_tol, mad_k=mad_k, abs_floor=abs_floor,
            unit=b.get("unit", "ms"),
        ))
    new = sorted(set(fresh_metrics) - set(base_metrics))
    verdict = {
        "pass": not any(c["regressed"] for c in checks),
        "checks": checks,
        "new_metrics": new,
        "params": {"rel_tol": rel_tol, "mad_k": mad_k,
                   "abs_floor": abs_floor},
    }
    return verdict


def summarize(verdict: dict) -> str:
    """One human line per check (the gate's console output)."""
    lines = []
    for c in verdict["checks"]:
        if "reason" in c:
            lines.append(f"FAIL {c['metric']}: {c['reason']}")
            continue
        state = ("REGRESSED" if c["regressed"]
                 else "improved" if c.get("improved") else "ok")
        lines.append(
            f"{state:>9} {c['metric']}: fresh {c['fresh_best']}"
            f"{c['unit']} vs baseline {c['baseline_best']}{c['unit']} "
            f"(tol {c['tolerance']}{c['unit']}, "
            f"mad {c['baseline_mad']}{c['unit']})"
        )
    return "\n".join(lines)
