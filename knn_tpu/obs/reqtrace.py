"""Per-request tracing: request contexts, timelines, the flight recorder.

The serving stack's aggregate histograms (``knn_serve_request_ms`` et al.)
answer "how is the fleet doing"; they cannot answer "WHY was *this*
request slow" or "which requests rode the oracle rung". This module is the
request-scoped layer underneath them — the Dapper lineage (PAPERS.md)
scaled down to one process:

- :class:`RequestTrace` — one request's structured timeline: an id
  (accepted via ``x-request-id`` or generated at admission), ordered
  phases (``queue_wait`` → ``dispatch``), per-rung dispatch attempts,
  zero-length events (breaker transitions, fallbacks, OOM halvings), and
  terminal outcome + annotations (rung, index_version, batch shape).
- :class:`FlightRecorder` — a bounded ring of the last-N completed
  timelines plus a slowest-K reservoir, served at ``/debug/requests`` /
  ``/debug/slowest`` and exportable as per-request Perfetto
  ``trace_event`` JSON (one track per request).
- the **active-context channel** — a thread-local set of traces the
  batcher worker arms around a dispatch, so layers that know nothing
  about requests (the circuit breaker, the degradation ladder) can
  :func:`emit` events that land in every request the dispatch was
  serving.

Cost contract (the PR 1 rule): with no recorder wired in, every call site
pays ONE predicate — ``req.trace is None`` in the batcher, one thread-local
``getattr`` in :func:`emit` — and allocates nothing. The classify path
never creates traces at all, so the disabled-path bench check
(scripts/check_disabled_overhead.py) pins the whole layer.

Thread model: a trace is created on the admitting (handler) thread,
mutated by the batcher worker, annotated with the HTTP status by the
handler after the worker finished it, and read by ``/debug`` handlers —
every mutation and snapshot is under the trace's own lock.
"""

from __future__ import annotations

import heapq
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

#: Upper bound accepted for client-supplied request ids (``x-request-id``).
MAX_REQUEST_ID_LEN = 128


def gen_request_id() -> str:
    """A fresh opaque request id (hex, collision-safe)."""
    return uuid.uuid4().hex


def valid_request_id(rid: str) -> bool:
    """Client-supplied ids must be printable ASCII (no controls, no
    spaces — they go into log lines and Prometheus exemplar labels) and
    bounded. Anything else is a 400 at the front door, never a traceback."""
    if not rid or len(rid) > MAX_REQUEST_ID_LEN:
        return False
    return all(33 <= ord(c) <= 126 for c in rid)


class RequestTrace:
    """One request's structured timeline.

    Phases are contiguous wall intervals owned by exactly one layer at a
    time (``queue_wait``: enqueue → worker pickup; ``dispatch``: pickup →
    terminal outcome), so their durations sum to ~``request_ms`` — the
    invariant tests/test_reqtrace.py pins under concurrent load.
    ``attempts`` record each degradation-ladder rung the batch tried while
    this request was live; ``events`` are zero-length markers (breaker
    transitions, fallbacks). :meth:`finish` is idempotent (first outcome
    wins), closes any still-open phase at the terminal instant, and hands
    the trace to the recorder — only finished traces are ever visible at
    ``/debug``.
    """

    __slots__ = (
        "request_id", "kind", "rows", "t0_ns", "wall_start_s", "phases",
        "attempts", "events", "annotations", "outcome", "request_ms",
        "_recorder", "_lock",
    )

    def __init__(self, kind: str, rows: int,
                 request_id: Optional[str] = None,
                 recorder: Optional["FlightRecorder"] = None):
        self.request_id = request_id or gen_request_id()
        self.kind = kind
        self.rows = int(rows)
        self.t0_ns = time.monotonic_ns()
        self.wall_start_s = time.time()
        self.phases: List[dict] = []  # {"phase","start_ms","ms"|None}
        self.attempts: List[dict] = []
        self.events: List[dict] = []
        self.annotations: Dict[str, Any] = {}
        self.outcome: Optional[str] = None
        self.request_ms: Optional[float] = None
        self._recorder = recorder
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def _rel_ms(self) -> float:
        return (time.monotonic_ns() - self.t0_ns) / 1e6

    def phase_start(self, name: str) -> None:
        with self._lock:
            self.phases.append(
                {"phase": name, "start_ms": round(self._rel_ms(), 3),
                 "ms": None}
            )

    def phase_end(self, name: str) -> None:
        now = self._rel_ms()
        with self._lock:
            for p in reversed(self.phases):
                if p["phase"] == name and p["ms"] is None:
                    p["ms"] = round(now - p["start_ms"], 3)
                    return

    def attempt(self, rung: str, ok: bool, ms: float,
                error: Optional[str] = None, **attrs) -> None:
        rec = {"rung": rung, "ok": ok, "ms": round(ms, 3), **attrs}
        if error is not None:
            rec["error"] = error
        with self._lock:
            self.attempts.append(rec)

    def event(self, name: str, **attrs) -> None:
        with self._lock:
            self.events.append(
                {"event": name, "at_ms": round(self._rel_ms(), 3), **attrs}
            )

    def annotate(self, **kw) -> None:
        with self._lock:
            self.annotations.update(kw)

    def finish(self, outcome: str) -> None:
        """Terminal: record the outcome (first call wins), close any open
        phase at this instant (the request ended — so did whatever phase it
        was in), and commit to the recorder."""
        now = self._rel_ms()
        with self._lock:
            if self.outcome is not None:
                return
            self.outcome = outcome
            self.request_ms = round(now, 3)
            for p in self.phases:
                if p["ms"] is None:
                    p["ms"] = round(now - p["start_ms"], 3)
        if self._recorder is not None:
            self._recorder.record(self)

    @property
    def finished(self) -> bool:
        return self.outcome is not None

    # -- snapshots ---------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "request_id": self.request_id,
                "kind": self.kind,
                "rows": self.rows,
                "start_unix": round(self.wall_start_s, 6),
                "outcome": self.outcome,
                "request_ms": self.request_ms,
                "phases": [dict(p) for p in self.phases],
                "attempts": [dict(a) for a in self.attempts],
                "events": [dict(e) for e in self.events],
                **{k: v for k, v in self.annotations.items()},
            }


class FlightRecorder:
    """Bounded ring of the last-``capacity`` finished timelines plus a
    slowest-``slowest_k`` reservoir (min-heap on ``request_ms``, so the
    cheapest of the K is evicted first). Both are snapshots of the SAME
    :class:`RequestTrace` objects — a late ``annotate`` (the handler
    stamping the HTTP status after the worker finished the trace) shows up
    in ``/debug`` without re-recording.

    Memory is bounded by ``capacity + slowest_k`` trace objects; recording
    is O(log K) under one lock — fine next to a device dispatch, and the
    layer is entirely absent unless a recorder was wired in.
    """

    def __init__(self, capacity: int = 256, slowest_k: int = 32):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if slowest_k < 0:
            raise ValueError(f"slowest_k must be >= 0, got {slowest_k}")
        self.capacity = int(capacity)
        self.slowest_k = int(slowest_k)
        self._lock = threading.Lock()
        self._ring: List[RequestTrace] = []
        self._ring_pos = 0
        self._slow: List[tuple] = []  # (request_ms, seq, trace) min-heap
        self._seq = 0
        self.completed = 0

    # -- producer side -----------------------------------------------------

    def new_trace(self, kind: str, rows: int,
                  request_id: Optional[str] = None) -> RequestTrace:
        return RequestTrace(kind, rows, request_id=request_id, recorder=self)

    def record(self, trace: RequestTrace) -> None:
        with self._lock:
            self.completed += 1
            self._seq += 1
            if len(self._ring) < self.capacity:
                self._ring.append(trace)
            else:
                self._ring[self._ring_pos] = trace
                self._ring_pos = (self._ring_pos + 1) % self.capacity
            if self.slowest_k:
                item = (trace.request_ms or 0.0, self._seq, trace)
                if len(self._slow) < self.slowest_k:
                    heapq.heappush(self._slow, item)
                elif item[0] > self._slow[0][0]:
                    heapq.heapreplace(self._slow, item)

    # -- consumer side -----------------------------------------------------

    def _recent_traces(self) -> List[RequestTrace]:
        with self._lock:
            if len(self._ring) < self.capacity:
                ordered = list(self._ring)
            else:
                ordered = (self._ring[self._ring_pos:]
                           + self._ring[:self._ring_pos])
        ordered.reverse()  # newest first
        return ordered

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """The last-N timelines, newest first."""
        out = self._recent_traces()
        if n is not None:
            out = out[:max(0, int(n))]
        return [t.to_dict() for t in out]

    def slowest(self) -> List[dict]:
        """The slowest-K reservoir, slowest first."""
        with self._lock:
            items = sorted(self._slow, key=lambda it: -it[0])
        return [t.to_dict() for _, _, t in items]

    def find(self, request_id: str) -> Optional[dict]:
        with self._lock:
            pool = list(self._ring) + [it[2] for it in self._slow]
        for t in pool:
            if t.request_id == request_id:
                return t.to_dict()
        return None

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "slowest_k": self.slowest_k,
                "recorded": len(self._ring),
                "completed": self.completed,
            }

    # -- Perfetto export ---------------------------------------------------

    def to_trace_events(self, timelines: List[dict]) -> List[dict]:
        """Chrome ``trace_event`` JSON for per-request timelines: one
        Perfetto track (tid) per request, named by its request_id; phases
        as matched B/E pairs, attempts as sub-slices under ``dispatch``,
        events as instants. Timestamps are each request's own relative
        milliseconds offset onto a shared epoch via ``start_unix``, so
        concurrent requests line up on one wall clock."""
        if not timelines:
            return []
        epoch = min(t.get("start_unix", 0.0) for t in timelines)
        events: List[dict] = []
        for tid, tl in enumerate(timelines, start=1):
            events.extend(timeline_trace_events(tl, pid=1, tid=tid,
                                                epoch=epoch))
        return events

    def to_chrome_trace(self, timelines: Optional[List[dict]] = None) -> dict:
        if timelines is None:
            timelines = self.recent()
        return {
            "traceEvents": self.to_trace_events(timelines),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "knn_tpu.obs.reqtrace",
                          "requests": len(timelines)},
        }


# ---------------------------------------------------------------------------
# Timeline -> trace_event rendering, shared by the in-process recorder and
# the router's cross-tier stitcher (which only ever holds timeline DICTS —
# the replica side of a stitched trace arrives over HTTP from the replica's
# own /debug/requests, not as live RequestTrace objects).


def timeline_trace_events(tl: dict, *, pid: int = 1, tid: int = 1,
                          epoch: Optional[float] = None) -> List[dict]:
    """One finished timeline dict (:meth:`RequestTrace.to_dict` shape) as
    Chrome ``trace_event`` records on track ``(pid, tid)``: the request
    envelope and phases as matched B/E pairs, attempts stacked back to
    back inside the ``dispatch`` phase, events as instants. ``epoch`` is
    the shared wall-clock origin (``start_unix`` seconds) timestamps are
    offset against; defaults to this timeline's own start."""
    if epoch is None:
        epoch = tl.get("start_unix", 0.0)
    base_us = (tl.get("start_unix", epoch) - epoch) * 1e6
    common = {"cat": "knn_tpu.request", "pid": pid, "tid": tid}
    events: List[dict] = [{
        "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
        "args": {"name": f"req {tl['request_id']}"},
    }]
    args = {
        "request_id": tl["request_id"], "kind": tl.get("kind"),
        "rows": tl.get("rows"), "outcome": tl.get("outcome"),
        "rung": tl.get("rung"),
    }
    # Cost attribution (obs/accounting.py), when the layer is on:
    # what this request paid rides its Perfetto track too.
    for extra in ("request_class", "cost"):
        if extra in tl:
            args[extra] = tl[extra]
    events.append(dict(common, ph="B", name=f"request:{tl.get('outcome')}",
                       ts=base_us, args=args))
    for p in tl.get("phases", ()):
        b = base_us + p["start_ms"] * 1e3
        events.append(dict(common, ph="B", name=p["phase"], ts=b))
        events.append(dict(common, ph="E", name=p["phase"],
                           ts=b + (p["ms"] or 0.0) * 1e3))
    # Attempts have no recorded start offset; stack them inside
    # the dispatch phase in order, back to back.
    disp = next((p for p in tl.get("phases", ())
                 if p["phase"] == "dispatch"), None)
    if disp is not None:
        cursor = base_us + disp["start_ms"] * 1e3
        for a in tl.get("attempts", ()):
            events.append(dict(
                common, ph="B", name=f"attempt:{a['rung']}",
                ts=cursor, args={k: v for k, v in a.items()},
            ))
            cursor += a["ms"] * 1e3
            events.append(dict(common, ph="E",
                               name=f"attempt:{a['rung']}", ts=cursor))
    for ev in tl.get("events", ()):
        events.append(dict(
            common, ph="i", s="t", name=ev["event"],
            ts=base_us + ev["at_ms"] * 1e3,
            args={k: v for k, v in ev.items()},
        ))
    events.append(dict(
        common, ph="E", name=f"request:{tl.get('outcome')}",
        ts=base_us + (tl.get("request_ms") or 0.0) * 1e3,
    ))
    return events


def stitch_trace_events(tiers: List[tuple]) -> List[dict]:
    """Cross-tier stitch: ``tiers`` is an ordered list of ``(tier_name,
    [timeline dicts])`` — e.g. ``[("router", [router_tl]),
    ("http://r2:8099", [replica_tl])]``. Each tier becomes one Perfetto
    PROCESS (pid, named by the tier), each timeline one track inside it,
    all offset onto one shared wall-clock epoch — so a request's router
    dispatch and the replica work it forwarded to line up vertically.

    Clock caveat: ``start_unix`` is each process's own ``time.time()``;
    cross-host skew shifts whole tracks against each other (same-host
    fleets — the soak topology — line up to NTP noise). Durations within
    a track are monotonic-clock true regardless."""
    all_tls = [tl for _, tls in tiers for tl in tls if tl]
    if not all_tls:
        return []
    epoch = min(tl.get("start_unix", 0.0) for tl in all_tls)
    events: List[dict] = []
    for pid, (tier, tls) in enumerate(tiers, start=1):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": str(tier)}})
        for tid, tl in enumerate(tls, start=1):
            if not tl:
                continue
            events.extend(timeline_trace_events(tl, pid=pid, tid=tid,
                                                epoch=epoch))
    return events


def stitch_chrome_trace(tiers: List[tuple]) -> dict:
    """The stitched tiers as a complete Chrome/Perfetto trace document
    (load at ui.perfetto.dev) — the router's ``/debug/requests?id=...&
    format=perfetto`` payload and the fleet soak's CI artifact."""
    return {
        "traceEvents": stitch_trace_events(tiers),
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "knn_tpu.obs.reqtrace",
            "tiers": [str(name) for name, _ in tiers],
        },
    }


# ---------------------------------------------------------------------------
# The active-context channel: layers with no request knowledge (the circuit
# breaker, the degradation ladder) emit into whatever traces the current
# thread's dispatch is serving. One thread-local getattr when nothing is
# armed — the classify path and the disabled serving path pay only that.

_tls = threading.local()


class _Activation:
    __slots__ = ("traces", "prev")

    def __init__(self, traces):
        self.traces = traces
        self.prev = None

    def __enter__(self):
        self.prev = getattr(_tls, "active", None)
        _tls.active = self.traces
        return self

    def __exit__(self, *exc):
        _tls.active = self.prev
        return False


def activate(traces: List[RequestTrace]) -> _Activation:
    """Arm ``traces`` as the current thread's active request contexts for
    the duration of a dispatch (context manager)."""
    return _Activation(traces)


def emit(name: str, **attrs) -> None:
    """Record a zero-length event into every active request context on
    this thread; a single-predicate no-op when none are armed."""
    active = getattr(_tls, "active", None)
    if not active:
        return
    for t in active:
        t.event(name, **attrs)
