"""Device-side observability: profiler capture sessions, device-memory
gauges, compile-event attribution, and executable-cache hit/miss counters.

PRs 1 and 5 made the host side legible (spans, per-request timelines, SLO
burn rates); the device was still a black box — nothing reported HBM in
use, compile events, or an actual XLA timeline. This module is the
device-side half of ``knn_tpu.obs``:

- :func:`capture` / :func:`capture_for` — on-demand ``jax.profiler``
  capture sessions returning ONE Perfetto-loadable Chrome ``trace_event``
  JSON object. During the window the global tracer's
  ``jax.profiler.TraceAnnotation`` pass-through (``obs/tracer.py``) is
  forced on, so every host span recorded while the capture runs appears
  *inside* the device timeline — the serve spans and the XLA executable
  events line up on one time axis. Exposed as ``--profile-out`` on the
  classify CLI and ``GET /debug/profile?ms=N`` on the serve front-end.
- :func:`record_device_memory` — ``knn_device_memory_bytes{kind=in_use|
  peak}`` gauges per device from ``device.memory_stats()``; where a
  backend reports none (CPU jaxlib), falls back to summing the client's
  live device buffers, with a module-tracked running peak, and labels the
  sample ``source="live_buffers"`` so the two can never be confused.
- :func:`install_compile_listeners` — ``jax.monitoring`` duration events
  (``/jax/core/compile/*``) become ``knn_compile_events_total{event=…}``
  counters and ``knn_compile_wall_ms{event=…}`` histograms: the *timed*
  compile walls the backend itself reports, with the registry-level
  ``knn_first_call_wall_ms`` (obs/instrument.py) remaining the fallback
  upper bound where jax emits nothing. Registered at ``obs.enable()``;
  the listener body gates on ``obs.enabled()`` so the disabled path
  records nothing (pinned by scripts/check_disabled_overhead.py).
- :func:`record_executable_lookup` — host-side executable-cache hit/miss
  counters (``knn_executable_cache_total{backend,outcome}``): the first
  dispatch of a (backend, signature) since enable/reset is a ``miss``
  (XLA compiles), repeats are ``hit``s. An explicit ``lower().compile()``
  can be timed with :func:`timed_compile` where a caller holds a
  lowerable fn — NOT on a serving path, because jax's jit call cache is
  not seeded by explicit compiles (measured: the next ``fn(x)`` compiles
  again).

Everything gates on ``obs.enabled()``: one predicate per call site while
off, nothing recorded, no listeners doing work.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import shutil
import tempfile
import threading
import time
from typing import List, Optional

from knn_tpu import obs

# Compile walls span sub-ms jaxpr traces through multi-minute TPU compiles.
COMPILE_MS_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
    2500.0, 5000.0, 15000.0, 60000.0, 300000.0,
)

#: /debug/profile refuses windows past this (a capture pins one handler
#: thread and the global capture lock for its whole duration).
MAX_CAPTURE_MS = 10_000


class CaptureBusy(RuntimeError):
    """A profiler capture is already running; one at a time — the backend
    profiler is a process-global singleton (jax raises otherwise, and two
    interleaved windows would attribute each other's events)."""


_capture_lock = threading.Lock()

_listener_lock = threading.Lock()
_listeners_installed = False

_exec_lock = threading.Lock()
_exec_seen: set = set()

# Fallback-peak tracking for backends whose memory_stats() is None: the
# running max of summed live-buffer bytes per device, since process start
# (or the last obs.reset()).
_peak_lock = threading.Lock()
_live_peak: dict = {}


def reset_state() -> None:
    """Clear the first-seen executable signatures and the fallback peak
    tracking (called from ``obs.reset()`` so a reset registry and the
    hit/miss memory stay consistent)."""
    with _exec_lock:
        _exec_seen.clear()
    with _peak_lock:
        _live_peak.clear()


# -- device memory ----------------------------------------------------------


def device_memory_stats(devices=None) -> List[dict]:
    """Per-device memory sample: ``[{"device", "platform", "in_use",
    "peak", "source"}, ...]``. ``source`` is ``"memory_stats"`` when the
    backend reports real allocator stats (TPU/GPU ``bytes_in_use`` /
    ``peak_bytes_in_use``) and ``"live_buffers"`` for the host-side
    fallback (sum of live device-buffer bytes; ``peak`` is the running max
    this process has observed, not the allocator's)."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    out = []
    for d in devices:
        label = f"{getattr(d, 'platform', '?')}:{getattr(d, 'id', 0)}"
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — a backend without the API
            stats = None
        if stats:
            in_use = int(stats.get("bytes_in_use", 0))
            peak = int(stats.get("peak_bytes_in_use", in_use))
            source = "memory_stats"
        else:
            in_use = _live_buffer_bytes(d)
            with _peak_lock:
                peak = max(_live_peak.get(label, 0), in_use)
                _live_peak[label] = peak
            source = "live_buffers"
        out.append({
            "device": label,
            "platform": getattr(d, "platform", "?"),
            "in_use": in_use,
            "peak": peak,
            "source": source,
        })
    return out


def _live_buffer_bytes(d) -> int:
    try:
        client = d.client
        total = 0
        for buf in client.live_buffers():
            try:
                dev = buf.device  # property on new jaxlib, method on old
                if callable(dev):
                    dev = dev()
                if dev is d:
                    total += int(getattr(buf, "nbytes", 0) or 0)
            except Exception:  # noqa: BLE001 — a donated/deleted buffer
                continue
        return total
    except Exception:  # noqa: BLE001 — no client/live_buffers on this jaxlib
        return 0


def record_device_memory(devices=None) -> List[dict]:
    """Sample device memory and (when obs is enabled) publish the
    ``knn_device_memory_bytes{kind=in_use|peak, device=…}`` gauges. Returns
    the sample either way so ``/healthz`` can embed it."""
    stats = device_memory_stats(devices)
    if obs.enabled():
        for s in stats:
            for kind in ("in_use", "peak"):
                obs.gauge_set(
                    "knn_device_memory_bytes", s[kind],
                    help="device memory bytes (memory_stats where the "
                         "backend reports it, live-buffer sum fallback)",
                    kind=kind, device=s["device"], source=s["source"],
                )
    return stats


# -- compile events ---------------------------------------------------------


def _event_leaf(name: str) -> str:
    """``/jax/core/compile/backend_compile_duration`` -> ``backend_compile``."""
    leaf = name.rsplit("/", 1)[-1]
    if leaf.endswith("_duration"):
        leaf = leaf[: -len("_duration")]
    return leaf


def _on_event_duration(name: str, dur_s: float, **kw) -> None:
    if not obs.enabled() or "compile" not in name:
        return
    leaf = _event_leaf(name)
    obs.counter_add(
        "knn_compile_events_total", 1,
        help="XLA/jax compile events (jax.monitoring durations)",
        event=leaf,
    )
    obs.histogram_observe(
        "knn_compile_wall_ms", dur_s * 1e3, buckets=COMPILE_MS_BUCKETS,
        help="per-event compile wall ms (jax.monitoring durations)",
        event=leaf,
    )


def install_compile_listeners() -> bool:
    """Register the ``jax.monitoring`` duration listener (idempotent —
    jax offers no unregistration, so the body gates on ``obs.enabled()``).
    Returns True when the listener is installed."""
    global _listeners_installed
    with _listener_lock:
        if _listeners_installed:
            return True
        try:
            import jax.monitoring
        except ImportError:
            return False
        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration
        )
        _listeners_installed = True
        return True


def timed_compile(jitted_fn, *args, label: str = "explicit", **kwargs):
    """Explicitly ``lower().compile()`` a jitted fn, recording the wall as
    ``knn_compile_explicit_wall_ms{label=…}``. Returns the Compiled object.

    For probing/benchmarks only — jax's jit call cache is NOT seeded by an
    explicit compile (measured: ``fn(x)`` after ``fn.lower(x).compile()``
    compiles again), so calling this on a serving path doubles compile
    cost. The live serving compile walls come from the monitoring listener
    instead."""
    lowered = jitted_fn.lower(*args, **kwargs)
    t0 = time.monotonic()
    compiled = lowered.compile()
    wall_ms = (time.monotonic() - t0) * 1e3
    obs.gauge_set(
        "knn_compile_explicit_wall_ms", round(wall_ms, 3),
        help="explicit lower().compile() wall ms (probing paths)",
        label=label,
    )
    return compiled


# -- executable-cache hit/miss ----------------------------------------------


def record_executable_lookup(backend: str, key: tuple) -> str:
    """Count one dispatch against the host-side executable-signature set:
    the first (backend, key) since enable/reset is a ``miss`` (the dispatch
    will compile), repeats are ``hit``s. Returns "hit"/"miss", or "off"
    (nothing recorded) while obs is disabled. ``key`` must capture
    everything that forces a new executable — shapes, dtypes, and every
    static argument."""
    if not obs.enabled():
        return "off"
    full = (backend, key)
    with _exec_lock:
        outcome = "hit" if full in _exec_seen else "miss"
        _exec_seen.add(full)
    obs.counter_add(
        "knn_executable_cache_total", 1,
        help="dispatches by executable-cache outcome (host-side signature "
             "tracking: first dispatch of a signature compiles)",
        backend=backend, outcome=outcome,
    )
    return outcome


# -- summaries (the /healthz device block) ----------------------------------


def compile_summary() -> dict:
    """``{event: {"count": n, "wall_ms_total": x}}`` from the registry's
    compile instruments (empty dict when none recorded)."""
    out: dict = {}
    for inst in obs.registry().instruments():
        labels = dict(inst.labels)
        if inst.name == "knn_compile_events_total":
            out.setdefault(labels.get("event", "?"), {}).update(
                count=inst.value
            )
        elif inst.name == "knn_compile_wall_ms":
            out.setdefault(labels.get("event", "?"), {}).update(
                wall_ms_total=round(inst.sum, 3)
            )
    return out


def executable_cache_summary() -> dict:
    """``{"hits": h, "misses": m}`` summed over backends."""
    hits = misses = 0
    for inst in obs.registry().instruments():
        if inst.name != "knn_executable_cache_total":
            continue
        outcome = dict(inst.labels).get("outcome")
        if outcome == "hit":
            hits += inst.value
        elif outcome == "miss":
            misses += inst.value
    return {"hits": hits, "misses": misses}


# -- capture sessions -------------------------------------------------------


class Capture:
    """Result slot for one profiler capture: ``trace`` (the merged Chrome
    ``trace_event`` dict) is set when the context exits; ``error`` carries
    a profiler failure message (the trace then falls back to host spans
    only, with the error noted in ``otherData``)."""

    __slots__ = ("trace", "error")

    def __init__(self):
        self.trace: Optional[dict] = None
        self.error: Optional[str] = None


@contextlib.contextmanager
def capture(annotate: bool = True):
    """Run a ``jax.profiler`` capture around the with-block, yielding a
    :class:`Capture` whose ``.trace`` is the Perfetto-loadable Chrome
    ``trace_event`` JSON after exit.

    ``annotate=True`` (default) forces the global tracer's
    ``TraceAnnotation`` pass-through on for the window, so host spans
    recorded meanwhile appear inside the device timeline (restored after).
    One capture at a time: a concurrent attempt raises
    :class:`CaptureBusy` immediately (the serve endpoint maps it to 409).
    """
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusy(
            "a profiler capture is already running (one at a time)"
        )
    cap = Capture()
    tmp = tempfile.mkdtemp(prefix="knn_devprof_")
    tracer = obs.tracer()
    prev_anno = tracer.jax_annotations
    started = False
    t0 = time.monotonic()
    try:
        try:
            import jax.profiler

            if annotate:
                tracer.jax_annotations = True
            jax.profiler.start_trace(tmp)
            started = True
        except Exception as e:  # noqa: BLE001 — backend without a profiler
            cap.error = f"{type(e).__name__}: {e}"
        try:
            yield cap
        finally:
            tracer.jax_annotations = prev_anno
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001
                    cap.error = f"{type(e).__name__}: {e}"
        cap.trace = _load_profile_trace(tmp, cap.error)
        cap.trace["otherData"]["capture_wall_ms"] = round(
            (time.monotonic() - t0) * 1e3, 3
        )
        obs.counter_add(
            "knn_profile_captures_total", 1,
            help="profiler capture sessions, by outcome",
            outcome="error" if cap.error else "ok",
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        _capture_lock.release()


def capture_for(duration_ms: float, annotate: bool = True) -> dict:
    """Blocking fixed-window capture (the ``/debug/profile?ms=N`` shape):
    start, sleep ``duration_ms`` while other threads keep working, stop,
    return the trace dict. The caller's thread contributes nothing to the
    window — the interesting events come from the threads serving load."""
    with capture(annotate=annotate) as cap:
        time.sleep(max(0.0, float(duration_ms)) / 1e3)
    return cap.trace


def _load_profile_trace(tmpdir: str, error: Optional[str]) -> dict:
    """Read the profiler's Chrome trace (``**/*.trace.json.gz``) and wrap
    it with provenance. When the profiler produced nothing (unsupported
    backend, start failure), fall back to the global tracer's host spans
    so the artifact is still a loadable timeline — with the degradation
    named in ``otherData`` instead of silently thinner data."""
    other = {"producer": "knn_tpu.obs.devprof", "epoch_unix_s": time.time()}
    if error:
        other["profiler_error"] = error
    paths = sorted(glob.glob(
        os.path.join(tmpdir, "**", "*.trace.json.gz"), recursive=True
    ))
    if paths:
        try:
            with gzip.open(paths[-1], "rt", encoding="utf-8") as f:
                data = json.load(f)
            events = data.get("traceEvents", [])
            out = {
                "traceEvents": events,
                "displayTimeUnit": data.get("displayTimeUnit", "ns"),
                "otherData": {**other, "source": "jax.profiler",
                              **{k: v for k, v in
                                 (data.get("metadata") or {}).items()
                                 if isinstance(v, (str, int, float))}},
            }
            return out
        except (OSError, ValueError) as e:
            other["profiler_error"] = f"unreadable profiler trace: {e}"
    # Host-span fallback: still a valid Perfetto file, clearly labeled.
    fallback = obs.tracer().to_chrome_trace()
    fallback["otherData"].update(other)
    fallback["otherData"]["source"] = "host_spans_fallback"
    return fallback
