"""Shadow-scored answer quality: recall/accuracy SLIs for the serving stack.

The observability stack sees hosts, requests, SLO burn, and devices — but
not the one thing a KNN service exists to get right: whether the answers
are CORRECT. Availability stays green while a corrupted index, a buggy
rung, or (ROADMAP item 4) an approximate retrieval quietly returns the
wrong neighbors. This module closes that gap with **shadow scoring**:

- the micro-batcher taps each served request into
  :meth:`ShadowScorer.offer` — one seeded RNG draw (``--shadow-rate``,
  default off) plus an O(1) bounded-queue append, on the worker thread;
- a background worker re-answers sampled requests on the exact
  :func:`~knn_tpu.backends.oracle.oracle_kneighbors` rung — THE reference
  retrieval contract, host-only, off the serving path — and scores the
  served answer against it:

  * **recall@k** over the (distance, index) candidate lists, tie-aware:
    a served neighbor counts when its index is in the oracle's top-k OR
    its RECOMPUTED distance ties the oracle's k-th distance (the shared
    (distance, index) contract makes exact rungs match exactly; the tie
    clause is what keeps a future approximate rung honestly scored —
    and because admissibility uses distances the scorer recomputes
    itself, a corrupted index cannot pass by claiming honest distances);
  * **vote agreement** for predict requests: the served predictions vs a
    vote over the oracle's candidates.

- divergence is **attributed to the answering rung**
  (``knn_quality_recall{rung}``, ``knn_quality_divergence_total{rung,
  kind}`` with kind ∈ neighbors/distance/vote), so a silently-wrong
  degraded rung is distinguishable from a healthy fast rung — the
  detection a bad approximate rung needs before ROADMAP item 4 ships one;
- each scored request feeds the ``quality`` SLI
  (:meth:`~knn_tpu.obs.slo.SLOTracker.record_quality`), riding the same
  multi-window burn-rate machinery as availability/latency/fast_rung.

Latency contract (pinned by tests/test_quality.py and the bench's
``c8_shadow_p50_ms`` row): the batcher worker NEVER blocks on shadow
scoring — a full queue sheds the sample (counted in
``knn_quality_shed_total``) and serving proceeds; the model reference
each sample carries is the batch's own snapshot, so scoring stays correct
across hot reloads (an old-index answer is scored against the old index).

Not constructed (rate 0) → the batcher pays one ``is None`` predicate and
nothing is recorded — the zero-cost-when-disabled contract
(scripts/check_disabled_overhead.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict

import numpy as np

from knn_tpu import obs
from knn_tpu.obs.shedqueue import ShedQueue

#: Relative tolerance for "the distances agree": exact rungs reproduce the
#: oracle bit-for-bit, but a matmul-form distance (MXU fast path) may differ
#: in the last ulps; beyond this the served DISTANCE is wrong even when the
#: neighbor index is right — a distinct divergence kind.
DISTANCE_RTOL = 1e-4

DIVERGENCE_KINDS = ("neighbors", "distance", "vote")


def recall_at_k(served_i: np.ndarray, oracle_i: np.ndarray,
                oracle_d: np.ndarray, true_d: np.ndarray) -> np.ndarray:
    """Per-row recall@k of a served candidate list against the oracle's,
    tie-aware under the shared (distance, index) contract.

    A served neighbor is a hit when its train index appears in the
    oracle's top-k for that row, OR its TRUE distance (``true_d`` — the
    scorer recomputes the distance of every served index itself; the
    server's claimed distances are never trusted for admissibility) ties
    the oracle's k-th (worst) distance: an equally-near neighbor that the
    deterministic (distance, index) order happened to break the other way
    is not a recall loss — the convention approximate retrieval is scored
    by, and what keeps a future approximate rung honestly scored. Exact
    rungs under the shared contract score exactly 1.0. Returns a float
    array ``[Q]`` in [0, 1].
    """
    served_i = np.asarray(served_i)
    oracle_i = np.asarray(oracle_i)
    oracle_d = np.asarray(oracle_d, np.float64)
    true_d = np.asarray(true_d, np.float64)
    if served_i.shape != oracle_i.shape:
        raise ValueError(
            f"served and oracle candidate shapes differ: "
            f"{served_i.shape} vs {oracle_i.shape}"
        )
    q, k = served_i.shape
    out = np.empty(q, np.float64)
    for row in range(q):
        in_set = np.isin(served_i[row], oracle_i[row])
        tie_ok = true_d[row] <= oracle_d[row, -1]
        # Each DISTINCT train index counts at most once: a degenerate
        # list that repeats the true nearest neighbor k times recalled
        # one neighbor, not k.
        hits = {int(t) for t, ok in zip(served_i[row], in_set | tie_ok)
                if ok}
        out[row] = len(hits) / k
    return out


def true_distances(train_x: np.ndarray, queries: np.ndarray,
                   served_i: np.ndarray, metric: str) -> np.ndarray:
    """Recompute the ACTUAL distance from each query row to each train row
    the server claims as a neighbor (``[Q, k]``) — the ground truth the
    tie clause and the distance-divergence check score against. Shares
    the oracle backend's metric formulas so exact rungs reproduce it
    bit-for-bit; NaNs follow the framework-wide NaN→+inf policy."""
    from knn_tpu.backends.oracle import _metric_dists

    queries = np.asarray(queries, np.float32)
    served_i = np.asarray(served_i)
    out = np.empty(served_i.shape, np.float64)
    for row in range(served_i.shape[0]):
        d = _metric_dists(queries[row:row + 1],
                          np.asarray(train_x, np.float32)[served_i[row]],
                          metric)[0]
        out[row] = np.nan_to_num(d.astype(np.float64), nan=np.inf)
    return out


class _Sample:
    """One sampled served request, queued for background scoring. Carries
    the batch's own (model, version) snapshot — and, under mutable
    serving, the batch's own immutable
    :class:`~knn_tpu.mutable.state.MutableView` — so scoring is correct
    across hot reloads AND compaction swaps."""

    __slots__ = ("features", "kind", "dists", "idx", "preds", "rung",
                 "model", "version", "mview", "t_ns")

    def __init__(self, features, kind, dists, idx, preds, rung, model,
                 version, mview=None):
        self.features = features
        self.kind = kind
        self.dists = dists
        self.idx = idx
        self.preds = preds
        self.rung = rung
        self.model = model
        self.version = version
        self.mview = mview
        self.t_ns = time.monotonic_ns()


class _RungStats:
    __slots__ = ("scored", "rows", "recall_sum", "vote_rows", "vote_ok",
                 "divergence")

    def __init__(self):
        self.scored = 0          # requests scored
        self.rows = 0            # query rows scored
        self.recall_sum = 0.0    # sum of per-row recalls
        self.vote_rows = 0       # predict rows compared
        self.vote_ok = 0         # predict rows agreeing with the oracle vote
        self.divergence: Dict[str, int] = {k: 0 for k in DIVERGENCE_KINDS}


class ShadowScorer:
    """Sampled oracle re-answering with per-rung streaming quality stats.

    ``rate``      — sampling probability per served request (seeded RNG;
                    the caller does not construct a scorer at rate 0);
    ``queue_cap`` — bounded sample queue; a full queue SHEDS (counted),
                    never blocks the batcher worker;
    ``slo``       — optional :class:`~knn_tpu.obs.slo.SLOTracker`; each
                    scored request records one ``quality`` SLI event
                    (good = recall 1.0 and vote agreement);
    ``approx_floors`` — ``{rung: recall_floor}`` for APPROXIMATE rungs
                    (the ivf rung's ``--ivf-recall-floor``): a request
                    answered by such a rung is quality-good when its mean
                    recall@k meets the floor and every served distance is
                    honest — rather than the exact rungs' bit-exact bar,
                    which an approximate rung would burn constantly at
                    its designed operating point. Divergence COUNTING is
                    unchanged (any row under recall 1.0 still counts
                    ``neighbors`` divergence for attribution); only the
                    SLI verdict applies the floor. Empty/None = every
                    rung held to the exact bar.
    ``autostart`` — tests pin shed/queue mechanics with the worker held
                    off; serving always autostarts.
    """

    def __init__(self, rate: float, *, queue_cap: int = 256, seed: int = 0,
                 slo=None, approx_floors: "Dict[str, float] | None" = None,
                 autostart: bool = True):
        if not 0.0 < rate <= 1.0:
            raise ValueError(
                f"shadow rate must be in (0, 1], got {rate} (omit the "
                f"scorer entirely to disable shadow scoring)"
            )
        self.rate = float(rate)
        self.slo = slo
        for rung, floor in (approx_floors or {}).items():
            if not 0.0 < floor <= 1.0:
                raise ValueError(
                    f"approx recall floor for rung {rung!r} must be in "
                    f"(0, 1], got {floor}")
        self.approx_floors = dict(approx_floors or {})
        # `offered` is mutated only on the batcher worker thread (the one
        # tap site); everything the scoring thread and readers share lives
        # under `_lock`.
        self.offered = 0
        self._lock = threading.Lock()
        self.scored = 0
        self.score_errors = 0
        self._rungs: Dict[str, _RungStats] = {}
        self._sq = ShedQueue(
            rate=rate, queue_cap=queue_cap, seed=seed,
            consume=self._score_absorbing,
            thread_name="knn-quality-scorer",
            on_shed=lambda: obs.counter_add(
                "knn_quality_shed_total",
                help="shadow samples dropped because the scoring queue "
                     "was full (shed-on-overload — the batcher worker "
                     "never blocks on shadow scoring)",
            ),
            autostart=autostart,
        )

    @property
    def queue_cap(self) -> int:
        return self._sq.queue_cap

    @property
    def shed(self) -> int:
        return self._sq.shed

    def set_rate(self, rate: float) -> None:
        """Move the live sampling rate (the control plane's brownout
        knob — :mod:`knn_tpu.control.brownout`). 0 is legal HERE (a
        temporary full brownout of scoring), unlike the constructor:
        a scorer built to sample nothing would be dead weight, a scorer
        told to pause is a reversible operating point."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"shadow rate must be in [0, 1], got {rate}")
        self.rate = float(rate)
        self._sq.rate = float(rate)

    def set_defer(self, defer) -> None:
        """Install (or clear, with None) the brownout's headroom gate:
        while it returns True, offers are counted shed instead of queued
        — scoring work waits for measured headroom."""
        self._sq.defer = defer

    # -- producer side (the batcher worker thread) -------------------------

    def offer(self, *, features, kind: str, dists, idx, preds, rung: str,
              model, version, mview=None) -> bool:
        """Sample one served request. O(1) — one RNG draw, one append —
        and NEVER blocks: a full queue sheds the sample and serving
        proceeds (the :class:`~knn_tpu.obs.shedqueue.ShedQueue`
        contract). ``dists``/``idx`` are the request's served slices;
        ``preds`` the served predictions (None for kneighbors requests);
        ``mview`` the batch's mutable view snapshot (None for immutable
        serving) — the scorer then re-answers against the LIVE
        base+delta+tombstone truth, so a server silently ignoring fresh
        writes (staleness) burns the quality SLI like any other wrong
        answer. Returns whether the sample was queued."""
        self.offered += 1
        return self._sq.offer(
            lambda: _Sample(features, kind, dists, idx, preds, rung,
                            model, version, mview)
        )

    # -- worker side -------------------------------------------------------

    def _score_absorbing(self, sample: "_Sample") -> None:
        try:
            self._score(sample)
        except Exception:  # noqa: BLE001 — scoring must never crash
            with self._lock:
                self.score_errors += 1
            obs.counter_add(
                "knn_quality_errors_total",
                help="shadow scorings that raised (sample dropped)",
            )

    def _score(self, s: _Sample) -> None:
        from knn_tpu.backends.oracle import oracle_kneighbors
        from knn_tpu.models.knn import KNNClassifier

        model = s.model
        train = model.train_
        merged = s.mview is not None and not s.mview.empty
        with obs.span("quality.shadow_score", rung=s.rung, kind=s.kind,
                      rows=int(np.shape(s.features)[0])):
            if merged:
                # Mutable serving: the truth is the LIVE view — oracle
                # base retrieval folded with this batch's own delta and
                # tombstone snapshot. A served answer that ignored fresh
                # writes (or resurrected a deleted row) diverges here.
                from knn_tpu.mutable.state import (
                    merged_oracle_kneighbors, view_true_distances,
                )

                oracle_d, oracle_i = merged_oracle_kneighbors(
                    model, s.mview, s.features)
                true_d = view_true_distances(model, s.mview, s.features,
                                             s.idx, model.metric)
            else:
                oracle_d, oracle_i = oracle_kneighbors(
                    train.features, s.features, model.k, model.metric)
                true_d = true_distances(train.features, s.features, s.idx,
                                        model.metric)
            recalls = recall_at_k(s.idx, oracle_i,
                                  oracle_d.astype(np.float64), true_d)
            # Distance divergence: the served DISTANCE disagrees with the
            # recomputed distance of the served index — corrupted distance
            # values, a failure mode selection recall cannot see.
            served_d = np.asarray(s.dists, np.float64)
            tol = DISTANCE_RTOL * np.maximum(np.abs(true_d), 1.0)
            with np.errstate(invalid="ignore"):
                # inf vs inf agrees (diff is NaN -> not > tol); a NaN
                # served distance violates the NaN->+inf policy outright.
                mismatch = np.abs(served_d - true_d) > tol
                mismatch |= np.isnan(served_d)
            dist_rows = int(np.count_nonzero(mismatch.any(axis=1)))
            vote_rows = vote_ok = 0
            if s.kind == "predict" and isinstance(model, KNNClassifier):
                if merged:
                    # The oracle's candidates span base+delta ids: vote
                    # through the view-aware label gather, the same
                    # helper the serving path votes with.
                    from knn_tpu.mutable.state import predict_from_view

                    want_preds = predict_from_view(
                        model, s.mview, oracle_d.astype(np.float32),
                        oracle_i)
                else:
                    want_preds = model.predict_from_candidates(
                        oracle_d.astype(np.float32), oracle_i)
                got = np.asarray(s.preds)
                vote_rows = int(got.shape[0])
                vote_ok = int(np.count_nonzero(got == want_preds))
        rows = int(recalls.shape[0])
        neighbor_rows = int(np.count_nonzero(recalls < 1.0))
        floor = self.approx_floors.get(s.rung)
        if floor is not None:
            # An approximate rung is held to its recall FLOOR, not the
            # exact bar: good = honest distances + mean recall at/over
            # the floor (vote flips below-floor recall causes are what
            # the floor already prices in; a dishonest distance is
            # always a defect).
            good = (dist_rows == 0
                    and float(recalls.mean()) >= floor)
        else:
            good = (neighbor_rows == 0 and dist_rows == 0
                    and vote_ok == vote_rows)
        with self._lock:
            self.scored += 1
            st = self._rungs.setdefault(s.rung, _RungStats())
            st.scored += 1
            st.rows += rows
            st.recall_sum += float(recalls.sum())
            st.vote_rows += vote_rows
            st.vote_ok += vote_ok
            if neighbor_rows:
                st.divergence["neighbors"] += neighbor_rows
            if dist_rows:
                st.divergence["distance"] += dist_rows
            if vote_rows - vote_ok:
                st.divergence["vote"] += vote_rows - vote_ok
        obs.counter_add(
            "knn_quality_scored_total", 1,
            help="served requests re-answered on the oracle rung by the "
                 "shadow scorer", rung=s.rung,
        )
        for kind, n in (("neighbors", neighbor_rows),
                        ("distance", dist_rows),
                        ("vote", vote_rows - vote_ok)):
            if n:
                obs.counter_add(
                    "knn_quality_divergence_total", n,
                    help="scored rows whose served answer diverged from "
                         "the oracle, by answering rung and divergence "
                         "kind (neighbors = wrong candidate set, distance "
                         "= right neighbor wrong distance, vote = wrong "
                         "prediction)",
                    rung=s.rung, kind=kind,
                )
        if self.slo is not None:
            self.slo.record_quality(good)

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued sample is consumed (tests + the soak
        gate); the serving path never calls this."""
        return self._sq.drain(timeout_s)

    def close(self) -> None:
        self._sq.close()

    # -- read side ---------------------------------------------------------

    def export(self) -> dict:
        """Refresh the ``knn_quality_*`` gauges (scrape-time, like
        ``knn_slo_*``) and return the per-rung summary ``/healthz`` and
        ``/debug/quality`` embed. Rungs are ordered by the serving
        ladder's canonical order so the view reads fast → degraded."""
        from knn_tpu.resilience.degrade import SERVING_RUNGS

        with self._lock:
            # Field-level snapshot under the lock: a concurrent _score
            # commits its whole update atomically, so recall can never be
            # computed from a torn (recall_sum, rows) pair.
            rungs = {
                r: {"scored": st.scored, "rows": st.rows,
                    "recall_sum": st.recall_sum,
                    "vote_rows": st.vote_rows, "vote_ok": st.vote_ok,
                    "divergence": dict(st.divergence)}
                for r, st in self._rungs.items()
            }
            summary = {
                "rate": self.rate,
                "approx_floors": dict(self.approx_floors) or None,
                "offered": self.offered,
                "scored": self.scored,
                "shed": self.shed,
                "score_errors": self.score_errors,
                "queue_depth": self._sq.depth(),
                "queue_cap": self.queue_cap,
            }
        order = {r: i for i, r in enumerate(SERVING_RUNGS)}
        per_rung = {}
        for rung in sorted(rungs, key=lambda r: order.get(r, len(order))):
            st = rungs[rung]
            recall = st["recall_sum"] / st["rows"] if st["rows"] else None
            accuracy = (st["vote_ok"] / st["vote_rows"]
                        if st["vote_rows"] else None)
            if recall is not None:
                obs.gauge_set(
                    "knn_quality_recall", round(recall, 6),
                    help="streaming mean recall@k of served answers vs the "
                         "oracle rung, by answering rung (shadow-scored)",
                    rung=rung,
                )
            if accuracy is not None:
                obs.gauge_set(
                    "knn_quality_accuracy", round(accuracy, 6),
                    help="vote agreement of served predictions vs a vote "
                         "over the oracle's candidates, by answering rung",
                    rung=rung,
                )
            per_rung[rung] = {
                "scored": st["scored"],
                "rows": st["rows"],
                "recall": None if recall is None else round(recall, 6),
                "vote_accuracy": (None if accuracy is None
                                  else round(accuracy, 6)),
                "divergence": {k: v for k, v in st["divergence"].items()
                               if v},
            }
        obs.gauge_set(
            "knn_quality_queue_depth", summary["queue_depth"],
            help="shadow samples waiting for the background scorer",
        )
        summary["rungs"] = per_rung
        return summary
