"""Observability subsystem: span tracer + metrics registry.

The reference's entire observability story is one ``CLOCK_MONOTONIC_RAW``
pair around the KNN loop printed as a single milliseconds number
(main.cpp:133-144). This package replaces that with:

- :mod:`knn_tpu.obs.tracer`  — nested, thread-safe wall-time spans,
  exportable as Chrome/Perfetto ``trace_event`` JSON (chrome://tracing or
  https://ui.perfetto.dev load the file directly);
- :mod:`knn_tpu.obs.metrics` — counters, gauges, and fixed-bucket
  histograms with JSON and Prometheus text exposition;
- :mod:`knn_tpu.obs.instrument` — the helpers that weave both through the
  model layer, the backends, and the sharded paths (collective-traffic
  counters reusing ``parallel/comm_audit.py``'s byte model);
- :mod:`knn_tpu.obs.export`  — file writers for ``--trace-out`` /
  ``--metrics-out``;
- :mod:`knn_tpu.obs.bench_timing` — the pipelined-slope measurement
  primitives shared by ``bench.py`` and ``scripts/tune_*.py``;
- :mod:`knn_tpu.obs.reqtrace` — request-scoped tracing for the serving
  stack: per-request timelines, the bounded flight recorder behind
  ``/debug/requests``/``/debug/slowest``, per-request Perfetto export,
  and the active-context channel the breaker/ladder emit through;
- :mod:`knn_tpu.obs.slo`     — SLO objectives and multi-window
  error-budget burn rates (``knn_slo_*`` gauges), including the
  shadow-scored ``quality`` objective;
- :mod:`knn_tpu.obs.quality` — shadow-scored answer quality: sampled
  serving requests re-answered on the oracle rung off the hot path,
  streaming recall@k + vote agreement attributed per answering rung
  (``knn_quality_*``, ``GET /debug/quality``);
- :mod:`knn_tpu.obs.drift`   — query-distribution drift: streaming
  per-feature Welford/P² sketches scored against the training-set
  reference sketch stored in the index artifact (``knn_drift_*``);
  both quality layers ride :mod:`knn_tpu.obs.shedqueue`'s bounded
  shed-on-overload sample queue (the never-block-serving primitive);
- :mod:`knn_tpu.obs.devprof` — the device-side half: ``jax.profiler``
  capture sessions (``--profile-out``, ``/debug/profile``),
  ``knn_device_memory_bytes`` gauges, compile-event counters/walls via
  ``jax.monitoring``, executable-cache hit/miss counters;
- :mod:`knn_tpu.obs.aggregate` — multihost fleet aggregation: per-process
  registry snapshots merged on process 0 with ``{proc=…}`` labels, plus
  straggler gauges over the sharded dispatch walls;
- :mod:`knn_tpu.obs.regress`  — the noise-aware perf-regression
  comparison (best-of-mins with MAD tolerance) behind
  ``scripts/bench_gate.py`` / ``make bench-gate``;
- :mod:`knn_tpu.obs.accounting` — per-request device-cost attribution:
  each serving dispatch's measured wall/bytes split across its coalesced
  requests proportional to rows (conservation-exact), tagged by request
  class and answering rung (``knn_cost_*``), padded compiled-shape rows
  counted as waste;
- :mod:`knn_tpu.obs.capacity` — saturation & headroom: worker duty
  cycle, batch occupancy, arrival/served rate rings (on
  :class:`~knn_tpu.obs.slo.SecondRing`), a Little's-law concurrency
  estimate, and the affine dispatch-cost headroom model behind
  ``GET /debug/capacity`` and ``make capacity-probe``
  (``knn_capacity_*``);
- :mod:`knn_tpu.obs.workload` — workload capture: the serving traffic
  itself (arrival timing, kind/class/rows/deadline/outcome/rung,
  ``index_version``/``mutation_seq``, the acknowledged mutation stream)
  recorded through the shed-never-block queue into schema-hash-pinned
  workload artifacts, armed by ``POST /admin/capture`` or an SLO burn
  trigger (``knn_workload_*``);
- :mod:`knn_tpu.obs.replay`  — deterministic open-loop replay of a
  captured workload against a live server or in-process batcher, with
  bit-identical answer verification at matching
  ``index_version``/``mutation_seq`` (the ``knn_tpu replay`` CLI,
  ``make replay-gate``);
- :mod:`knn_tpu.obs.whatif`  — a discrete-event simulator of the
  batcher's admission/coalesce policy over a captured arrival process,
  costed by the capacity model's fitted ``w(r) = a + b·r`` — candidate
  policy frontiers (max_batch / max_wait_ms / shape buckets) in
  milliseconds without booting a server.

Everything is OFF by default and zero-cost when off: ``span()`` returns a
shared no-op context manager and the metric helpers return immediately, so
the default path pays one predicate per call site (measured ≤1% on the
bench medium preset — docs/OBSERVABILITY.md). Enable programmatically with
:func:`enable`, from the CLI with ``--metrics-out``/``--trace-out``, or
ambiently with ``KNN_TPU_OBS=1``.

The module-level :func:`span` / :func:`counter_add` / :func:`gauge_set` /
:func:`histogram_observe` helpers operate on one process-global tracer and
registry — instrumented library code calls those, while tests and embedders
that want isolation construct their own :class:`SpanTracer` /
:class:`MetricsRegistry`.
"""

from __future__ import annotations

import os

from knn_tpu.obs.tracer import SpanTracer, Span
from knn_tpu.obs.metrics import MetricsRegistry, Counter, Gauge, Histogram

__all__ = [
    "SpanTracer", "Span", "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "enable", "disable", "enabled", "reset", "span", "tracer", "registry",
    "counter_add", "gauge_set", "histogram_observe",
]

_ENABLED = False
_JAX_ANNOTATIONS = False

_TRACER = SpanTracer()
_REGISTRY = MetricsRegistry()


class _NullSpan:
    """The disabled-path span: one shared instance, no state, no work."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def enable(jax_annotations: bool = False) -> None:
    """Turn the global tracer + registry on. ``jax_annotations=True``
    additionally wraps every span in a ``jax.profiler.TraceAnnotation`` so
    host spans line up with device timelines in a jax profiler trace."""
    global _ENABLED, _JAX_ANNOTATIONS
    _ENABLED = True
    _JAX_ANNOTATIONS = bool(jax_annotations)
    _TRACER.jax_annotations = _JAX_ANNOTATIONS
    # Device-side compile attribution (obs/devprof.py): the jax.monitoring
    # listener is registered once here — never at import — and its body
    # gates on enabled(), so the disabled path stays zero-record.
    from knn_tpu.obs import devprof

    devprof.install_compile_listeners()


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def reset() -> None:
    """Drop all recorded spans and metric values (state stays on/off).
    Also clears the instrumentation layer's first-call memory so the next
    predict per backend records ``knn_first_call_wall_ms`` again."""
    _TRACER.reset()
    _REGISTRY.reset()
    from knn_tpu.obs import devprof, instrument

    with instrument._first_call_lock:
        instrument._first_call_seen.clear()
    devprof.reset_state()


def tracer() -> SpanTracer:
    return _TRACER


def registry() -> MetricsRegistry:
    return _REGISTRY


def span(name: str, **attrs):
    """Context manager recording a nested span on the global tracer; a
    shared no-op when observability is disabled."""
    if not _ENABLED:
        return _NULL_SPAN
    return _TRACER.span(name, **attrs)


def counter_add(name: str, value=1, *, help: str = "", **labels) -> None:
    if _ENABLED:
        _REGISTRY.counter(name, help=help, **labels).add(value)


def gauge_set(name: str, value, *, help: str = "", **labels) -> None:
    if _ENABLED:
        _REGISTRY.gauge(name, help=help, **labels).set(value)


def histogram_observe(
    name: str, value, *, buckets=None, help: str = "", exemplar=None,
    **labels
) -> None:
    if _ENABLED:
        _REGISTRY.histogram(name, buckets=buckets, help=help, **labels) \
            .observe(value, exemplar=exemplar)


if os.environ.get("KNN_TPU_OBS", "") not in ("", "0"):
    enable(jax_annotations=os.environ.get("KNN_TPU_OBS") == "jax")
