"""File writers for the observability artifacts.

``write_trace`` emits the Chrome/Perfetto ``trace_event`` JSON;
``write_metrics`` emits either the combined JSON document (the
``--metrics-out`` payload: metric values + span aggregates + the per-phase
breakdown) or, for ``.prom``/``.txt`` paths, the Prometheus text format.
Both validate the destination directory up front so a bad path fails with
a clean error before any compute is discarded.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from knn_tpu.obs.metrics import MetricsRegistry
from knn_tpu.obs.tracer import Span, SpanTracer


def check_parent_dir(path: str) -> None:
    """Raise OSError (with a clean message) when ``path``'s directory is
    missing or not writable — called up front by the CLI so a bad
    ``--metrics-out`` / ``--trace-out`` fails before any compute runs."""
    from knn_tpu.utils.timing import ensure_writable_dir

    ensure_writable_dir(os.path.dirname(os.path.abspath(path)))


def write_trace(path: str, tracer: SpanTracer) -> None:
    """Write the tracer's spans as Perfetto-loadable trace JSON."""
    check_parent_dir(path)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(tracer.to_chrome_trace(), f)
        f.write("\n")


def metrics_document(
    tracer: SpanTracer,
    registry: MetricsRegistry,
    phase_parent: Optional[Span] = None,
    wall_ms: Optional[float] = None,
) -> dict:
    """The combined metrics JSON document.

    ``phases`` aggregates the direct children of ``phase_parent`` (the
    timed classify region in the CLI) — sequential children partition the
    region, so their ``total_ms`` values sum to ~the region's wall time.
    ``spans`` aggregates every completed span by name; ``metrics`` is the
    registry dump. ``wall_ms`` records the caller's headline number so the
    document is self-contained.
    """
    doc = {
        "spans": tracer.aggregate(),
        "metrics": registry.to_json(),
    }
    if tracer.dropped:
        # The buffer cap truncated recording; say so rather than letting
        # the aggregates read as complete.
        doc["spans_dropped"] = tracer.dropped
    if phase_parent is not None:
        # Flat {phase: total_ms} — the same shape the CLI's --json "phases"
        # key carries (one definition: SpanTracer.phase_totals), so the two
        # artifacts compare with plain equality and sum(phases.values()) is
        # the region's covered wall time.
        doc["phases"] = tracer.phase_totals(phase_parent)
    if wall_ms is not None:
        doc["wall_ms"] = wall_ms
    return doc


def write_metrics(
    path: str,
    tracer: SpanTracer,
    registry: MetricsRegistry,
    phase_parent: Optional[Span] = None,
    wall_ms: Optional[float] = None,
) -> None:
    """Write the metrics document; ``.prom``/``.txt`` suffixes select the
    Prometheus text exposition instead of JSON."""
    check_parent_dir(path)
    if path.endswith((".prom", ".txt")):
        with open(path, "w", encoding="utf-8") as f:
            f.write(registry.to_prometheus())
        return
    doc = metrics_document(tracer, registry, phase_parent, wall_ms)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
