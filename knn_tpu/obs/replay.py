"""Deterministic workload replay: re-drive a captured trace, verify it.

The capture half (:mod:`knn_tpu.obs.workload`) records what happened;
this module makes it happen AGAIN — open-loop, with the original
inter-arrival timing (or scaled by ``--speed``) — against either an
in-process :class:`~knn_tpu.serve.batcher.MicroBatcher` or a live server
over HTTP, and checks the answers:

- **reads** fire at their recorded arrival offsets without waiting for
  earlier completions (open-loop: a slow target builds queue, exactly as
  the original traffic would have), each resolved on a waiter pool that
  records its wall and answer digest;
- **mutations** replay in ``mutation_seq`` order ON THE DRIVER THREAD,
  each acknowledged before any later event fires: a mutation is a
  sequence point, so replaying it as a TWO-SIDED barrier — every
  outstanding read drained first (a mutation applies between dispatches
  and would otherwise jump still-queued reads, serving them at a later
  ``mutation_seq`` than the capture recorded), then the mutation applied
  and acknowledged — is what keeps later reads' ``mutation_seq`` tags
  aligned with the capture (an insert overtaking its delete would
  diverge every read after it); the driver clock absorbs both waits and
  ``late_fires`` counts any slip;
- **verification**: wherever a replayed answer's
  ``(index_version, mutation_seq)`` matches the recorded one, the answer
  digests must match BIT-IDENTICALLY (the canonical float64 digest of
  :func:`~knn_tpu.obs.workload.answer_digest` — transport-independent,
  so a JSON body from a live server verifies against an in-process
  capture). Tag mismatches are counted ``skipped``, never divergences:
  a replay against a rebuilt index or a differently-timed mutation
  boundary is reported honestly rather than failing noisily.

The verdict dict (``knn_tpu replay --verdict-out``) carries measured
p50/p99/QPS next to the CAPTURED run's numbers and the verification
counts — the artifact ``make replay-gate`` asserts on.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from knn_tpu.obs.workload import Workload, answer_digest

VERIFY_MODES = ("tag", "always", "off")


class _Results:
    """Thread-safe collection of per-event replay outcomes."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reads: list = []       # (event, result dict)
        self.mutations: list = []   # (event, result dict)

    def add_read(self, ev, res) -> None:
        with self._lock:
            self.reads.append((ev, res))

    def add_mutation(self, ev, res) -> None:
        with self._lock:
            self.mutations.append((ev, res))


def _resolve_inproc(ev, handle, t0, results: _Results,
                    timeout_s: float) -> None:
    try:
        value = handle.result(timeout=timeout_s)
    except Exception as e:  # noqa: BLE001 — a typed failure is an outcome
        results.add_read(ev, {
            "outcome": "error", "error": f"{type(e).__name__}: {e}",
            "ms": (time.monotonic() - t0) * 1e3,
        })
        return
    meta = handle.meta or {}
    results.add_read(ev, {
        "outcome": "ok",
        "ms": (time.monotonic() - t0) * 1e3,
        "rung": meta.get("rung"),
        "index_version": meta.get("index_version"),
        "mutation_seq": meta.get("mutation_seq"),
        "digest": answer_digest(ev["kind"], value),
    })


def _http_json(base_url: str, path: str, payload: dict,
               headers: Optional[dict] = None, timeout_s: float = 60.0):
    req = urllib.request.Request(
        base_url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode())
        except ValueError:
            body = {}
        return e.code, body


def _http_read(ev, rows, base_url, results: _Results,
               timeout_s: float) -> None:
    payload = {"instances": rows.tolist()}
    if ev.get("deadline_ms") is not None:
        payload["deadline_ms"] = ev["deadline_ms"]
    if ev.get("class") is not None:
        payload["class"] = ev["class"]
    t0 = time.monotonic()
    try:
        status, body = _http_json(base_url, "/" + ev["kind"], payload,
                                  timeout_s=timeout_s)
    except Exception as e:  # noqa: BLE001 — connection-level failure
        results.add_read(ev, {
            "outcome": "error", "error": f"{type(e).__name__}: {e}",
            "ms": (time.monotonic() - t0) * 1e3,
        })
        return
    ms = (time.monotonic() - t0) * 1e3
    if status != 200:
        results.add_read(ev, {
            "outcome": "error", "ms": ms, "status": status,
            "error": str(body.get("error", ""))[:200],
        })
        return
    if ev["kind"] == "predict":
        value = np.asarray(body["predictions"], dtype=np.float64)
    else:
        value = (np.asarray(body["distances"], dtype=np.float64),
                 np.asarray(body["indices"], dtype=np.float64))
    results.add_read(ev, {
        "outcome": "ok", "ms": ms,
        "index_version": body.get("index_version"),
        "mutation_seq": body.get("mutation_seq"),
        "digest": answer_digest(ev["kind"], value),
    })


def _fire_mutation(ev, workload: Workload, batcher, base_url,
                   results: _Results, timeout_s: float) -> None:
    """Apply one mutation and WAIT for its ack (the sequence-point
    barrier — see the module docstring)."""
    try:
        if ev["op"] == "insert":
            rows = workload.rows_for(ev)
            values = ev.get("values")
            if batcher is not None:
                out = batcher.submit_mutation(
                    "insert", {"rows": rows, "values": values}
                ).result(timeout=timeout_s)
            else:
                st, out = _http_json(
                    base_url, "/insert",
                    {"rows": rows.tolist(), "labels": values},
                    timeout_s=timeout_s)
                if st != 200:
                    raise RuntimeError(
                        f"/insert {st}: {out.get('error', '')}")
        else:
            if batcher is not None:
                out = batcher.submit_mutation(
                    "delete", {"ids": ev.get("ids", [])}
                ).result(timeout=timeout_s)
            else:
                st, out = _http_json(base_url, "/delete",
                                     {"ids": ev.get("ids", [])},
                                     timeout_s=timeout_s)
                if st != 200:
                    raise RuntimeError(
                        f"/delete {st}: {out.get('error', '')}")
        results.add_mutation(ev, {
            "outcome": "ok",
            "seq": out.get("seq") if isinstance(out, dict) else None,
        })
    except Exception as e:  # noqa: BLE001 — recorded per mutation
        results.add_mutation(ev, {
            "outcome": "error",
            "error": f"{type(e).__name__}: {e}",
        })


def replay_workload(workload: Workload, *, batcher=None,
                    base_url: Optional[str] = None, speed: float = 1.0,
                    verify: str = "tag", timeout_s: float = 120.0,
                    pool_size: Optional[int] = None,
                    replay_mutations: bool = True) -> dict:
    """Re-drive ``workload`` and return the verdict dict.

    Exactly one of ``batcher`` (in-process) / ``base_url`` (live server)
    must be given. ``speed`` scales the arrival clock (2.0 = twice as
    fast; 0 = no pacing, fire as fast as the driver loop runs).
    ``verify``: ``tag`` (default) checks digests only at matching
    ``(index_version, mutation_seq)``; ``always`` checks every ok/ok
    pair (for replays against a rebuilt-but-identical index whose
    version TAG necessarily moved); ``off`` skips verification.

    ``pool_size`` bounds the waiter/HTTP worker threads. The default
    sizes it to the workload (one per read, capped at 128) so open-loop
    pacing and latency measurement stay faithful up to 128 concurrently
    outstanding requests: past a saturated pool, HTTP reads fire late
    and in-process walls absorb waiter pickup delay — pass a larger
    ``pool_size`` when replaying deeper concurrency.
    """
    if (batcher is None) == (base_url is None):
        raise ValueError("exactly one of batcher / base_url is required")
    if verify not in VERIFY_MODES:
        raise ValueError(f"verify must be one of {VERIFY_MODES}, got "
                         f"{verify!r}")
    if speed < 0:
        raise ValueError(f"speed must be >= 0, got {speed}")
    from concurrent.futures import ThreadPoolExecutor

    results = _Results()
    events = workload.events
    mutations = workload.mutation_events
    if pool_size is None:
        pool_size = min(128, max(16, len(workload.read_events)))
    skipped_mutations = 0 if (replay_mutations or not mutations) \
        else len(mutations)
    late_fires = 0
    t_start = time.monotonic()
    with ThreadPoolExecutor(max_workers=pool_size,
                            thread_name_prefix="knn-replay") as pool:
        outstanding: list = []
        for ev in events:
            if speed > 0:
                target = t_start + (ev["t_ms"] / 1e3) / speed
                delay = target - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                elif -delay > 0.05:
                    late_fires += 1
            if "op" in ev:
                if replay_mutations:
                    # A sequence-point barrier is two-sided: drain every
                    # outstanding read FIRST (a mutation applies between
                    # dispatches and would otherwise jump reads still
                    # queued, serving them at a later mutation_seq than
                    # the capture recorded — the flake this closes), then
                    # apply and wait for the ack.
                    for f in outstanding:
                        f.result()
                    outstanding.clear()
                    _fire_mutation(ev, workload, batcher, base_url,
                                   results, timeout_s)
                continue
            rows = workload.rows_for(ev)
            if batcher is not None:
                t0 = time.monotonic()
                try:
                    handle = batcher.submit(
                        rows, ev["kind"],
                        deadline_ms=ev.get("deadline_ms"),
                        request_class=ev.get("class"),
                    )
                except Exception as e:  # noqa: BLE001 — typed admission
                    results.add_read(ev, {
                        "outcome": "error",
                        "error": f"{type(e).__name__}: {e}", "ms": 0.0,
                    })
                    continue
                outstanding.append(pool.submit(
                    _resolve_inproc, ev, handle, t0, results, timeout_s))
            else:
                outstanding.append(pool.submit(
                    _http_read, ev, rows, base_url, results, timeout_s))
    wall_s = max(time.monotonic() - t_start, 1e-9)

    # -- verdict -------------------------------------------------------------
    ok_ms = sorted(r["ms"] for _e, r in results.reads
                   if r["outcome"] == "ok")
    errors = [(e, r) for e, r in results.reads if r["outcome"] != "ok"]
    verified = divergent = skipped_tag = unverifiable = 0
    divergence_samples = []
    for ev, res in results.reads:
        if verify == "off":
            break
        if (ev.get("outcome") != "ok" or ev.get("digest") is None
                or res["outcome"] != "ok"):
            unverifiable += 1
            continue
        if verify == "tag" and (
                ev.get("index_version") != res.get("index_version")
                or ev.get("mutation_seq") != res.get("mutation_seq")):
            skipped_tag += 1
            continue
        if res["digest"] == ev["digest"]:
            verified += 1
        else:
            divergent += 1
            if len(divergence_samples) < 8:
                divergence_samples.append({
                    "id": ev.get("id"),
                    "request_id": ev.get("request_id"),
                    "kind": ev["kind"],
                    "t_ms": ev["t_ms"],
                    "captured_digest": ev["digest"],
                    "replayed_digest": res["digest"],
                    "index_version": res.get("index_version"),
                    "mutation_seq": res.get("mutation_seq"),
                })
    mut_ok = sum(1 for _e, r in results.mutations
                 if r["outcome"] == "ok")
    mut_seq_aligned = sum(
        1 for e, r in results.mutations
        if r["outcome"] == "ok" and r.get("seq") == e.get("seq")
    )
    measured = {
        "requests": len(results.reads),
        "ok": len(ok_ms),
        "errors": len(errors),
        "wall_s": round(wall_s, 3),
        "qps": round(len(results.reads) / wall_s, 2),
        "late_fires": late_fires,
    }
    if ok_ms:
        arr = np.asarray(ok_ms)
        measured["p50_ms"] = round(float(np.percentile(arr, 50)), 3)
        measured["p99_ms"] = round(float(np.percentile(arr, 99)), 3)
        measured["mean_ms"] = round(float(arr.mean()), 3)
    else:
        measured["p50_ms"] = measured["p99_ms"] = measured["mean_ms"] = None
    return {
        "workload": {
            "path": str(workload.path),
            "requests": workload.manifest["requests"],
            "mutations": workload.manifest["mutations"],
            "duration_ms": workload.manifest.get("duration_ms"),
            "policy": workload.manifest.get("policy"),
            "index_version": workload.manifest.get("index_version"),
            "mutation_stream_complete": workload.manifest.get(
                "mutation_stream_complete", True),
        },
        "target": "in-process" if batcher is not None else base_url,
        "speed": speed,
        "measured": measured,
        "captured": workload.captured_latency_summary(),
        "verify": {
            "mode": verify,
            "verified": verified,
            "divergences": divergent,
            "skipped_tag_mismatch": skipped_tag,
            "unverifiable": unverifiable,
            "divergence_samples": divergence_samples,
        },
        "mutations": {
            "fired": len(results.mutations),
            "ok": mut_ok,
            "seq_aligned": mut_seq_aligned,
            "skipped": skipped_mutations,
            "error_samples": [
                r.get("error") for _e, r in results.mutations
                if r["outcome"] != "ok"
            ][:4],
        },
        "error_samples": [
            {"id": e.get("id"), "error": r.get("error"),
             "status": r.get("status")} for e, r in errors
        ][:8],
    }
