"""What-if batching simulation: cost a candidate policy in milliseconds.

Changing ``max_batch`` / ``max_wait_ms`` / the compiled shape buckets on a
live replica means a reboot-warm-measure cycle per candidate. This module
replaces that loop with a **discrete-event simulator** of the batcher's
admission/coalesce policy (``serve/batcher.py::_collect``'s exact rules)
driven by a CAPTURED arrival process (:mod:`knn_tpu.obs.workload`) and
costed by the capacity model's fitted affine dispatch cost
``w(r) = a + b·r`` (:mod:`knn_tpu.obs.capacity`) — so a whole
policy frontier (predicted p50/p99/occupancy/waste per candidate) comes
back in milliseconds without booting a server.

What is modeled — exactly the single-worker batcher:

- one worker; FIFO queue; while the worker is busy, arrivals queue;
- a batch closes at the earlier of ``max_wait_ms`` from the OLDEST queued
  arrival or queued rows reaching ``max_batch`` (and never before the
  worker is free — an expired window dispatches immediately at pickup);
- whole requests only, greedily packed up to ``max_batch`` rows; a
  single request larger than ``max_batch`` dispatches alone, chunked
  (paying the intercept ``a`` once per chunk, the same rule the capacity
  fit excludes chunked dispatches for);
- a dispatch of ``rows`` costs ``a + b·padded(rows)`` ms, where
  ``padded`` quantizes to the policy's shape buckets (pad to the next
  bucket — ROADMAP item 3's proposal) or is ``rows`` itself for the
  bucket-less live policy, matching how the fit was measured.

What is NOT modeled (and why the gate's agreement band exists): HTTP
handler overhead, GC/scheduler jitter, deadline expiries, the
degradation ladder, and mutations. ``make replay-gate`` holds the
simulator's predicted p50 for the live policy against a real replay's
measured p50 within the band documented in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

from collections import deque
from typing import Optional, Sequence

import numpy as np


def padded_rows(rows: int, buckets: Optional[Sequence[int]],
                max_batch: int) -> int:
    """The compiled-shape rows a dispatch of ``rows`` pays under a shape
    bucket policy: the smallest bucket >= rows (``max_batch`` tops the
    ladder implicitly); bucket-less policies pay the actual rows."""
    if not buckets:
        return rows
    for b in buckets:
        if rows <= b:
            return int(b)
    return max(rows, int(max_batch))


def simulate(arrivals: Sequence, *, max_batch: int, max_wait_ms: float,
             a_ms: float, b_ms_per_row: float,
             buckets: Optional[Sequence[int]] = None) -> dict:
    """Run the arrival process through one candidate policy.

    ``arrivals`` — ``[(t_ms, rows)]``, sorted by time (a
    :meth:`~knn_tpu.obs.workload.Workload.arrivals` list).
    Returns the predicted serving summary: per-request latency
    percentiles, dispatch count, occupancy, padded-row waste, duty cycle.
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if max_wait_ms < 0:
        raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
    if a_ms < 0 or b_ms_per_row < 0:
        raise ValueError(
            f"dispatch cost must be non-negative, got a={a_ms}, "
            f"b={b_ms_per_row}")
    if buckets is not None:
        buckets = sorted({int(b) for b in buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints: {buckets}")
    arrivals = sorted((float(t), int(r)) for t, r in arrivals)
    n = len(arrivals)
    if n == 0:
        return {"requests": 0, "dispatches": 0, "p50_ms": None,
                "p99_ms": None, "mean_ms": None, "occupancy_mean": 0.0,
                "padded_row_waste_ratio": 0.0, "duty_cycle": 0.0,
                "predicted_qps": 0.0}
    i = 0
    pending: deque = deque()
    t_free = arrivals[0][0]
    lat: list = []
    busy = 0.0
    total_rows = total_padded = dispatches = 0
    occ_sum = 0.0
    while i < n or pending:
        if not pending:
            pending.append(arrivals[i])
            i += 1
        t0 = pending[0][0]
        start = max(t_free, t0)  # the worker picks the batch up here
        while i < n and arrivals[i][0] <= start:
            pending.append(arrivals[i])
            i += 1
        queued = sum(r for _, r in pending)
        deadline = t0 + max_wait_ms
        close = start
        if queued < max_batch and start < deadline:
            # Coalescing window: wait for more work until the deadline,
            # closing early the instant queued rows reach max_batch.
            close = deadline
            while i < n and arrivals[i][0] <= deadline:
                pending.append(arrivals[i])
                queued += arrivals[i][1]
                i += 1
                if queued >= max_batch:
                    close = max(start, arrivals[i - 1][0])
                    break
        batch, rows_b = [], 0
        while pending:
            t_a, r = pending[0]
            if batch and rows_b + r > max_batch:
                break
            batch.append((t_a, r))
            rows_b += r
            pending.popleft()
        pad = padded_rows(rows_b, buckets, max_batch)
        if rows_b > max_batch:
            # Oversized single request: chunked dispatch pays the
            # intercept per chunk (the capacity fit's exclusion rule).
            chunks = -(-rows_b // max_batch)
            wall = chunks * a_ms + b_ms_per_row * pad
        else:
            wall = a_ms + b_ms_per_row * pad
        finish = close + wall
        for t_a, _r in batch:
            lat.append(finish - t_a)
        busy += wall
        total_rows += rows_b
        total_padded += pad
        # Occupancy mirrors the live tracker (obs/capacity.py) FOR
        # BUCKET POLICIES: rows over the compiled shape the dispatch
        # padded to — the definition the whatif-vs-live parity test
        # holds the two to. Bucket-less candidates keep the
        # rows/max_batch meaning (the sim does not know the engine's
        # legacy pad quantum, so their occupancy is NOT comparable to a
        # live quantum-padded serve's — compare bucketed to bucketed).
        if buckets:
            occ_sum += min(1.0, rows_b / max(1, pad if pad >= rows_b
                                             else max_batch))
        else:
            occ_sum += min(1.0, rows_b / max_batch)
        dispatches += 1
        t_free = finish
    span_ms = max(t_free - arrivals[0][0], 1e-9)
    arr = np.asarray(sorted(lat))
    return {
        "requests": n,
        "dispatches": dispatches,
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "mean_ms": round(float(arr.mean()), 3),
        "occupancy_mean": round(occ_sum / dispatches, 4),
        "padded_row_waste_ratio": round(
            (total_padded - total_rows) / total_padded
            if total_padded else 0.0, 4),
        "duty_cycle": round(min(1.0, busy / span_ms), 4),
        "predicted_qps": round(n / (span_ms / 1e3), 2),
    }


def frontier(arrivals: Sequence, policies: Sequence[dict], *, a_ms: float,
             b_ms_per_row: float) -> "list[dict]":
    """Simulate every candidate policy over one arrival process.

    ``policies`` — dicts with ``max_batch``, ``max_wait_ms``, optional
    ``buckets``. Returns one row per candidate: the policy + its
    predicted summary — the occupancy/waste/p50/p99 frontier an operator
    (or ROADMAP item 3's bucketing work) reads to pick a setting without
    booting a server per candidate.
    """
    out = []
    for p in policies:
        sim = simulate(
            arrivals, max_batch=p["max_batch"],
            max_wait_ms=p["max_wait_ms"], a_ms=a_ms,
            b_ms_per_row=b_ms_per_row, buckets=p.get("buckets"),
        )
        out.append({"policy": {k: p.get(k) for k in
                               ("max_batch", "max_wait_ms", "buckets")},
                    **sim})
    return out


def default_policy_candidates(max_batch: int, max_wait_ms: float,
                              buckets: Optional[Sequence[int]] = None
                              ) -> "list[dict]":
    """The autotuner's candidate grid around the LIVE policy: the current
    ``max_wait_ms`` plus halvings/doublings of it (and 0 — pure
    anti-coalescing — when the current wait is small), same ``max_batch``
    and bucket ladder throughout. Only the coalescing window varies:
    ``max_batch``/``buckets`` change compiled shapes, which the
    replay-verification contract treats as an operator decision, not a
    cadence re-tune (:mod:`knn_tpu.control.autotune`)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if max_wait_ms < 0:
        raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
    waits = {round(float(max_wait_ms), 4)}
    base = max(float(max_wait_ms), 0.25)
    for factor in (0.25, 0.5, 2.0, 4.0):
        waits.add(round(base * factor, 4))
    if base <= 1.0:
        waits.add(0.0)
    return [{"max_batch": int(max_batch), "max_wait_ms": w,
             "buckets": list(buckets) if buckets else None}
            for w in sorted(waits)]
